"""Topology-aware placement search inside one cluster view.

TPU-native analogue of the reference's ``pkg/algorithm/topology_aware_scheduler.go``.
Places a gang's pods onto "nodes" (node-level cells, or top-level cells below
node level), packing onto busier nodes first, then picks chips inside each
node minimizing the level of their lowest common ancestor (LCA) — on a mesh
chain that LCA level is exactly the smallest enclosing sub-mesh, so best
affinity = tightest contiguous ICI slice.

Two packing modes (reference rationale at ``topology_aware_scheduler.go:42-48``):
- ``cross_priority_pack=True`` (intra-VC): pack across priorities, since a
  high-priority group avoids preemption across the whole view;
- ``cross_priority_pack=False`` (opportunistic): pack within the same priority
  and stay away from higher priorities, since guaranteed pods can avoid
  preempting opportunistic pods only among buddy cells.

The in-node chip selection (``find_leaf_cells_in_node``) can be delegated to
the C++ accelerator in ``hivedscheduler_tpu/native`` when available; the pure
Python path is the semantic reference.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Set, Tuple

from hivedscheduler_tpu.algorithm.cell import Cell, CellLevel, CellPriority, PhysicalCell, VirtualCell, cell_equal
from hivedscheduler_tpu.algorithm.constants import (
    FREE_PRIORITY,
    HIGHEST_LEVEL,
    LOWEST_LEVEL,
    OPPORTUNISTIC_PRIORITY,
)
from hivedscheduler_tpu.algorithm.types import CellList, ChainCellList

log = logging.getLogger(__name__)


class _Node:
    """One schedulable unit of the cluster view (reference: node struct,
    topology_aware_scheduler.go:118-154).

    ``seen_gen``/``seen_priority`` make the view persistent: the node's
    scoring fields are recomputed only when the underlying cell's
    ``view_gen`` moved or the probe priority changed since the last
    refresh (see TopologyAwareScheduler._update_cluster_view)."""

    __slots__ = (
        "cell",
        "free_leaf_cell_num_at_priority",
        "used_leaf_cell_num_same_priority",
        "used_leaf_cell_num_higher_priority",
        "healthy",
        "suggested",
        "node_address",
        "seen_gen",
        "seen_priority",
    )

    def __init__(self, cell: Cell):
        self.cell = cell
        self.free_leaf_cell_num_at_priority = 0
        self.used_leaf_cell_num_same_priority = 0
        self.used_leaf_cell_num_higher_priority = 0
        self.healthy = True
        self.suggested = True
        self.node_address = ""
        self.seen_gen = -1  # never refreshed
        self.seen_priority: Optional[CellPriority] = None

    def update_used_leaf_cell_num_for_priority(
        self, p: CellPriority, cross_priority_pack: bool
    ) -> None:
        used = self.cell.used_leaf_cell_num_at_priorities
        self.used_leaf_cell_num_same_priority = used.get(p, 0)
        self.used_leaf_cell_num_higher_priority = 0
        self.free_leaf_cell_num_at_priority = self.cell.total_leaf_cell_num
        for priority, num in used.items():
            if cross_priority_pack:
                if priority != p:
                    self.used_leaf_cell_num_same_priority += num
            elif priority > p:
                self.used_leaf_cell_num_higher_priority += num
            if priority >= p:
                self.free_leaf_cell_num_at_priority -= num


def _ancestor_no_higher_than_node(c: Cell) -> Cell:
    """Reference: ancestorNoHigherThanNode, topology_aware_scheduler.go:183-189."""
    while not c.at_or_higher_than_node and c.parent is not None:
        c = c.parent
    return c


def _new_cluster_view(ccl: ChainCellList) -> List[_Node]:
    """Extract node-level cells (or lower-level cells with no node-level
    ancestor in the list) from a cell list (reference: newClusterView,
    topology_aware_scheduler.go:158-179)."""
    levels = sorted(lv for lv in ccl if ccl.get(lv))
    start: Optional[CellLevel] = None
    for lv in levels:
        if ccl[lv][0].at_or_higher_than_node:
            start = lv
            break
    if start is None:
        start = levels[-1] if levels else LOWEST_LEVEL
    cv: List[_Node] = []
    addresses: Set[str] = set()
    for lv in range(start, LOWEST_LEVEL - 1, -1):
        for c in ccl.get(lv, []):
            anc = _ancestor_no_higher_than_node(c)
            if anc.address not in addresses:
                addresses.add(anc.address)
                cv.append(_Node(c))
    return cv


def _node_healthy_and_in_suggested(
    n: _Node, suggested_nodes: Set[str], ignore_suggested_nodes: bool
) -> Tuple[bool, bool, str]:
    """Reference: nodeHealthyAndInSuggested, topology_aware_scheduler.go:242-265."""
    c = n.cell
    if isinstance(c, PhysicalCell):
        return (
            c.healthy,
            ignore_suggested_nodes or c.nodes[0] in suggested_nodes,
            c.address,
        )
    if isinstance(c, VirtualCell) and c.physical_cell is not None:
        pn = c.physical_cell
        return (
            pn.healthy,
            ignore_suggested_nodes or pn.nodes[0] in suggested_nodes,
            pn.address,
        )
    return True, True, ""


def _greedy_assign(
    cv: List[_Node], order: List[int], leaf_cell_nums: List[int]
) -> Tuple[Optional[List[int]], str]:
    """The reference's greedy walk (findNodesForPods inner loop,
    topology_aware_scheduler.go:280-305) over ``order`` (indices into cv).
    The gang-contiguity pass calls this with enclosure members pre-filtered
    to healthy+suggested nodes, so for it the bad/non-suggested failures
    cannot fire; the flat fallback owns those failure reasons."""
    picked = [0] * len(leaf_cell_nums)
    pod_index = 0
    picked_leaf_cell_num = 0
    oi = 0
    while oi < len(order):
        node_index = order[oi]
        n = cv[node_index]
        if n.free_leaf_cell_num_at_priority - picked_leaf_cell_num >= leaf_cell_nums[pod_index]:
            # fail when forced onto a bad or non-suggested node
            if not n.healthy:
                return None, f"have to use at least one bad node {n.node_address}"
            if not n.suggested:
                return None, f"have to use at least one non-suggested node {n.node_address}"
            picked[pod_index] = node_index
            picked_leaf_cell_num += leaf_cell_nums[pod_index]
            pod_index += 1
            if pod_index == len(leaf_cell_nums):
                return picked, ""
        else:
            picked_leaf_cell_num = 0
            oi += 1
    return None, "insufficient capacity"


def _find_nodes_for_pods(
    cv: List[_Node], leaf_cell_nums: List[int], pack: bool = True
) -> Tuple[Optional[List[int]], str]:
    """Node selection for a gang (reference: findNodesForPods,
    topology_aware_scheduler.go:268-306). Nodes sorted by: healthy first,
    suggested first, then busiest-first (``pack``, the reference behavior) or
    emptiest-first (``spread`` policy), fewer higher-priority-used last.

    TPU-first extension over the reference's flat greedy: a multi-node gang
    first tries to fit inside the TIGHTEST enclosing cell (gang-level LCA
    minimization) — on a mesh chain that enclosing cell is a contiguous ICI
    sub-mesh, so a gang no longer straddles buddy cells in an L-shape while a
    whole free cell exists. Falls back to the reference's flat greedy (which
    also owns the bad/non-suggested failure reasons).

    This rebuild-per-call function is the semantic REFERENCE; the scheduler's
    hot path runs the incremental equivalent
    (TopologyAwareScheduler._find_nodes_incremental), which must pick the
    same nodes (guard: tests/test_incremental_views.py)."""
    sign = -1 if pack else 1
    cv.sort(
        key=lambda n: (
            not n.healthy,
            not n.suggested,
            sign * n.used_leaf_cell_num_same_priority,
            n.used_leaf_cell_num_higher_priority,
        )
    )
    if len(leaf_cell_nums) > 1:
        total = sum(leaf_cell_nums)
        # (ancestor level, ancestor address) -> member indices into the
        # sorted cv, ascending; only healthy+suggested nodes join an
        # enclosure, so enclosure capacity is usable capacity
        groups: Dict[Tuple[int, str], List[int]] = {}
        for i, n in enumerate(cv):
            if not n.healthy or not n.suggested:
                continue
            anc = n.cell.parent
            while anc is not None:
                groups.setdefault((anc.level, anc.address), []).append(i)
                anc = anc.parent
        # visit enclosures tightest level first, then by their best (lowest)
        # position in the sorted view — pack order within a level
        for (_lv, _addr), members in sorted(
            groups.items(), key=lambda kv: (kv[0][0], kv[1][0])
        ):
            if sum(cv[i].free_leaf_cell_num_at_priority for i in members) < total:
                continue
            picked, _ = _greedy_assign(cv, members, leaf_cell_nums)
            if picked is not None:
                return picked, ""
    return _greedy_assign(cv, list(range(len(cv))), leaf_cell_nums)


def _get_optimal_affinity(leaf_cell_num: int, level_leaf_cell_num: Dict[CellLevel, int]) -> CellLevel:
    """Lowest level whose cells can hold the pod (reference:
    getOptimalAffinity, topology_aware_scheduler.go:389-399)."""
    for lv in range(1, len(level_leaf_cell_num) + 1):
        if level_leaf_cell_num.get(lv, 0) >= leaf_cell_num:
            return lv
    raise AssertionError(
        "Assert Failure: pod allocated a node but exceeds the capacity of the current chain"
    )


def _find_lca(lower: Cell, higher: Cell) -> Optional[Cell]:
    """Reference: findLCA, topology_aware_scheduler.go:444-462."""
    while lower.level < higher.level:
        if lower.parent is None:
            return None
        lower = lower.parent
    if cell_equal(lower, higher):
        return lower
    while not cell_equal(lower.parent, higher.parent):
        if lower.parent is None or higher.parent is None:
            return None
        lower = lower.parent
        higher = higher.parent
    return lower.parent


def _get_leaf_cells_from_node(
    c: Cell, p: CellPriority, free: CellList, preemptible: CellList
) -> None:
    """Reference: getLeafCellsFromNode, topology_aware_scheduler.go:465-476."""
    if c.level > 1:
        for cc in c.children:
            _get_leaf_cells_from_node(cc, p, free, preemptible)
    elif c.priority == FREE_PRIORITY:
        free.append(c)
    elif c.priority < p:
        preemptible.append(c)


# below this many candidates the Python search beats ctypes marshalling
_NATIVE_THRESHOLD = 16


def _node_ancestor_matrix(n: Cell):
    """Static per-node ancestor-id matrix for the native search, cached on the
    node cell (topology never changes after construction)."""
    import ctypes

    cached = getattr(n, "_native_ancestors", None)
    if cached is not None:
        return cached
    leaves: CellList = []

    def collect(c: Cell) -> None:
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(n)
    n_levels = n.level
    ids: Dict[str, int] = {}
    flat = (ctypes.c_int32 * (len(leaves) * n_levels))()
    row_of: Dict[str, int] = {}
    for r, leaf in enumerate(leaves):
        row_of[leaf.address] = r
        c: Optional[Cell] = leaf
        for lv in range(1, n_levels + 1):
            while c.level < lv:
                c = c.parent
            flat[r * n_levels + (lv - 1)] = ids.setdefault(c.address, len(ids))
    cached = (row_of, flat, n_levels)
    n._native_ancestors = cached  # type: ignore[attr-defined]
    return cached


def _find_leaf_cells_native(
    n: Cell,
    available_leaf_cells: CellList,
    leaf_cell_num: int,
    optimal_affinity: CellLevel,
) -> Optional[List[int]]:
    """Gather the available rows and run the C++ search; returns candidate
    indices or None when the native library is unavailable."""
    import ctypes

    from hivedscheduler_tpu import native

    if not native.available():
        return None
    row_of, full, n_levels = _node_ancestor_matrix(n)
    n_avail = len(available_leaf_cells)
    gathered = (ctypes.c_int32 * (n_avail * n_levels))()
    for i, cell in enumerate(available_leaf_cells):
        r = row_of.get(cell.address)
        if r is None:  # cell not under this node (shouldn't happen)
            return None
        src = r * n_levels
        gathered[i * n_levels : (i + 1) * n_levels] = full[src : src + n_levels]
    return native.find_leaf_cells(
        gathered, n_avail, n_levels, leaf_cell_num, optimal_affinity
    )


def _find_leaf_cells_direct(
    n: Cell, available_leaf_cells: CellList, leaf_cell_num: int
) -> List[int]:
    """Direct aligned-enclosure enumeration: the mesh-first replacement for
    the reference's combination-backtracking search.

    Key fact (why this is exact, not a heuristic): the reference's search
    (topology_aware_scheduler.go:309-387) enumerates index combinations
    lexicographically, keeps the first strictly-better LCA, and prunes
    prefixes whose running LCA already exceeds the best. Since the LCA level
    of a chip set is monotone in set growth, the set it returns is exactly
    "the first ``leaf_cell_num`` candidates inside the lowest-level cell
    that encloses at least ``leaf_cell_num`` candidates" (ties between
    equal-level cells broken by earliest candidate index). Cells of a mesh
    chain ARE the aligned sub-meshes (algorithm/mesh.py tilings), so walking
    each candidate's ancestor chain enumerates exactly the aligned
    enclosures — O(candidates x levels) total, no backtracking. The same
    argument holds for generic chains, so this path serves both; the
    backtracking implementation is kept below as the semantic reference for
    differential tests (and the ``HIVED_DIRECT=0`` escape hatch).

    Returns ascending indices into ``available_leaf_cells``.
    """
    counts: Dict[int, int] = {}
    for chip in available_leaf_cells:
        c: Optional[Cell] = chip
        while c is not None:
            counts[id(c)] = counts.get(id(c), 0) + 1
            if c is n:
                break
            c = c.parent
    best_level = HIGHEST_LEVEL
    best_cell: Optional[Cell] = None
    for chip in available_leaf_cells:
        c = chip
        while c is not None:
            if counts[id(c)] >= leaf_cell_num:
                # lowest qualifying enclosure containing this chip; counts
                # are monotone up the tree so ancestors only tie or worsen,
                # and later chips can only tie on level, never beat the
                # first-index tie-break
                if c.level < best_level:
                    best_level = c.level
                    best_cell = c
                break
            if c is n:
                break
            c = c.parent
    if best_cell is None:
        raise AssertionError(
            f"Assert Failure: failed to allocate {leaf_cell_num} leaf cells "
            f"in picked node {n.address}"
        )
    picked: List[int] = []
    target = id(best_cell)
    for idx, chip in enumerate(available_leaf_cells):
        c = chip
        while c is not None:
            if id(c) == target:
                picked.append(idx)
                break
            if c is n:
                break
            c = c.parent
        if len(picked) == leaf_cell_num:
            break
    return picked


def find_leaf_cells_in_node(
    n: Cell,
    leaf_cell_num: int,
    p: CellPriority,
    available_leaf_cells: Optional[CellList],
    level_leaf_cell_num: Dict[CellLevel, int],
) -> Tuple[CellList, CellList]:
    """Pick the `leaf_cell_num` chips with the lowest LCA in a node — on a
    mesh chain, the tightest aligned sub-mesh enclosure (reference:
    findLeafCellsInNode, topology_aware_scheduler.go:309-387).

    Free chips come before preemptible ones in the candidate list, so free
    chips are preferred. Uses the direct aligned-enclosure enumeration
    (`_find_leaf_cells_direct`, exact and near-linear); the reference's
    backtracking search below is the differential-testing reference,
    selectable with HIVED_DIRECT=0. Returns (picked cells, remaining
    available cells)."""
    if available_leaf_cells is None:
        free: CellList = []
        preemptible: CellList = []
        _get_leaf_cells_from_node(n, p, free, preemptible)
        available_leaf_cells = free + preemptible

    if leaf_cell_num == len(available_leaf_cells):
        # taking every candidate: any LCA-minimizing search returns exactly
        # this set in ascending index order, so skip the search — the common
        # whole-node allocation on small (e.g. 4-chip) hosts
        picked = list(available_leaf_cells)
        del available_leaf_cells[:]
        return picked, available_leaf_cells

    optimal = _get_optimal_affinity(leaf_cell_num, level_leaf_cell_num)
    # Hybrid dispatch: below the threshold (typical mesh hosts hold 4-8
    # chips) the reference's tight backtracking loop has the best constant
    # factor; at or above it, the direct aligned-enclosure enumeration wins
    # and is immune to the search's combinatorial worst case (it replaces
    # the C++ accelerated backtracking on that tier).
    if (
        len(available_leaf_cells) >= _NATIVE_THRESHOLD
        and os.environ.get("HIVED_DIRECT", "1") != "0"
    ):
        picked_idx = _find_leaf_cells_direct(
            n, available_leaf_cells, leaf_cell_num
        )
        best_cells = [available_leaf_cells[i] for i in picked_idx]
        _remove_picked(available_leaf_cells, picked_idx)
        return best_cells, available_leaf_cells

    if len(available_leaf_cells) >= _NATIVE_THRESHOLD:
        picked_idx = _find_leaf_cells_native(
            n, available_leaf_cells, leaf_cell_num, optimal
        )
        if picked_idx is not None:
            best_cells = [available_leaf_cells[i] for i in picked_idx]
            _remove_picked(available_leaf_cells, picked_idx)
            return best_cells, available_leaf_cells

    current_indices = [0] * leaf_cell_num
    current_affinity: List[Optional[Cell]] = [None] * leaf_cell_num
    best_cells: CellList = [None] * leaf_cell_num  # type: ignore[list-item]
    best_indices = [0] * leaf_cell_num
    best_affinity = HIGHEST_LEVEL
    optimal_affinity = optimal

    avail_index = 0
    search_index = 0
    while True:
        while avail_index < len(available_leaf_cells):
            leaf_cell = available_leaf_cells[avail_index]
            current_indices[search_index] = avail_index
            if search_index == 0:
                current_affinity[0] = leaf_cell
            else:
                lca = _find_lca(leaf_cell, current_affinity[search_index - 1])
                current_affinity[search_index] = lca
                # prune: running LCA already worse than the best seen
                if (lca is None and best_affinity < HIGHEST_LEVEL) or (
                    lca is not None and lca.level > best_affinity
                ):
                    avail_index += 1
                    continue
            if search_index == leaf_cell_num - 1:
                affinity = current_affinity[-1].level
                if affinity < best_affinity:
                    best_affinity = affinity
                    best_indices[:] = current_indices
                    for i, idx in enumerate(current_indices):
                        best_cells[i] = available_leaf_cells[idx]
                    if affinity == optimal_affinity:
                        # early stop: all-buddy solution
                        _remove_picked(available_leaf_cells, best_indices)
                        return best_cells, available_leaf_cells
            else:
                search_index += 1
            avail_index += 1
        search_index -= 1
        if search_index < 0:
            if best_affinity == HIGHEST_LEVEL:
                raise AssertionError(
                    f"Assert Failure: failed to allocate {leaf_cell_num} leaf cells "
                    f"in picked node {n.address}"
                )
            _remove_picked(available_leaf_cells, best_indices)
            return best_cells, available_leaf_cells
        avail_index = current_indices[search_index] + 1


def _remove_picked(leaf_cells: CellList, indices: List[int]) -> None:
    """Remove the picked cells (ascending indices) in place."""
    for offset, index in enumerate(indices):
        del leaf_cells[index - offset]


# below this many nodes the Python packing path beats ctypes marshalling
_PACK_NATIVE_THRESHOLD = 32


class TopologyAwareScheduler:
    """Reference: topologyAwareScheduler, topology_aware_scheduler.go:36-116.

    The cluster view is PERSISTENT and incremental: ``self.cv`` keeps its
    construction order forever, ``self._order`` carries the sorted
    permutation across calls (re-sorted — stably, seeding ties with the
    previous order exactly like the old in-place ``cv.sort()`` — only when a
    node's scoring inputs changed), and the enclosure structure for the
    multi-pod packing pass is precomputed once from the static topology.
    ``HIVED_INCR=0`` forces the rebuild-per-call reference path
    (:func:`_find_nodes_for_pods`); both must pick identical nodes (guards:
    tests/test_incremental_views.py, chaos.invariants.check_cluster_views).
    """

    def __init__(
        self,
        ccl: ChainCellList,
        level_leaf_cell_num: Dict[CellLevel, int],
        cross_priority_pack: bool,
        pack: bool = True,
    ):
        self.ccl = ccl  # kept for from-scratch view rebuilds (invariants)
        self.cv = _new_cluster_view(ccl)
        self.level_leaf_cell_num = level_leaf_cell_num
        self.cross_priority_pack = cross_priority_pack
        # pack=False = "spread" policy: prefer emptier nodes
        self.pack = pack
        # persistent sorted permutation (static indices into cv) + validity
        self._order: List[int] = list(range(len(self.cv)))
        self._order_dirty = True
        # static enclosure structure: [(ancestor level, [static indices])]
        # visited tightest level first — ancestors never change after
        # construction, only the per-call member filtering does
        enclosures: Dict[Tuple[int, str], List[int]] = {}
        for i, n in enumerate(self.cv):
            anc = n.cell.parent
            while anc is not None:
                enclosures.setdefault((anc.level, anc.address), []).append(i)
                anc = anc.parent
        self._enclosures: List[Tuple[int, List[int]]] = [
            (lv, members) for (lv, _addr), members in sorted(
                enclosures.items(), key=lambda kv: kv[0][0]
            )
        ]
        self._native_pack = None  # lazily-built native packing state

    def schedule(
        self,
        pod_leaf_cell_numbers: Dict[int, int],
        p: CellPriority,
        suggested_nodes: Set[str],
        ignore_suggested_nodes: bool,
    ) -> Tuple[Optional[Dict[int, List[CellList]]], str]:
        """Two-phase placement: first with preemption disabled (schedule at
        opportunistic priority), then retry with the real priority
        (reference: Schedule, topology_aware_scheduler.go:65-116)."""
        sorted_pod_nums: List[int] = []
        for leaf_cell_num, pod_num in pod_leaf_cell_numbers.items():
            sorted_pod_nums.extend([leaf_cell_num] * pod_num)
        sorted_pod_nums.sort()

        incremental = os.environ.get("HIVED_INCR", "1") != "0"
        priority = OPPORTUNISTIC_PRIORITY
        self._update_cluster_view(priority, suggested_nodes, ignore_suggested_nodes)
        picked_indices, failed_reason = self._find_nodes(
            sorted_pod_nums, incremental
        )
        if picked_indices is None and p > OPPORTUNISTIC_PRIORITY:
            priority = p
            self._update_cluster_view(priority, suggested_nodes, ignore_suggested_nodes)
            picked_indices, failed_reason = self._find_nodes(
                sorted_pod_nums, incremental
            )
        if picked_indices is None:
            return None, failed_reason

        selected_nodes = [self.cv[i].cell for i in picked_indices]
        node_available: Dict[str, CellList] = {}
        pod_placements: Dict[int, List[CellList]] = {}
        for pod_index, leaf_cell_num in enumerate(sorted_pod_nums):
            node_cell = selected_nodes[pod_index]
            picked_cells, node_available[node_cell.address] = find_leaf_cells_in_node(
                node_cell,
                leaf_cell_num,
                priority,
                node_available.get(node_cell.address),
                self.level_leaf_cell_num,
            )
            pod_placements.setdefault(leaf_cell_num, []).append(picked_cells)
        return pod_placements, ""

    # ------------------------------------------------------------------
    # incremental node selection
    # ------------------------------------------------------------------

    def _find_nodes(
        self, sorted_pod_nums: List[int], incremental: bool
    ) -> Tuple[Optional[List[int]], str]:
        """Dispatch: native one-call packing (sort + enclosure pass + greedy
        in C), the incremental Python path (cached order + static
        enclosures), or the rebuild-per-call reference (HIVED_INCR=0)."""
        if not incremental:
            # rebuild-per-call reference: sort a COPY so the static cv order
            # (which the enclosure structure and native buffers index) is
            # never disturbed, then translate positional picks back to
            # static indices
            cv_copy = list(self.cv)
            picked, reason = _find_nodes_for_pods(
                cv_copy, sorted_pod_nums, self.pack
            )
            if picked is not None:
                pos = {id(n): i for i, n in enumerate(self.cv)}
                picked = [pos[id(cv_copy[k])] for k in picked]
            return picked, reason
        native = self._native_pack_state()
        if native is not None:
            picked, reason = self._find_nodes_native(native, sorted_pod_nums)
            if picked is not None or reason:
                return picked, reason
            # reason == "": native declined (shouldn't happen) — fall through
        if self._order_dirty:
            sign = -1 if self.pack else 1
            cv = self.cv
            # stable re-sort of the PREVIOUS order: ties keep their old
            # relative position, exactly like the reference's repeated
            # in-place cv.sort()
            self._order.sort(
                key=lambda i: (
                    not cv[i].healthy,
                    not cv[i].suggested,
                    sign * cv[i].used_leaf_cell_num_same_priority,
                    cv[i].used_leaf_cell_num_higher_priority,
                )
            )
            self._order_dirty = False
        return self._find_nodes_incremental(sorted_pod_nums)

    def _find_nodes_native(self, state, sorted_pod_nums: List[int]):
        """One C call for the whole cross-node packing loop (stable sort of
        the persistent order, enclosure pass, greedy assign) — the common
        single-chain case. Failure strings are formatted here so they stay
        byte-identical to the Python reference's."""
        from hivedscheduler_tpu import native

        rc, picked, fail_idx = native.find_nodes_for_pods(
            state, sorted_pod_nums, self.pack, 1 if self._order_dirty else 0
        )
        if self._order_dirty:
            self._order = list(state["order_buf"])
            self._order_dirty = False
        if rc == 0:
            return picked, ""
        if rc == 2:
            return None, (
                f"have to use at least one bad node "
                f"{self.cv[fail_idx].node_address}"
            )
        if rc == 3:
            return None, (
                f"have to use at least one non-suggested node "
                f"{self.cv[fail_idx].node_address}"
            )
        return None, "insufficient capacity"

    def _find_nodes_incremental(
        self, sorted_pod_nums: List[int]
    ) -> Tuple[Optional[List[int]], str]:
        """The reference's findNodesForPods over the cached order + static
        enclosures; returns STATIC indices into cv. Must pick exactly the
        nodes :func:`_find_nodes_for_pods` picks."""
        cv = self.cv
        order = self._order
        if len(sorted_pod_nums) > 1 and self._enclosures:
            total = sum(sorted_pod_nums)
            rank = [0] * len(cv)
            for r, j in enumerate(order):
                rank[j] = r
            # candidate enclosures: filter members to healthy+suggested,
            # capacity-check, then visit (level asc, best member rank asc) —
            # identical to the reference's sorted((level, first-member)) walk
            candidates: List[Tuple[int, int, List[int]]] = []
            for lv, members in self._enclosures:
                cap = 0
                rs: List[int] = []
                for j in members:
                    n = cv[j]
                    if n.healthy and n.suggested:
                        cap += n.free_leaf_cell_num_at_priority
                        rs.append(rank[j])
                if not rs or cap < total:
                    continue
                rs.sort()
                candidates.append((lv, rs[0], rs))
            candidates.sort(key=lambda t: (t[0], t[1]))
            for _lv, _first, rs in candidates:
                picked, _ = _greedy_assign(
                    cv, [order[r] for r in rs], sorted_pod_nums
                )
                if picked is not None:
                    return picked, ""
        return _greedy_assign(cv, order, sorted_pod_nums)

    def max_feasible_prefix(
        self,
        flat_desc: List[int],
        p: CellPriority,
        suggested_nodes: Set[str],
        ignore_suggested_nodes: bool,
    ) -> int:
        """Largest prefix of ``flat_desc`` (gang member sizes, DESCENDING —
        the multi-chain relax walk's ``flat`` segment) that could pack on
        this view at either probe phase (opportunistic first, then ``p`` —
        mirroring :meth:`schedule`'s two-phase retry), computed in one
        native call per phase (``hived_find_nodes_prefix``).

        The result is an EXACT upper bound on the relax walk's
        descending-take descent: a take above it provably fails the same
        packing the real probe would run first, so skipping it changes no
        decision; every take at or below the bound still runs the full
        probe (VC mapping can fail for reasons packing cannot see).

        Returns ``len(flat_desc)`` — no pruning — whenever the native
        packing fast path is not engaged (small view, ``HIVED_NATIVE=0``,
        ``HIVED_INCR=0``, stale .so), so the pure-Python reference walk is
        byte-identical to the pre-native one.

        The native call sorts a SCRATCH copy of the persistent order: the
        reference's stable-sort tie history (which the real ``_order``
        carries) is never perturbed by probing.
        """
        n = len(flat_desc)
        if n == 0 or os.environ.get("HIVED_INCR", "1") == "0":
            return n
        state = self._native_pack_state()
        if state is None:
            return n
        import ctypes

        from hivedscheduler_tpu import native

        if not native.prefix_available():
            return n
        best = 0
        phases = [OPPORTUNISTIC_PRIORITY]
        if p > OPPORTUNISTIC_PRIORITY:
            phases.append(p)
        # one scratch order carried across phases — exactly the order
        # evolution schedule()'s sequential phase sorts would produce
        scratch = (ctypes.c_int32 * state["n"])(*self._order)
        for prio in phases:
            self._update_cluster_view(
                prio, suggested_nodes, ignore_suggested_nodes)
            take = native.find_nodes_prefix(
                state, flat_desc, self.pack, scratch)
            if take > best:
                best = take
                if best == n:
                    break
        return best

    def _native_pack_state(self):
        """Build (once) the persistent buffers feeding the native packing
        call: per-node score arrays in static order plus the static
        node-level ancestor-id matrix (tentpole: cached ancestor matrices —
        topology never changes after construction, so the matrix is built
        exactly once; the score buffers are kept in sync by the same dirty
        tracking that refreshes the Python view). Returns None when the
        native library is unavailable or the view is too small to bother;
        ``False`` is the cached "disabled" marker."""
        state = self._native_pack
        if state is not None:
            return state if state is not False else None
        import ctypes

        from hivedscheduler_tpu import native

        if (len(self.cv) < _PACK_NATIVE_THRESHOLD
                or os.environ.get("HIVED_NATIVE", "") == "0"
                or not native.pack_available()):
            self._native_pack = False
            return None
        n = len(self.cv)
        # static ancestor-id matrix: columns are ancestor levels ascending
        # (tightest enclosure first); -1 where a node lacks an ancestor at
        # that level. Ids are per-(level, address), assigned once.
        level_set = set()
        chains = []
        for node in self.cv:
            anc_chain = []
            anc = node.cell.parent
            while anc is not None:
                anc_chain.append(anc)
                level_set.add(anc.level)
                anc = anc.parent
            chains.append(anc_chain)
        levels = sorted(level_set)
        n_anc = len(levels)
        col_of = {lv: c for c, lv in enumerate(levels)}
        ids: Dict[Tuple[int, str], int] = {}
        anc_buf = (ctypes.c_int32 * max(1, n * n_anc))()
        for i in range(n * n_anc):
            anc_buf[i] = -1
        for i, anc_chain in enumerate(chains):
            for anc in anc_chain:
                anc_buf[i * n_anc + col_of[anc.level]] = ids.setdefault(
                    (anc.level, anc.address), len(ids)
                )
        state = {
            "n": n,
            "n_anc": n_anc,
            "n_ids": len(ids),
            "anc_buf": anc_buf,
            "order_buf": (ctypes.c_int32 * n)(*self._order),
            "healthy_buf": (ctypes.c_int32 * n)(),
            "suggested_buf": (ctypes.c_int32 * n)(),
            "same_buf": (ctypes.c_int32 * n)(),
            "higher_buf": (ctypes.c_int32 * n)(),
            "free_buf": (ctypes.c_int32 * n)(),
        }
        for i, node in enumerate(self.cv):
            state["healthy_buf"][i] = 1 if node.healthy else 0
            state["suggested_buf"][i] = 1 if node.suggested else 0
            state["same_buf"][i] = node.used_leaf_cell_num_same_priority
            state["higher_buf"][i] = node.used_leaf_cell_num_higher_priority
            state["free_buf"][i] = node.free_leaf_cell_num_at_priority
        self._native_pack = state
        return state

    def _update_cluster_view(
        self, p: CellPriority, suggested_nodes: Set[str], ignore_suggested_nodes: bool
    ) -> None:
        """Refresh only nodes whose cell mutated (``view_gen``) or whose
        probe priority changed; recheck suggested-node membership per call
        (it arrives from outside the cell trees) unless ignored. Any change
        marks the cached sort order dirty."""
        changed = False
        state = self._native_pack if self._native_pack else None
        for i, n in enumerate(self.cv):
            c = n.cell
            gen = c.view_gen
            if gen != n.seen_gen or p != n.seen_priority:
                n.update_used_leaf_cell_num_for_priority(p, self.cross_priority_pack)
                n.healthy, n.suggested, n.node_address = _node_healthy_and_in_suggested(
                    n, suggested_nodes, ignore_suggested_nodes
                )
                n.seen_gen = gen
                n.seen_priority = p
                changed = True
                if state is not None:
                    state["healthy_buf"][i] = 1 if n.healthy else 0
                    state["suggested_buf"][i] = 1 if n.suggested else 0
                    state["same_buf"][i] = n.used_leaf_cell_num_same_priority
                    state["higher_buf"][i] = n.used_leaf_cell_num_higher_priority
                    state["free_buf"][i] = n.free_leaf_cell_num_at_priority
            elif not ignore_suggested_nodes:
                healthy, suggested, addr = _node_healthy_and_in_suggested(
                    n, suggested_nodes, ignore_suggested_nodes
                )
                if suggested != n.suggested or healthy != n.healthy:
                    n.healthy, n.suggested, n.node_address = healthy, suggested, addr
                    changed = True
                    if state is not None:
                        state["healthy_buf"][i] = 1 if healthy else 0
                        state["suggested_buf"][i] = 1 if suggested else 0
        if changed:
            self._order_dirty = True
