"""Per-VC scheduler: dispatches to a topology-aware scheduler per chain or
per pinned cell.

TPU-native analogue of the reference's ``pkg/algorithm/intra_vc_scheduler.go``.
All intra-VC schedulers use ``cross_priority_pack=True`` (see rationale in
``algorithm/topology_aware.py``).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from hivedscheduler_tpu.algorithm.cell import CellChain, CellLevel
from hivedscheduler_tpu.algorithm.topology_aware import TopologyAwareScheduler
from hivedscheduler_tpu.algorithm.types import (
    ChainCellList,
    GroupVirtualPlacement,
    SchedulingRequest,
)

log = logging.getLogger(__name__)


SCHEDULING_POLICIES = ("pack", "spread")


class IntraVCScheduler:
    """Reference: defaultIntraVCScheduler, intra_vc_scheduler.go:45-117, plus
    the per-VC policy hook the reference leaves as a TODO
    (hived_algorithm.go:133): "pack" (default) or "spread"."""

    def __init__(
        self,
        non_pinned_full_list: Dict[CellChain, ChainCellList],
        non_pinned_free_list: Dict[CellChain, ChainCellList],
        pinned_list: Dict[str, ChainCellList],
        leaf_cell_nums: Dict[CellChain, Dict[CellLevel, int]],
        policy: str = "pack",
    ):
        if policy not in SCHEDULING_POLICIES:
            raise ValueError(
                f"unknown schedulingPolicy {policy!r}; supported: "
                f"{', '.join(SCHEDULING_POLICIES)}"
            )
        pack = policy == "pack"
        self.non_pinned_full_cell_list = non_pinned_full_list
        self.non_pinned_preassigned_cells = non_pinned_free_list
        self.pinned_cells = pinned_list
        # chains absent from the physical cluster have no leaf-cell-num table;
        # HivedAlgorithm._init_cell_nums rejects such configs right after
        self.non_pinned_cell_schedulers: Dict[CellChain, TopologyAwareScheduler] = {
            chain: TopologyAwareScheduler(
                ccl, leaf_cell_nums.get(chain, {}), cross_priority_pack=True,
                pack=pack,
            )
            for chain, ccl in non_pinned_full_list.items()
        }
        self.pinned_cell_schedulers: Dict[str, TopologyAwareScheduler] = {
            pid: TopologyAwareScheduler(
                ccl, leaf_cell_nums[ccl[1][0].chain], cross_priority_pack=True,
                pack=pack,
            )
            for pid, ccl in pinned_list.items()
        }

    def schedule(self, sr: SchedulingRequest) -> Tuple[Optional[GroupVirtualPlacement], str]:
        """Reference: intra_vc_scheduler.go:92-117."""
        if sr.pinned_cell_id:
            scheduler = self.pinned_cell_schedulers.get(sr.pinned_cell_id)
            where = f"pinned cell {sr.pinned_cell_id}"
        else:
            scheduler = self.non_pinned_cell_schedulers.get(sr.chain)
            where = f"chain {sr.chain}"
        log.info(
            "Processing scheduling request in VC %s: %s, leaf cell numbers %s, priority %s",
            sr.vc, where, sr.affinity_group_pod_nums, sr.priority,
        )
        placement: Optional[GroupVirtualPlacement] = None
        failed_reason = ""
        if scheduler is not None:
            placement, failed_reason = scheduler.schedule(
                sr.affinity_group_pod_nums,
                sr.priority,
                sr.suggested_nodes,
                sr.ignore_suggested_nodes,
            )
        if placement is None:
            return None, f"{failed_reason} when scheduling in VC {sr.vc}"
        log.info("Found placement in VC %s", sr.vc)
        return placement, ""
