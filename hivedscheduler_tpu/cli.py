"""tpu-hive scheduler entry point.

Analogue of the reference's ``cmd/hivedscheduler/main.go``: init, load config,
watch it (exit-on-change -> restart-based work-preserving reconfiguration),
run the scheduler runtime + webserver until signaled.

Run with a fake in-memory cluster (demo mode) via ``--fake-cluster``; a real
deployment plugs a REST KubeClient implementation against
``kubeApiServerAddress`` (insecure ApiServer or kubectl proxy).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

from hivedscheduler_tpu.api import config as api_config
from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.common import utils as common
from hivedscheduler_tpu.k8s.fake import FakeKubeClient
from hivedscheduler_tpu.k8s.types import Node
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
from hivedscheduler_tpu.webserver import WebServer

log = logging.getLogger(__name__)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-hive")
    parser.add_argument(
        "--config",
        default=os.environ.get(C.ENV_CONFIG_FILE, C.DEFAULT_CONFIG_FILE_PATH),
        help="scheduler config YAML path",
    )
    parser.add_argument(
        "--fake-cluster",
        action="store_true",
        help="serve against an in-memory cluster with all config nodes healthy "
        "(demo / development mode)",
    )
    parser.add_argument(
        "--explain",
        action="store_true",
        help="log a one-line explanation of every scheduling decision "
        "(chains probed, path, outcome); decisions are always served at "
        "GET /v1/inspect/traces",
    )
    parser.add_argument(
        "--trace-file",
        default="",
        help="write the Chrome-trace/Perfetto JSON of the run to this path "
        "on shutdown (also served live at GET /v1/inspect/traces/chrome)",
    )
    parser.add_argument(
        "--journal-file",
        default="",
        help="append the gang-lifecycle journal (obs/journal.py) to this "
        "JSONL spool — one causal event per line, flushed per append, so "
        "a kill -9 loses nothing; the journal itself is always on in the "
        "server and served at GET /v1/inspect/gangs",
    )
    parser.add_argument(
        "--capacity-dump",
        default="",
        help="write the capacity ledger's snapshot JSON (per-state "
        "chip-seconds, occupancy, conservation gap; obs/ledger.py) to "
        "this path on shutdown — the same payload served live at "
        "GET /v1/inspect/capacity",
    )
    parser.add_argument(
        "--drain-secs",
        type=float,
        default=2.0,
        help="graceful-termination window after SIGTERM/SIGINT: /readyz "
        "flips to 503 + Retry-After immediately (stop sending work) while "
        "in-flight extender requests finish for this many seconds, then "
        "the server stops; /healthz stays green throughout (0 = stop "
        "immediately)",
    )
    parser.add_argument(
        "--defrag-tick-secs",
        type=float,
        default=5.0,
        help="period of the defragmentation watch loop (scheduler."
        "defrag_tick: sweep expired reservations, advance in-flight "
        "migrations, plan for the longest-waiting gang; see "
        "doc/design/defrag.md). 0 disables the loop; HIVED_DEFRAG=0 "
        "makes every tick a no-op",
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    common.init_all(logging.DEBUG if args.verbose else logging.INFO)

    # observability: the server always records decision traces (bounded
    # ring; the /v1/inspect/traces endpoint must answer "why did this gang
    # land there?") and the shared span tracer (bounded ring, served at
    # /v1/inspect/traces/chrome). Library/bench users stay on the
    # zero-overhead disabled path — only this entry point opts in.
    from hivedscheduler_tpu.common import envflags
    from hivedscheduler_tpu.obs import decisions as obs_decisions
    from hivedscheduler_tpu.obs import journal as obs_journal
    from hivedscheduler_tpu.obs import ledger as obs_ledger
    from hivedscheduler_tpu.obs import trace as obs_trace

    obs_decisions.RECORDER.enable()
    obs_trace.enable()
    # the gang-lifecycle journal (bounded ring) backs /v1/inspect/gangs and
    # the wait-attribution histograms; --journal-file adds the crash-safe
    # JSONL spool for post-mortem replay
    obs_journal.enable(spool_path=args.journal_file or None)
    # the capacity ledger backs /v1/inspect/capacity + the wait-ETA
    # forecasts; HIVED_LEDGER=0 is the kill switch. Enabled BEFORE the
    # scheduler so the algorithm registers its leaf cells at construction.
    if envflags.get("HIVED_LEDGER") != "0":
        obs_ledger.enable()
    if args.explain:
        obs_decisions.RECORDER.on_commit = lambda d: log.info("%s", d.explain())
    config = api_config.load_config(args.config)
    api_config.watch_config(args.config, config)

    if args.fake_cluster:
        kube_client = FakeKubeClient()
    elif config.kube_api_server_address:
        from hivedscheduler_tpu.k8s.rest import RestKubeClient

        kube_client = RestKubeClient(config.kube_api_server_address)
        log.info("Using Kubernetes ApiServer at %s", config.kube_api_server_address)
    else:
        log.error(
            "No Kubernetes ApiServer configured: set kubeApiServerAddress in the "
            "config (insecure port or kubectl proxy), or run with --fake-cluster."
        )
        return 1

    scheduler = HivedScheduler(config, kube_client)
    if args.fake_cluster:
        # demo: all nodes in the config exist and are healthy
        algo = scheduler.scheduler_algorithm
        nodes = sorted(
            {
                n
                for ccl in algo.full_cell_list.values()
                for c in ccl[max(ccl)]
                for n in c.nodes
            }
        )
        for n in nodes:
            kube_client.create_node(Node(name=n))
    scheduler.start()
    server = WebServer(scheduler)
    host, port = server.async_run()
    log.info("tpu-hive ready on %s:%s", host, port)
    stop = common.new_stop_event()
    if args.defrag_tick_secs > 0:
        # the defrag watch loop rides the main thread's signal wait: each
        # tick sweeps expired reservations, advances in-flight migrations
        # and plans for the longest-waiting gang
        while not stop.wait(args.defrag_tick_secs):
            scheduler.defrag_tick()
    else:
        stop.wait()
    # graceful termination: readiness flips first (load balancer / probes
    # stop routing new work), in-flight requests get the drain window,
    # liveness stays green — then the listener closes
    if args.drain_secs > 0:
        import time

        server.begin_drain(retry_after_s=max(1, int(args.drain_secs)))
        time.sleep(args.drain_secs)
    server.stop()
    if args.trace_file:
        obs_trace.write_chrome_trace(args.trace_file)
        log.info("Chrome trace written to %s (open in https://ui.perfetto.dev)",
                 args.trace_file)
    if args.capacity_dump:
        import json

        with open(args.capacity_dump, "w") as f:
            json.dump(obs_ledger.LEDGER.snapshot(), f)
        log.info("Capacity ledger snapshot written to %s", args.capacity_dump)
    return 0


if __name__ == "__main__":
    sys.exit(main())
