"""Runtime helpers: pod predicates, annotation (de)serialization with
defaulting + validation, binding construction.

TPU-native analogue of the reference's ``pkg/internal/utils.go``. The
``gpuType``/``gpuNumber``/``gpuIsolation`` and ``chipType``/``chipNumber``
annotation keys are rewritten to the canonical leaf-cell keys for backward and
TPU-idiomatic compatibility (reference: convertOldAnnotation,
``internal/utils.go:189-197``).
"""

from __future__ import annotations

import json
from typing import Dict, List

from hivedscheduler_tpu.api import constants as api_constants
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.common import utils as common
from hivedscheduler_tpu.k8s.types import Node, Pod


def key(pod: Pod) -> str:
    # memoized on the pod: built several times per scheduling event (log
    # prefixes), and pods are effectively immutable once constructed.
    # Pod.deep_copy builds a fresh object, so copies re-derive it.
    k = pod.__dict__.get("_key_memo")
    if k is None:
        k = f"{pod.uid}({pod.namespace}/{pod.name})"
        pod._key_memo = k
    return k


def freeze_long_lived_state() -> None:
    """Move everything allocated so far (notably the physical/virtual cell
    trees — ~100k objects on a v5p-1024) into the GC's permanent generation.

    Called at the end of the recovery barrier: the cell trees live for the
    process lifetime (reconfiguration restarts the process), so letting every
    full collection re-traverse them only buys pause time — measured on the
    v5p-1024 bench, gen-2 pauses put gang-schedule p99 at ~34 ms vs ~8 ms
    frozen. Cyclic garbage created *after* the freeze is still collected
    normally.

    The unfreeze-first makes repeated calls safe for embedders (and tests)
    that build several schedulers in one process: graphs frozen by an earlier
    instance and dropped since are thawed and reclaimed by the collect below
    instead of leaking in the permanent generation forever.

    NOTE: ``gc.freeze()`` is process-global — it exempts *everything* alive
    right now from cycle collection, not just the cell trees. An embedder
    holding large cyclic graphs it intends to drop later should set
    ``HIVED_GC_FREEZE=0`` to opt out (the scheduler then just pays the gen-2
    pauses)."""
    import gc
    import os

    if os.environ.get("HIVED_GC_FREEZE", "1") == "0":
        return
    gc.unfreeze()
    gc.collect()
    gc.freeze()


def is_completed(pod: Pod) -> bool:
    return pod.phase in ("Succeeded", "Failed")


def is_live(pod: Pod) -> bool:
    return not is_completed(pod)


def is_hived_enabled(pod: Pod) -> bool:
    """A pod opts in via the pod-scheduling-enable resource limit on any
    container (reference: internal/utils.go:116-139)."""
    for container in pod.containers:
        quantity = container.resource_limits.get(
            api_constants.RESOURCE_NAME_POD_SCHEDULING_ENABLE
        )
        if quantity is not None and float(quantity) > 0:
            return True
    return False


def is_interested(pod: Pod) -> bool:
    return is_live(pod) and is_hived_enabled(pod)


def is_bound(pod: Pod) -> bool:
    return pod.node_name != "" and is_live(pod)


def is_unbound(pod: Pod) -> bool:
    return pod.node_name == "" and is_live(pod)


def is_node_healthy(node: Node) -> bool:
    """Schedulable + Ready (reference: internal/utils.go:160-170)."""
    if node.unschedulable:
        return False
    return any(c.type == "Ready" and c.status == "True" for c in node.conditions)


def to_indices_string(indices: List[int]) -> str:
    """The TPU_VISIBLE_CHIPS-style comma-joined chip index list."""
    return ",".join(str(i) for i in indices)


def encode_group_fragment(members) -> str:
    """Encode the gang-placement fragment (identical for every pod of a gang;
    the scheduler caches it per placement version)."""
    return common.to_json([m.to_dict() for m in members])


def _encode_bind_info(pod_bind_info: api.PodBindInfo) -> str:
    """Serialize a bind info, reusing the pre-encoded gang fragment when the
    scheduler attached one (``_encoded_group``, keyed to the group's
    placement version). Field names come from to_dict — one source of
    truth; the hand-rolled head below is pinned byte-identical to the
    to_dict + to_json composition by
    tests/test_e2e.py::test_encode_bind_info_head_matches_to_dict."""
    frag = getattr(pod_bind_info, "_encoded_group", None)
    if frag is None:
        frag = encode_group_fragment(pod_bind_info.affinity_group_bind_info)
    node, iso, chain = (pod_bind_info.node, pod_bind_info.leaf_cell_isolation,
                        pod_bind_info.cell_chain)
    if type(node) is str and type(chain) is str and all(
        type(i) is int for i in iso
    ):
        # per-pod hot path: skip the dict build + json.dumps machinery
        head = '{"node":%s,"leafCellIsolation":[%s],"cellChain":%s}' % (
            json.dumps(node), ",".join(map(str, iso)), json.dumps(chain)
        )
    else:  # pragma: no cover - defensive (fields are typed by extract)
        head = common.to_json(pod_bind_info.to_dict(include_group=False))
    return head[:-1] + ',"affinityGroupBindInfo":' + frag + "}"


def new_binding_pod(pod: Pod, pod_bind_info: api.PodBindInfo) -> Pod:
    """Stamp node + chip-isolation + bind-info annotations onto a copy of the
    pod (reference: NewBindingPod, internal/utils.go:172-186)."""
    binding_pod = pod.deep_copy()
    binding_pod.node_name = pod_bind_info.node
    binding_pod.annotations[api_constants.ANNOTATION_POD_CHIP_ISOLATION] = to_indices_string(
        pod_bind_info.leaf_cell_isolation
    )
    # JSON is valid YAML: machine-written bind info uses the fast codec
    encoded = _encode_bind_info(pod_bind_info)
    binding_pod.annotations[api_constants.ANNOTATION_POD_BIND_INFO] = encoded
    # In-process handoff: stash the bind-info object the annotation was just
    # serialized FROM, so extract_pod_bind_info skips hashing/parsing the
    # (gang-sized) annotation string when the very same string is still in
    # place — verified by object identity, so any replaced annotation falls
    # back to the parse path. Pods arriving over the API server have no
    # stash and behave as before.
    frag = getattr(pod_bind_info, "_encoded_group", None)
    if frag is not None:
        pod_bind_info._frag = frag
        binding_pod._bind_info_stash = (encoded, pod_bind_info)
    return binding_pod


_OLD_KEY_REWRITES = [
    ("gpuType", "leafCellType"),
    ("gpuNumber", "leafCellNumber"),
    ("gpuIsolation", "leafCellIsolation"),
    ("physicalGpuIndices", "physicalLeafCellIndices"),
    ("chipType", "leafCellType"),
    ("chipNumber", "leafCellNumber"),
    ("chipIsolation", "leafCellIsolation"),
    ("physicalChipIndices", "physicalLeafCellIndices"),
]


def convert_old_annotation(annotation: str) -> str:
    for old, new in _OLD_KEY_REWRITES:
        annotation = annotation.replace(old, new)
    return annotation


# Annotation extraction memo: the same annotation string is re-parsed on
# every scheduler event for a pod (and bind infos repeat the whole gang's
# placement), so caching by the exact string is a large win. Entries are
# treated as immutable by all callers.
_MEMO_CAP = 4096
_bind_info_memo: Dict[str, api.PodBindInfo] = {}
_sched_spec_memo: Dict[tuple, api.PodSchedulingSpec] = {}


def _memo_put(memo: dict, key, value):
    if len(memo) >= _MEMO_CAP:
        memo.clear()
    memo[key] = value
    return value


_GROUP_SPLICE_MARKER = ',"affinityGroupBindInfo":'
_group_frag_memo: Dict[str, list] = {}


def extract_pod_bind_info(allocated_pod: Pod) -> api.PodBindInfo:
    """Bind info comes from us, so deserialization just asserts (reference:
    internal/utils.go:200-214).

    Fast path: annotations written by ``_encode_bind_info`` splice one shared
    gang fragment after ``_GROUP_SPLICE_MARKER``, byte-identical across all
    pods of the gang — so the O(gang)-sized member list is parsed once per
    gang instead of once per pod (the naive path is O(gang^2) dataclass
    construction for a gang replay). Anything not in that exact machine
    format (legacy keys, human YAML) falls back to the full parse."""
    raw = allocated_pod.annotations.get(api_constants.ANNOTATION_POD_BIND_INFO, "")
    stash = getattr(allocated_pod, "_bind_info_stash", None)
    if stash is not None and stash[0] is raw:
        info = stash[1]
        # seed the gang-fragment memo so pods of the same gang arriving
        # WITHOUT a stash (e.g. replayed through the API server) still hit
        # the shared-fragment fast path; the fragment string object is
        # shared gang-wide, so its hash is computed once per gang. Safe to
        # skip the legacy-key scan: the fragment came from our own
        # serializer (canonical to_dict field names).
        frag = getattr(info, "_frag", None)
        if frag is not None and frag not in _group_frag_memo:
            _memo_put(_group_frag_memo, frag, info.affinity_group_bind_info)
        return info
    cached = _bind_info_memo.get(raw)
    if cached is not None:
        return cached
    if not raw:
        raise AssertionError(
            f"Pod does not contain or contains empty annotation: "
            f"{api_constants.ANNOTATION_POD_BIND_INFO}"
        )
    if raw.startswith("{") and raw.endswith("}"):
        head, marker, frag_tail = raw.partition(_GROUP_SPLICE_MARKER)
        if marker and _GROUP_SPLICE_MARKER not in frag_tail:
            frag = frag_tail[:-1]
            group = _group_frag_memo.get(frag)
            # legacy-key scan for machine-format detection: a memoized
            # fragment already passed it on first sight, so per-pod cost
            # drops from O(gang fragment) to O(head)
            scan = head if group is not None else raw
            if not any(old in scan for old, _ in _OLD_KEY_REWRITES):
                try:
                    head_d = json.loads(head + "}")
                    if group is None:
                        group = _memo_put(
                            _group_frag_memo,
                            frag,
                            [
                                api.AffinityGroupMemberBindInfo.from_dict(m)
                                for m in json.loads(frag)
                            ],
                        )
                    info = api.PodBindInfo(
                        node=head_d.get("node", ""),
                        leaf_cell_isolation=[
                            int(i) for i in head_d.get("leafCellIsolation", [])
                        ],
                        cell_chain=head_d.get("cellChain", ""),
                        affinity_group_bind_info=group,
                    )
                    # the raw gang fragment, for the algorithm's
                    # live-placement handoff (add_allocated_pod)
                    info._frag = frag
                    return _memo_put(_bind_info_memo, raw, info)
                except (ValueError, KeyError, TypeError):
                    pass  # not our machine format after all
    annotation = convert_old_annotation(raw)
    return _memo_put(
        _bind_info_memo, raw, api.PodBindInfo.from_dict(common.from_yaml(annotation))
    )


def extract_pod_bind_annotations(allocated_pod: Pod) -> Dict[str, str]:
    return {
        api_constants.ANNOTATION_POD_CHIP_ISOLATION: allocated_pod.annotations.get(
            api_constants.ANNOTATION_POD_CHIP_ISOLATION, ""
        ),
        api_constants.ANNOTATION_POD_BIND_INFO: allocated_pod.annotations.get(
            api_constants.ANNOTATION_POD_BIND_INFO, ""
        ),
    }


def extract_pod_scheduling_spec(pod: Pod) -> api.PodSchedulingSpec:
    """User-facing spec: parse + default + validate; all errors are
    bad-request (HTTP 400) class (reference: ExtractPodSchedulingSpec,
    internal/utils.go:230-289)."""
    err_pfx = f"Pod annotation {api_constants.ANNOTATION_POD_SCHEDULING_SPEC}: "
    raw = pod.annotations.get(api_constants.ANNOTATION_POD_SCHEDULING_SPEC, "")
    # Specs with an explicit affinity group parse pod-independently, so they
    # memo by the raw string alone — the pods of a gang share one annotation.
    # Only the defaulted group name depends on the pod (ns/name), so those
    # specs memo per pod key.
    cached = _sched_spec_memo.get(raw)
    if cached is not None:
        return cached
    memo_key = (raw, pod.namespace, pod.name)
    cached = _sched_spec_memo.get(memo_key)
    if cached is not None:
        return cached
    annotation = convert_old_annotation(raw)
    if not annotation:
        raise api.as_bad_request(err_pfx + "Annotation does not exist or is empty")
    try:
        parsed = common.from_yaml(annotation)
        spec = api.PodSchedulingSpec.from_dict(parsed or {})
    except api.WebServerError:
        raise
    except Exception as e:
        raise api.as_bad_request(err_pfx + f"Failed to parse: {e}")

    # Defaulting: a pod with no affinity group is its own gang of one.
    pod_independent = spec.affinity_group is not None
    if spec.affinity_group is None:
        spec.affinity_group = api.AffinityGroupSpec(
            name=f"{pod.namespace}/{pod.name}",
            members=[
                api.AffinityGroupMemberSpec(
                    pod_number=1, leaf_cell_number=spec.leaf_cell_number
                )
            ],
        )

    # Validation
    if not spec.virtual_cluster:
        raise api.as_bad_request(err_pfx + "VirtualCluster is empty")
    if spec.priority < api_constants.OPPORTUNISTIC_PRIORITY:
        raise api.as_bad_request(
            err_pfx + f"Priority is less than {api_constants.OPPORTUNISTIC_PRIORITY}"
        )
    if spec.priority > api_constants.MAX_GUARANTEED_PRIORITY:
        raise api.as_bad_request(
            err_pfx + f"Priority is greater than {api_constants.MAX_GUARANTEED_PRIORITY}"
        )
    if spec.leaf_cell_number <= 0:
        raise api.as_bad_request(err_pfx + "LeafCellNumber is non-positive")
    if spec.multi_chain_relax_policy not in ("fewest", "balanced"):
        raise api.as_bad_request(
            err_pfx + "MultiChainRelaxPolicy must be fewest or balanced"
        )
    if not spec.affinity_group.name:
        raise api.as_bad_request(err_pfx + "AffinityGroup.Name is empty")
    is_pod_in_group = False
    for member in spec.affinity_group.members:
        if member.pod_number <= 0:
            raise api.as_bad_request(err_pfx + "AffinityGroup.Members has non-positive PodNumber")
        if member.leaf_cell_number <= 0:
            raise api.as_bad_request(
                err_pfx + "AffinityGroup.Members has non-positive LeafCellNumber"
            )
        if member.leaf_cell_number == spec.leaf_cell_number:
            is_pod_in_group = True
    if not is_pod_in_group:
        raise api.as_bad_request(err_pfx + "AffinityGroup.Members does not contain current Pod")
    if spec.duration_seconds < 0:
        raise api.as_bad_request(err_pfx + "durationSeconds is negative")
    if spec.elastic_min_chips < 0:
        raise api.as_bad_request(err_pfx + "elasticMinChips is negative")
    total_chips = sum(
        m.pod_number * m.leaf_cell_number for m in spec.affinity_group.members
    )
    if spec.elastic_min_chips > total_chips:
        raise api.as_bad_request(
            err_pfx + f"elasticMinChips exceeds the gang's total leaf cells "
            f"({total_chips})"
        )
    return _memo_put(_sched_spec_memo, raw if pod_independent else memo_key, spec)
