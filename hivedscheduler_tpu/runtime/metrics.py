"""Prometheus-format scheduler metrics.

The reference exposes no metrics (klog only, SURVEY.md §5); tpu-hive adds a
minimal dependency-free registry rendered in the Prometheus text exposition
format at ``GET /metrics``: request counters and latency histograms per
extender routine, bind/preemption/wait outcome counters, and a bad-node
gauge.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from hivedscheduler_tpu.common import lockcheck

_LATENCY_BUCKETS = [0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0]


class Registry:
    def __init__(self):
        self._lock = lockcheck.make_lock("metrics_lock", late=True)
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        # gauges key like counters: (name, sorted label items) — plain
        # set_gauge(name, v) is the ()-labels series
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        # (name, labels) -> (bucket counts, sum, count)
        self._histograms: Dict[
            Tuple[str, Tuple[Tuple[str, str], ...]],
            Tuple[List[int], float, int],
        ] = {}
        self._help: Dict[str, str] = {}

    def describe(self, name: str, help_text: str) -> None:
        self._help[name] = help_text

    def inc(self, name: str, amount: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + amount

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    def get_gauge(self, name: str, **labels: str):
        """Last value set for a gauge, or None if never set — the
        scheduler-side admission hints read serving-published gauges
        through this (runtime/scheduler.py get_admission_hints)."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key)

    def observe(self, name: str, seconds: float, **labels: str) -> None:
        """Record one histogram sample. ``labels`` mirror ``inc`` (e.g. the
        serving histograms split by priority class); each label set keeps
        its own buckets/sum/count, rendered as separate series."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            buckets, total, count = self._histograms.get(
                key, ([0] * (len(_LATENCY_BUCKETS) + 1), 0.0, 0)
            )
            buckets = list(buckets)
            for i, bound in enumerate(_LATENCY_BUCKETS):
                if seconds <= bound:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            self._histograms[key] = (buckets, total + seconds, count + 1)

    @staticmethod
    def _fmt(value: float) -> str:
        """Full-precision sample rendering: %g quantizes above ~1e6, which
        would flatline rate() on long-lived counters."""
        if float(value).is_integer():
            return str(int(value))
        return repr(float(value))

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: List[str] = []
        with self._lock:
            names = sorted({n for n, _ in self._counters})
            for name in names:
                if name in self._help:
                    out.append(f"# HELP {name} {self._help[name]}")
                out.append(f"# TYPE {name} counter")
                for (n, labels), value in sorted(self._counters.items()):
                    if n != name:
                        continue
                    label_str = (
                        "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"
                        if labels
                        else ""
                    )
                    out.append(f"{name}{label_str} {self._fmt(value)}")
            gauge_names = sorted({n for n, _ in self._gauges})
            for name in gauge_names:
                if name in self._help:
                    out.append(f"# HELP {name} {self._help[name]}")
                out.append(f"# TYPE {name} gauge")
                for (n, labels), value in sorted(self._gauges.items()):
                    if n != name:
                        continue
                    label_str = (
                        "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"
                        if labels
                        else ""
                    )
                    out.append(f"{name}{label_str} {self._fmt(value)}")
            hist_names = sorted({n for n, _ in self._histograms})
            for name in hist_names:
                if name in self._help:
                    out.append(f"# HELP {name} {self._help[name]}")
                out.append(f"# TYPE {name} histogram")
                for (n, labels), (buckets, total, count) in sorted(
                    self._histograms.items()
                ):
                    if n != name:
                        continue
                    base = ",".join(f'{k}="{v}"' for k, v in labels)
                    sep = "," if base else ""
                    series = "{" + base + "}" if base else ""
                    cumulative = 0
                    for i, bound in enumerate(_LATENCY_BUCKETS):
                        cumulative += buckets[i]
                        out.append(
                            f'{name}_bucket{{{base}{sep}le="{bound}"}} {cumulative}'
                        )
                    cumulative += buckets[-1]
                    out.append(
                        f'{name}_bucket{{{base}{sep}le="+Inf"}} {cumulative}'
                    )
                    out.append(f"{name}_sum{series} {self._fmt(total)}")
                    out.append(f"{name}_count{series} {count}")
        return "\n".join(out) + "\n"


REGISTRY = Registry()
REGISTRY.describe("tpu_hive_http_requests_total",
                  "All HTTP responses by method and status code")
REGISTRY.describe("tpu_hive_extender_requests_total",
                  "Extender requests by routine and outcome")
REGISTRY.describe("tpu_hive_binds_total", "Bind subresource commits")
REGISTRY.describe("tpu_hive_bind_retries_total",
                  "Idempotent bind re-deliveries after transient failures")
REGISTRY.describe("tpu_hive_k8s_retries_total",
                  "K8s REST request retries by operation and reason")
REGISTRY.describe("tpu_hive_force_binds_total", "Force-bind escalations")
REGISTRY.describe("tpu_hive_bad_nodes", "Nodes currently considered bad")
REGISTRY.describe("tpu_hive_event_batches_total",
                  "Batched watch-event deltas applied (HIVED_EVENT_BATCH=1: "
                  "one scheduler-lock acquisition per batch)")
REGISTRY.describe("tpu_hive_events_applied_total",
                  "Watch events applied through batched deltas, after "
                  "coalescing (add-delete dedup, node-flap folds)")
REGISTRY.describe("tpu_hive_filter_latency_seconds", "filterRoutine latency")
REGISTRY.describe("tpu_hive_preempt_latency_seconds", "preemptRoutine latency")
# serving-engine request lifecycle (models/serving.py), split by priority
# class via observe() labels
REGISTRY.describe("tpu_hive_serve_queue_wait_seconds",
                  "Serving request wait from submit to slot admission")
REGISTRY.describe("tpu_hive_serve_ttft_seconds",
                  "Serving time-to-first-token (queue wait + prefill)")
REGISTRY.describe("tpu_hive_serve_tpot_seconds",
                  "Serving time-per-output-token after the first token")
REGISTRY.describe("tpu_hive_serve_requests_total",
                  "Serving requests completed by priority class")
REGISTRY.describe("tpu_hive_serve_shed_total",
                  "Serving requests shed on queue-wait deadline by priority "
                  "class")
REGISTRY.describe("tpu_hive_serve_drain_rejected_total",
                  "Serving requests rejected at submit because the engine "
                  "is draining (preemption; the 503 + Retry-After path)")
REGISTRY.describe("tpu_hive_serve_fused_decode_windows_total",
                  "Multi-step fused decode windows executed (ServingEngine "
                  "decode_steps > 1: K tokens per host round-trip)")
# paged KV cache (ServingEngine page_size > 0): block-pool allocator and
# block-granular prefix sharing
REGISTRY.describe("tpu_hive_serve_block_pool_occupancy",
                  "Fraction of allocatable KV blocks currently referenced "
                  "(paged serving; 1.0 = pool pressure, admission gates)")
REGISTRY.describe("tpu_hive_serve_prefix_block_hits_total",
                  "KV blocks reused by reference from the block-granular "
                  "prefix cache at admission (each is a whole block of "
                  "prompt prefill skipped AND not re-stored)")
REGISTRY.describe("tpu_hive_serve_block_cow_total",
                  "Copy-on-write block copies (a stream wrote into a "
                  "block still shared with the prefix cache or another "
                  "stream)")
REGISTRY.describe("tpu_hive_serve_pool_preempted_total",
                  "Streams truncated (finish_reason=preempted) to relieve "
                  "KV block-pool exhaustion after cache reclaim ran dry")
REGISTRY.describe("tpu_hive_serve_spec_acceptance_ratio",
                  "Per-verify-round speculative acceptance fraction "
                  "(accepted draft tokens / gamma) as a histogram")
# serving fleet tier (fleet/router.py + fleet/autoscaler.py): the
# cross-replica router and the scheduler-driven autoscaler
REGISTRY.describe("tpu_hive_fleet_requests_total",
                  "Fleet requests finished by outcome (eos/length/shed/"
                  "preempted/no_replica — shed/preempted here means "
                  "retries were exhausted)")
REGISTRY.describe("tpu_hive_fleet_retries_total",
                  "Shed/preempted/lost legs re-routed to another replica "
                  "by leg (prefill/decode)")
REGISTRY.describe("tpu_hive_fleet_handoffs_total",
                  "Disaggregated prefill->decode handoffs by mode (ship = "
                  "KV crossed host-side, miss = no exportable prefix, "
                  "reprefill = HIVED_FLEET_KV_SHIP=0 path)")
REGISTRY.describe("tpu_hive_fleet_prefix_affinity_hits_total",
                  "Requests routed by a content-hash prefix-index hit "
                  "(the caching replica serves the prompt's leading "
                  "blocks from its prefix cache)")
REGISTRY.describe("tpu_hive_fleet_replicas",
                  "Live fleet replicas (active + draining)")
REGISTRY.describe("tpu_hive_fleet_target_replicas",
                  "Fleet autoscaler target replica count (sum over roles)")
REGISTRY.describe("tpu_hive_fleet_scale_events_total",
                  "Autoscaler scale actions by direction (up = replica "
                  "added, down = drain-based removal started)")
# workload supervisor (parallel/supervisor.py + the train CLI): the
# preemption-tolerance surface of the training loop
REGISTRY.describe("tpu_hive_train_resumes_total",
                  "Training incarnations that resumed from a committed "
                  "checkpoint (preemption/crash restarts)")
REGISTRY.describe("tpu_hive_train_rollbacks_total",
                  "Divergence-guard rollbacks to the last good checkpoint "
                  "(non-finite or spiking loss)")
REGISTRY.describe("tpu_hive_watchdog_stalls_total",
                  "Watchdog step-deadline expiries (hung step; the process "
                  "exits nonzero so the gang restarts)")
# defragmentation / backfill (defrag/ + runtime/scheduler.py executor)
REGISTRY.describe("tpu_hive_defrag_migrations_total",
                  "Work-preserving migrations by outcome (planned, "
                  "completed, failed, aborted, expired)")
REGISTRY.describe("tpu_hive_defrag_moved_chips_total",
                  "Chips relocated by completed migration moves")
REGISTRY.describe("tpu_hive_defrag_planner_rejections_total",
                  "Migration planning attempts that produced no plan, by "
                  "reason (capacity, no-candidates, infeasible, "
                  "not-worth-it, evict-unsupported)")
REGISTRY.describe("tpu_hive_defrag_reservations",
                  "Live defrag reservations (cells held for a waiter or a "
                  "mid-migration re-placement)")
REGISTRY.describe("tpu_hive_backfill_admissions_total",
                  "Gang scheduling decisions that crossed a reservation, "
                  "by outcome (admitted = preemptible rider allowed into "
                  "reserved nodes, fits-window = guaranteed rider whose "
                  "declared duration ends before every intersecting hold "
                  "expires, blocked = reserved nodes withheld)")
# elastic offers (doc/design/elastic.md): shrink a blocked elastic waiter
# onto a degraded slice, grow it back when capacity frees
REGISTRY.describe("tpu_hive_elastic_offers_total",
                  "Elastic shrink offers by outcome (offered = degraded "
                  "incarnation bound, infeasible = no ladder shape fits, "
                  "failed = degraded bind lost a race with state drift)")
REGISTRY.describe("tpu_hive_elastic_grows_total",
                  "Grow-promotions of degraded elastic gangs by outcome "
                  "(planned, completed, infeasible)")
REGISTRY.describe("tpu_hive_elastic_degraded_gangs",
                  "Elastic gangs currently running on a degraded slice "
                  "(shrink-offered, not yet grown back)")
# gang-lifecycle flight recorder (obs/journal.py + runtime/scheduler.py):
# wait attribution and phase timers derived from the causal event journal
REGISTRY.describe("tpu_hive_gang_wait_seconds",
                  "Closed gang wait intervals by attribution bucket "
                  "(reason label: vc_quota, fragmentation, capacity, "
                  "bad_hardware, reservation_hold, priority, "
                  "elastic_degraded, unknown — obs/journal.py "
                  "WAIT_BUCKETS)")
REGISTRY.describe("tpu_hive_migration_phase_seconds",
                  "Work-preserving migration phase durations (phase: "
                  "evict = plan to movers released, rebind = re-placement "
                  "to done, total = plan to terminal)")
REGISTRY.describe("tpu_hive_sched_loop_phase_seconds",
                  "Scheduler-loop phase durations per cycle (phase: "
                  "schedule = one filter routine, migrations = advancing "
                  "in-flight migrations, plan = defrag planning + elastic "
                  "shrink offers for waiters, elastic = grow-promotion "
                  "scan)")
# request flight recorder + SLO layer (obs/journal.py + obs/slo.py):
# per-request TTFT leg decomposition and declared-objective accounting
REGISTRY.describe("tpu_hive_request_leg_seconds",
                  "Closed request-flight legs by leg name (leg label: "
                  "route, router_queue, retry, admission_wait, prefill, "
                  "handoff_ship, handoff_import, first_decode — "
                  "obs/journal.py REQUEST_LEGS; TTFT legs sum to the "
                  "measured ttft_s)")
REGISTRY.describe("tpu_hive_slo_violations_total",
                  "Observations exceeding a declared SLO ceiling, by "
                  "objective and the request's dominant leg "
                  "(leg=unattributed when the flight recorder is off)")
REGISTRY.describe("tpu_hive_slo_ttft_p99_seconds",
                  "Windowed p99 TTFT over the SLO tracker's window — the "
                  "same number the autoscaler reads as up-pressure and "
                  "/v1/inspect/slo serves")
REGISTRY.describe("tpu_hive_slo_burn_rate",
                  "Worst error-budget burn rate across declared "
                  "objectives (violating fraction / (1 - quantile) over "
                  "the window; 1.0 = burning exactly at budget)")
REGISTRY.describe("tpu_hive_train_cross_topology_resumes_total",
                  "Training incarnations that restored a checkpoint saved "
                  "on a DIFFERENT (dp, fsdp, pp, ep, tp, sp) mesh "
                  "(reshard-on-load; loss allclose, not bit-exact)")
# capacity ledger (obs/ledger.py): live chip-second attribution — at any
# instant every registered chip is in exactly one CHIP_STATES state, and
# the per-state chip-seconds sum to chips x wallclock (check_ledger)
REGISTRY.describe("tpu_hive_chip_seconds_total",
                  "Closed chip-state intervals by state and VC (state "
                  "label: obs/ledger.py CHIP_STATES — busy_guaranteed, "
                  "busy_opportunistic, busy_backfill, migration_downtime, "
                  "idle_free, idle_quota_stranded, idle_fragmented, "
                  "idle_reserved, bad_hardware; the buckets sum to "
                  "chips x wallclock, the conservation invariant)")
REGISTRY.describe("tpu_hive_chip_state_chips",
                  "Chips currently in each ledger state (occupancy "
                  "gauge; sums to the registered chip count)")
# workload goodput ledger (obs/goodput.py): step-phase badput
# attribution — the process is in exactly one STEP_PHASES phase and the
# per-phase seconds sum to the process wallclock (check_goodput)
REGISTRY.describe("tpu_hive_goodput_seconds_total",
                  "Closed workload step-phase intervals by phase (phase "
                  "label: obs/goodput.py STEP_PHASES — init, compile, "
                  "step_compute, data_wait, checkpoint_save, "
                  "checkpoint_restore, rework, eval, drain, idle; the "
                  "phases sum to the process wallclock, the conservation "
                  "invariant; step_compute alone is goodput)")
