"""Runtime contracts: SchedulerAlgorithm interface, scheduling phases, pod
schedule results, pod states.

TPU-native analogue of the reference's ``pkg/internal/types.go``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from hivedscheduler_tpu.api.types import PodBindInfo
from hivedscheduler_tpu.k8s.types import Node, Pod

# --- scheduling phases (reference: internal/types.go:102-114) ---------------
FILTERING_PHASE = "Filtering"
PREEMPTING_PHASE = "Preempting"

# --- pod states (reference: internal/types.go:154-198) ----------------------
# The Pod is unknown to the scheduler: it may not exist or its state has not
# been recovered yet.
POD_UNKNOWN = "Unknown"
# Waiting for free resources.
POD_WAITING = "Waiting"
# Waiting for preemption to complete.
POD_PREEMPTING = "Preempting"
# The scheduler has decided the placement and is delivering the bind.
POD_BINDING = "Binding"
# The bind has been committed to the ApiServer.
POD_BOUND = "Bound"


def is_allocated(state: str) -> bool:
    """Binding|Bound hold resources (reference: internal/types.go:190-198)."""
    return state in (POD_BINDING, POD_BOUND)


@dataclass
class PodWaitInfo:
    reason: str = ""


@dataclass
class PodPreemptInfo:
    victim_pods: List[Pod] = field(default_factory=list)


@dataclass
class PodScheduleResult:
    """Exactly one of the three is set: wait | preempt | bind (reference:
    internal/types.go:116-136)."""

    pod_wait_info: Optional[PodWaitInfo] = None
    pod_preempt_info: Optional[PodPreemptInfo] = None
    pod_bind_info: Optional[PodBindInfo] = None


@dataclass
class PodScheduleStatus:
    """In-flight pod record (reference: internal/types.go:138-152)."""

    pod: Optional[Pod] = None
    pod_state: str = POD_UNKNOWN
    pod_schedule_result: Optional[PodScheduleResult] = None
    # number of bind attempts; beyond ForcePodBindThreshold we force-bind
    pod_bind_attempts: int = 0


class SchedulerAlgorithm:
    """Interface + concurrency contract (reference: internal/types.go:57-100):
    the caller serializes all mutating calls (one global scheduler lock);
    implementations need not be thread-safe beyond their own inspect reads."""

    def add_node(self, node: Node) -> None:
        raise NotImplementedError

    def update_node(self, old_node: Node, new_node: Node) -> None:
        raise NotImplementedError

    def delete_node(self, node: Node) -> None:
        raise NotImplementedError

    def add_unallocated_pod(self, pod: Pod) -> None:
        raise NotImplementedError

    def delete_unallocated_pod(self, pod: Pod) -> None:
        raise NotImplementedError

    def add_allocated_pod(self, pod: Pod) -> None:
        raise NotImplementedError

    def delete_allocated_pod(self, pod: Pod) -> None:
        raise NotImplementedError

    def schedule(self, pod: Pod, suggested_nodes: List[str], phase: str) -> PodScheduleResult:
        raise NotImplementedError

    # inspect getters
    def get_all_affinity_groups(self):
        raise NotImplementedError

    def get_affinity_group(self, name: str):
        raise NotImplementedError

    def get_cluster_status(self):
        raise NotImplementedError

    def get_physical_cluster_status(self):
        raise NotImplementedError

    def get_all_virtual_clusters_status(self):
        raise NotImplementedError

    def get_virtual_cluster_status(self, vcn: str):
        raise NotImplementedError
