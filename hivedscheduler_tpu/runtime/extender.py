"""K8s scheduler-extender wire types (v1 extender protocol JSON).

Field names match k8s.io/kubernetes scheduler api ExtenderArgs /
ExtenderFilterResult / ExtenderBindingArgs / ExtenderPreemptionArgs so a stock
kube-scheduler extender policy (reference: example/run/deploy.yaml:25-47)
works against this server unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from hivedscheduler_tpu.k8s import serde
from hivedscheduler_tpu.k8s.types import Pod


@dataclass
class ExtenderArgs:
    pod: Pod
    node_names: List[str] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderArgs":
        if not d.get("Pod"):
            raise ValueError("ExtenderArgs.Pod is missing")
        return ExtenderArgs(
            pod=serde.pod_from_k8s(d["Pod"]),
            node_names=list(d.get("NodeNames") or []),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"Pod": serde.pod_to_k8s(self.pod), "NodeNames": self.node_names}


@dataclass
class ExtenderFilterResult:
    node_names: Optional[List[str]] = None
    failed_nodes: Dict[str, str] = field(default_factory=dict)
    error: str = ""

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.node_names is not None:
            out["NodeNames"] = self.node_names
        if self.failed_nodes:
            out["FailedNodes"] = self.failed_nodes
        if self.error:
            out["Error"] = self.error
        return out


@dataclass
class ExtenderBindingArgs:
    pod_name: str
    pod_namespace: str
    pod_uid: str
    node: str

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderBindingArgs":
        for f in ("PodName", "PodNamespace", "PodUID", "Node"):
            if not d.get(f):
                raise ValueError(f"ExtenderBindingArgs.{f} is missing")
        return ExtenderBindingArgs(
            pod_name=d["PodName"],
            pod_namespace=d["PodNamespace"],
            pod_uid=d["PodUID"],
            node=d["Node"],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "PodName": self.pod_name,
            "PodNamespace": self.pod_namespace,
            "PodUID": self.pod_uid,
            "Node": self.node,
        }


@dataclass
class ExtenderBindingResult:
    error: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"Error": self.error} if self.error else {}


@dataclass
class ExtenderPreemptionArgs:
    pod: Pod
    node_name_to_meta_victims: Dict[str, List[str]] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderPreemptionArgs":
        if not d.get("Pod"):
            raise ValueError("ExtenderPreemptionArgs.Pod is missing")
        victims: Dict[str, List[str]] = {}
        for node, mv in (d.get("NodeNameToMetaVictims") or {}).items():
            victims[node] = [p.get("UID", "") for p in (mv or {}).get("Pods") or []]
        # non-nodeCacheCapable fallback: Pods are full v1.Pod objects
        for node, mv in (d.get("NodeNameToVictims") or {}).items():
            victims.setdefault(node, []).extend(
                ((p.get("metadata") or {}).get("uid", ""))
                for p in (mv or {}).get("Pods") or []
            )
        return ExtenderPreemptionArgs(
            pod=serde.pod_from_k8s(d["Pod"]),
            node_name_to_meta_victims=victims,
        )


@dataclass
class ExtenderPreemptionResult:
    node_name_to_meta_victims: Dict[str, List[str]] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        if not self.node_name_to_meta_victims:
            return {}
        return {
            "NodeNameToMetaVictims": {
                node: {"Pods": [{"UID": uid} for uid in uids]}
                for node, uids in self.node_name_to_meta_victims.items()
            }
        }
