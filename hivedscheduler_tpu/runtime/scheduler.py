"""HivedScheduler runtime: the bridge between K8s and the algorithm.

TPU-native analogue of the reference's ``pkg/scheduler/scheduler.go``: informer
event handlers, the pod state machine ground truth (``pod_schedule_statuses``),
filter/bind/preempt routines behind one global scheduler lock, force-bind
escalation, and the recovery barrier (all bound pods replayed via
``add_allocated_pod`` before the webserver starts).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from hivedscheduler_tpu.api.constants import COMPONENT_NAME as _COMPONENT
from hivedscheduler_tpu.obs import trace
from hivedscheduler_tpu.runtime.metrics import REGISTRY as metrics

from hivedscheduler_tpu.api import config as api_config
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.algorithm.hived import HivedAlgorithm
from hivedscheduler_tpu.common import lockcheck
from hivedscheduler_tpu.k8s.client import KubeClient
from hivedscheduler_tpu.k8s.types import Binding, Node, Pod
from hivedscheduler_tpu.runtime import extender as ei
from hivedscheduler_tpu.runtime import types as internal
from hivedscheduler_tpu.runtime import utils as internal_utils
from hivedscheduler_tpu.runtime.types import (
    PodScheduleStatus,
    SchedulerAlgorithm,
)

log = logging.getLogger(__name__)

# Bind-commit retry policy: binds are idempotent by construction (same pod,
# same node, same annotations — the ApiServer merge converges), so bounded
# at-least-once delivery is safe. The backoff is deliberately short: the
# retry loop runs under the scheduler lock (as the reference's bindRoutine
# does), so a wedged ApiServer must fail fast and leave the retry to the
# next kube-scheduler cycle (the POD_BINDING insist path re-delivers).
BIND_RETRY_ATTEMPTS = 3
BIND_RETRY_BACKOFF_S = 0.05


class HivedScheduler:
    """Reference: HivedScheduler, scheduler.go:53-120."""

    def __init__(
        self,
        config: api_config.Config,
        kube_client: KubeClient,
        algorithm: Optional[SchedulerAlgorithm] = None,
    ):
        self.config = config
        self.kube_client = kube_client
        # One coarse lock serializes scheduling (reference: schedulerLock,
        # scheduler.go:104-108); bind reads take it shared.
        self.scheduler_lock = lockcheck.make_rlock("scheduler_lock")
        # uid -> PodScheduleStatus: ground truth of in-flight pods
        self.pod_schedule_statuses: Dict[str, PodScheduleStatus] = {}
        self.scheduler_algorithm: SchedulerAlgorithm = algorithm or HivedAlgorithm(config)
        # single-threaded contract: every mutating call into the algorithm
        # happens under the scheduler lock (asserted when HIVED_LOCKCHECK=1)
        lockcheck.serialize_under(self.scheduler_algorithm, "scheduler_lock")
        self._started = False

        kube_client.on_node_event(self._add_node, self._update_node, self._delete_node)
        kube_client.on_pod_event(self._add_pod, self._update_pod, self._delete_pod)
        # all nodes start bad until informed: publish that state immediately
        self._update_bad_node_gauge()

    def healthy(self, timeout: float = 2.0) -> bool:
        """Liveness for /healthz: the scheduler lock must be obtainable within
        ``timeout`` and the kube client's watch threads must be alive. A
        scheduler wedged on the algorithm lock, or one whose informer threads
        died, reports unhealthy so the probe can restart it."""
        if not self.scheduler_lock.acquire(timeout=timeout):
            return False
        self.scheduler_lock.release()
        return self.kube_client.watches_alive()

    def start(self) -> None:
        """Sync current cluster state through the handlers — the crash-recovery
        barrier: every bound pod is replayed into add_allocated_pod before any
        scheduling request is served (reference: Run, scheduler.go:196-216).

        Also freezes the process heap out of gen-2 GC scans (the cell trees
        are permanent; this bounds scheduling p99) — a process-global side
        effect embedders can disable with ``HIVED_GC_FREEZE=0``; see
        runtime.utils.freeze_long_lived_state."""
        log.info("Recovering tpu-hive scheduler")
        self.kube_client.sync()
        internal_utils.freeze_long_lived_state()
        self._started = True
        log.info("Running tpu-hive scheduler")

    # ------------------------------------------------------------------
    # informer callbacks
    # ------------------------------------------------------------------

    # Node events mutate the algorithm too, so they hold the scheduler lock
    # like the pod handlers do: the contract is that ONE lock serializes all
    # mutating calls (found by hivedlint's scheduler-lock path rule — the
    # algorithm lock alone covered these, but the stated contract is the
    # scheduler lock, and ROADMAP item 3 refactors against that contract).

    def _add_node(self, node: Node) -> None:
        with self.scheduler_lock:
            self.scheduler_algorithm.add_node(node)
            self._update_bad_node_gauge()

    def _update_node(self, old_node: Node, new_node: Node) -> None:
        with self.scheduler_lock:
            self.scheduler_algorithm.update_node(old_node, new_node)
            self._update_bad_node_gauge()

    def _delete_node(self, node: Node) -> None:
        with self.scheduler_lock:
            self.scheduler_algorithm.delete_node(node)
            self._update_bad_node_gauge()

    def _update_bad_node_gauge(self) -> None:
        bad = getattr(self.scheduler_algorithm, "bad_nodes", None)
        if bad is not None:
            metrics.set_gauge("tpu_hive_bad_nodes", len(bad))

    def _add_pod(self, pod: Pod) -> None:
        """Reference: addPod, scheduler.go:253-260."""
        if not internal_utils.is_interested(pod):
            return
        if internal_utils.is_bound(pod):
            self._add_bound_pod(pod)
        else:
            self._add_unbound_pod(pod)

    def _update_pod(self, old_pod: Pod, new_pod: Pod) -> None:
        """Reference: updatePod, scheduler.go:262-284."""
        if old_pod.uid != new_pod.uid:
            self._delete_pod(old_pod)
            self._add_pod(new_pod)
            return
        if not internal_utils.is_interested(new_pod):
            if internal_utils.is_interested(old_pod):
                self._delete_pod(old_pod)
            return
        old_bound = internal_utils.is_bound(old_pod)
        new_bound = internal_utils.is_bound(new_pod)
        if not old_bound and new_bound:
            self._add_bound_pod(new_pod)
        elif old_bound and not new_bound:
            raise AssertionError(
                f"[{internal_utils.key(new_pod)}]: Pod updated from bound to unbound: "
                f"previous bound node: {old_pod.node_name}"
            )

    def _delete_pod(self, pod: Pod) -> None:
        """Reference: deletePod, scheduler.go:285-304."""
        if not internal_utils.is_hived_enabled(pod):
            return
        with self.scheduler_lock:
            pod_status = self.pod_schedule_statuses.get(pod.uid)
            if pod_status is not None:
                if internal.is_allocated(pod_status.pod_state):
                    self.scheduler_algorithm.delete_allocated_pod(pod_status.pod)
                else:
                    self.scheduler_algorithm.delete_unallocated_pod(pod_status.pod)
                del self.pod_schedule_statuses[pod.uid]

    def _add_bound_pod(self, pod: Pod) -> None:
        """Reference: addBoundPod, scheduler.go:306-337."""
        with self.scheduler_lock:
            pod_status = self.pod_schedule_statuses.get(pod.uid)
            if pod_status is not None and internal.is_allocated(pod_status.pod_state):
                # already allocated: the placement never changes again
                if pod_status.pod_state != internal.POD_BOUND:
                    self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                        pod=pod_status.pod, pod_state=internal.POD_BOUND
                    )
                return
            # recover the bound pod
            self.scheduler_algorithm.add_allocated_pod(pod)
            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=internal.POD_BOUND
            )

    def _add_unbound_pod(self, pod: Pod) -> None:
        """Reference: addUnboundPod, scheduler.go:339-359."""
        with self.scheduler_lock:
            if pod.uid in self.pod_schedule_statuses:
                return
            self.scheduler_algorithm.add_unallocated_pod(pod)
            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=internal.POD_WAITING
            )

    # ------------------------------------------------------------------
    # admission / force bind
    # ------------------------------------------------------------------

    def _general_schedule_admission_check(
        self, pod_status: Optional[PodScheduleStatus]
    ) -> PodScheduleStatus:
        """Reference: generalScheduleAdmissionCheck, scheduler.go:364-383."""
        if pod_status is None:
            raise api.as_bad_request(
                "Pod does not exist, completed or has not been informed to the scheduler"
            )
        if pod_status.pod_state == internal.POD_BOUND:
            raise api.as_bad_request(
                f"Pod has already been bound to node {pod_status.pod.node_name}"
            )
        return pod_status

    def _validate_pod_bind_info(
        self, pod_bind_info: api.PodBindInfo, suggested_nodes: List[str]
    ) -> Optional[str]:
        """Reference: validatePodBindInfo, scheduler.go:385-421."""
        node = pod_bind_info.node
        try:
            known_node = self.kube_client.get_node(node)
        except Exception as e:
            # a transient ApiServer read failure must not fail the filter
            # after the algorithm already allocated: treat the placement as
            # unverifiable, which escalates to force bind (the bind itself
            # retries) instead of surfacing a 500 mid-gang
            return (
                f"The SchedulerAlgorithm decided to bind on node {node}, but the "
                f"ApiServer read to verify it failed transiently: {e}"
            )
        if known_node is None:
            return (
                f"The SchedulerAlgorithm decided to bind on node {node}, but the node "
                f"does not exist or has not been informed to the scheduler"
            )
        if node not in suggested_nodes:
            return (
                f"The SchedulerAlgorithm decided to bind on node {node} but the node "
                f"is not within the selected nodes from the default scheduler"
            )
        return None

    def _should_force_bind(
        self, pod_status: PodScheduleStatus, suggested_nodes: List[str]
    ) -> bool:
        """Keep binding regardless of potentially stale decisions; failed pods
        are retried/GC'd by K8s (reference: shouldForceBind,
        scheduler.go:423-466)."""
        pod = pod_status.pod
        if pod_status.pod_bind_attempts >= self.config.force_pod_bind_threshold:
            log.warning(
                "[%s]: Will force bind Pod: binding tried %s times, reaching the "
                "ForcePodBindThreshold %s",
                internal_utils.key(pod), pod_status.pod_bind_attempts,
                self.config.force_pod_bind_threshold,
            )
            return True
        err = self._validate_pod_bind_info(
            pod_status.pod_schedule_result.pod_bind_info, suggested_nodes
        )
        if err is not None:
            log.warning("[%s]: Will force bind Pod: %s", internal_utils.key(pod), err)
            return True
        return False

    def _force_bind_executor(self, binding_pod: Pod) -> None:
        """Bypass the default scheduler and trigger bindRoutine directly
        (reference: forceBindExecutor, scheduler.go:471-483)."""
        log.info("[%s]: forceBindExecutor: Started", internal_utils.key(binding_pod))
        metrics.inc("tpu_hive_force_binds_total")
        try:
            self._bind_routine(
                ei.ExtenderBindingArgs(
                    pod_name=binding_pod.name,
                    pod_namespace=binding_pod.namespace,
                    pod_uid=binding_pod.uid,
                    node=binding_pod.node_name,
                )
            )
        except Exception as e:  # async shadow of bindRoutine; log-and-drop
            log.warning("[%s]: forceBindExecutor failed: %s",
                        internal_utils.key(binding_pod), e)

    # ------------------------------------------------------------------
    # extender routines
    # ------------------------------------------------------------------

    def filter_routine(self, args: ei.ExtenderArgs) -> ei.ExtenderFilterResult:
        """Reference: filterRoutine, scheduler.go:485-587."""
        t0 = time.perf_counter()
        with trace.span("filter_routine", cat="extender",
                        pod=internal_utils.key(args.pod)) as sp:
            try:
                result, outcome = self._filter_routine(args)
                sp.add(outcome=outcome)
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="filter", outcome=outcome)
                return result
            except Exception:
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="filter", outcome="error")
                raise
            finally:
                metrics.observe("tpu_hive_filter_latency_seconds",
                                time.perf_counter() - t0)

    def _filter_routine(self, args: ei.ExtenderArgs):
        """Returns (result, metric outcome); each return site knows its own
        outcome exactly."""
        with self.scheduler_lock:
            pod = args.pod
            suggested_nodes = args.node_names
            log.info("[%s]: filterRoutine: Started", internal_utils.key(pod))

            pod_status = self._general_schedule_admission_check(
                self.pod_schedule_statuses.get(pod.uid)
            )
            if pod_status.pod_state == internal.POD_BINDING:
                # insist the previous bind: binding must be idempotent and the
                # algorithm has already assumed the pod allocated
                binding_pod = pod_status.pod
                pod_status.pod_bind_attempts += 1
                if self._should_force_bind(pod_status, suggested_nodes):
                    threading.Thread(
                        target=self._force_bind_executor, args=(binding_pod,), daemon=True
                    ).start()
                return (
                    ei.ExtenderFilterResult(node_names=[binding_pod.node_name]),
                    "bind",
                )

            # pod state is Waiting or Preempting: run a new scheduling
            result = self.scheduler_algorithm.schedule(
                pod, suggested_nodes, internal.FILTERING_PHASE
            )
            if result.pod_bind_info is not None:
                binding_pod = internal_utils.new_binding_pod(pod, result.pod_bind_info)
                # assume allocated so the next scheduling needn't wait for the bind
                self.scheduler_algorithm.add_allocated_pod(binding_pod)
                self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                    pod=binding_pod,
                    pod_state=internal.POD_BINDING,
                    pod_schedule_result=result,
                )
                if self._should_force_bind(
                    self.pod_schedule_statuses[pod.uid], suggested_nodes
                ):
                    threading.Thread(
                        target=self._force_bind_executor, args=(binding_pod,), daemon=True
                    ).start()
                log.info("[%s]: Pod is binding to %s",
                         internal_utils.key(pod), binding_pod.node_name)
                return (
                    ei.ExtenderFilterResult(node_names=[binding_pod.node_name]),
                    "bind",
                )
            if result.pod_preempt_info is not None:
                # FailedNodes tell the default scheduler preemption may help
                failed_nodes: Dict[str, str] = {}
                for victim in result.pod_preempt_info.victim_pods:
                    node = victim.node_name
                    if node not in failed_nodes:
                        failed_nodes[node] = (
                            f"node({node}) has preemptible Pods: {internal_utils.key(victim)}"
                        )
                    else:
                        failed_nodes[node] += ", " + internal_utils.key(victim)
                log.info("[%s]: Pod is waiting for preemptRoutine", internal_utils.key(pod))
                return (
                    ei.ExtenderFilterResult(failed_nodes=failed_nodes),
                    "preempt_candidates",
                )

            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=internal.POD_WAITING, pod_schedule_result=result
            )
            # block to achieve stronger FIFO (reference: scheduler.go:566-570)
            if self.config.waiting_pod_scheduling_block_milli_sec > 0:
                time.sleep(self.config.waiting_pod_scheduling_block_milli_sec / 1000.0)
            wait_reason = "Pod is waiting for preemptible or free resource to appear"
            if result.pod_wait_info is not None:
                wait_reason += ": " + result.pod_wait_info.reason
            log.info("[%s]: %s", internal_utils.key(pod), wait_reason)
            return (
                ei.ExtenderFilterResult(failed_nodes={_COMPONENT: wait_reason}),
                "wait",
            )

    def bind_routine(self, args: ei.ExtenderBindingArgs) -> ei.ExtenderBindingResult:
        """Idempotent bind executor (reference: bindRoutine, scheduler.go:594-627)."""
        with trace.span("bind_routine", cat="extender",
                        pod=f"{args.pod_namespace}/{args.pod_name}",
                        node=args.node):
            try:
                result = self._bind_routine(args)
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="bind", outcome="ok")
                return result
            except Exception:
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="bind", outcome="error")
                raise

    def _bind_routine(self, args: ei.ExtenderBindingArgs) -> ei.ExtenderBindingResult:
        with self.scheduler_lock:
            pod_key = f"{args.pod_namespace}/{args.pod_name}"
            log.info("[%s(%s)]: bindRoutine: Started", args.pod_uid, pod_key)
            pod_status = self._general_schedule_admission_check(
                self.pod_schedule_statuses.get(args.pod_uid)
            )
            if pod_status.pod_state == internal.POD_BINDING:
                binding_pod = pod_status.pod
                if binding_pod.node_name != args.node:
                    raise api.as_bad_request(
                        f"Pod binding node mismatch: expected {binding_pod.node_name}, "
                        f"received {args.node}"
                    )
                self._commit_bind(
                    Binding(
                        pod_name=binding_pod.name,
                        pod_namespace=binding_pod.namespace,
                        pod_uid=binding_pod.uid,
                        node=binding_pod.node_name,
                        annotations=internal_utils.extract_pod_bind_annotations(binding_pod),
                    )
                )
                metrics.inc("tpu_hive_binds_total")  # commits from any path
                return ei.ExtenderBindingResult()
            raise api.as_bad_request(
                f"Pod cannot be bound without a scheduling placement: Pod current "
                f"scheduling state {pod_status.pod_state}, received node {args.node}"
            )

    def _commit_bind(self, binding: Binding) -> None:
        """Deliver one bind to the ApiServer with bounded, idempotent retry.

        A transient failure (429/5xx/timeout) may be *ambiguous* — the POST
        committed but the response was lost — so before giving an attempt
        up the pod is re-read: a pod already on the target node with the
        same UID means the bind landed and the failure was response-side.
        The terminal failure re-raises; the pod stays POD_BINDING and the
        next filter cycle insists the bind again (force-bind ladder)."""
        last_exc: Optional[Exception] = None
        delay = BIND_RETRY_BACKOFF_S
        for attempt in range(BIND_RETRY_ATTEMPTS):
            if attempt:
                metrics.inc("tpu_hive_bind_retries_total")
                time.sleep(delay)
                delay *= 2
            try:
                self.kube_client.bind_pod(binding)
                return
            except Exception as e:
                last_exc = e
                try:
                    stored = self.kube_client.get_pod(
                        binding.pod_namespace, binding.pod_name
                    )
                except Exception:
                    stored = None
                if (
                    stored is not None
                    and stored.uid == binding.pod_uid
                    and stored.node_name == binding.node
                ):
                    log.warning(
                        "[%s/%s]: bind reported failure (%s) but the pod is "
                        "already bound to %s — treating as committed",
                        binding.pod_namespace, binding.pod_name, e, binding.node,
                    )
                    return
                log.warning(
                    "[%s/%s]: bind attempt %d/%d failed: %s",
                    binding.pod_namespace, binding.pod_name, attempt + 1,
                    BIND_RETRY_ATTEMPTS, e,
                )
        assert last_exc is not None
        raise last_exc

    def preempt_routine(self, args: ei.ExtenderPreemptionArgs) -> ei.ExtenderPreemptionResult:
        """Reference: preemptRoutine, scheduler.go:629-721."""
        t0 = time.perf_counter()
        with trace.span("preempt_routine", cat="extender",
                        pod=internal_utils.key(args.pod)) as sp:
            try:
                result = self._preempt_routine(args)
                outcome = "victims" if result.node_name_to_meta_victims else "none"
                sp.add(outcome=outcome)
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="preempt", outcome=outcome)
                return result
            except Exception:
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="preempt", outcome="error")
                raise
            finally:
                metrics.observe("tpu_hive_preempt_latency_seconds",
                                time.perf_counter() - t0)

    def _preempt_routine(self, args: ei.ExtenderPreemptionArgs) -> ei.ExtenderPreemptionResult:
        with self.scheduler_lock:
            pod = args.pod
            suggested_nodes = list(args.node_name_to_meta_victims)
            log.info("[%s]: preemptRoutine: Started", internal_utils.key(pod))
            pod_status = self._general_schedule_admission_check(
                self.pod_schedule_statuses.get(pod.uid)
            )
            if pod_status.pod_state == internal.POD_BINDING:
                raise api.as_bad_request(
                    f"Pod has already been binding to node {pod_status.pod.node_name}"
                )
            # re-schedule with the victims' nodes as suggested nodes; do not
            # insist a previous (possibly stale) preemption result
            result = self.scheduler_algorithm.schedule(
                pod, suggested_nodes, internal.PREEMPTING_PHASE
            )
            if result.pod_bind_info is not None:
                log.info("[%s]: Pod is waiting for filterRoutine as free resource appeared",
                         internal_utils.key(pod))
                return ei.ExtenderPreemptionResult()
            if result.pod_preempt_info is not None:
                self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                    pod=pod,
                    pod_state=internal.POD_PREEMPTING,
                    pod_schedule_result=result,
                )
                nodes_victims: Dict[str, List[str]] = {}
                for victim in result.pod_preempt_info.victim_pods:
                    nodes_victims.setdefault(victim.node_name, []).append(victim.uid)
                log.info("[%s]: Pod is preempting: %s", internal_utils.key(pod), nodes_victims)
                return ei.ExtenderPreemptionResult(node_name_to_meta_victims=nodes_victims)
            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=internal.POD_WAITING, pod_schedule_result=result
            )
            wait_reason = "Pod is waiting for preemptible or free resource to appear"
            if result.pod_wait_info is not None:
                wait_reason += ": " + result.pod_wait_info.reason
            log.info("[%s]: %s", internal_utils.key(pod), wait_reason)
            return ei.ExtenderPreemptionResult()

    # ------------------------------------------------------------------
    # inspect delegates (reference: scheduler.go:723-745)
    # ------------------------------------------------------------------

    def get_all_affinity_groups(self):
        return self.scheduler_algorithm.get_all_affinity_groups()

    def get_affinity_group(self, name: str):
        return self.scheduler_algorithm.get_affinity_group(name)

    def get_cluster_status(self):
        return self.scheduler_algorithm.get_cluster_status()

    def get_physical_cluster_status(self):
        return self.scheduler_algorithm.get_physical_cluster_status()

    def get_all_virtual_clusters_status(self):
        return self.scheduler_algorithm.get_all_virtual_clusters_status()

    def get_virtual_cluster_status(self, vcn: str):
        return self.scheduler_algorithm.get_virtual_cluster_status(vcn)

    # copy-on-read variants: serialize under the algorithm lock instead of
    # deep-copying the whole status forest per inspect request
    def get_cluster_status_dict(self):
        return self.scheduler_algorithm.get_cluster_status_dict()

    def get_physical_cluster_status_dict(self):
        return self.scheduler_algorithm.get_physical_cluster_status_dict()

    def get_all_virtual_clusters_status_dict(self):
        return self.scheduler_algorithm.get_all_virtual_clusters_status_dict()

    def get_virtual_cluster_status_dict(self, vcn: str):
        return self.scheduler_algorithm.get_virtual_cluster_status_dict(vcn)
