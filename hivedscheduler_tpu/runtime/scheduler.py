"""HivedScheduler runtime: the bridge between K8s and the algorithm.

TPU-native analogue of the reference's ``pkg/scheduler/scheduler.go``: informer
event handlers, the pod state machine ground truth (``pod_schedule_statuses``),
filter/bind/preempt routines behind one global scheduler lock, force-bind
escalation, and the recovery barrier (all bound pods replayed via
``add_allocated_pod`` before the webserver starts).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from hivedscheduler_tpu.api.constants import COMPONENT_NAME as _COMPONENT
from hivedscheduler_tpu.obs import journal as obs_journal
from hivedscheduler_tpu.obs import ledger as obs_ledger
from hivedscheduler_tpu.obs import trace
from hivedscheduler_tpu.runtime.metrics import REGISTRY as metrics

from hivedscheduler_tpu.api import config as api_config
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.api.constants import OPPORTUNISTIC_PRIORITY
from hivedscheduler_tpu.algorithm.hived import HivedAlgorithm
from hivedscheduler_tpu.common import envflags, lockcheck
from hivedscheduler_tpu import defrag as defrag_pkg
from hivedscheduler_tpu.defrag import executor as defrag_exec
from hivedscheduler_tpu.defrag.planner import (
    MigrationPlanner,
    RunningGroup,
    vc_quota_chips,
)
from hivedscheduler_tpu.defrag.probe import (
    GangSpec,
    WhatIfProbe,
    gang_pods,
    shrink_ladder,
)
from hivedscheduler_tpu.k8s.client import KubeClient
from hivedscheduler_tpu.k8s.types import Binding, Node, Pod
from hivedscheduler_tpu.runtime import eventbatch
from hivedscheduler_tpu.runtime import extender as ei
from hivedscheduler_tpu.runtime import types as internal
from hivedscheduler_tpu.runtime import utils as internal_utils
from hivedscheduler_tpu.runtime.types import (
    PodScheduleStatus,
    SchedulerAlgorithm,
)

log = logging.getLogger(__name__)

# Bind-commit retry policy: binds are idempotent by construction (same pod,
# same node, same annotations — the ApiServer merge converges), so bounded
# at-least-once delivery is safe. The backoff is deliberately short: the
# retry loop runs under the scheduler lock (as the reference's bindRoutine
# does), so a wedged ApiServer must fail fast and leave the retry to the
# next kube-scheduler cycle (the POD_BINDING insist path re-delivers).
BIND_RETRY_ATTEMPTS = 3
BIND_RETRY_BACKOFF_S = 0.05


class HivedScheduler:
    """Reference: HivedScheduler, scheduler.go:53-120."""

    def __init__(
        self,
        config: api_config.Config,
        kube_client: KubeClient,
        algorithm: Optional[SchedulerAlgorithm] = None,
    ):
        self.config = config
        self.kube_client = kube_client
        # One coarse lock serializes scheduling (reference: schedulerLock,
        # scheduler.go:104-108); bind reads take it shared.
        self.scheduler_lock = lockcheck.make_rlock("scheduler_lock")
        # uid -> PodScheduleStatus: ground truth of in-flight pods
        self.pod_schedule_statuses: Dict[str, PodScheduleStatus] = {}
        self.scheduler_algorithm: SchedulerAlgorithm = algorithm or HivedAlgorithm(config)
        # single-threaded contract: every mutating call into the algorithm
        # happens under the scheduler lock (asserted when HIVED_LOCKCHECK=1)
        lockcheck.serialize_under(self.scheduler_algorithm, "scheduler_lock")
        self._started = False
        # -- defrag/backfill executor state (doc/design/defrag.md) ---------
        # All of it is in-memory only BY DESIGN: a scheduler crash drops
        # every reservation and migration record; recovery rebuilds
        # allocations from bound pods and nothing else, so a mid-migration
        # crash can orphan neither cells nor holds (the chaos invariant).
        # With HIVED_DEFRAG=0 nothing below is ever populated, so the
        # filter/preempt paths are bit-identical to the pre-defrag
        # scheduler (the kill-switch differential).
        self._reservations: Dict[str, defrag_exec.Reservation] = {}
        self._migrations: Dict[str, defrag_exec.Migration] = {}
        self._defrag_waiters: Dict[str, dict] = {}  # group -> {pod, since}
        self._migration_seq = 0
        self._all_nodes_cache: Optional[List[str]] = None
        self.defrag_reserve_ttl_s = float(
            envflags.get("HIVED_DEFRAG_RESERVE_TTL_S", "300") or 300)
        # -- elastic offers (doc/design/elastic.md) ------------------------
        # group -> {offeredChips, fullChips, since}: bookkeeping for the
        # inspect surface and gauges. The state of RECORD is the degraded
        # pods' own annotations (elasticFullMembers), so a scheduler crash
        # loses nothing: recovery rebuilds the bound degraded gang and the
        # next defrag_tick re-derives its grow eligibility from the specs.
        self._elastic_degraded: Dict[str, dict] = {}
        self._elastic_seq = 0
        # duration-aware guaranteed backfill (defrag/backfill.py): the
        # pure policy shared with the trace sim
        self._backfill_policy = defrag_pkg.BackfillPolicy()

        # -- watch-event delivery (doc/design/perf.md) ---------------------
        # HIVED_EVENT_BATCH=1: informer callbacks enqueue into a coalescing
        # delta queue (runtime/eventbatch.py) drained at the start of every
        # scheduling cycle under the cycle's own scheduler-lock acquisition
        # — one contended acquisition per cycle instead of one per event.
        # Default (=0) is the per-event reference path, pinned
        # decision-identical by tests/test_eventbatch.py.
        self._pending: Optional[eventbatch.PendingDeltas] = (
            eventbatch.PendingDeltas() if eventbatch.batch_enabled() else None
        )
        if self._pending is not None:
            kube_client.on_node_event(
                self._pending.node_add, self._pending.node_update,
                self._pending.node_delete)
            kube_client.on_pod_event(
                self._pending.pod_add, self._pending.pod_update,
                self._pending.pod_delete)
        else:
            kube_client.on_node_event(self._add_node, self._update_node, self._delete_node)
            kube_client.on_pod_event(self._add_pod, self._update_pod, self._delete_pod)
        # all nodes start bad until informed: publish that state immediately
        self._update_bad_node_gauge()

    def healthy(self, timeout: float = 2.0) -> bool:
        """Liveness for /healthz: the scheduler lock must be obtainable within
        ``timeout`` and the kube client's watch threads must be alive. A
        scheduler wedged on the algorithm lock, or one whose informer threads
        died, reports unhealthy so the probe can restart it."""
        if not self.scheduler_lock.acquire(timeout=timeout):
            return False
        self.scheduler_lock.release()
        return self.kube_client.watches_alive()

    def start(self) -> None:
        """Sync current cluster state through the handlers — the crash-recovery
        barrier: every bound pod is replayed into add_allocated_pod before any
        scheduling request is served (reference: Run, scheduler.go:196-216).

        Also freezes the process heap out of gen-2 GC scans (the cell trees
        are permanent; this bounds scheduling p99) — a process-global side
        effect embedders can disable with ``HIVED_GC_FREEZE=0``; see
        runtime.utils.freeze_long_lived_state."""
        log.info("Recovering tpu-hive scheduler")
        self.kube_client.sync()
        # batched mode: the sync's replayed events are still queued — apply
        # them NOW so the recovery barrier holds (every bound pod is in the
        # algorithm before any scheduling request is served)
        self.flush_events()
        internal_utils.freeze_long_lived_state()
        self._started = True
        log.info("Running tpu-hive scheduler")

    # ------------------------------------------------------------------
    # batched watch-event application (runtime/eventbatch.py)
    # ------------------------------------------------------------------

    def flush_events(self) -> int:
        """Apply every pending batched watch event under one scheduler-lock
        acquisition; returns the number applied. No-op (0) when
        ``HIVED_EVENT_BATCH`` is off. Every extender routine and defrag
        tick flushes on entry, so embedders only need this when they read
        scheduler state without driving a cycle."""
        if self._pending is None:
            return 0
        with self.scheduler_lock:
            return self._apply_deltas_locked()

    def _apply_deltas_locked(self) -> int:
        """Drain the coalesced backlog and replay it through the per-event
        handlers (re-entrant under the already-held scheduler lock, so the
        applied semantics are byte-for-byte the unbatched path's). Caller
        holds the scheduler lock — hivedlint CON002 traverses the
        ``drain`` call as a mutating site to enforce exactly that."""
        if self._pending is None:
            return 0
        entries = self._pending.drain()
        if not entries:
            return 0
        for entry in entries:
            kind = entry[0]
            if kind == eventbatch.POD_ADD:
                self._add_pod(entry[1])
            elif kind == eventbatch.POD_UPDATE:
                self._update_pod(entry[1], entry[2])
            elif kind == eventbatch.POD_DELETE:
                self._delete_pod(entry[1])
            elif kind == eventbatch.NODE_ADD:
                self._add_node(entry[1])
            elif kind == eventbatch.NODE_UPDATE:
                self._update_node(entry[1], entry[2])
            else:
                self._delete_node(entry[1])
        metrics.inc("tpu_hive_event_batches_total")
        metrics.inc("tpu_hive_events_applied_total", amount=len(entries))
        return len(entries)

    # ------------------------------------------------------------------
    # informer callbacks
    # ------------------------------------------------------------------

    # Node events mutate the algorithm too, so they hold the scheduler lock
    # like the pod handlers do: the contract is that ONE lock serializes all
    # mutating calls (found by hivedlint's scheduler-lock path rule — the
    # algorithm lock alone covered these, but the stated contract is the
    # scheduler lock, and ROADMAP item 3 refactors against that contract).

    def _add_node(self, node: Node) -> None:
        with self.scheduler_lock:
            self.scheduler_algorithm.add_node(node)
            self._update_bad_node_gauge()

    def _update_node(self, old_node: Node, new_node: Node) -> None:
        with self.scheduler_lock:
            self.scheduler_algorithm.update_node(old_node, new_node)
            self._update_bad_node_gauge()

    def _delete_node(self, node: Node) -> None:
        with self.scheduler_lock:
            self.scheduler_algorithm.delete_node(node)
            self._update_bad_node_gauge()

    def _update_bad_node_gauge(self) -> None:
        bad = getattr(self.scheduler_algorithm, "bad_nodes", None)
        if bad is not None:
            metrics.set_gauge("tpu_hive_bad_nodes", len(bad))

    def _add_pod(self, pod: Pod) -> None:
        """Reference: addPod, scheduler.go:253-260."""
        if not internal_utils.is_interested(pod):
            return
        if internal_utils.is_bound(pod):
            self._add_bound_pod(pod)
        else:
            self._add_unbound_pod(pod)

    def _update_pod(self, old_pod: Pod, new_pod: Pod) -> None:
        """Reference: updatePod, scheduler.go:262-284."""
        if old_pod.uid != new_pod.uid:
            self._delete_pod(old_pod)
            self._add_pod(new_pod)
            return
        if not internal_utils.is_interested(new_pod):
            if internal_utils.is_interested(old_pod):
                self._delete_pod(old_pod)
            return
        old_bound = internal_utils.is_bound(old_pod)
        new_bound = internal_utils.is_bound(new_pod)
        if not old_bound and new_bound:
            self._add_bound_pod(new_pod)
        elif old_bound and not new_bound:
            raise AssertionError(
                f"[{internal_utils.key(new_pod)}]: Pod updated from bound to unbound: "
                f"previous bound node: {old_pod.node_name}"
            )

    def _delete_pod(self, pod: Pod) -> None:
        """Reference: deletePod, scheduler.go:285-304."""
        if not internal_utils.is_hived_enabled(pod):
            return
        with self.scheduler_lock:
            pod_status = self.pod_schedule_statuses.get(pod.uid)
            if pod_status is not None:
                if internal.is_allocated(pod_status.pod_state):
                    self.scheduler_algorithm.delete_allocated_pod(pod_status.pod)
                else:
                    self.scheduler_algorithm.delete_unallocated_pod(pod_status.pod)
                del self.pod_schedule_statuses[pod.uid]
            if (self._defrag_waiters or self._reservations
                    or self._elastic_degraded):
                self._on_waiter_pod_deleted(pod)

    def _on_waiter_pod_deleted(self, pod: Pod) -> None:
        """A cancelled waiting gang must not strand its waiter record,
        reservation or elastic-degraded record until TTL: when the last
        pod of a recorded/reserved group is deleted, drop them."""
        try:
            group = internal_utils.extract_pod_scheduling_spec(
                pod).affinity_group.name
        except Exception:
            return
        if (group not in self._defrag_waiters
                and group not in self._reservations
                and group not in self._elastic_degraded):
            return
        for st in self.pod_schedule_statuses.values():
            if st.pod is None:
                continue
            try:
                other = internal_utils.extract_pod_scheduling_spec(
                    st.pod).affinity_group.name
            except Exception:
                continue
            if other == group:
                return  # gang still has live pods
        self._defrag_waiters.pop(group, None)
        # a degraded gang completing/cancelled with no live pods is no
        # longer grow-eligible — unless a migration is mid-flight (its
        # eviction legitimately empties the gang; the re-bind restores it)
        mid_migration = any(
            m.active and any(mv.group == group for mv in m.moves)
            for m in self._migrations.values()
        )
        if (not mid_migration
                and self._elastic_degraded.pop(group, None) is not None):
            self._update_elastic_gauge()
        res = self._reservations.get(group)
        if res is not None and res.kind == "waiter":
            del self._reservations[group]
            self._update_reservation_gauge()

    def _add_bound_pod(self, pod: Pod) -> None:
        """Reference: addBoundPod, scheduler.go:306-337."""
        with self.scheduler_lock:
            pod_status = self.pod_schedule_statuses.get(pod.uid)
            if pod_status is not None and internal.is_allocated(pod_status.pod_state):
                # already allocated: the placement never changes again
                if pod_status.pod_state != internal.POD_BOUND:
                    self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                        pod=pod_status.pod, pod_state=internal.POD_BOUND
                    )
                return
            # recover the bound pod
            self.scheduler_algorithm.add_allocated_pod(pod)
            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=internal.POD_BOUND
            )

    def _add_unbound_pod(self, pod: Pod) -> None:
        """Reference: addUnboundPod, scheduler.go:339-359."""
        with self.scheduler_lock:
            if pod.uid in self.pod_schedule_statuses:
                return
            self.scheduler_algorithm.add_unallocated_pod(pod)
            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=internal.POD_WAITING
            )

    # ------------------------------------------------------------------
    # admission / force bind
    # ------------------------------------------------------------------

    def _general_schedule_admission_check(
        self, pod_status: Optional[PodScheduleStatus]
    ) -> PodScheduleStatus:
        """Reference: generalScheduleAdmissionCheck, scheduler.go:364-383."""
        if pod_status is None:
            raise api.as_bad_request(
                "Pod does not exist, completed or has not been informed to the scheduler"
            )
        if pod_status.pod_state == internal.POD_BOUND:
            raise api.as_bad_request(
                f"Pod has already been bound to node {pod_status.pod.node_name}"
            )
        return pod_status

    def _validate_pod_bind_info(
        self, pod_bind_info: api.PodBindInfo, suggested_nodes: List[str]
    ) -> Optional[str]:
        """Reference: validatePodBindInfo, scheduler.go:385-421."""
        node = pod_bind_info.node
        try:
            known_node = self.kube_client.get_node(node)
        except Exception as e:
            # a transient ApiServer read failure must not fail the filter
            # after the algorithm already allocated: treat the placement as
            # unverifiable, which escalates to force bind (the bind itself
            # retries) instead of surfacing a 500 mid-gang
            return (
                f"The SchedulerAlgorithm decided to bind on node {node}, but the "
                f"ApiServer read to verify it failed transiently: {e}"
            )
        if known_node is None:
            return (
                f"The SchedulerAlgorithm decided to bind on node {node}, but the node "
                f"does not exist or has not been informed to the scheduler"
            )
        if node not in suggested_nodes:
            return (
                f"The SchedulerAlgorithm decided to bind on node {node} but the node "
                f"is not within the selected nodes from the default scheduler"
            )
        return None

    def _should_force_bind(
        self, pod_status: PodScheduleStatus, suggested_nodes: List[str]
    ) -> bool:
        """Keep binding regardless of potentially stale decisions; failed pods
        are retried/GC'd by K8s (reference: shouldForceBind,
        scheduler.go:423-466)."""
        pod = pod_status.pod
        if pod_status.pod_bind_attempts >= self.config.force_pod_bind_threshold:
            log.warning(
                "[%s]: Will force bind Pod: binding tried %s times, reaching the "
                "ForcePodBindThreshold %s",
                internal_utils.key(pod), pod_status.pod_bind_attempts,
                self.config.force_pod_bind_threshold,
            )
            return True
        err = self._validate_pod_bind_info(
            pod_status.pod_schedule_result.pod_bind_info, suggested_nodes
        )
        if err is not None:
            log.warning("[%s]: Will force bind Pod: %s", internal_utils.key(pod), err)
            return True
        return False

    def _force_bind_executor(self, binding_pod: Pod) -> None:
        """Bypass the default scheduler and trigger bindRoutine directly
        (reference: forceBindExecutor, scheduler.go:471-483)."""
        log.info("[%s]: forceBindExecutor: Started", internal_utils.key(binding_pod))
        metrics.inc("tpu_hive_force_binds_total")
        try:
            self._bind_routine(
                ei.ExtenderBindingArgs(
                    pod_name=binding_pod.name,
                    pod_namespace=binding_pod.namespace,
                    pod_uid=binding_pod.uid,
                    node=binding_pod.node_name,
                )
            )
        except Exception as e:  # async shadow of bindRoutine; log-and-drop
            log.warning("[%s]: forceBindExecutor failed: %s",
                        internal_utils.key(binding_pod), e)

    # ------------------------------------------------------------------
    # extender routines
    # ------------------------------------------------------------------

    def filter_routine(self, args: ei.ExtenderArgs) -> ei.ExtenderFilterResult:
        """Reference: filterRoutine, scheduler.go:485-587."""
        t0 = time.perf_counter()
        with trace.span("filter_routine", cat="extender",
                        pod=internal_utils.key(args.pod)) as sp:
            try:
                result, outcome = self._filter_routine(args)
                sp.add(outcome=outcome)
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="filter", outcome=outcome)
                return result
            except Exception:
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="filter", outcome="error")
                raise
            finally:
                dt = time.perf_counter() - t0
                metrics.observe("tpu_hive_filter_latency_seconds", dt)
                metrics.observe("tpu_hive_sched_loop_phase_seconds", dt,
                                phase="schedule")

    def _filter_routine(self, args: ei.ExtenderArgs):
        """Returns (result, metric outcome); each return site knows its own
        outcome exactly."""
        with self.scheduler_lock:
            self._apply_deltas_locked()
            pod = args.pod
            suggested_nodes = args.node_names
            log.info("[%s]: filterRoutine: Started", internal_utils.key(pod))

            pod_status = self._general_schedule_admission_check(
                self.pod_schedule_statuses.get(pod.uid)
            )
            if pod_status.pod_state == internal.POD_BINDING:
                # insist the previous bind: binding must be idempotent and the
                # algorithm has already assumed the pod allocated
                binding_pod = pod_status.pod
                pod_status.pod_bind_attempts += 1
                if self._should_force_bind(pod_status, suggested_nodes):
                    threading.Thread(
                        target=self._force_bind_executor, args=(binding_pod,), daemon=True
                    ).start()
                return (
                    ei.ExtenderFilterResult(node_names=[binding_pod.node_name]),
                    "bind",
                )

            # pod state is Waiting or Preempting: run a new scheduling.
            # Defrag reservations (when any exist) withhold held nodes from
            # other gangs; with HIVED_DEFRAG=0 the dict is always empty and
            # this is exactly the pre-defrag call.
            offered_nodes = suggested_nodes
            if self._reservations:
                offered_nodes = self._admissible_nodes(pod, suggested_nodes)
            result = self.scheduler_algorithm.schedule(
                pod, offered_nodes, internal.FILTERING_PHASE
            )
            if (result.pod_bind_info is not None and self._reservations
                    and self._placement_violates_reservation(
                        pod, result.pod_bind_info)):
                # the node offer is best-effort for guaranteed gangs (they
                # ignore k8s suggestions by design), so the hold is
                # ENFORCED on the decided placement: nothing is committed
                # yet for a new group, so converting to a wait is safe
                self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                    pod=pod, pod_state=internal.POD_WAITING,
                    pod_schedule_result=internal.PodScheduleResult(
                        pod_wait_info=internal.PodWaitInfo(
                            reason="placement overlaps cells held by a "
                                   "defrag reservation")),
                )
                wait_reason = ("Pod is waiting for preemptible or free "
                               "resource to appear: placement overlaps a "
                               "defrag reservation")
                if obs_journal.JOURNAL.enabled:
                    # the algorithm hook just recorded a bind; the runtime
                    # vetoed it — re-attribute the gang to the hold
                    obs_journal.note_wait(
                        internal_utils.extract_pod_scheduling_spec(
                            pod).affinity_group.name,
                        "reservation_hold", detail=wait_reason)
                log.info("[%s]: %s", internal_utils.key(pod), wait_reason)
                return (
                    ei.ExtenderFilterResult(
                        failed_nodes={_COMPONENT: wait_reason}),
                    "wait",
                )
            if result.pod_bind_info is not None:
                binding_pod = internal_utils.new_binding_pod(pod, result.pod_bind_info)
                # assume allocated so the next scheduling needn't wait for the bind
                self.scheduler_algorithm.add_allocated_pod(binding_pod)
                if self._reservations or self._defrag_waiters:
                    self._on_group_allocated(
                        internal_utils.extract_pod_scheduling_spec(
                            pod).affinity_group.name)
                self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                    pod=binding_pod,
                    pod_state=internal.POD_BINDING,
                    pod_schedule_result=result,
                )
                if self._should_force_bind(
                    self.pod_schedule_statuses[pod.uid], suggested_nodes
                ):
                    threading.Thread(
                        target=self._force_bind_executor, args=(binding_pod,), daemon=True
                    ).start()
                log.info("[%s]: Pod is binding to %s",
                         internal_utils.key(pod), binding_pod.node_name)
                if obs_ledger.LEDGER.enabled and not any(
                    st.pod_state == internal.POD_WAITING
                    for st in self.pod_schedule_statuses.values()
                ):
                    # no gang is waiting any more: idle chips are plain
                    # spare capacity again
                    obs_ledger.LEDGER.set_idle_diagnosis("idle_free")
                return (
                    ei.ExtenderFilterResult(node_names=[binding_pod.node_name]),
                    "bind",
                )
            if result.pod_preempt_info is not None:
                # FailedNodes tell the default scheduler preemption may help
                failed_nodes: Dict[str, str] = {}
                for victim in result.pod_preempt_info.victim_pods:
                    node = victim.node_name
                    if node not in failed_nodes:
                        failed_nodes[node] = (
                            f"node({node}) has preemptible Pods: {internal_utils.key(victim)}"
                        )
                    else:
                        failed_nodes[node] += ", " + internal_utils.key(victim)
                log.info("[%s]: Pod is waiting for preemptRoutine", internal_utils.key(pod))
                return (
                    ei.ExtenderFilterResult(failed_nodes=failed_nodes),
                    "preempt_candidates",
                )

            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=internal.POD_WAITING, pod_schedule_result=result
            )
            # block to achieve stronger FIFO (reference: scheduler.go:566-570)
            if self.config.waiting_pod_scheduling_block_milli_sec > 0:
                time.sleep(self.config.waiting_pod_scheduling_block_milli_sec / 1000.0)
            wait_reason = "Pod is waiting for preemptible or free resource to appear"
            if result.pod_wait_info is not None:
                wait_reason += ": " + result.pod_wait_info.reason
            if defrag_pkg.defrag_enabled():
                # record the waiter for defrag_tick: the planner targets the
                # longest-waiting gang (recording only — no behavior change
                # until an embedder drives the tick)
                group = internal_utils.extract_pod_scheduling_spec(
                    pod).affinity_group.name
                self._defrag_waiters.setdefault(
                    group, {"pod": pod, "since": time.monotonic()})
            if obs_ledger.LEDGER.enabled:
                # capacity ledger: diagnose WHY idle chips are idle from
                # this waiter's journal bucket (vc_quota -> stranded,
                # fragmentation -> fragmented; capacity keeps idle_free)
                bucket = obs_journal.classify_wait(
                    result.pod_wait_info.reason
                    if result.pod_wait_info is not None else "")
                obs_ledger.LEDGER.set_idle_diagnosis(
                    obs_ledger.IDLE_STATE_FOR_BUCKET.get(
                        bucket, "idle_free"))
            log.info("[%s]: %s", internal_utils.key(pod), wait_reason)
            return (
                ei.ExtenderFilterResult(failed_nodes={_COMPONENT: wait_reason}),
                "wait",
            )

    def bind_routine(self, args: ei.ExtenderBindingArgs) -> ei.ExtenderBindingResult:
        """Idempotent bind executor (reference: bindRoutine, scheduler.go:594-627)."""
        with trace.span("bind_routine", cat="extender",
                        pod=f"{args.pod_namespace}/{args.pod_name}",
                        node=args.node):
            try:
                result = self._bind_routine(args)
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="bind", outcome="ok")
                return result
            except Exception:
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="bind", outcome="error")
                raise

    def _bind_routine(self, args: ei.ExtenderBindingArgs) -> ei.ExtenderBindingResult:
        with self.scheduler_lock:
            self._apply_deltas_locked()
            pod_key = f"{args.pod_namespace}/{args.pod_name}"
            log.info("[%s(%s)]: bindRoutine: Started", args.pod_uid, pod_key)
            pod_status = self._general_schedule_admission_check(
                self.pod_schedule_statuses.get(args.pod_uid)
            )
            if pod_status.pod_state == internal.POD_BINDING:
                binding_pod = pod_status.pod
                if binding_pod.node_name != args.node:
                    raise api.as_bad_request(
                        f"Pod binding node mismatch: expected {binding_pod.node_name}, "
                        f"received {args.node}"
                    )
                self._commit_bind(
                    Binding(
                        pod_name=binding_pod.name,
                        pod_namespace=binding_pod.namespace,
                        pod_uid=binding_pod.uid,
                        node=binding_pod.node_name,
                        annotations=internal_utils.extract_pod_bind_annotations(binding_pod),
                    )
                )
                metrics.inc("tpu_hive_binds_total")  # commits from any path
                return ei.ExtenderBindingResult()
            raise api.as_bad_request(
                f"Pod cannot be bound without a scheduling placement: Pod current "
                f"scheduling state {pod_status.pod_state}, received node {args.node}"
            )

    def _commit_bind(self, binding: Binding) -> None:
        """Deliver one bind to the ApiServer with bounded, idempotent retry.

        A transient failure (429/5xx/timeout) may be *ambiguous* — the POST
        committed but the response was lost — so before giving an attempt
        up the pod is re-read: a pod already on the target node with the
        same UID means the bind landed and the failure was response-side.
        The terminal failure re-raises; the pod stays POD_BINDING and the
        next filter cycle insists the bind again (force-bind ladder)."""
        last_exc: Optional[Exception] = None
        delay = BIND_RETRY_BACKOFF_S
        for attempt in range(BIND_RETRY_ATTEMPTS):
            if attempt:
                metrics.inc("tpu_hive_bind_retries_total")
                time.sleep(delay)
                delay *= 2
            try:
                self.kube_client.bind_pod(binding)
                return
            except Exception as e:
                last_exc = e
                try:
                    stored = self.kube_client.get_pod(
                        binding.pod_namespace, binding.pod_name
                    )
                except Exception:
                    stored = None
                if (
                    stored is not None
                    and stored.uid == binding.pod_uid
                    and stored.node_name == binding.node
                ):
                    log.warning(
                        "[%s/%s]: bind reported failure (%s) but the pod is "
                        "already bound to %s — treating as committed",
                        binding.pod_namespace, binding.pod_name, e, binding.node,
                    )
                    return
                log.warning(
                    "[%s/%s]: bind attempt %d/%d failed: %s",
                    binding.pod_namespace, binding.pod_name, attempt + 1,
                    BIND_RETRY_ATTEMPTS, e,
                )
        assert last_exc is not None
        raise last_exc

    def preempt_routine(self, args: ei.ExtenderPreemptionArgs) -> ei.ExtenderPreemptionResult:
        """Reference: preemptRoutine, scheduler.go:629-721."""
        t0 = time.perf_counter()
        with trace.span("preempt_routine", cat="extender",
                        pod=internal_utils.key(args.pod)) as sp:
            try:
                result = self._preempt_routine(args)
                outcome = "victims" if result.node_name_to_meta_victims else "none"
                sp.add(outcome=outcome)
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="preempt", outcome=outcome)
                return result
            except Exception:
                metrics.inc("tpu_hive_extender_requests_total",
                            routine="preempt", outcome="error")
                raise
            finally:
                metrics.observe("tpu_hive_preempt_latency_seconds",
                                time.perf_counter() - t0)

    def _preempt_routine(self, args: ei.ExtenderPreemptionArgs) -> ei.ExtenderPreemptionResult:
        with self.scheduler_lock:
            self._apply_deltas_locked()
            pod = args.pod
            suggested_nodes = list(args.node_name_to_meta_victims)
            log.info("[%s]: preemptRoutine: Started", internal_utils.key(pod))
            pod_status = self._general_schedule_admission_check(
                self.pod_schedule_statuses.get(pod.uid)
            )
            if pod_status.pod_state == internal.POD_BINDING:
                raise api.as_bad_request(
                    f"Pod has already been binding to node {pod_status.pod.node_name}"
                )
            # re-schedule with the victims' nodes as suggested nodes; do not
            # insist a previous (possibly stale) preemption result
            result = self.scheduler_algorithm.schedule(
                pod, suggested_nodes, internal.PREEMPTING_PHASE
            )
            if result.pod_bind_info is not None:
                log.info("[%s]: Pod is waiting for filterRoutine as free resource appeared",
                         internal_utils.key(pod))
                return ei.ExtenderPreemptionResult()
            if result.pod_preempt_info is not None:
                self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                    pod=pod,
                    pod_state=internal.POD_PREEMPTING,
                    pod_schedule_result=result,
                )
                nodes_victims: Dict[str, List[str]] = {}
                for victim in result.pod_preempt_info.victim_pods:
                    nodes_victims.setdefault(victim.node_name, []).append(victim.uid)
                log.info("[%s]: Pod is preempting: %s", internal_utils.key(pod), nodes_victims)
                return ei.ExtenderPreemptionResult(node_name_to_meta_victims=nodes_victims)
            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=internal.POD_WAITING, pod_schedule_result=result
            )
            wait_reason = "Pod is waiting for preemptible or free resource to appear"
            if result.pod_wait_info is not None:
                wait_reason += ": " + result.pod_wait_info.reason
            log.info("[%s]: %s", internal_utils.key(pod), wait_reason)
            return ei.ExtenderPreemptionResult()

    # ------------------------------------------------------------------
    # defragmentation / backfill executor (doc/design/defrag.md)
    #
    # The executor lives HERE — runtime/scheduler.py is the one file
    # allowed to call algorithm mutators (hivedlint CON003), and every
    # entry point below takes the scheduler lock before reaching the
    # planner/probe (CON002 traverses plan_migration/run_probe as
    # mutating calls). The passive state machine types live in
    # defrag/executor.py.
    # ------------------------------------------------------------------

    def _all_nodes(self) -> List[str]:
        if self._all_nodes_cache is None:
            algo = self.scheduler_algorithm
            self._all_nodes_cache = sorted({
                n
                for ccl in algo.full_cell_list.values()
                for c in ccl[max(ccl)]
                for n in c.nodes
            })
        return self._all_nodes_cache

    def _reserved_against(self, group: str) -> set:
        """Nodes held by reservations whose holder is not ``group``."""
        blocked = set()
        for res in self._reservations.values():
            if res.holder != group:
                blocked |= res.nodes
        return blocked

    def _admissible_nodes(self, pod: Pod, suggested_nodes: List[str]) -> List[str]:
        """Reservation-aware node offer for a NEW gang: held nodes are
        withheld unless the backfill policy admits the candidate
        (opportunistic = preemptible = the holder reclaims by preemption,
        so the ride can never delay the reservation). Existing groups keep
        the full offer — their placement is already committed and must not
        be perturbed mid-gang."""
        self._sweep_expired_reservations()
        if not self._reservations:
            return suggested_nodes
        s = internal_utils.extract_pod_scheduling_spec(pod)
        group = s.affinity_group.name
        if group in getattr(self.scheduler_algorithm, "affinity_groups", {}):
            return suggested_nodes
        blocked = self._reserved_against(group)
        if not blocked:
            return suggested_nodes
        if (defrag_pkg.backfill_enabled()
                and s.priority <= OPPORTUNISTIC_PRIORITY):
            return suggested_nodes
        if (defrag_pkg.backfill_enabled() and s.duration_seconds > 0
                and self._duration_fits_all_holds(s, group)):
            # duration-aware guaranteed backfill: this gang declares it
            # finishes before ANY live hold expires, so it cannot delay
            # the reservations it might ride in
            return suggested_nodes
        # advisory prefilter only — guaranteed gangs ignore suggestions,
        # so _placement_violates_reservation enforces on the decided
        # placement (and owns the admitted/blocked metrics)
        return [n for n in suggested_nodes if n not in blocked]

    def _duration_fits_all_holds(self, s, group: str,
                                 nodes: Optional[set] = None) -> bool:
        """The duration-aware backfill bound (defrag/backfill.py): a
        guaranteed gang with a declared ``durationSeconds`` may ride a
        reserved hole when ``now + duration*slack <= eta`` for every hold
        it would intersect. The runtime's honest ETA for a hold is its TTL
        deadline — the hold cannot outlive it (the sweep releases it), so
        finishing first provably never delays the waiter. ``nodes``
        restricts the check to holds intersecting that placement (the
        enforcement point); None checks against every foreign hold (the
        advisory node-offer prefilter)."""
        now = time.monotonic()
        etas = [
            r.deadline for r in self._reservations.values()
            if r.holder != group and (nodes is None or (r.nodes & nodes))
        ]
        if not etas:
            return True
        return self._backfill_policy.admits(
            s.priority, now, duration=s.duration_seconds,
            reservation_eta=min(etas),
        ).admit

    @staticmethod
    def _bind_info_nodes(pod_bind_info: api.PodBindInfo) -> set:
        """Every node the gang's decided placement touches."""
        return {
            pp.physical_node
            for member in pod_bind_info.affinity_group_bind_info
            for pp in member.pod_placements
        }

    def _placement_violates_reservation(
        self, pod: Pod, pod_bind_info: api.PodBindInfo
    ) -> bool:
        """Does a NEW gang's decided placement intrude on cells held for
        someone else? (The enforcement half of reservations — the node
        offer alone is advisory, guaranteed gangs ignore suggestions.)"""
        s = internal_utils.extract_pod_scheduling_spec(pod)
        group = s.affinity_group.name
        if group in getattr(self.scheduler_algorithm, "affinity_groups", {}):
            return False  # committed gangs complete unimpeded
        blocked = self._reserved_against(group)
        if not blocked or not (self._bind_info_nodes(pod_bind_info)
                               & blocked):
            return False
        if (defrag_pkg.backfill_enabled()
                and s.priority <= OPPORTUNISTIC_PRIORITY):
            # preemptible rider INTO the hold: the backfill admission —
            # the holder reclaims by preemption, so the ride is free
            metrics.inc("tpu_hive_backfill_admissions_total",
                        outcome="admitted")
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("backfill_admitted", group,
                                 outcome="admitted")
            if obs_ledger.LEDGER.enabled:
                # the gang's chips will bind as a backfill rider, not a
                # plain opportunistic gang — the ledger's flavor hint
                obs_ledger.LEDGER.hint_flavor(group, "busy_backfill")
            return False
        if (defrag_pkg.backfill_enabled() and s.duration_seconds > 0
                and self._duration_fits_all_holds(
                    s, group, nodes=self._bind_info_nodes(pod_bind_info))):
            # guaranteed rider that provably finishes before every hold it
            # intersects expires: the duration-aware backfill window
            metrics.inc("tpu_hive_backfill_admissions_total",
                        outcome="fits-window")
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("backfill_admitted", group,
                                 outcome="fits-window")
            if obs_ledger.LEDGER.enabled:
                obs_ledger.LEDGER.hint_flavor(group, "busy_backfill")
            return False
        metrics.inc("tpu_hive_backfill_admissions_total", outcome="blocked")
        return True

    def _on_group_allocated(self, group: str) -> None:
        """A gang landed: drop its waiter bookkeeping and release a waiter
        reservation it held (its cells now hold themselves)."""
        self._defrag_waiters.pop(group, None)
        res = self._reservations.get(group)
        if res is not None and res.kind == "waiter":
            del self._reservations[group]
            self._update_reservation_gauge()

    def _update_reservation_gauge(self) -> None:
        metrics.set_gauge("tpu_hive_defrag_reservations",
                          len(self._reservations))
        if obs_ledger.LEDGER.enabled:
            # capacity ledger: idle chips on held nodes burn as
            # idle_reserved (waiter holds) / migration_downtime (move
            # targets); called at every reservation mutation site, so the
            # diff-based sync sees every hold change
            holds = {}
            for r in self._reservations.values():
                state = obs_ledger.HOLD_STATE_FOR_KIND[r.kind]
                for n in r.nodes:
                    holds[n] = state
            obs_ledger.LEDGER.sync_reserved(holds)

    def _sweep_expired_reservations(self) -> None:
        now = time.monotonic()
        expired = [k for k, r in self._reservations.items() if r.expired(now)]
        for k in expired:
            # _finish_migration below may have already released siblings of
            # the same migration mid-sweep
            res = self._reservations.pop(k, None)
            if res is None:
                continue
            log.warning("defrag: reservation for %s (%s) expired after "
                        "%.0fs — sweeping", res.holder, res.kind,
                        now - res.created_at)
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("reservation_expired", res.holder,
                                 kind=res.kind, heldSecs=round(
                                     now - res.created_at, 3))
            if res.migration_id is not None:
                mig = self._migrations.get(res.migration_id)
                if mig is not None and mig.active:
                    self._finish_migration(mig, defrag_exec.MIGRATION_ABORTED,
                                           "reservation-expired")
            else:
                metrics.inc("tpu_hive_defrag_migrations_total",
                            outcome="expired")
        if expired:
            self._update_reservation_gauge()

    def _finish_migration(self, mig, state: str, why: str) -> None:
        """Terminal transition: release every reservation the migration
        still holds (waiter included — a failed consolidation must not
        fence cells) and record the outcome."""
        mig.state = state
        for key in [k for k, r in self._reservations.items()
                    if r.migration_id == mig.id]:
            del self._reservations[key]
        self._update_reservation_gauge()
        if state != defrag_exec.MIGRATION_DONE and self._elastic_degraded:
            # a failed/aborted grow leaves the gang fully evicted: its
            # degraded record has no pods to grow any more — the job
            # framework resubmits from the checkpoint (full or ladder
            # shape, its call)
            groups = {mv.group for mv in mig.moves}
            live = {self._group_of(st.pod)
                    for st in self.pod_schedule_statuses.values()
                    if st.pod is not None}
            for group in groups & set(self._elastic_degraded) - live:
                del self._elastic_degraded[group]
                self._update_elastic_gauge()
        outcome = {defrag_exec.MIGRATION_DONE: "completed",
                   defrag_exec.MIGRATION_FAILED: "failed",
                   defrag_exec.MIGRATION_ABORTED: "aborted"}[state]
        metrics.inc("tpu_hive_defrag_migrations_total", outcome=outcome)
        if mig.created_at:
            metrics.observe("tpu_hive_migration_phase_seconds",
                            time.monotonic() - mig.created_at,
                            phase="total")
        if obs_journal.JOURNAL.enabled:
            if state == defrag_exec.MIGRATION_FAILED:
                obs_journal.emit("migration_failed", mig.waiter,
                                 cause=mig.journal_event or None,
                                 migration=mig.id, why=why)
            elif state == defrag_exec.MIGRATION_ABORTED:
                obs_journal.emit("migration_aborted", mig.waiter,
                                 cause=mig.journal_event or None,
                                 migration=mig.id, why=why)
        log.info("defrag: migration %s for waiter %s -> %s (%s)",
                 mig.id, mig.waiter, state, why)
        self._prune_migrations()

    # terminal migration records kept for inspect, bounded so a long-lived
    # scheduler never grows without limit
    _MIGRATION_HISTORY = 32

    def _prune_migrations(self) -> None:
        terminal = [m for m in self._migrations.values() if not m.active]
        excess = len(terminal) - self._MIGRATION_HISTORY
        if excess > 0:
            for m in terminal[:excess]:
                del self._migrations[m.id]

    def _running_groups(self) -> List[RunningGroup]:
        """Fully-bound gangs eligible as movers: every member pod
        allocated, group Allocated, not already migrating or holding a
        reservation."""
        algo = self.scheduler_algorithm
        by_group: Dict[str, List[Pod]] = {}
        for st in self.pod_schedule_statuses.values():
            if st.pod is None or not internal.is_allocated(st.pod_state):
                continue
            spec = internal_utils.extract_pod_scheduling_spec(st.pod)
            by_group.setdefault(spec.affinity_group.name, []).append(st.pod)
        migrating = {
            m.group for mig in self._migrations.values() if mig.active
            for m in mig.moves
        }
        out: List[RunningGroup] = []
        for name, pods in by_group.items():
            if name in migrating or name in self._reservations:
                continue
            g = getattr(algo, "affinity_groups", {}).get(name)
            if g is None or g.state != "Allocated":
                continue
            spec = GangSpec.from_pod(pods[0])
            if len(pods) != spec.pod_count:
                continue  # mid-bind gang: not a safe mover
            out.append(RunningGroup(name=name, spec=spec, bound_pods=pods))
        return out

    def plan_defrag_for(self, pod: Pod) -> Optional[dict]:
        """Plan (and start executing) a consolidation that unblocks
        ``pod``'s waiting gang: probe-validated move set, ``migrating``
        reservations on the waiter slice and every move target, then
        eviction of the movers (pod deletion = SIGTERM = the supervisor's
        checkpoint-and-exit-0 contract). Returns the plan dict, or None
        with the rejection recorded in
        ``tpu_hive_defrag_planner_rejections_total``."""
        if not defrag_pkg.defrag_enabled():
            return None
        with self.scheduler_lock:
            with trace.span("defrag_plan", cat="defrag",
                            pod=internal_utils.key(pod)) as sp:
                plan = self._plan_defrag_locked(pod, sp)
                return plan

    def _plan_defrag_locked(self, pod: Pod, sp) -> Optional[dict]:
        delete_pod = getattr(self.kube_client, "delete_pod", None)
        if delete_pod is None:
            self._reject_plan(sp, "evict-unsupported",
                              "kube client cannot delete pods")
            return None
        bad_nodes = getattr(self.scheduler_algorithm, "bad_nodes", None)
        if bad_nodes:
            # the what-if probe's remove/restore rollback is only exact on
            # a healthy view: doomed-bad cell rebinding makes delete+re-add
            # non-idempotent while nodes are down (found by chaos seed 23's
            # VC-safety break), and consolidating mid-failure is futile
            # anyway — the failure handler owns the cluster right now
            self._reject_plan(sp, "cluster-unhealthy",
                              f"{len(bad_nodes)} bad node(s)")
            return None
        waiter = GangSpec.from_pod(pod)
        if waiter.name in getattr(
                self.scheduler_algorithm, "affinity_groups", {}):
            self._reject_plan(sp, "already-placed", waiter.name)
            return None
        if any(m.waiter == waiter.name and m.active
               for m in self._migrations.values()):
            self._reject_plan(sp, "already-migrating", waiter.name)
            return None
        running = self._running_groups()
        free_chips = None
        if waiter.priority >= 0:
            quota = vc_quota_chips(self.scheduler_algorithm, waiter.vc)
            used = sum(g.chips for g in running
                       if g.spec.vc == waiter.vc and g.priority >= 0)
            free_chips = quota - used
        planner = MigrationPlanner()
        probe = WhatIfProbe(self.scheduler_algorithm, self._all_nodes())
        plan = planner.plan_migration(probe, waiter, running,
                                      free_chips=free_chips)
        if not hasattr(plan, "moves"):
            self._reject_plan(sp, plan.reason, plan.detail)
            return None
        # register the migration + reservations, then evict
        self._migration_seq += 1
        mid = f"mig-{self._migration_seq}"
        now = time.monotonic()
        deadline = now + self.defrag_reserve_ttl_s
        mig = defrag_exec.Migration(
            id=mid, waiter=waiter.name, waiter_chips=waiter.chips,
            moves=[
                defrag_exec.Move(
                    group=m.group.name, spec=m.group.spec,
                    evicted_pods=list(m.group.bound_pods),
                    target_nodes=m.target_nodes,
                )
                for m in plan.moves
            ],
            created_at=now, phase_t=now,
        )
        self._migrations[mid] = mig
        if obs_journal.JOURNAL.enabled:
            # the plan event chains off the waiter's open queued event; the
            # movers' evictions chain off the plan — the causal spine the
            # /v1/inspect/gangs timelines reconstruct a migration from
            pid = obs_journal.emit(
                "defrag_planned", waiter.name, migration=mid,
                moves=[m.group.name for m in plan.moves],
                waiterNodes=sorted(plan.waiter_nodes),
                movedChips=plan.moved_chips)
            mig.journal_event = pid or 0
            for m in plan.moves:
                obs_journal.emit(
                    "migration_evict", m.group.name, cause=pid,
                    migration=mid, targetNodes=sorted(m.target_nodes))
        self._reservations[waiter.name] = defrag_exec.Reservation(
            holder=waiter.name, nodes=set(plan.waiter_nodes), kind="waiter",
            created_at=now, deadline=deadline, migration_id=mid)
        for m in plan.moves:
            self._reservations[m.group.name] = defrag_exec.Reservation(
                holder=m.group.name, nodes=set(m.target_nodes),
                kind="migration", created_at=now, deadline=deadline,
                migration_id=mid)
        self._update_reservation_gauge()
        metrics.inc("tpu_hive_defrag_migrations_total", outcome="planned")
        sp.add(outcome="planned", moves=len(plan.moves),
               moved_chips=plan.moved_chips)
        log.info("defrag: plan %s — move %s to free %d chips for %s",
                 mid, [m.group.name for m in plan.moves], waiter.chips,
                 waiter.name)
        self._evict_moves(mig)
        plan_dict = plan.to_dict()
        plan_dict["migrationId"] = mid
        return plan_dict

    def _reject_plan(self, sp, reason: str, detail: str) -> None:
        metrics.inc("tpu_hive_defrag_planner_rejections_total",
                    reason=reason)
        sp.add(outcome="rejected", reason=reason, detail=detail)

    def _evict_moves(self, mig) -> None:
        """Issue (or re-issue) the SIGTERM-analogue pod deletions for every
        still-present mover pod; transient ApiServer failures are left to
        the next resume_migrations pass (evictions are idempotent)."""
        delete_pod = getattr(self.kube_client, "delete_pod", None)
        for move in mig.moves:
            if move.state != defrag_exec.MIGRATION_EVICTING:
                continue
            for p in move.evicted_pods:
                if p.uid not in self.pod_schedule_statuses:
                    continue
                try:
                    delete_pod(p.namespace, p.name)
                except Exception as e:
                    log.warning("defrag: evict of %s failed transiently: %s",
                                internal_utils.key(p), e)

    def resume_migrations(self) -> dict:
        """Advance every in-flight migration: re-issue pending evictions,
        and re-place movers whose cells the informer has fully released
        (gang-atomic per move; a member failure rolls the whole move back
        and fails the migration — the evicted job's work stays safe in its
        checkpoint for resubmission). Call from the embedder's watch loop
        or after eviction events settle."""
        if not defrag_pkg.defrag_enabled():
            return {}
        report = {}
        with self.scheduler_lock:
            self._apply_deltas_locked()
            self._sweep_expired_reservations()
            for mig in list(self._migrations.values()):
                if not mig.active:
                    continue
                if mig.state == defrag_exec.MIGRATION_EVICTING:
                    self._evict_moves(mig)
                    if self._movers_released(mig):
                        mig.state = defrag_exec.MIGRATION_REBINDING
                        mono = time.monotonic()
                        metrics.observe("tpu_hive_migration_phase_seconds",
                                        mono - mig.phase_t, phase="evict")
                        mig.phase_t = mono
                if mig.state == defrag_exec.MIGRATION_REBINDING:
                    self._rebind_moves(mig)
                report[mig.id] = mig.to_dict()
        return report

    def _movers_released(self, mig) -> bool:
        algo_groups = getattr(self.scheduler_algorithm, "affinity_groups", {})
        for move in mig.moves:
            if move.group in algo_groups:
                return False
            if any(p.uid in self.pod_schedule_statuses
                   for p in move.evicted_pods):
                return False
        return True

    def _bind_gang_atomically(
        self, group: str, replacement_pods: List[Pod], blocked: set
    ) -> Optional[List[Pod]]:
        """Create, schedule and bind a gang's replacement pods as one unit:
        any member failure unwinds the whole gang (allocations released,
        every created pod deleted from the ApiServer) and returns None.
        Shared by migration re-binds and elastic shrink offers; the caller
        holds the scheduler lock."""
        create_pod = getattr(self.kube_client, "create_pod", None)
        if create_pod is None:
            return None
        allowed = [n for n in self._all_nodes() if n not in blocked]
        placed: List[Pod] = []
        created: List[Pod] = []
        ok = True
        for rp in replacement_pods:
            try:
                create_pod(rp)
                created.append(rp)
                result = self.scheduler_algorithm.schedule(
                    rp, allowed, internal.FILTERING_PHASE)
                if result.pod_bind_info is None:
                    raise RuntimeError(
                        f"replacement for {group} found no "
                        f"placement (state drifted since the probe)")
                if self._bind_info_nodes(result.pod_bind_info) & blocked:
                    # the node offer is advisory: a re-placement that
                    # grabbed someone else's held slice (e.g. the
                    # waiter's) must not commit
                    raise RuntimeError(
                        f"replacement for {group} landed on "
                        f"reserved cells (state drifted since the "
                        f"probe)")
                bp = internal_utils.new_binding_pod(
                    rp, result.pod_bind_info)
                self.scheduler_algorithm.add_allocated_pod(bp)
                self.pod_schedule_statuses[bp.uid] = PodScheduleStatus(
                    pod=bp, pod_state=internal.POD_BINDING)
                self._commit_bind(Binding(
                    pod_name=bp.name, pod_namespace=bp.namespace,
                    pod_uid=bp.uid, node=bp.node_name,
                    annotations=internal_utils
                    .extract_pod_bind_annotations(bp),
                ))
                metrics.inc("tpu_hive_binds_total")
                self.pod_schedule_statuses[bp.uid] = PodScheduleStatus(
                    pod=bp, pod_state=internal.POD_BOUND)
                placed.append(bp)
            except Exception as e:
                log.warning("defrag: re-bind of %s member failed: %s",
                            group, e)
                ok = False
                break
        if not ok:
            # gang atomicity: unwind the half-placed gang entirely
            delete_pod = getattr(self.kube_client, "delete_pod", None)
            for bp in reversed(placed):
                if bp.uid in self.pod_schedule_statuses:
                    self.scheduler_algorithm.delete_allocated_pod(bp)
                    self.pod_schedule_statuses.pop(bp.uid, None)
            for rp in reversed(created):
                if delete_pod is not None:
                    try:
                        delete_pod(rp.namespace, rp.name)
                    except Exception:
                        pass
            return None
        return placed

    def _rebind_moves(self, mig) -> None:
        if getattr(self.kube_client, "create_pod", None) is None:
            self._finish_migration(mig, defrag_exec.MIGRATION_FAILED,
                                   "kube client cannot create pods")
            return
        for move in mig.moves:
            if move.state != defrag_exec.MIGRATION_EVICTING:
                continue
            placed = self._bind_gang_atomically(
                move.group,
                gang_pods(move.spec, uid_prefix=f"{mig.id}g{mig.generation}-"),
                self._reserved_against(move.group),
            )
            if placed is None:
                self._finish_migration(mig, defrag_exec.MIGRATION_FAILED,
                                       f"move {move.group} could not re-place")
                return
            move.rebound_pods = placed
            move.state = defrag_exec.MIGRATION_DONE
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit(
                    "migration_rebound", move.group,
                    cause=mig.journal_event or None, migration=mig.id,
                    nodes=sorted({p.node_name for p in placed}))
            if (not move.spec.degraded
                    and self._elastic_degraded.pop(move.group, None)
                    is not None):
                # a grow-promotion landed: the gang runs at full shape
                # again (an ordinary defrag move of a still-degraded gang
                # keeps its record — its spec still carries the ladder)
                self._update_elastic_gauge()
                metrics.inc("tpu_hive_elastic_grows_total",
                            outcome="completed")
                if obs_journal.JOURNAL.enabled:
                    obs_journal.emit(
                        "elastic_grow_done", move.group,
                        cause=mig.journal_event or None,
                        chips=move.spec.chips)
                log.info("elastic: %s grew back to full shape (%d chips)",
                         move.group, move.spec.chips)
            res = self._reservations.get(move.group)
            if res is not None and res.kind == "migration":
                del self._reservations[move.group]
                self._update_reservation_gauge()
            metrics.inc("tpu_hive_defrag_moved_chips_total",
                        amount=move.spec.chips)
        if all(m.state == defrag_exec.MIGRATION_DONE for m in mig.moves):
            mig.state = defrag_exec.MIGRATION_DONE
            metrics.inc("tpu_hive_defrag_migrations_total",
                        outcome="completed")
            mono = time.monotonic()
            metrics.observe("tpu_hive_migration_phase_seconds",
                            mono - mig.phase_t, phase="rebind")
            metrics.observe("tpu_hive_migration_phase_seconds",
                            mono - mig.created_at, phase="total")
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("migration_done", mig.waiter,
                                 cause=mig.journal_event or None,
                                 migration=mig.id)
            # the waiter reservation stays until the waiter binds (or TTL)
            log.info("defrag: migration %s complete — %s's slice is free",
                     mig.id, mig.waiter)
            self._prune_migrations()

    def abort_migration(self, migration_id: str,
                        why: str = "job died") -> bool:
        """The job framework reports a mid-migration death (e.g. kill -9
        after checkpoint, before re-bind): release every hold, mark the
        migration aborted. Nothing half-bound survives; the checkpoint
        keeps the work."""
        with self.scheduler_lock:
            mig = self._migrations.get(migration_id)
            if mig is None or not mig.active:
                return False
            self._finish_migration(mig, defrag_exec.MIGRATION_ABORTED, why)
            return True

    def defrag_tick(self) -> dict:
        """One defrag scan: sweep expiries, advance in-flight migrations,
        plan for the longest-waiting recorded gang, then the elastic arm —
        a waiter whose full shape the planner could not unblock is offered
        the largest feasible shrink from its declared ladder, and degraded
        running gangs are grow-promoted back to full shape when capacity
        frees. The embedder's watch loop (cli/demo) or the chaos harness
        drives this; with HIVED_DEFRAG=0 it is a no-op."""
        if not defrag_pkg.defrag_enabled():
            return {"enabled": False}
        with self.scheduler_lock:
            self._apply_deltas_locked()
            t0 = time.perf_counter()
            progressed = self.resume_migrations()
            t1 = time.perf_counter()
            metrics.observe("tpu_hive_sched_loop_phase_seconds", t1 - t0,
                            phase="migrations")
            planned = None
            offered = None
            for group, rec in sorted(self._defrag_waiters.items(),
                                     key=lambda kv: kv[1]["since"]):
                if group in self._reservations:
                    continue  # already holding a consolidated slice
                if any(m.waiter == group and m.active
                       for m in self._migrations.values()):
                    continue
                planned = self.plan_defrag_for(rec["pod"])
                if planned is not None:
                    break
                # the defrag planner declined this waiter: the elastic arm
                # may still put it to work on a degraded slice
                offered = self._offer_elastic_shrink(group, rec["pod"])
                if offered is not None:
                    break
            t2 = time.perf_counter()
            metrics.observe("tpu_hive_sched_loop_phase_seconds", t2 - t1,
                            phase="plan")
            grown = self._promote_elastic_grows()
            metrics.observe("tpu_hive_sched_loop_phase_seconds",
                            time.perf_counter() - t2, phase="elastic")
            return {"enabled": True, "planned": planned,
                    "migrations": progressed, "elasticOffer": offered,
                    "elasticGrows": grown}

    # ------------------------------------------------------------------
    # elastic offers: shrink a blocked waiter, grow a degraded gang back
    # (doc/design/elastic.md)
    # ------------------------------------------------------------------

    def _update_elastic_gauge(self) -> None:
        metrics.set_gauge("tpu_hive_elastic_degraded_gangs",
                          len(self._elastic_degraded))

    def _offer_elastic_shrink(self, group: str, pod: Pod) -> Optional[dict]:
        """A waiting elastic gang whose full shape is infeasible (and whose
        wait the defrag planner just declined to fix) is offered the
        largest feasible shrink from its declared ladder: the waiting
        full-shape pods are replaced by a degraded incarnation, created
        and gang-atomically bound in their place. The degraded pods' bind
        annotations ARE the offer — their slice is what the workload's
        ``train --elastic`` entry point derives its mesh from — and their
        scheduling specs carry ``elasticFullMembers`` so the full shape
        survives crashes and grow-promotion can restore it. Caller holds
        the scheduler lock."""
        if not defrag_pkg.elastic_enabled():
            return None
        if getattr(self.scheduler_algorithm, "bad_nodes", None):
            # same rule as plan_defrag_for: probe rollback is only exact
            # on a healthy view
            return None
        try:
            spec = GangSpec.from_pod(pod)
        except Exception:
            return None
        if not spec.elastic or spec.degraded:
            return None
        if spec.name in getattr(self.scheduler_algorithm,
                                "affinity_groups", {}):
            return None  # already placed since recorded
        probe = WhatIfProbe(self.scheduler_algorithm, self._all_nodes())
        rung = None
        for candidate in shrink_ladder(spec):
            if probe.run_fit_probe(candidate).feasible:
                rung = candidate
                break
        if rung is None:
            metrics.inc("tpu_hive_elastic_offers_total",
                        outcome="infeasible")
            return None
        # replace the waiting full-shape pods with the degraded incarnation
        # (same group name, fresh uids — a deleted pod's uid never returns)
        delete_pod = getattr(self.kube_client, "delete_pod", None)
        waiting = [
            st.pod for st in list(self.pod_schedule_statuses.values())
            if st.pod is not None and not internal.is_allocated(st.pod_state)
            and self._group_of(st.pod) == group
        ]
        if delete_pod is not None:
            for p in waiting:
                try:
                    delete_pod(p.namespace, p.name)
                except Exception as e:
                    log.warning("elastic: delete of waiting pod %s failed "
                                "transiently: %s", internal_utils.key(p), e)
        self._defrag_waiters.pop(group, None)
        self._elastic_seq += 1
        offer_event = None
        if obs_journal.JOURNAL.enabled:
            offer_event = obs_journal.emit(
                "elastic_offer", group, offeredChips=rung.chips,
                fullChips=spec.chips)
        placed = self._bind_gang_atomically(
            group,
            gang_pods(rung, uid_prefix=f"el{self._elastic_seq}-"),
            self._reserved_against(group),
        )
        if placed is None:
            # the job framework resubmits the gang like any preempted one
            # (nothing was running yet — no work is lost)
            metrics.inc("tpu_hive_elastic_offers_total", outcome="failed")
            log.warning("elastic: degraded bind of %s failed; the gang "
                        "must be resubmitted", group)
            return None
        self._elastic_degraded[group] = {
            "offeredChips": rung.chips, "fullChips": spec.chips,
            "since": time.monotonic(),
        }
        self._update_elastic_gauge()
        if obs_journal.JOURNAL.enabled:
            # the gang now runs degraded: its time on the small slice is a
            # wait on grow-promotion, attributed as elastic_degraded
            obs_journal.note_wait(
                group, "elastic_degraded", cause=offer_event,
                detail=f"running {rung.chips}/{spec.chips} chips")
        metrics.inc("tpu_hive_elastic_offers_total", outcome="offered")
        log.info("elastic: offered %s a degraded %d-chip slice (full "
                 "shape %d chips blocked)", group, rung.chips, spec.chips)
        return {"group": group, "offeredChips": rung.chips,
                "fullChips": spec.chips,
                "nodes": sorted({p.node_name for p in placed})}

    @staticmethod
    def _group_of(pod: Pod) -> Optional[str]:
        try:
            return internal_utils.extract_pod_scheduling_spec(
                pod).affinity_group.name
        except Exception:
            return None

    def _promote_elastic_grows(self) -> List[dict]:
        """Degraded running gangs whose full shape fits again are
        grow-migrated back through the migration machinery: reserve the
        target slice, evict (pod deletion = SIGTERM = the supervisor's
        checkpoint-and-exit-0 contract), re-place at full shape, resume —
        the workload's cross-topology restore turns the bigger slice back
        into goodput. Degradedness is read from the running pods' own
        specs, so this works across scheduler restarts. Caller holds the
        scheduler lock."""
        if not defrag_pkg.elastic_enabled():
            return []
        if getattr(self.scheduler_algorithm, "bad_nodes", None):
            return []
        if getattr(self.kube_client, "delete_pod", None) is None:
            return []
        grown: List[dict] = []
        for g in self._running_groups():
            if not g.spec.degraded:
                continue
            full = g.spec.full_spec()
            probe = WhatIfProbe(self.scheduler_algorithm, self._all_nodes())
            result = probe.run_swap_probe(g.bound_pods, full)
            if not result.feasible:
                metrics.inc("tpu_hive_elastic_grows_total",
                            outcome="infeasible")
                continue
            self._migration_seq += 1
            mid = f"mig-{self._migration_seq}"
            now = time.monotonic()
            target = set(result.nodes_of(full.name))
            mig = defrag_exec.Migration(
                id=mid, waiter=g.name, waiter_chips=full.chips,
                moves=[defrag_exec.Move(
                    group=g.name, spec=full,
                    evicted_pods=list(g.bound_pods),
                    target_nodes=sorted(target),
                )],
                created_at=now, phase_t=now,
            )
            self._migrations[mid] = mig
            if obs_journal.JOURNAL.enabled:
                pid = obs_journal.emit(
                    "elastic_grow_planned", g.name, migration=mid,
                    fromChips=g.spec.chips, toChips=full.chips)
                mig.journal_event = pid or 0
                obs_journal.emit("migration_evict", g.name, cause=pid,
                                 migration=mid,
                                 targetNodes=sorted(target))
            self._reservations[g.name] = defrag_exec.Reservation(
                holder=g.name, nodes=target, kind="migration",
                created_at=now, deadline=now + self.defrag_reserve_ttl_s,
                migration_id=mid)
            self._update_reservation_gauge()
            self._elastic_degraded.setdefault(g.name, {
                "offeredChips": g.spec.chips, "fullChips": full.chips,
                "since": now,
            })
            metrics.inc("tpu_hive_defrag_migrations_total",
                        outcome="planned")
            metrics.inc("tpu_hive_elastic_grows_total", outcome="planned")
            log.info("elastic: promoting %s from %d back to %d chips "
                     "(migration %s)", g.name, g.spec.chips, full.chips, mid)
            self._evict_moves(mig)
            grown.append({"group": g.name, "migrationId": mid,
                          "fromChips": g.spec.chips, "toChips": full.chips})
        return grown

    def get_defrag_status(self) -> dict:
        """Inspect view of the reservation/migration state machine."""
        with self.scheduler_lock:
            self._apply_deltas_locked()
            return {
                "enabled": defrag_pkg.defrag_enabled(),
                "backfill": defrag_pkg.backfill_enabled(),
                "elastic": defrag_pkg.elastic_enabled(),
                "reservations": [
                    r.to_dict() for r in self._reservations.values()
                ],
                "migrations": [
                    m.to_dict() for m in self._migrations.values()
                ],
                "waiters": sorted(self._defrag_waiters),
                "elasticDegraded": {
                    group: {k: v for k, v in rec.items() if k != "since"}
                    for group, rec in sorted(self._elastic_degraded.items())
                },
            }

    def get_gang_eta(self, group: str) -> dict:
        """Wait-ETA forecast for a waiting gang (obs/eta.py, read-only):
        capacity-without-a-move from the capacity ledger's running-gang
        ages + completed-gang durations and the defrag reservations' TTL
        deadlines; served at ``GET /v1/inspect/gangs/<id>/eta`` and
        recorded as an ``eta_forecast`` journal annotation so later PRs
        can score forecasts against realized waits."""
        from hivedscheduler_tpu.obs import eta as obs_eta

        with self.scheduler_lock:
            self._apply_deltas_locked()
            rec = self._defrag_waiters.get(group)
            pod = rec["pod"] if rec is not None else None
            if pod is None:
                for st in self.pod_schedule_statuses.values():
                    if (st.pod is not None
                            and not internal.is_allocated(st.pod_state)
                            and self._group_of(st.pod) == group):
                        pod = st.pod
                        break
            if pod is None:
                raise api.WebServerError(
                    404, f"no waiting gang named {group!r} is known to "
                         f"the scheduler")
            spec = GangSpec.from_pod(pod)
            lg = obs_ledger.LEDGER
            occ = lg.occupancy()
            idle = sum(occ.get(s, 0) for s in obs_ledger.IDLE_DIAG_STATES)
            held = (occ.get("idle_reserved", 0)
                    + occ.get("migration_downtime", 0))
            reserved = []
            if held and self._reservations:
                now_m = time.monotonic()
                soonest = min(r.deadline for r in
                              self._reservations.values())
                reserved = [(max(0.0, soonest - now_m), held)]
            forecast = obs_eta.estimate(
                group, spec.chips, idle_chips=idle,
                running=lg.running_gangs(), reserved=reserved,
                completed_durations=lg.completed_durations())
            obs_eta.record(forecast)
            out = forecast.to_dict()
            out["ledgerEnabled"] = lg.enabled
            return out

    def get_admission_hints(self) -> dict:
        """Scheduler-visible admission hints: the serving tier's block-pool
        occupancy (published by ServingEngine as the
        ``tpu_hive_serve_block_pool_occupancy`` gauge) plus the defrag
        subsystem's current holds — what gang admission should know about
        headroom it cannot see in the cell trees."""
        occupancy = metrics.get_gauge("tpu_hive_serve_block_pool_occupancy")
        with self.scheduler_lock:
            self._apply_deltas_locked()
            reserved_nodes = sorted({
                n for r in self._reservations.values() for n in r.nodes
            })
            return {
                "serveBlockPoolOccupancy": occupancy,
                "serveBlockPoolHeadroom": (
                    None if occupancy is None
                    else round(max(0.0, 1.0 - occupancy), 4)
                ),
                "defragReservedNodes": reserved_nodes,
                "defragMigrationsInFlight": sum(
                    1 for m in self._migrations.values() if m.active),
                "waitingGangs": sorted(self._defrag_waiters),
            }

    # ------------------------------------------------------------------
    # inspect delegates (reference: scheduler.go:723-745)
    # ------------------------------------------------------------------

    def get_all_affinity_groups(self):
        return self.scheduler_algorithm.get_all_affinity_groups()

    def get_affinity_group(self, name: str):
        return self.scheduler_algorithm.get_affinity_group(name)

    def get_cluster_status(self):
        return self.scheduler_algorithm.get_cluster_status()

    def get_physical_cluster_status(self):
        return self.scheduler_algorithm.get_physical_cluster_status()

    def get_all_virtual_clusters_status(self):
        return self.scheduler_algorithm.get_all_virtual_clusters_status()

    def get_virtual_cluster_status(self, vcn: str):
        return self.scheduler_algorithm.get_virtual_cluster_status(vcn)

    # copy-on-read variants: serialize under the algorithm lock instead of
    # deep-copying the whole status forest per inspect request
    def get_cluster_status_dict(self):
        return self.scheduler_algorithm.get_cluster_status_dict()

    def get_physical_cluster_status_dict(self):
        return self.scheduler_algorithm.get_physical_cluster_status_dict()

    def get_all_virtual_clusters_status_dict(self):
        return self.scheduler_algorithm.get_all_virtual_clusters_status_dict()

    def get_virtual_cluster_status_dict(self, vcn: str):
        return self.scheduler_algorithm.get_virtual_cluster_status_dict(vcn)
