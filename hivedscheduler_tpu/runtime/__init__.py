"""Scheduler runtime: pod state machine, routines, contracts.

TPU-native analogue of the reference's ``pkg/internal`` + ``pkg/scheduler``.
"""
