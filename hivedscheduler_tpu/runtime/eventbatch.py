"""Batched informer deltas for the scheduler runtime (``HIVED_EVENT_BATCH``).

The unbatched informer path takes the scheduler lock once **per watch
event**: under bursty churn (tens of thousands of pod ADDED/MODIFIED/DELETED
events per second on a 16k-chip fleet) the informer thread and the
scheduling thread bounce the lock per event, and every bounce lands between
two gang decisions. With ``HIVED_EVENT_BATCH=1`` the informer callbacks
instead append to this queue under a tiny leaf lock, and the scheduler
drains the whole backlog at the start of its next cycle (filter / preempt /
bind / defrag tick) under the scheduler-lock acquisition that cycle already
pays — ONE contended acquisition per cycle instead of one per event.

The queue coalesces while it buffers — rules chosen so the applied delta is
**decision-identical** to applying every event individually (the
differential guard: tests/test_eventbatch.py pins ``HIVED_EVENT_BATCH=0``
vs ``=1`` on bound placements, failure strings and journal events across
chaos seeds):

- **global FIFO**: events apply in arrival order (stronger than the per-
  object ordering the informer contract requires), so cross-object effects
  (a delete freeing cells a later add's gang needs) replay faithfully;
- **pod add→delete dedup**: an *unbound* pod whose ADDED is still pending
  when its DELETED arrives is dropped entirely — ``add_unallocated_pod``
  is a no-op and the runtime status round-trips, so the scheduler provably
  never observes the pod. Bound adds (recovery replays) are never deduped:
  ``add_allocated_pod`` + ``delete_allocated_pod`` is only bit-exact on a
  healthy view (the what-if-probe caveat), so the pair is applied as-is;
- **node-flap folding**: consecutive pending updates of one node fold into
  (first_old, last_new), and a pending add followed by updates folds into
  add(last_new) — ``update_node`` acts only on the healthiness *edge*, so a
  NotReady↔Ready flap that completes inside one batch window applies as a
  no-op instead of a doomed-bad bind/unbind round trip (the round trip is
  deterministic and state-restoring, so the fold changes no decision).
  Node deletes are never folded away: DELETED marks the node bad whatever
  came before, and dropping a pending add could resurrect a stale healthy
  state.

Lock contract: enqueue touches only ``event_queue_lock`` (a leaf — informer
threads may already hold the scheduler lock via the fake ApiServer's
synchronous delivery, and nothing is ever acquired under the queue lock).
``drain()`` is destructive and MUST be called with the scheduler lock held:
hivedlint's CON002 fixpoint treats a call to any attr in
:data:`LOCKED_APPLY_ATTRS` inside ``HivedScheduler`` as an algorithm-
mutating site, so an unlocked path to the delta apply fails lint (seeded
fixture: tests/test_hivedlint.py::test_con002_event_batch_apply_traversed).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hivedscheduler_tpu.common import envflags, lockcheck
from hivedscheduler_tpu.k8s.types import Node, Pod
from hivedscheduler_tpu.runtime import utils as internal_utils

# Attrs that consume/apply the batched delta; CON002 requires every call
# path to them inside HivedScheduler to hold the scheduler lock end-to-end
# (the batched analogue of defrag.LOCKED_ENTRY_ATTRS).
LOCKED_APPLY_ATTRS = frozenset({"drain"})

# entry kinds, in the vocabulary of the scheduler's informer handlers
POD_ADD = "pod_add"
POD_UPDATE = "pod_update"
POD_DELETE = "pod_delete"
NODE_ADD = "node_add"
NODE_UPDATE = "node_update"
NODE_DELETE = "node_delete"


def batch_enabled() -> bool:
    """``HIVED_EVENT_BATCH=1`` opts the runtime into batched watch deltas;
    the default (unset/`0`) keeps the per-event reference path — the
    decision-identical differential the batched path is pinned against."""
    return envflags.get("HIVED_EVENT_BATCH", "0") == "1"


class PendingDeltas:
    """The coalescing watch-event queue (see module docstring).

    Enqueue methods are registered directly as informer callbacks; they are
    safe from any thread and never block on scheduler state. ``drain()``
    hands the backlog to the applying cycle (scheduler lock held — CON002).
    """

    __slots__ = (
        "_lock",
        "_entries",
        "_last",
        "coalesced_pod_pairs",
        "coalesced_node_folds",
        "drained_events",
        "drained_batches",
    )

    def __init__(self):
        self._lock = lockcheck.make_lock("event_queue_lock")
        # each entry is a mutable list [kind, obj, ...]; kind None = dropped
        self._entries: List[list] = []
        # ("pod"|"node", key) -> the LAST pending entry for that object
        self._last: Dict[Tuple[str, str], list] = {}
        self.coalesced_pod_pairs = 0
        self.coalesced_node_folds = 0
        self.drained_events = 0
        self.drained_batches = 0

    def _push(self, key: Tuple[str, str], entry: list) -> None:
        """Caller holds the queue lock."""
        self._entries.append(entry)
        self._last[key] = entry

    # -- informer-side enqueue -------------------------------------------

    def pod_add(self, pod: Pod) -> None:
        with self._lock:
            self._push(("pod", pod.uid), [POD_ADD, pod])

    def pod_update(self, old_pod: Pod, new_pod: Pod) -> None:
        with self._lock:
            self._push(("pod", new_pod.uid), [POD_UPDATE, old_pod, new_pod])

    def pod_delete(self, pod: Pod) -> None:
        with self._lock:
            key = ("pod", pod.uid)
            last = self._last.get(key)
            if (
                last is not None
                and last[0] == POD_ADD
                and not internal_utils.is_bound(last[1])
            ):
                # add→delete dedup: the unbound pod lived and died inside
                # one batch window — the scheduler never observes it
                last[0] = None
                del self._last[key]
                self.coalesced_pod_pairs += 1
                return
            self._push(key, [POD_DELETE, pod])

    def node_add(self, node: Node) -> None:
        with self._lock:
            self._push(("node", node.name), [NODE_ADD, node])

    def node_update(self, old_node: Node, new_node: Node) -> None:
        with self._lock:
            key = ("node", new_node.name)
            last = self._last.get(key)
            if last is not None and last[0] == NODE_UPDATE:
                last[2] = new_node  # flap fold: (o0,o1)+(o1,o2) -> (o0,o2)
                self.coalesced_node_folds += 1
                return
            if last is not None and last[0] == NODE_ADD:
                last[1] = new_node  # add+update -> add(latest state)
                self.coalesced_node_folds += 1
                return
            self._push(key, [NODE_UPDATE, old_node, new_node])

    def node_delete(self, node: Node) -> None:
        with self._lock:
            # never folded: DELETED must mark the node bad whatever the
            # pending history says (see module docstring)
            self._push(("node", node.name), [NODE_DELETE, node])

    # -- scheduler-side apply --------------------------------------------

    def drain(self) -> List[list]:
        """Take the whole backlog (coalesced, arrival order). Destructive —
        the caller MUST hold the scheduler lock and apply every returned
        entry (CON002 traverses calls to this attr as mutating sites)."""
        with self._lock:
            entries, self._entries = self._entries, []
            self._last.clear()
        live = [e for e in entries if e[0] is not None]
        if live:
            self.drained_events += len(live)
            self.drained_batches += 1
        return live

    def __len__(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries if e[0] is not None)
