"""Chaos engineering for the scheduler stack.

The reference claims fault tolerance (bad-hardware awareness, work-preserving
reconfiguration, crash recovery from pod annotations — README.md:42) but only
exercises it through hand-written unit cases. This package *attacks* those
paths systematically:

- ``chaos.injector``: a deterministic, seeded fault injector wrapping any
  ``KubeClient`` — dropped/delayed/reordered watch events, transient HTTP
  429/500/timeout errors on reads and binds (including the ambiguous
  failed-after-commit case), while keeping the list path (the recovery
  barrier) reliable, as in real list+watch.
- ``chaos.invariants``: a reusable checker re-deriving the algorithm's
  structural guarantees from scratch — VC safety, gang atomicity, used-count
  books, no leaked or doubly-allocated cells — plus chip-granular placement
  preservation across restart (the ``test_recovery_scale.py`` contract).
- ``chaos.harness``: a seeded soak driver running full schedule/bind cycles
  through the runtime over a fake ApiServer while injecting node
  NotReady flaps, mid-gang pod deletions, and scheduler crash-restarts
  (fresh ``HivedScheduler`` replaying recovery from pod annotations),
  checking invariants after every schedule.
- ``chaos.workload``: the *workload*-side soak — SIGKILL/SIGTERM/injected
  hangs against a real CPU-only training subprocess, asserting the
  supervisor's exit contracts and bit-exact checkpoint resume
  (``parallel/supervisor.py``; seeds pinned in
  ``tools/check_workload_seeds.py``).

The fault model — which faults are tolerated at which layer — is catalogued
in ``doc/design/fault-model.md``. Seeds that ever found a violation are
pinned forever in ``tools/check_chaos_seeds.py`` /
``tools/check_workload_seeds.py``.
"""

from hivedscheduler_tpu.chaos.injector import ChaosKubeClient, FaultPlan, InjectedApiError
from hivedscheduler_tpu.chaos.invariants import (
    InvariantViolation,
    check_all,
    check_placement_preserved,
    placement_snapshot,
)
from hivedscheduler_tpu.chaos.harness import ChaosHarness
from hivedscheduler_tpu.chaos.workload import (
    WorkloadChaosHarness,
    WorkloadFaultPlan,
)

__all__ = [
    "ChaosHarness",
    "ChaosKubeClient",
    "FaultPlan",
    "InjectedApiError",
    "InvariantViolation",
    "WorkloadChaosHarness",
    "WorkloadFaultPlan",
    "check_all",
    "check_placement_preserved",
    "placement_snapshot",
]
