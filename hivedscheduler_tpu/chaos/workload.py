"""Process-level workload chaos: kill, hang and restart a real training
subprocess, then prove the resumed run is bit-exact.

The scheduler-side soak (``chaos.harness``) attacks the control plane; this
harness attacks the *workload* contract that makes HiveD's preemption
work-preserving end to end (ISSUE 3): a training job must survive

- **SIGKILL** mid-step (hard preemption / OOM-killer / node loss): the next
  incarnation restores the newest committed checkpoint — params, optimizer
  AND data-loader RNG state — and reproduces the uninterrupted run's loss
  trajectory **bit-exactly** (CPU; guard against silent data replay/skip).
- **SIGTERM** (cooperative preemption): the supervisor checkpoints at the
  next step boundary and exits 0 within the grace period.
- **hang** (wedged step, injected via ``HIVED_FAULT_HANG_AT``): the
  watchdog records ``hived_stall.json`` and exits ``EXIT_STALLED`` so the
  gang restarts instead of wedging forever.

Every fault decision is drawn from one ``random.Random(seed)``, so a seed
replays the same episode plan forever — the same pin-the-seed policy as the
scheduler soak (``tools/check_workload_seeds.py`` mirrors
``tools/check_chaos_seeds.py``).

All subprocesses run CPU-only with the CLAUDE.md env recipe
(``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu``): a process holding the
single-grant TPU tunnel must NEVER be killed — which is exactly what this
harness does for a living. ``HIVED_FAULT_STEP_DELAY`` paces the tiny model's
steps so signals land inside the training window deterministically enough
to matter.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from hivedscheduler_tpu.parallel import supervisor as sup_lib

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the fault ladder the seeded plan draws from (NaN/divergence rollback is
# exercised by its own deterministic test: it legitimately changes the
# data stream, so it cannot share the bit-exactness assertion)
EPISODE_KINDS = ("sigkill", "sigterm", "hang")


@dataclasses.dataclass
class WorkloadFaultPlan:
    """Seeded episode plan: how many times to interrupt the run, and the
    step window faults may land in. Steps are drawn in
    ``[min_step, steps - 2]`` so a checkpoint can exist before the first
    fault and at least one step remains after the last."""

    episodes: int = 2
    min_step: int = 3
    kinds: Tuple[str, ...] = EPISODE_KINDS

    def draw(self, rng: random.Random, steps: int) -> List[Tuple[str, int]]:
        hi = max(self.min_step, steps - 2)
        return [(rng.choice(list(self.kinds)), rng.randint(self.min_step, hi))
                for _ in range(self.episodes)]


def cpu_only_env(devices: int = 1, **extra: str) -> Dict[str, str]:
    """The CLAUDE.md subprocess recipe: never let a killable child touch
    the axon TPU backend (single-grant tunnel). ``devices`` sizes the
    virtual CPU mesh — the elastic ladder episodes model the scheduler
    offering differently-sized slices by varying it per incarnation."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    # never inherit a caller's armed fault hooks
    for k in list(env):
        if k.startswith("HIVED_FAULT_"):
            del env[k]
    env.update(extra)
    return env


def read_timeline(path: str) -> Dict[int, float]:
    """step -> loss from a ``train --timeline`` JSONL (empty if absent)."""
    out: Dict[int, float] = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn final line of a killed incarnation
                out[rec["step"]] = rec["loss"]
    except OSError:
        pass
    return out


class WorkloadChaosHarness:
    """Drive one seeded episode plan over a tiny CPU-only training run.

    ``run()`` executes the plan — each episode launches an incarnation of
    ``python -m hivedscheduler_tpu.train`` against a shared checkpoint
    directory, injects its fault, and asserts the per-fault exit contract —
    then a final incarnation runs to completion and the merged trajectory
    is compared bit-for-bit against an uninterrupted reference run.
    Violations are collected (not raised) and returned in a deterministic
    report dict, mirroring ``chaos.harness.ChaosHarness.run``.
    """

    def __init__(self, seed: int, workdir: str, *, steps: int = 8,
                 checkpoint_every: int = 2,
                 plan: Optional[WorkloadFaultPlan] = None,
                 step_delay_s: float = 0.25, watchdog_secs: float = 2.0,
                 grace_secs: float = 30.0, run_timeout_s: float = 240.0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.workdir = workdir
        self.steps = steps
        self.checkpoint_every = checkpoint_every
        self.plan = plan or WorkloadFaultPlan()
        self.episodes = self.plan.draw(self.rng, steps)
        self.step_delay_s = step_delay_s
        self.watchdog_secs = watchdog_secs
        self.grace_secs = grace_secs
        self.run_timeout_s = run_timeout_s
        self.violations: List[str] = []

    # -- building blocks ---------------------------------------------------
    def train_cmd(self, ckpt_dir: str, timeline: str,
                  steps: Optional[int] = None,
                  goodput: str = "") -> List[str]:
        cmd = [
            sys.executable, "-m", "hivedscheduler_tpu.train",
            "--steps", str(steps if steps is not None else self.steps),
            "--batch", "2", "--seq-len", "16", "--vocab-size", "64",
            "--d-model", "16", "--n-layers", "1", "--n-heads", "2",
            "--d-ff", "32", "--log-every", "100",
            "--checkpoint-dir", ckpt_dir,
            "--checkpoint-every", str(self.checkpoint_every),
            "--timeline", timeline,
            "--grace-secs", str(self.grace_secs),
            "--watchdog-secs", str(self.watchdog_secs),
        ]
        if goodput:
            cmd += ["--goodput-file", goodput]
        return cmd

    def _wait_for_step(self, proc: subprocess.Popen, timeline: str,
                       step: int) -> bool:
        """Poll the incarnation's timeline until ``step`` is recorded (True)
        or the process exits first (False)."""
        deadline = time.monotonic() + self.run_timeout_s
        while time.monotonic() < deadline:
            if read_timeline(timeline).get(step) is not None:
                return True
            if proc.poll() is not None:
                return False
            time.sleep(0.02)
        return False

    def _wait(self, proc: subprocess.Popen, what: str) -> Optional[int]:
        try:
            proc.wait(timeout=self.run_timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            self.violations.append(f"{what}: incarnation did not exit within "
                                   f"{self.run_timeout_s}s")
            return None
        return proc.returncode

    def reference_run(self) -> Dict[int, float]:
        """The uninterrupted ground-truth trajectory (own checkpoint dir)."""
        ck = os.path.join(self.workdir, "ref-ck")
        tl = os.path.join(self.workdir, "ref-timeline.jsonl")
        proc = subprocess.Popen(
            self.train_cmd(ck, tl), cwd=_REPO_ROOT, env=cpu_only_env(),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        rc = self._wait(proc, "reference")
        if rc != 0:
            self.violations.append(f"reference run exited {rc}")
        return read_timeline(tl)

    def check_goodput(self, gp: str, want_torn: int) -> dict:
        """Post-soak goodput audit over the shared spool: conservation per
        summarized incarnation (``check_spool``), the rework classification
        replay, incarnation/torn bookkeeping against the *observed* exits
        (``want_torn`` = incarnations that exited nonzero: SIGKILL and the
        watchdog's ``os._exit`` skip the atexit summary — a fault whose
        step predates the resume point never fires and completes cleanly),
        and SIGTERM → ``checkpoint_save`` non-vacuity. Violations land in
        ``self.violations``; returns the report's ``goodput`` block."""
        from hivedscheduler_tpu.obs import goodput as obs_goodput

        self.violations += obs_goodput.check_spool(gp)
        records = obs_goodput.read_spool(gp)
        self.violations += obs_goodput.check_rework_classification(records)
        agg = obs_goodput.aggregate_spool(records)
        want = len(self.episodes) + 1
        if agg["incarnations"] != want:
            self.violations.append(
                f"goodput spool records {agg['incarnations']} incarnations, "
                f"expected {want} (enable() unreached, or the spool was not "
                f"shared across incarnations)")
        if agg["torn"] != want_torn:
            self.violations.append(
                f"goodput spool has {agg['torn']} torn incarnations, "
                f"expected {want_torn} (incarnations that exited nonzero)")
        if any(kind == "sigterm" for kind, _ in self.episodes):
            if not any(s.get("phases", {}).get("checkpoint_save", 0.0) > 0.0
                       for s in agg["summaries"]):
                self.violations.append(
                    "no summarized incarnation attributed checkpoint_save "
                    "time despite a SIGTERM checkpoint-and-exit episode")
        return {
            "phases": {p: round(s, 6) for p, s in sorted(agg["phases"].items())},
            "goodput_fraction": agg["goodput_fraction"],
            "steps": agg["steps"],
            "rework_steps": agg["rework_steps"],
            "incarnations": agg["incarnations"],
            "torn": agg["torn"],
        }

    # -- the soak ----------------------------------------------------------
    def run(self) -> dict:
        ck = os.path.join(self.workdir, "soak-ck")
        gp = os.path.join(self.workdir, "soak-goodput.jsonl")
        timelines: List[str] = []
        soak_rcs: List[Optional[int]] = []  # goodput torn accounting
        reference = self.reference_run()
        if len(reference) != self.steps:
            self.violations.append(
                f"reference covered {len(reference)}/{self.steps} steps")

        for i, (kind, at_step) in enumerate(self.episodes):
            tl = os.path.join(self.workdir, f"incarnation-{i}.jsonl")
            timelines.append(tl)
            extra = {sup_lib.ENV_FAULT_STEP_DELAY: str(self.step_delay_s)}
            if kind == "hang":
                extra[sup_lib.ENV_FAULT_HANG_AT] = str(at_step)
            proc = subprocess.Popen(
                self.train_cmd(ck, tl, goodput=gp), cwd=_REPO_ROOT,
                env=cpu_only_env(**extra),
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            if kind == "sigkill":
                if self._wait_for_step(proc, tl, at_step):
                    proc.send_signal(signal.SIGKILL)
                rc = self._wait(proc, f"episode {i} ({kind}@{at_step})")
                soak_rcs.append(rc)
                if rc == 0 and read_timeline(tl).get(self.steps) is None:
                    self.violations.append(
                        f"episode {i}: sigkill incarnation exited 0 without "
                        f"finishing")
            elif kind == "sigterm":
                if self._wait_for_step(proc, tl, at_step):
                    proc.send_signal(signal.SIGTERM)
                rc = self._wait(proc, f"episode {i} ({kind}@{at_step})")
                soak_rcs.append(rc)
                if rc != 0:
                    self.violations.append(
                        f"episode {i}: SIGTERM incarnation exited {rc}, "
                        f"expected a clean checkpoint-and-exit (0)")
                from hivedscheduler_tpu.parallel import checkpoint as ckpt_lib

                if ckpt_lib.latest_step(ck) is None:
                    self.violations.append(
                        f"episode {i}: SIGTERM left no committed checkpoint")
            else:  # hang
                rc = self._wait(proc, f"episode {i} ({kind}@{at_step})")
                soak_rcs.append(rc)
                if rc != sup_lib.EXIT_STALLED:
                    self.violations.append(
                        f"episode {i}: hung incarnation exited {rc}, "
                        f"expected EXIT_STALLED ({sup_lib.EXIT_STALLED})")
                if not os.path.exists(
                        os.path.join(ck, sup_lib.STALL_RECORD)):
                    self.violations.append(
                        f"episode {i}: watchdog left no stall record")

        # final incarnation: run to completion
        tl = os.path.join(self.workdir, "incarnation-final.jsonl")
        timelines.append(tl)
        proc = subprocess.Popen(
            self.train_cmd(ck, tl, goodput=gp), cwd=_REPO_ROOT,
            env=cpu_only_env(),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        rc = self._wait(proc, "final incarnation")
        soak_rcs.append(rc)
        if rc != 0:
            self.violations.append(f"final incarnation exited {rc}")

        # bit-exactness: EVERY step any incarnation ever recorded must match
        # the uninterrupted reference — replayed steps (between the restored
        # checkpoint and the kill point) included; a mismatch means the
        # resume silently changed the data stream or the restored state
        covered: set = set()
        for t in timelines:
            for step, loss in read_timeline(t).items():
                covered.add(step)
                ref = reference.get(step)
                if ref is None:
                    self.violations.append(
                        f"{os.path.basename(t)}: step {step} beyond the "
                        f"reference run")
                elif loss != ref:
                    self.violations.append(
                        f"{os.path.basename(t)}: step {step} loss {loss!r} "
                        f"!= reference {ref!r} (resume not bit-exact)")
        missing = set(range(1, self.steps + 1)) - covered
        if missing:
            self.violations.append(
                f"steps never executed by any incarnation: {sorted(missing)}")

        goodput_report = self.check_goodput(
            gp, want_torn=sum(1 for r in soak_rcs if r != 0))

        return {
            "seed": self.seed,
            "episodes": [list(e) for e in self.episodes],
            "steps": self.steps,
            "incarnations": len(self.episodes) + 1,
            "goodput": goodput_report,
            "violations": list(self.violations),
        }


class ElasticWorkloadHarness:
    """The elastic end-to-end episode: kill -9 mid-step on the full slice →
    shrink resume on HALF the devices (cross-topology restore) → grow
    promote back to the full slice, all against one checkpoint directory.

    Models what the scheduler's elastic arm does to a training job
    (doc/design/elastic.md): the full-shape incarnation dies hard, the next
    incarnation is offered a degraded slice (``--elastic`` derives a
    smaller mesh and the checkpoint reshards on load), and once capacity
    "frees" the grow-promotion evicts (SIGTERM → checkpoint-and-exit-0)
    and restarts at full shape. Cross-topology resumes change reduction
    orders, so the merged trajectory is pinned **allclose** against an
    uninterrupted full-slice reference (LOSS_ATOL) — the same-topology
    bit-exactness discipline stays with :class:`WorkloadChaosHarness`.
    The checkpoint metadata is additionally asserted to record each
    incarnation's mesh (the cross-topology marker trail).
    """

    FULL_DEVICES = 2
    SHRUNK_DEVICES = 1
    # bf16 compute: measured cross-reduction-order drift is ~1e-4 absolute
    # over 8 steps on the CPU mesh; 0.02 keeps real resume bugs (wrong
    # step, replayed/skipped data: whole-loss-scale errors) detectable
    LOSS_ATOL = 0.02

    # scheduler-busy vs workload-observed slack per incarnation: interpreter
    # startup + jax import before goodput.enable(), teardown after close,
    # and the killed incarnation's open interval all burn busy_guaranteed
    # seconds the workload never attributes (measured ~2-4 s each on the
    # 1-core dev box; generous so a loaded box doesn't flake)
    BRIDGE_SLACK_PER_INCARNATION_S = 20.0

    def __init__(self, seed: int, workdir: str, *, steps: int = 8,
                 checkpoint_every: int = 2, step_delay_s: float = 0.25,
                 grace_secs: float = 30.0, run_timeout_s: float = 240.0,
                 bridge_ledger: bool = False, reference: bool = True):
        self.seed = seed
        rng = random.Random(seed)
        self.workdir = workdir
        self.steps = steps
        self.checkpoint_every = checkpoint_every
        self.step_delay_s = step_delay_s
        self.grace_secs = grace_secs
        self.run_timeout_s = run_timeout_s
        # bridge_ledger: meter each incarnation's lifetime as a
        # busy_guaranteed interval on a parent-side CapacityLedger and
        # reconcile it against the workload's own phase accounting
        # (goodput.reconcile_busy) — the workload<->capacity bridge.
        # reference=False skips the uninterrupted reference run and the
        # loss comparison (the bench's goodput stage only needs the fault
        # episode + the accounting, not the trajectory pin).
        self.bridge_ledger = bridge_ledger
        self.reference = reference
        # the hard kill lands after the first possible commit; the
        # cooperative preemption (grow offer) lands strictly later so the
        # degraded incarnation does real work first
        self.kill_step = rng.randint(checkpoint_every + 1, steps - 3)
        self.preempt_step = rng.randint(self.kill_step + 1, steps - 2)
        self.violations: List[str] = []

    def train_cmd(self, ckpt_dir: str, timeline: str,
                  goodput: str = "") -> List[str]:
        cmd = [
            sys.executable, "-m", "hivedscheduler_tpu.train",
            "--steps", str(self.steps),
            "--batch", "2", "--seq-len", "16", "--vocab-size", "64",
            "--d-model", "16", "--n-layers", "1", "--n-heads", "2",
            "--d-ff", "32", "--log-every", "100",
            "--elastic", "--min-chips", "1",
            "--checkpoint-dir", ckpt_dir,
            "--checkpoint-every", str(self.checkpoint_every),
            "--timeline", timeline,
            "--grace-secs", str(self.grace_secs),
        ]
        if goodput:
            cmd += ["--goodput-file", goodput]
        return cmd

    def _spawn(self, ckpt: str, timeline: str, devices: int,
               paced: bool, goodput: str = "") -> subprocess.Popen:
        extra = ({sup_lib.ENV_FAULT_STEP_DELAY: str(self.step_delay_s)}
                 if paced else {})
        return subprocess.Popen(
            self.train_cmd(ckpt, timeline, goodput=goodput), cwd=_REPO_ROOT,
            env=cpu_only_env(devices=devices, **extra),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def _wait_for_step(self, proc, timeline: str, step: int) -> bool:
        deadline = time.monotonic() + self.run_timeout_s
        while time.monotonic() < deadline:
            if read_timeline(timeline).get(step) is not None:
                return True
            if proc.poll() is not None:
                return False
            time.sleep(0.02)
        return False

    def _wait(self, proc, what: str) -> Optional[int]:
        try:
            proc.wait(timeout=self.run_timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            self.violations.append(f"{what}: incarnation did not exit within "
                                   f"{self.run_timeout_s}s")
            return None
        return proc.returncode

    def _checkpoint_mesh(self, ckpt: str) -> Optional[dict]:
        from hivedscheduler_tpu.parallel import checkpoint as ckpt_lib

        return ckpt_lib.read_metadata(ckpt).get("mesh")

    def _bridge_begin(self, ledger) -> None:
        if ledger is not None:
            ledger.transition("workload-host", [0], "busy_guaranteed",
                              gang="elastic-train")

    def _bridge_end(self, ledger) -> None:
        if ledger is not None:
            ledger.release("workload-host", [0])

    def run(self) -> dict:
        ck = os.path.join(self.workdir, "elastic-ck")
        gp = os.path.join(self.workdir, "elastic-goodput.jsonl")
        timelines: List[str] = []

        ledger = None
        if self.bridge_ledger:
            # the scheduler side of the bridge: a private 1-chip ledger
            # metering each incarnation's spawn->exit span as the gang's
            # busy_guaranteed interval (what the cluster would bill)
            from hivedscheduler_tpu.obs import ledger as ledger_lib

            ledger = ledger_lib.CapacityLedger(metrics=False)
            ledger.enabled = True
            ledger.register_node("workload-host", 1)

        reference: Dict[int, float] = {}
        if self.reference:
            # uninterrupted full-slice reference (own checkpoint dir)
            ref_tl = os.path.join(self.workdir, "elastic-ref.jsonl")
            proc = self._spawn(os.path.join(self.workdir, "elastic-ref-ck"),
                               ref_tl, self.FULL_DEVICES, paced=False)
            if self._wait(proc, "reference") != 0:
                self.violations.append("reference run failed")
            reference = read_timeline(ref_tl)
            if len(reference) != self.steps:
                self.violations.append(
                    f"reference covered {len(reference)}/{self.steps} steps")

        # 1. full slice, kill -9 mid-step
        tl = os.path.join(self.workdir, "elastic-full.jsonl")
        timelines.append(tl)
        self._bridge_begin(ledger)
        proc = self._spawn(ck, tl, self.FULL_DEVICES, paced=True, goodput=gp)
        if self._wait_for_step(proc, tl, self.kill_step):
            proc.send_signal(signal.SIGKILL)
        self._wait(proc, f"full incarnation (sigkill@{self.kill_step})")
        self._bridge_end(ledger)
        mesh = self._checkpoint_mesh(ck)
        if mesh is None:
            self.violations.append("full incarnation left no committed "
                                   "checkpoint to shrink-resume from")
        elif mesh.get("dp") != self.FULL_DEVICES:
            self.violations.append(
                f"full incarnation's checkpoint records mesh {mesh}, "
                f"expected dp={self.FULL_DEVICES}")

        # 2. shrink resume on the degraded slice; SIGTERM = the grow offer
        #    evicting it (checkpoint-and-exit-0)
        tl = os.path.join(self.workdir, "elastic-shrunk.jsonl")
        timelines.append(tl)
        self._bridge_begin(ledger)
        proc = self._spawn(ck, tl, self.SHRUNK_DEVICES, paced=True,
                           goodput=gp)
        if self._wait_for_step(proc, tl, self.preempt_step):
            proc.send_signal(signal.SIGTERM)
        rc = self._wait(proc, f"shrunk incarnation (sigterm@{self.preempt_step})")
        if rc != 0:
            self.violations.append(
                f"shrunk incarnation exited {rc}, expected a clean "
                f"checkpoint-and-exit (0)")
        self._bridge_end(ledger)
        mesh = self._checkpoint_mesh(ck)
        if mesh is not None and mesh.get("dp") != self.SHRUNK_DEVICES:
            self.violations.append(
                f"shrunk incarnation's checkpoint records mesh {mesh}, "
                f"expected dp={self.SHRUNK_DEVICES} (cross-topology "
                f"metadata trail broken)")

        # 3. grow promote: back to the full slice, run to completion
        tl = os.path.join(self.workdir, "elastic-grown.jsonl")
        timelines.append(tl)
        self._bridge_begin(ledger)
        proc = self._spawn(ck, tl, self.FULL_DEVICES, paced=False, goodput=gp)
        rc = self._wait(proc, "grown incarnation")
        if rc != 0:
            self.violations.append(f"grown incarnation exited {rc}")
        self._bridge_end(ledger)

        # the merged trajectory stays allclose to the uninterrupted
        # reference: a resume that replayed/skipped data or restored the
        # wrong state shows up as a whole-loss-scale divergence
        covered: set = set()
        for t in timelines:
            for step, loss in read_timeline(t).items():
                covered.add(step)
                if not self.reference:
                    continue
                ref = reference.get(step)
                if ref is None:
                    self.violations.append(
                        f"{os.path.basename(t)}: step {step} beyond the "
                        f"reference run")
                elif abs(loss - ref) > self.LOSS_ATOL:
                    self.violations.append(
                        f"{os.path.basename(t)}: step {step} loss {loss!r} "
                        f"vs reference {ref!r} exceeds atol "
                        f"{self.LOSS_ATOL} (elastic resume diverged)")
        missing = set(range(1, self.steps + 1)) - covered
        if missing:
            self.violations.append(
                f"steps never executed by any incarnation: {sorted(missing)}")

        busy_s = None
        if ledger is not None:
            busy_s = sum(ledger.gang_seconds("elastic-train").values())
        goodput_report = self.check_goodput(gp, busy_s)

        return {
            "seed": self.seed,
            "kind": "elastic",
            "kill_step": self.kill_step,
            "preempt_step": self.preempt_step,
            "steps": self.steps,
            "incarnations": 3,
            "goodput": goodput_report,
            "violations": list(self.violations),
        }

    def check_goodput(self, gp: str, busy_s: Optional[float]) -> dict:
        """Post-episode goodput audit: conservation (``check_spool``), the
        rework replay, torn/incarnation bookkeeping (exactly the sigkilled
        full-slice incarnation is torn), rework and ``checkpoint_save``
        non-vacuity, and — when the bridge ledger ran — the
        workload<->capacity reconciliation (``reconcile_busy``)."""
        from hivedscheduler_tpu.obs import goodput as obs_goodput

        self.violations += obs_goodput.check_spool(gp)
        records = obs_goodput.read_spool(gp)
        self.violations += obs_goodput.check_rework_classification(records)
        agg = obs_goodput.aggregate_spool(records)
        if agg["incarnations"] != 3:
            self.violations.append(
                f"goodput spool records {agg['incarnations']} incarnations, "
                f"expected 3 (kill -> shrink -> grow)")
        if agg["torn"] != 1:
            self.violations.append(
                f"goodput spool has {agg['torn']} torn incarnations, "
                f"expected exactly the sigkilled full-slice one")
        if self.kill_step % self.checkpoint_every != 0 \
                and agg["rework_steps"] == 0:
            # a kill between commits forces the shrink resume to re-train
            # from the last committed step; zero rework here means the
            # classification (or the cross-incarnation seed replay) broke
            self.violations.append(
                f"kill@{self.kill_step} landed between commits "
                f"(checkpoint_every={self.checkpoint_every}) yet the spool "
                f"attributes 0 rework steps")
        if not any(s.get("phases", {}).get("checkpoint_save", 0.0) > 0.0
                   for s in agg["summaries"]):
            self.violations.append(
                "no summarized incarnation attributed checkpoint_save time "
                "despite the SIGTERM grow offer's checkpoint-and-exit")
        report = {
            "phases": {p: round(s, 6) for p, s in sorted(agg["phases"].items())},
            "goodput_fraction": agg["goodput_fraction"],
            "steps": agg["steps"],
            "rework_steps": agg["rework_steps"],
            "incarnations": agg["incarnations"],
            "torn": agg["torn"],
        }
        if busy_s is not None:
            slack = 3 * self.BRIDGE_SLACK_PER_INCARNATION_S
            violation = obs_goodput.reconcile_busy(
                busy_s, agg["observed_s"], slack_s=slack)
            if violation:
                self.violations.append(violation)
            report["bridge"] = {
                "busy_guaranteed_s": round(busy_s, 6),
                "observed_s": round(agg["observed_s"], 6),
                "uncovered_s": round(busy_s - agg["observed_s"], 6),
                "slack_s": slack,
            }
        return report
