"""Structural invariants of the scheduler algorithm, re-derived from scratch.

One reusable checker shared by the chaos harness (``chaos.harness``), the
randomized fuzz (``tests/test_invariant_fuzz.py``) and the pinned-seed replay
tool (``tools/check_chaos_seeds.py``). Every check recomputes its ground truth
from the cell trees instead of trusting the algorithm's own books, so drift in
the incremental bookkeeping cannot hide itself:

- **VC safety** (the paper's core guarantee, reference
  ``hived_algorithm.go:1242-1292``): at every chain/level,
  ``totalLeftCellNum >= allVCFreeCellNum`` — no tenant can be pushed under
  quota by other tenants' allocations.
- **Used-count books**: each cell's ``used_leaf_cell_num_at_priorities``
  equals a recount of its allocated leaf descendants, on the physical AND
  every virtual tree.
- **Priority max-invariant**: ``parent.priority == max(children priorities)``
  (reference ``cell_allocation.go:425-441``).
- **Free-list hygiene**: no free cell carries a guaranteed priority (a
  leaked VC binding).
- **No leaked or doubly-allocated cells**: the set of physical leaf cells
  carrying a used priority must exactly tile the union of all affinity-group
  placements, with no leaf owned by two non-preempting groups (a preemptor in
  ``Preempting`` state legitimately *reserves* cells a victim still uses).
- **Gang atomicity**: an ``Allocated`` group's placement is fully decided
  (no ``None`` slot), and — at quiescent points, where the caller passes the
  gangs it believes complete — every member pod slot is filled: never a
  partially-bound affinity group.
- **Placement preservation**: chip-granular (node -> exact leaf-cell
  indices) equality across a crash-restart — the same contract as
  ``tests/test_recovery_scale.py`` (same nodes but different chips counts as
  lost: ICI contiguity is broken).

All checks raise :class:`InvariantViolation` (an ``AssertionError`` subclass,
so plain ``assert``-style consumers and pytest treat it naturally).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

from hivedscheduler_tpu.algorithm.constants import (
    FREE_PRIORITY,
    GROUP_ALLOCATED,
    GROUP_PREEMPTING,
    MIN_GUARANTEED_PRIORITY,
)


class InvariantViolation(AssertionError):
    """A structural guarantee of the scheduler was broken."""


def _fail(ctx: str, msg: str) -> None:
    raise InvariantViolation(f"{ctx}: {msg}" if ctx else msg)


def _all_cells(ccl):
    for level in sorted(ccl):
        for c in ccl[level]:
            yield c


def _leaf_descendants(c):
    if not c.children:
        yield c
        return
    for ch in c.children:
        yield from _leaf_descendants(ch)


# ---------------------------------------------------------------------------
# individual checks
# ---------------------------------------------------------------------------

def check_vc_safety(algo, ctx: str = "") -> None:
    """totalLeftCellNum >= allVCFreeCellNum at every chain/level."""
    for chain, levels in algo.total_left_cell_num.items():
        for level, left in levels.items():
            free = algo.all_vc_free_cell_num.get(chain, {}).get(level, 0)
            if left < free:
                _fail(ctx, f"VC safety broken: chain {chain} level {level}: "
                           f"{left} left < {free} free in all VCs")


def check_books(algo, ctx: str = "") -> None:
    """Used-count recount + priority max-invariant on the physical and every
    virtual tree, plus free-list hygiene."""
    trees = list(algo.full_cell_list.items()) + [
        (f"{vcn}/{chain}", ccl)
        for vcn, sched in algo.vc_schedulers.items()
        for chain, ccl in sched.non_pinned_full_cell_list.items()
    ]
    for label, ccl in trees:
        for c in _all_cells(ccl):
            recount: Dict[int, int] = {}
            for leaf in _leaf_descendants(c):
                if leaf.priority != FREE_PRIORITY:
                    recount[leaf.priority] = recount.get(leaf.priority, 0) + 1
            if dict(c.used_leaf_cell_num_at_priorities) != recount:
                _fail(ctx, f"used-count books drifted at {label}:{c.address}: "
                           f"{c.used_leaf_cell_num_at_priorities} != recount "
                           f"{recount}")
            if c.children:
                max_child = max(ch.priority for ch in c.children)
                if c.priority != max_child:
                    _fail(ctx, f"priority invariant broken at {label}:"
                               f"{c.address}: {c.priority} != max(children) "
                               f"{max_child}")
    for chain, fl in algo.free_cell_list.items():
        for level in sorted(fl):
            for c in fl[level]:
                if c.priority >= MIN_GUARANTEED_PRIORITY:
                    _fail(ctx, f"free cell {c.address} carries guaranteed "
                               f"priority {c.priority}")


def check_cell_ownership(algo, ctx: str = "") -> None:
    """No leaked and no doubly-allocated leaf cells.

    - *Double allocation*: a physical leaf cell placed in two groups that
      both really hold it (``Allocated``/``BeingPreempted``). A
      ``Preempting`` group's placement legitimately overlaps its victims'
      (its cells are Reserving while the victim still runs), so preemptors
      are excluded from the uniqueness check.
    - *Leak*: a leaf cell carrying a used (non-FREE) priority that belongs
      to no group's placement — an allocation whose owner vanished.
    """
    owners: Dict[str, List[str]] = {}     # leaf address -> owning group names
    placed: Set[str] = set()              # union over ALL groups (any state)
    for g in algo.affinity_groups.values():
        for podps in g.physical_leaf_cell_placement.values():
            for podp in podps:
                for c in podp:
                    if c is None:
                        continue
                    placed.add(c.address)
                    if g.state != GROUP_PREEMPTING:
                        owners.setdefault(c.address, [])
                        if g.name not in owners[c.address]:
                            owners[c.address].append(g.name)
    for addr, names in owners.items():
        if len(names) > 1:
            _fail(ctx, f"leaf cell {addr} doubly allocated to groups {names}")
    for chain, ccl in algo.full_cell_list.items():
        for top in ccl[max(ccl)]:
            for leaf in _leaf_descendants(top):
                if leaf.priority != FREE_PRIORITY and leaf.address not in placed:
                    _fail(ctx, f"leaf cell {leaf.address} (chain {chain}) "
                               f"carries priority {leaf.priority} but belongs "
                               f"to no affinity group — leaked allocation")


def check_gang_atomicity(
    algo,
    ctx: str = "",
    full_groups: Optional[Iterable[str]] = None,
    allow_partial_placement: bool = False,
) -> None:
    """Never a partially-bound affinity group.

    Structural part: every ``Allocated`` group's physical placement is
    fully decided — no ``None`` cell slot (the gang's slice was committed
    atomically at schedule time). ``allow_partial_placement=True`` waives
    it for *reconfiguration* replays: the tolerance ladder deliberately
    ignores placements on chains that vanished from the new config (the
    pods are still absorbed, never lost — see PARITY.md), which leaves
    legitimate undecided slots.

    Quiescent part (when ``full_groups`` is given — the gang names the
    caller believes completely bound, with nothing mid-flight): the set of
    ``Allocated`` groups must equal ``full_groups`` exactly, and each must
    have every member pod slot filled.
    """
    allocated = {
        g.name: g for g in algo.affinity_groups.values()
        if g.state == GROUP_ALLOCATED
    }
    if not allow_partial_placement:
        for name, g in allocated.items():
            for ln, podps in g.physical_leaf_cell_placement.items():
                for i, podp in enumerate(podps):
                    if any(c is None for c in podp):
                        _fail(ctx, f"group {name} member {ln}x#{i} has an "
                                   f"undecided cell slot in an Allocated "
                                   f"group")
    if full_groups is None:
        return
    expected = set(full_groups)
    if set(allocated) != expected:
        _fail(ctx, f"gang atomicity: allocated groups {sorted(allocated)} != "
                   f"expected complete gangs {sorted(expected)}")
    for name in expected:
        g = allocated[name]
        for ln, pods in g.allocated_pods.items():
            missing = sum(1 for p in pods if p is None)
            if missing:
                _fail(ctx, f"group {name} is partially bound: {missing} of "
                           f"{len(pods)} member pods ({ln} cells each) "
                           f"never bound")


def _all_topology_schedulers(algo):
    """Every live TopologyAwareScheduler of the algorithm (opportunistic
    per chain, per-VC non-pinned per chain, per-VC pinned)."""
    for chain, s in algo.opportunistic_schedulers.items():
        yield f"opportunistic/{chain}", s
    for vcn, vcs in algo.vc_schedulers.items():
        for chain, s in vcs.non_pinned_cell_schedulers.items():
            yield f"{vcn}/{chain}", s
        for pid, s in vcs.pinned_cell_schedulers.items():
            yield f"{vcn}/pinned:{pid}", s


def check_cluster_views(algo, ctx: str = "") -> None:
    """The persistent incremental cluster views must equal a from-scratch
    rebuild (the perf-PR contract: dirty tracking may defer work, never
    change results).

    - *Node set*: the static view holds exactly the cells a fresh
      ``_new_cluster_view`` over the same ChainCellList extracts, in order
      (topology never changes, so any drift is a bug).
    - *Scoring state*: for every node the view believes CURRENT
      (``seen_gen == cell.view_gen``), the cached free/same/higher counters
      must equal a fresh recompute at the node's ``seen_priority`` — this
      is precisely what catches a mutation site that forgot to bump
      ``view_gen`` (stale counters masquerading as current).
    - *Native buffers*: the persistent score buffers feeding the C packing
      call are written in lockstep with the node fields, so they must
      mirror them at all times.
    - *Cached ancestor/enclosure structure*: rebuilt from the cell parents,
      the static enclosure member lists must match bit-for-bit.
    """
    from hivedscheduler_tpu.algorithm.topology_aware import (
        _Node,
        _new_cluster_view,
        _node_healthy_and_in_suggested,
    )

    for label, s in _all_topology_schedulers(algo):
        fresh = _new_cluster_view(s.ccl)
        if [n.cell.address for n in fresh] != [n.cell.address for n in s.cv]:
            _fail(ctx, f"cluster view {label}: node set drifted from "
                       f"from-scratch rebuild")
        for i, n in enumerate(s.cv):
            if n.seen_priority is None or n.seen_gen != n.cell.view_gen:
                continue  # legitimately stale: will refresh before next use
            ref = _Node(n.cell)
            ref.update_used_leaf_cell_num_for_priority(
                n.seen_priority, s.cross_priority_pack
            )
            fresh_healthy, _, _ = _node_healthy_and_in_suggested(
                n, set(), True
            )
            if fresh_healthy != n.healthy:
                _fail(ctx, f"cluster view {label} node {n.cell.address}: "
                           f"cached healthiness stale while marked current")
            if (
                ref.free_leaf_cell_num_at_priority
                != n.free_leaf_cell_num_at_priority
                or ref.used_leaf_cell_num_same_priority
                != n.used_leaf_cell_num_same_priority
                or ref.used_leaf_cell_num_higher_priority
                != n.used_leaf_cell_num_higher_priority
            ):
                _fail(ctx, f"cluster view {label} node {n.cell.address}: "
                           f"cached counters stale while marked current "
                           f"(missed view_gen bump?): cached "
                           f"({n.free_leaf_cell_num_at_priority}, "
                           f"{n.used_leaf_cell_num_same_priority}, "
                           f"{n.used_leaf_cell_num_higher_priority}) != fresh "
                           f"({ref.free_leaf_cell_num_at_priority}, "
                           f"{ref.used_leaf_cell_num_same_priority}, "
                           f"{ref.used_leaf_cell_num_higher_priority})")
        state = s._native_pack
        if state and state is not False:
            for i, n in enumerate(s.cv):
                if (
                    state["healthy_buf"][i] != (1 if n.healthy else 0)
                    or state["suggested_buf"][i] != (1 if n.suggested else 0)
                    or state["same_buf"][i]
                    != n.used_leaf_cell_num_same_priority
                    or state["higher_buf"][i]
                    != n.used_leaf_cell_num_higher_priority
                    or state["free_buf"][i]
                    != n.free_leaf_cell_num_at_priority
                ):
                    _fail(ctx, f"cluster view {label} node {n.cell.address}: "
                               f"native score buffer out of sync with the "
                               f"Python view")
            if sorted(state["order_buf"]) != list(range(len(s.cv))):
                _fail(ctx, f"cluster view {label}: native order buffer is "
                           f"not a permutation")
        # static enclosure structure == rebuild from cell parents
        rebuilt = {}
        for i, n in enumerate(s.cv):
            anc = n.cell.parent
            while anc is not None:
                rebuilt.setdefault((anc.level, anc.address), []).append(i)
                anc = anc.parent
        rebuilt_list = [
            (lv, members) for (lv, _a), members in sorted(
                rebuilt.items(), key=lambda kv: kv[0][0]
            )
        ]
        if rebuilt_list != s._enclosures:
            _fail(ctx, f"cluster view {label}: cached enclosure structure "
                       f"drifted from topology rebuild")


def check_defrag(scheduler, ctx: str = "") -> None:
    """Structural invariants of the defrag executor's reservation +
    migration state machine (runtime/scheduler.py; in-memory by design, so
    a crash-restart must come back with NOTHING — recovery rebuilds
    allocations from bound pods only):

    - **No orphaned reservation**: every reservation's holder is alive —
      a waiter reservation's holder is a recorded waiter, an in-flight
      migration's waiter, or an already-allocated group *momentarily*
      between placement and release (never observed at a quiescent check);
      a migration reservation's migration must exist and be active.
    - **No double hold**: two reservations never hold the same node (a
      plan that reserved overlapping slices would dead-lock itself).
    - **No half-released mover**: an Evicting move's group is either still
      fully allocated (eviction in flight) or completely gone — a group
      absent from the algorithm with member pods still in
      ``pod_schedule_statuses`` would be a placement leak.
    - **Terminal migrations hold nothing**: Done/Failed/Aborted migrations
      have no reservations left.
    """
    reservations = getattr(scheduler, "_reservations", None)
    migrations = getattr(scheduler, "_migrations", None)
    if reservations is None or migrations is None:
        return  # pre-defrag scheduler object: nothing to check
    algo = scheduler.scheduler_algorithm
    seen_nodes: Dict[str, str] = {}
    for res in reservations.values():
        for n in res.nodes:
            if n in seen_nodes and seen_nodes[n] != res.holder:
                _fail(ctx, f"node {n} reserved for both {seen_nodes[n]} "
                           f"and {res.holder} — double hold")
            seen_nodes[n] = res.holder
        mig = migrations.get(res.migration_id) if res.migration_id else None
        if res.kind == "migration":
            if mig is None or not mig.active:
                _fail(ctx, f"migration reservation for {res.holder} has no "
                           f"active migration ({res.migration_id}) — "
                           f"orphaned reservation")
        elif res.kind == "waiter":
            holder_live = (
                res.holder in getattr(scheduler, "_defrag_waiters", {})
                or res.holder in algo.affinity_groups
                or (mig is not None and mig.active)
                or any(m.waiter == res.holder and m.active
                       for m in migrations.values())
            )
            if not holder_live:
                _fail(ctx, f"waiter reservation for {res.holder} has no "
                           f"live waiter, group, or migration — orphaned "
                           f"reservation")
    for mig in migrations.values():
        held = [r for r in reservations.values()
                if r.migration_id == mig.id]
        if not mig.active:
            if mig.state == "Done":
                # a completed consolidation legitimately keeps the WAITER
                # hold until the waiter binds (or TTL); move-target holds
                # must be gone
                leftover = [r.holder for r in held if r.kind != "waiter"
                            or r.holder != mig.waiter]
            else:
                leftover = [r.holder for r in held]
            if leftover:
                _fail(ctx, f"terminal migration {mig.id} ({mig.state}) "
                           f"still holds reservations for {leftover}")
        for move in mig.moves:
            if not mig.active:
                continue
            group_alive = move.group in algo.affinity_groups
            pods_tracked = [
                p.uid for p in move.evicted_pods
                if p.uid in scheduler.pod_schedule_statuses
            ]
            if move.state == "Evicting" and not group_alive and pods_tracked:
                # the informer deletes a pod's status and its allocation in
                # one locked block, and the group only dies when the last
                # pod releases — a dead group with tracked member pods is
                # unreachable unless that atomicity broke
                _fail(ctx, f"mover {move.group} of {mig.id} is half-released"
                           f": group gone but pods {pods_tracked} still "
                           f"tracked — placement leak window")


def check_journal(journal=None, ctx: str = "") -> None:
    """Structural invariants of the gang-lifecycle journal
    (obs/journal.py). No-op when the journal is disabled, so every
    existing soak covers it for free once the harness opts in:

    - **Causal integrity**: every event's ``cause`` points BACKWARD to an
      event id that is retained (the ring evicts oldest-first, so retained
      ids are contiguous — a cause inside the retained range that is
      missing, or a cause >= its own event id, is an orphan/cycle).
    - **Complete lifecycles**: a terminal event (``released`` /
      ``serve_finish`` / ``serve_shed``) requires an open episode — an
      opening event for the same gang after its previous terminal. Two
      terminals with no re-open between them is a duplicate close. The
      open-before-close direction is only enforced while the ring has
      never evicted (a wrapped ring may have dropped the opener).
    """
    from hivedscheduler_tpu.obs import journal as obs_journal

    j = journal if journal is not None else obs_journal.JOURNAL
    if not j.enabled:
        return
    events = j.snapshot()
    if not events:
        return
    ids = {e.id for e in events}
    min_id = min(ids)
    terminal_types = {"released", "serve_finish", "serve_shed"}
    full_history = j.evicted == 0
    open_state: Dict[str, Optional[bool]] = {}  # gang -> episode open?
    for e in events:
        if e.cause is not None:
            if e.cause >= e.id:
                _fail(ctx, f"journal event {e.id} ({e.type}, gang {e.gang}) "
                           f"names a non-backward cause {e.cause}")
            if e.cause >= min_id and e.cause not in ids:
                _fail(ctx, f"journal event {e.id} ({e.type}, gang {e.gang}) "
                           f"has an orphan cause {e.cause} — the cause id "
                           f"is inside the retained range but missing")
        is_open = open_state.get(e.gang)
        if e.type in terminal_types:
            if is_open is False:
                _fail(ctx, f"journal gang {e.gang}: duplicate terminal "
                           f"event {e.type} (id {e.id}) with no re-open "
                           f"since the previous close")
            if is_open is None and full_history:
                _fail(ctx, f"journal gang {e.gang}: terminal event "
                           f"{e.type} (id {e.id}) with no opening event — "
                           f"incomplete open->close lifecycle")
            open_state[e.gang] = False
        else:
            open_state[e.gang] = True


def check_ledger(ledger=None, ctx: str = "",
                 at: Optional[float] = None) -> None:
    """Structural invariants of the capacity ledger (obs/ledger.py).
    No-op while the ledger is disabled, so every soak covers it for free
    once the harness opts in:

    - **Conservation**: the per-(state, vc, chain) chip-second buckets —
      closed intervals plus open intervals measured to ``at`` — sum to
      ``sum over chips (at - registered_at)``. A lost or double-opened
      interval breaks the telescoping sum and trips here.
    - **Occupancy totals**: the per-state chip counts sum to the
      registered chip count (every chip is in exactly one state).
    - **Registered states only**: no chip is in a state missing from
      ``CHIP_STATES`` (the OBS002 runtime half).

    Individual buckets are NOT asserted non-negative: the bench's
    virtual-clock replay legitimately reattributes a moved gang's
    checkpoint downtime out of busy *before* the gang has re-accrued it
    (see ``CapacityLedger.reattribute``); only the total is conserved.
    """
    from hivedscheduler_tpu.obs import ledger as obs_ledger

    l = ledger if ledger is not None else obs_ledger.LEDGER
    if not l.enabled:
        return
    t = l._now(at)
    totals = l.totals(t)
    for (state, _vc, _chain) in totals:
        if state not in obs_ledger.CHIP_STATES:
            _fail(ctx, f"ledger bucket carries unregistered chip state "
                       f"{state!r} — OBS002 registry drift")
    expected = l.expected_chip_seconds(t)
    got = sum(totals.values())
    if abs(got - expected) > 1e-6 * max(1.0, expected):
        _fail(ctx, f"ledger conservation broken: buckets sum to "
                   f"{got!r} chip-seconds but chips x wallclock is "
                   f"{expected!r} — an interval was lost or double-opened")
    occ = l.occupancy()
    for state in occ:
        if state not in obs_ledger.CHIP_STATES:
            _fail(ctx, f"ledger occupancy carries unregistered chip "
                       f"state {state!r}")
    chips = l.chips()
    if sum(occ.values()) != chips:
        _fail(ctx, f"ledger occupancy sums to {sum(occ.values())} chips "
                   f"but {chips} are registered — a chip is in zero or "
                   f"two states")


def check_goodput(goodput=None, ctx: str = "",
                  at: Optional[float] = None) -> None:
    """Structural invariants of the workload goodput ledger
    (obs/goodput.py). No-op while disabled, so every soak covers it for
    free once the workload opts in:

    - **Conservation**: the per-phase seconds — closed intervals plus
      the open phase measured to ``at`` — sum to the process wallclock
      since ``start()``. A lost or double-opened interval breaks the
      telescoping sum and trips here.
    - **Registered phases only**: no accumulated time in a phase missing
      from ``STEP_PHASES`` (the OBS003 runtime half).
    - **Exactly one open phase**: once started and not yet closed, the
      workload is always *in* a phase (the per-instant analogue of the
      capacity ledger's one-state-per-chip rule).

    The cross-process form — per-incarnation conservation from a shared
    ``--goodput-file`` spool — is ``goodput.check_spool``; the chaos
    workload harnesses run it after every soak."""
    from hivedscheduler_tpu.obs import goodput as obs_goodput

    g = goodput if goodput is not None else obs_goodput.GOODPUT
    if not g.enabled:
        return
    t = g._now(at)
    totals = g.totals(t)
    for phase in totals:
        if phase not in obs_goodput.STEP_PHASES:
            _fail(ctx, f"goodput accumulator carries unregistered step "
                       f"phase {phase!r} — OBS003 registry drift")
    wall = g.wallclock(t)
    got = sum(totals.values())
    if abs(got - wall) > 1e-6 * max(1.0, wall):
        _fail(ctx, f"goodput conservation broken: phases sum to {got!r}s "
                   f"but the process wallclock is {wall!r}s — an interval "
                   f"was lost or double-opened")
    if wall > 0 and not g._closed and g.current_phase() is None:
        _fail(ctx, "goodput ledger started but in no phase — the "
                   "workload must be in exactly one STEP_PHASES phase "
                   "at every instant")


def check_all(
    algo,
    ctx: str = "",
    full_groups: Optional[Iterable[str]] = None,
    allow_partial_placement: bool = False,
    scheduler=None,
    router=None,
) -> None:
    """Run every algorithm-state invariant (one locked snapshot per check).
    Pass the owning ``HivedScheduler`` as ``scheduler`` to additionally
    check the defrag reservation/migration state machine, and a
    ``fleet.FleetRouter`` as ``router`` for the serving-fleet invariants.
    The journal, capacity-ledger and goodput-ledger checks piggyback on
    every call (no-ops while disabled)."""
    check_vc_safety(algo, ctx)
    check_books(algo, ctx)
    check_cell_ownership(algo, ctx)
    check_cluster_views(algo, ctx)
    check_gang_atomicity(algo, ctx, full_groups=full_groups,
                         allow_partial_placement=allow_partial_placement)
    if scheduler is not None:
        check_defrag(scheduler, ctx)
    if router is not None:
        check_fleet(router, ctx)
    check_journal(ctx=ctx)
    check_ledger(ctx=ctx)
    check_goodput(ctx=ctx)


# ---------------------------------------------------------------------------
# serving fleet tier (fleet/router.py)
# ---------------------------------------------------------------------------

def check_fleet(router, ctx: str = "") -> None:
    """Structural invariants of the serving-fleet router
    (doc/design/fleet.md), re-derived from its bookkeeping:

    - **No request lost between shed and retry**: every non-done
      FleetRequest has exactly one live leg — an in-flight handoff on a
      live replica, or a last decode attempt whose replica is live (a
      dead replica's streams must be retried or finished, never
      forgotten).
    - **No double-routed stream**: at most one undone engine Request
      across a fleet request's attempts (the last one); earlier attempts
      were all finished (shed/preempted/truncated) before the retry.
    - **Drain-before-teardown**: every removed replica left in state
      ``drained`` or ``dead`` — an active/draining replica was never
      torn down (work-preserving scale-down), and a drained replica's
      engine really holds no work.
    - **Handoff never leaves orphaned blocks**: every live replica's
      paged block pool passes :func:`check_block_pool` (imported handoff
      blocks are refcounted prefix-cache entries, so a leak shows as a
      refcount/recount mismatch).
    - **Prefix-index hygiene**: every index entry names a live replica.

    Call at quiescent points (between ``step()`` calls — the same
    contract as the scheduler checks)."""
    for freq in router.requests:
        live_handoff = 0
        if freq.handoff is not None:
            rep = router.replicas.get(freq.handoff["replica"])
            if rep is not None and rep.state != "dead":
                live_handoff = 1
        undone = [(name, r) for name, r in freq.attempts if not r.done]
        live_attempts = [
            (name, r) for name, r in undone
            if name in router.replicas
            and router.replicas[name].state != "dead"
        ]
        if len(undone) > 1 or (undone and undone[-1][1]
                               is not freq.attempts[-1][1]):
            _fail(ctx, f"fleet request {freq.fid} is double-routed: "
                       f"undone attempts on {[n for n, _ in undone]} "
                       f"(only the LAST attempt may be live)")
        if freq.done:
            continue
        legs = live_handoff + len(live_attempts)
        if freq.handoff is None and not freq.attempts:
            _fail(ctx, f"fleet request {freq.fid} has neither a handoff "
                       f"nor any attempt — never dispatched")
        if legs == 0:
            _fail(ctx, f"fleet request {freq.fid} lost: not done, no live "
                       f"handoff, no live attempt (last attempt on "
                       f"{freq.attempts[-1][0] if freq.attempts else None})")
        if legs > 1:
            _fail(ctx, f"fleet request {freq.fid} double-routed: "
                       f"{legs} live legs at once")
    for rep in router.removed:
        if rep.state not in ("drained", "dead"):
            _fail(ctx, f"replica {rep.name} was removed in state "
                       f"{rep.state!r} — scale-down must drain before "
                       f"teardown")
        if rep.state == "drained" and rep.has_work():
            _fail(ctx, f"replica {rep.name} was removed as drained but "
                       f"its engine still holds work")
    for rep in router.replicas.values():
        if rep.state != "dead":
            check_block_pool(rep.engine, f"{ctx}:fleet/{rep.name}")
    for h, name in router._prefix_index.items():
        if name not in router.replicas:
            _fail(ctx, f"prefix-index entry names removed replica "
                       f"{name!r} — index not scrubbed at teardown")
    check_requests(router, ctx)


def check_requests(router, ctx: str = "") -> None:
    """Structural invariants of the request flight recorder
    (obs/journal.py REQUEST_LEGS), re-derived against the router's own
    request bookkeeping. No-op while the journal is disabled, so every
    fleet soak (``check_fleet`` calls this, and ``check_all(router=)``
    calls ``check_fleet``) attacks the recorder for free once it opts in:

    - **Exactly one terminal leg**: a done request's flight has one
      ``note_request_done`` terminal — never zero (a finish the recorder
      missed) nor two (a double close); a live request has none.
    - **Legs are exclusive, non-overlapping and contiguous** (each leg
      starts where the previous ended), and their sum never exceeds the
      request's wall time.
    - **TTFT legs sum to the measured ttft_s** (the ``ttft_gap`` the
      journal computed at terminal is ~0): an uninstrumented segment on
      the request path shows up here, not in a dashboard.
    - **Retries re-attribute**: every counted retry left a ``retry``
      leg — no time is lost between shed and retry.
    """
    from hivedscheduler_tpu.obs import journal as obs_journal

    j = obs_journal.JOURNAL
    if not j.enabled:
        return
    flights = j.flights()
    for freq in router.requests:
        key = f"fleet/{freq.fid}"
        fl = flights.get(key)
        if fl is None or not fl["opened"]:
            # journal enabled mid-flight (or another router's incarnation
            # overwrote the key): no complete record to check
            continue
        legs = fl["legs"]
        for (l1, s1, e1), (l2, s2, e2) in zip(legs, legs[1:]):
            if s2 < e1 - 1e-9:
                _fail(ctx, f"request {key}: legs {l1!r} [{s1}, {e1}] and "
                           f"{l2!r} [{s2}, {e2}] overlap")
            if s2 > e2 + 1e-9:
                _fail(ctx, f"request {key}: leg {l2!r} is negative")
        if any(s2 > e1 + 1e-9
               for (_l1, _s1, e1), (_l2, s2, _e2) in zip(legs, legs[1:])):
            _fail(ctx, f"request {key}: legs are not contiguous — an "
                       f"interval on the request path went unattributed")
        if freq.done:
            if fl["terminals"] == 0:
                _fail(ctx, f"request {key} is done "
                           f"({freq.finish_reason}) but its flight never "
                           f"reached a terminal leg")
            if fl["terminals"] > 1:
                _fail(ctx, f"request {key} reached {fl['terminals']} "
                           f"terminal legs — exactly one is the contract")
            wall = (freq.done_at or 0.0) - freq.submitted_at
            total = sum(e - s for _l, s, e in legs)
            if total > wall + 1e-6:
                _fail(ctx, f"request {key}: leg sum {total:.6f}s exceeds "
                           f"wall time {wall:.6f}s")
            gap = fl["ttft_gap"]
            if freq.ttft_s is not None and gap is not None \
                    and abs(gap) > 1e-6:
                _fail(ctx, f"request {key}: TTFT legs sum differs from "
                           f"measured ttft_s by {gap:+.9f}s — an "
                           f"uninstrumented (or double-counted) segment "
                           f"on the request path")
            retry_legs = sum(1 for leg, _s, _e in legs if leg == "retry")
            if retry_legs < freq.retries:
                _fail(ctx, f"request {key}: {freq.retries} retries but "
                           f"only {retry_legs} `retry` legs — a leg was "
                           f"lost between shed and retry")
        elif fl["terminals"]:
            _fail(ctx, f"request {key} is live but its flight already "
                       f"reached a terminal leg")


# ---------------------------------------------------------------------------
# placement preservation across restart
# ---------------------------------------------------------------------------

def placement_snapshot(algo, names: Optional[Iterable[str]] = None):
    """{group name -> {node -> sorted leaf-cell indices}} at chip
    granularity — the identity of each gang's physical slice. ``names``
    restricts the snapshot; default is every current group."""
    if names is None:
        names = list(algo.affinity_groups)
    snap = {}
    for name in names:
        g = algo.get_affinity_group(name)
        snap[name] = {
            n: sorted(ix) for n, ix in g.status.physical_placement.items()
        }
    return snap


def check_placement_preserved(before, after, ctx: str = "") -> None:
    """Every group present before must exist after with the exact same
    chip-granular placement (same nodes but different chips = lost slice:
    ICI contiguity broken — the ``test_recovery_scale.py`` contract)."""
    for name, chips_before in before.items():
        if name not in after:
            _fail(ctx, f"group {name} lost across restart")
        if after[name] != chips_before:
            _fail(ctx, f"group {name} placement changed across restart: "
                       f"{chips_before} -> {after[name]}")


# ---------------------------------------------------------------------------
# serving-engine paged KV block pool
# ---------------------------------------------------------------------------

def check_block_pool(engine, ctx: str = "") -> None:
    """From-scratch accounting of a paged ``ServingEngine``'s block
    allocator (models/serving.py): the free-list/refcount books must equal
    a recount over every holder — no leak, no double-alloc.

    - block 0 (trash) is never allocated, never refcounted, never free;
    - every other block is EITHER on the free list with refcount 0 OR
      referenced, and its refcount equals the recount: #slot block-tables
      holding it + #prefix-cache entries naming it;
    - the free list holds no duplicates;
    - each slot's device-visible table row is exactly its owned/shared bid
      list followed by trash zeros (the jitted programs read the table, so
      a drifted row would silently mis-address KV);
    - a parked/idle slot holds no blocks.

    No-op for dense engines (nothing to check). Same raise contract as the
    scheduler checks: :class:`InvariantViolation`.
    """
    if not getattr(engine, "paged", False):
        return
    n = engine.num_blocks
    counts = [0] * n
    for slot, bids in enumerate(engine._slot_bids):
        if engine.slots[slot] is None and bids:
            _fail(ctx, f"retired slot {slot} still holds blocks {bids}")
        for bid in bids:
            if not 1 <= bid < n:
                _fail(ctx, f"slot {slot} holds out-of-range block {bid}")
            counts[bid] += 1
        row = list(engine._table[slot])
        want = bids + [0] * (len(row) - len(bids))
        if row != want:
            _fail(ctx, f"slot {slot} table row {row} != owned bids {want}")
    for key, (payload, _plen) in engine._prefix_cache.items():
        for bid in engine._entry_bids(payload):
            if not 1 <= bid < n:
                _fail(ctx, f"cache entry {key!r} names out-of-range "
                           f"block {bid}")
            counts[bid] += 1
    free = list(engine._free)
    if len(set(free)) != len(free):
        _fail(ctx, f"free list has duplicates: {sorted(free)}")
    if 0 in free or counts[0] or engine._ref[0]:
        _fail(ctx, "trash block 0 entered the allocator")
    free_set = set(free)
    for bid in range(1, n):
        if int(engine._ref[bid]) != counts[bid]:
            _fail(ctx, f"block {bid}: refcount {int(engine._ref[bid])} != "
                       f"recount {counts[bid]}")
        if counts[bid] == 0 and bid not in free_set:
            _fail(ctx, f"block {bid} leaked: unreferenced but not free")
        if counts[bid] > 0 and bid in free_set:
            _fail(ctx, f"block {bid} double-allocated: referenced AND free")
