"""Seeded chaos soak: full schedule/bind cycles through the runtime under
injected faults, with invariants checked after every schedule.

The harness plays the kube-scheduler's role against a real
``HivedScheduler`` wired to a :class:`~hivedscheduler_tpu.chaos.injector.
ChaosKubeClient` over the in-memory fake ApiServer:

- **schedule gang**: create the member pods, drive ``filter_routine`` (and
  ``preempt_routine`` when the filter nominates victims — the harness then
  kills the victim gangs, as the kube-scheduler's preemption would) and
  commit with ``bind_routine``. Transient injected errors are retried the
  way the real control loop retries (each kube-scheduler cycle re-enters
  filter); a gang that cannot place is rolled back whole — gang semantics.
- **node flap**: NotReady <-> healthy through the informer (exercises
  ``_set_bad_cell`` / doomed-bad binding / ``_set_healthy_cell``).
- **kill pod mid-gang**: delete one member, then — as a gang framework
  would — the rest of the gang.
- **crash-restart**: detach the dead scheduler's informers, build a fresh
  ``HivedScheduler`` over the same cluster state and replay recovery from
  pod annotations; every previously-bound gang must come back with its
  exact chip-granular placement (the ``test_recovery_scale.py`` contract).

After every completed schedule the harness runs the internal-consistency
invariants (VC safety, books, ownership); at quiescent points (held events
flushed) it additionally checks gang atomicity against its own registry of
complete gangs. Violations are *collected*, not raised, so one soak reports
everything a seed finds; ``tools/check_chaos_seeds.py`` replays pinned seeds
as a permanent regression suite.
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional

from hivedscheduler_tpu.api import constants as api_constants
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.api.config import Config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualCellSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.chaos import invariants
from hivedscheduler_tpu.chaos.injector import (
    ChaosKubeClient,
    FaultPlan,
    InjectedApiError,
)
from hivedscheduler_tpu.common.utils import to_json
from hivedscheduler_tpu.k8s.fake import FakeKubeClient
from hivedscheduler_tpu.k8s.types import Container, Node, NodeCondition, Pod
from hivedscheduler_tpu.runtime import extender as ei
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler

log = logging.getLogger(__name__)


def default_config() -> Config:
    """A compact two-v5p-chain (multi-chain relaxation reachable) + generic
    v4 pool cluster with three VCs — the chaos analogue of the fuzz
    harness's cluster, sized for tier-1 soak speed."""
    mesh_a = MeshSpec(
        topology=(4, 4, 2), chip_type="v5p-chip", host_shape=(2, 2, 1),
        levels=[
            MeshLevelSpec(name="cA-2x2x1", shape=(2, 2, 1)),
            MeshLevelSpec(name="cA-2x2x2", shape=(2, 2, 2)),
            MeshLevelSpec(name="cA-4x2x2", shape=(4, 2, 2)),
            MeshLevelSpec(name="cA-4x4x2", shape=(4, 4, 2)),
        ],
    )
    mesh_b = MeshSpec(
        topology=(2, 2, 2), chip_type="v5p-chip", host_shape=(2, 2, 1),
        levels=[
            MeshLevelSpec(name="cB-2x2x1", shape=(2, 2, 1)),
        ],
    )
    generic = CellTypeSpec(
        child_cell_type="v4-node", child_cell_number=4, is_node_level=False,
    )
    v4_node = CellTypeSpec(
        child_cell_type="v4-chip", child_cell_number=4, is_node_level=True,
    )
    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={
                "chainA": CellTypeSpec(mesh=mesh_a),
                "chainB": CellTypeSpec(mesh=mesh_b),
                "v4-pool": generic,
                "v4-node": v4_node,
            },
            physical_cells=[
                PhysicalCellSpec(cell_type="chainA", cell_address="podA"),
                PhysicalCellSpec(cell_type="chainB", cell_address="podB"),
                PhysicalCellSpec(cell_type="v4-pool", cell_address="pool0"),
            ],
        ),
        virtual_clusters={
            "vc-a": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=1, cell_type="chainA.cA-4x2x2"),
                VirtualCellSpec(cell_number=2, cell_type="chainB.cB-2x2x1"),
                VirtualCellSpec(cell_number=2, cell_type="v4-pool.v4-node"),
            ]),
            "vc-b": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=1, cell_type="chainA.cA-2x2x2"),
            ]),
            "vc-c": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=2, cell_type="chainA.cA-2x2x1"),
                VirtualCellSpec(cell_number=1, cell_type="v4-pool.v4-node"),
            ]),
        },
    ))


def _make_pod(name: str, spec: dict) -> Pod:
    return Pod(
        name=name,
        uid=name,
        annotations={
            api_constants.ANNOTATION_POD_SCHEDULING_SPEC: to_json(spec)
        },
        containers=[Container(resource_limits={
            api_constants.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1
        })],
    )


_NOT_READY = [NodeCondition(type="Ready", status="False")]

# gang shapes: (pods, chips per pod); (6, 4) = 24 chips exceeds vc-a's
# per-chain v5p quota (16 on chainA + 8 on chainB) so a guaranteed vc-a
# draw exercises multi-chain relaxation
_GANG_SHAPES = [(1, 1), (1, 2), (1, 4), (2, 4), (4, 4), (2, 8), (6, 4)]


class ChaosHarness:
    """One seeded soak run; see the module docstring. ``run(n)`` executes
    ``n`` schedule attempts interleaved with flaps/kills/restarts and
    returns a report dict (``violations`` empty on a clean run)."""

    def __init__(
        self,
        seed: int,
        plan: Optional[FaultPlan] = None,
        config_factory=default_config,
        restart_every: int = 8,
        ops_profile: str = "v1",
    ):
        # "v1" = the original fault mix (pinned seeds replay it forever);
        # "defrag-v1" adds migration episodes: a deliberately-waiting gang,
        # defrag_tick planning + eviction, resume_migrations re-binds, and
        # a kill -9 window (job dies after checkpoint, before re-bind ->
        # abort_migration). Invariants now always include check_defrag.
        if ops_profile not in ("v1", "defrag-v1"):
            raise ValueError(f"unknown ops profile {ops_profile!r}")
        self.ops_profile = ops_profile
        self.seed = seed
        self.rng = random.Random(seed)
        self.config_factory = config_factory
        self.fake = FakeKubeClient()
        self.chaos = ChaosKubeClient(self.fake, seed=seed, plan=plan)
        self.scheduler = HivedScheduler(config_factory(), self.chaos)
        self.nodes = sorted({
            n for ccl in self.algo.full_cell_list.values()
            for c in ccl[max(ccl)] for n in c.nodes
        })
        for n in self.nodes:
            self.fake.create_node(Node(name=n))
        self.scheduler.start()
        self.bad_nodes: set = set()
        self.groups: Dict[str, List[Pod]] = {}  # complete gangs: bound pods
        self.violations: List[str] = []
        self.restart_every = max(1, restart_every)
        self.restarts = 0
        self.schedules_done = 0
        self.gangs_completed = 0
        self.gid = 0
        # defrag-episode accounting (non-vacuity: tests assert the soak
        # actually exercised migrations, not just scheduled around them)
        self.migrations_planned = 0
        self.migrations_killed = 0
        self.migrations_rebound = 0

    @property
    def algo(self):
        return self.scheduler.scheduler_algorithm

    # ------------------------------------------------------------------
    # invariant checking
    # ------------------------------------------------------------------

    def _check(self, ctx: str, quiesce: bool = False) -> None:
        """Internal-consistency invariants always; gang atomicity against
        the harness's registry only at quiescent points (held watch events
        flushed, nothing mid-flight)."""
        if quiesce:
            self.chaos.flush_held()
            # batched-delta mode (HIVED_EVENT_BATCH=1): the flushed watch
            # events are now queued, not applied — quiescence means applied
            self.scheduler.flush_events()
        full = set(self.groups) if quiesce else None
        try:
            with self.scheduler.scheduler_lock:
                invariants.check_all(
                    self.algo, f"seed {self.seed} {ctx}", full_groups=full,
                    scheduler=self.scheduler,
                )
        except invariants.InvariantViolation as e:
            self.violations.append(str(e))

    # ------------------------------------------------------------------
    # schedule / bind driving (the kube-scheduler's role)
    # ------------------------------------------------------------------

    def _heal_missing_pod(self) -> None:
        """A dropped ADDED means the scheduler never heard of the pod; the
        real ladder heals through relist — replay the store as a sync."""
        self.chaos.flush_held()
        self.chaos.sync()

    def _bind(self, pod_name: str, node: str) -> bool:
        """Commit one member bind, absorbing injected transients and the
        already-bound rejection (a concurrent force-bind won the race)."""
        for _ in range(8):
            try:
                self.scheduler.bind_routine(ei.ExtenderBindingArgs(
                    pod_name=pod_name, pod_namespace="default",
                    pod_uid=pod_name, node=node,
                ))
                return True
            except api.WebServerError as e:
                stored = self.fake.get_pod("default", pod_name)
                if stored is not None and stored.node_name == node:
                    return True  # bound through another path
                if 400 <= e.code < 500:
                    return False
            except InjectedApiError:
                pass
        stored = self.fake.get_pod("default", pod_name)
        return stored is not None and stored.node_name == node

    def op_schedule_gang(self) -> None:
        rng = self.rng
        vc = rng.choice(["vc-a", "vc-b", "vc-c"])
        prio = rng.choice([-1, -1, 0, 1, 5, 10])
        pods, chips = rng.choice(_GANG_SHAPES)
        name = f"g{self.gid}"
        self.gid += 1
        spec = {
            "virtualCluster": vc, "priority": prio,
            "leafCellType": rng.choice(["v5p-chip", "v5p-chip", "v4-chip"]),
            "leafCellNumber": chips,
            "multiChainRelaxPolicy": rng.choice(["fewest", "balanced"]),
            "affinityGroup": {
                "name": name,
                "members": [{"podNumber": pods, "leafCellNumber": chips}],
            },
        }
        created: List[str] = []
        bound: List[Pod] = []
        ok = True
        for i in range(pods):
            pod_name = f"{name}-{i}"
            self.fake.create_pod(_make_pod(pod_name, spec))
            created.append(pod_name)
            node = self._filter_member(pod_name, spec)
            if node is None or not self._bind(pod_name, node):
                ok = False
                break
            stored = self.fake.get_pod("default", pod_name)
            if stored is None or not stored.node_name:
                ok = False
                break
            bound.append(stored)
        if ok:
            self.groups[name] = bound
            self.gangs_completed += 1
        else:
            self._rollback(created)
        self.schedules_done += 1
        self._check(f"after schedule #{self.schedules_done} ({name})")

    def _filter_member(self, pod_name: str, spec: dict) -> Optional[str]:
        """Drive filter (+ preempt) for one member until it lands on a node
        or is judged unplaceable. Returns the placement node or None."""
        for attempt in range(24):
            pod = self.fake.get_pod("default", pod_name)
            if pod is None:
                return None
            try:
                result = self.scheduler.filter_routine(ei.ExtenderArgs(
                    pod=pod, node_names=list(self.nodes)))
            except api.WebServerError as e:
                if 400 <= e.code < 500:
                    stored = self.fake.get_pod("default", pod_name)
                    if stored is not None and stored.node_name:
                        # a racing force-bind already committed the member
                        return stored.node_name
                    # else most commonly "Pod does not exist...": the ADDED
                    # was dropped or still held — heal and retry once more
                    self._heal_missing_pod()
                    continue
                raise
            except InjectedApiError:
                continue  # transient; the control loop just re-enters
            if result.node_names:
                return result.node_names[0]
            if result.failed_nodes and any(
                k != api_constants.COMPONENT_NAME
                for k in result.failed_nodes
            ):
                # preemption may help: run the preempt phase, kill victims
                if not self._preempt_member(pod_name):
                    return None
                continue
            return None  # waiting: gang can't place now
        return None

    def _preempt_member(self, pod_name: str) -> bool:
        pod = self.fake.get_pod("default", pod_name)
        if pod is None:
            return False
        try:
            result = self.scheduler.preempt_routine(ei.ExtenderPreemptionArgs(
                pod=pod,
                node_name_to_meta_victims={n: [] for n in self.nodes},
            ))
        except (api.WebServerError, InjectedApiError):
            return False
        victims = {
            uid for uids in result.node_name_to_meta_victims.values()
            for uid in uids
        }
        if not victims:
            return True  # free resource appeared; filter will place
        for gname, gpods in list(self.groups.items()):
            if any(bp.uid in victims for bp in gpods):
                self._delete_gang(gname)
        return True

    def _rollback(self, pod_names: List[str]) -> None:
        """Gang semantics: a member that cannot place takes the whole gang
        down (and a possible half-scheduled group with it)."""
        for pn in pod_names:
            self.fake.delete_pod("default", pn)
        self.chaos.flush_held()

    def _delete_gang(self, name: str) -> None:
        for bp in self.groups.pop(name, []):
            self.fake.delete_pod(bp.namespace, bp.name)

    # ------------------------------------------------------------------
    # fault operations
    # ------------------------------------------------------------------

    def op_delete_gang(self) -> None:
        if not self.groups:
            return
        self._delete_gang(self.rng.choice(sorted(self.groups)))

    def op_flip_node(self) -> None:
        """NotReady <-> healthy through the informer — bad-cell flap."""
        n = self.rng.choice(self.nodes)
        if n in self.bad_nodes:
            self.bad_nodes.discard(n)
            self.fake.update_node(Node(name=n))
        else:
            self.bad_nodes.add(n)
            self.fake.update_node(Node(name=n, conditions=list(_NOT_READY)))

    def op_migrate(self) -> None:
        """One defrag episode: a gang that cannot place records itself as a
        waiter; ``defrag_tick`` plans + evicts; then EITHER the job dies in
        the kill -9 window (after checkpoint, before re-bind —
        ``abort_migration``) OR ``resume_migrations`` re-binds the movers
        and the waiter is driven to completion. The harness registry tracks
        moved gangs across their pod-identity change, so the quiesce
        gang-atomicity check covers migrated placements too."""
        rng = self.rng
        # construct the fragmentation pattern defrag exists for: vc-c's two
        # v5p 2x2x1 cells get three 2-chip guaranteed gangs (packer pairs
        # two in one cell), the middle one dies — now both cells are
        # half-used, 4 quota chips are free, and a 4-chip waiter cannot
        # place until one survivor moves. The surrounding soak state
        # perturbs the pattern freely; a degenerate layout just yields an
        # honest planner rejection.
        helpers = []
        for _ in range(3):
            hname = f"mgh{self.gid}"
            self.gid += 1
            hspec = {
                "virtualCluster": "vc-c", "priority": 5,
                "leafCellType": "v5p-chip", "leafCellNumber": 2,
                "affinityGroup": {
                    "name": hname,
                    "members": [{"podNumber": 1, "leafCellNumber": 2}],
                },
            }
            self.fake.create_pod(_make_pod(f"{hname}-0", hspec))
            node = self._filter_member(f"{hname}-0", hspec)
            stored = (self.fake.get_pod("default", f"{hname}-0")
                      if node and self._bind(f"{hname}-0", node) else None)
            if stored is not None and stored.node_name:
                self.groups[hname] = [stored]
                helpers.append(hname)
            else:
                self._rollback([f"{hname}-0"])
        if len(helpers) >= 2:
            self._delete_gang(helpers[1])
        # the waiter arrives at the SAME priority as the survivors: it
        # cannot preempt them (strictly-lower only), so fragmentation is
        # the genuine blocker — the case migration exists for
        name = f"mg{self.gid}"
        self.gid += 1
        pods, chips = 1, 4
        spec = {
            "virtualCluster": "vc-c", "priority": 5,
            "leafCellType": "v5p-chip", "leafCellNumber": chips,
            "affinityGroup": {
                "name": name,
                "members": [{"podNumber": pods, "leafCellNumber": chips}],
            },
        }
        pod_name = f"{name}-0"
        self.fake.create_pod(_make_pod(pod_name, spec))
        created = [pod_name]
        try:
            self.scheduler.filter_routine(ei.ExtenderArgs(
                pod=self.fake.get_pod("default", pod_name),
                node_names=list(self.nodes)))
        except (api.WebServerError, InjectedApiError):
            pass  # a wait/transient is exactly the interesting outcome
        planned = self.scheduler.defrag_tick().get("planned")
        if planned is not None:
            self.migrations_planned += 1
            mid = planned["migrationId"]
            movers = [m["group"] for m in planned["moves"]]
            # the evictions are in flight: the moved gangs are mid-flight,
            # not "complete" — drop them from the registry until (unless)
            # they re-bind; capture their pods for job-framework teardown
            mover_pods = {g: list(self.groups.get(g, [])) for g in movers}
            for g in movers:
                self.groups.pop(g, None)
            killed = rng.random() < 0.35
            if not killed:
                self.chaos.flush_held()
                report = {}
                for _ in range(4):  # re-drive past injected transients
                    report = self.scheduler.resume_migrations()
                    state = report.get(mid, {}).get("state")
                    if state and state != "Evicting":
                        break
                if report.get(mid, {}).get("state") == "Evicting":
                    # evictions kept failing (injected): treat the move as
                    # dead rather than leave a half-evicted gang behind
                    killed = True
                for move in report.get(mid, {}).get("moves", []):
                    if move["state"] != "Done":
                        continue
                    rebound = [self.fake.get_pod("default", nm)
                               for nm in move["rebound"]]
                    rebound = [p for p in rebound
                               if p is not None and p.node_name]
                    if len(rebound) == len(move["rebound"]):
                        self.groups[move["group"]] = rebound
                        self.migrations_rebound += 1
            if killed:
                # kill -9 window: the job dies after its checkpoint,
                # before the re-bind — the executor must release every
                # hold with nothing half-bound, and the job framework
                # (played here) tears down whatever pods remain
                self.scheduler.abort_migration(mid, why="chaos kill -9")
                self.migrations_killed += 1
                for g, gpods in mover_pods.items():
                    if g in self.groups:
                        continue  # re-bound before the kill landed
                    for bp in gpods:
                        self.fake.delete_pod(bp.namespace, bp.name)
        # drive the waiter gang to completion through the normal ladder
        # (reservation-steered when the migration landed); gang semantics
        # on failure
        ok = True
        bound: List[Pod] = []
        for i in range(pods):
            member = f"{name}-{i}"
            if member not in created:
                self.fake.create_pod(_make_pod(member, spec))
                created.append(member)
            node = self._filter_member(member, spec)
            if node is None or not self._bind(member, node):
                ok = False
                break
            stored = self.fake.get_pod("default", member)
            if stored is None or not stored.node_name:
                ok = False
                break
            bound.append(stored)
        if ok:
            self.groups[name] = bound
            self.gangs_completed += 1
        else:
            self._rollback(created)
        self.schedules_done += 1
        self._check(f"after migrate op #{self.schedules_done} ({name})")

    def op_kill_pod_mid_gang(self) -> None:
        """Delete one member of a bound gang, then (as the gang framework
        would) tear down the rest — never leaves a partial gang behind."""
        if not self.groups:
            return
        name = self.rng.choice(sorted(self.groups))
        pods = self.groups[name]
        victim = self.rng.choice(pods)
        self.fake.delete_pod(victim.namespace, victim.name)
        self._delete_gang(name)

    def heal_all(self) -> None:
        for n in sorted(self.bad_nodes):
            self.fake.update_node(Node(name=n))
        self.bad_nodes.clear()
        self.chaos.flush_held()

    # ------------------------------------------------------------------
    # crash-restart (recovery from pod annotations)
    # ------------------------------------------------------------------

    def crash_restart(self, quiesced: bool = True) -> None:
        """Tear the scheduler down and replay recovery: a fresh
        ``HivedScheduler`` over the same cluster state must rebuild every
        bound gang at identical chip-granular placement. Pass
        ``quiesced=False`` when crashing deliberately mid-gang (members
        still unbound): internal invariants are still enforced, but the
        complete-gang registry comparison is skipped — the half-bound gang
        is legitimately present with open slots."""
        self.chaos.flush_held()
        with self.scheduler.scheduler_lock:
            known = [n for n in self.groups if n in self.algo.affinity_groups]
            before = invariants.placement_snapshot(self.algo, known)
        self.chaos.detach_handlers()
        self.scheduler = HivedScheduler(self.config_factory(), self.chaos)
        self.scheduler.start()
        self.restarts += 1
        with self.scheduler.scheduler_lock:
            after = invariants.placement_snapshot(
                self.algo,
                [n for n in known if n in self.algo.affinity_groups],
            )
        try:
            invariants.check_placement_preserved(
                before, after, f"seed {self.seed} restart #{self.restarts}"
            )
        except invariants.InvariantViolation as e:
            self.violations.append(str(e))
        self._check(f"after restart #{self.restarts}", quiesce=quiesced)

    # ------------------------------------------------------------------
    # the soak loop
    # ------------------------------------------------------------------

    def run(self, n_schedules: int) -> dict:
        # the gang-lifecycle journal rides every soak: check_journal (in
        # check_all) then covers causal integrity and open->close
        # lifecycles under the same faults — incl. the kill -9
        # mid-migration windows — for free. Fresh ring per soak so gang
        # names reused across seeds cannot alias; restored afterwards so
        # the process-global singleton never leaks into other tests.
        from hivedscheduler_tpu.obs import journal as obs_journal
        from hivedscheduler_tpu.obs import ledger as obs_ledger

        was_enabled = obs_journal.JOURNAL.enabled
        obs_journal.enable(capacity=65536)
        # the capacity ledger rides the same way: check_ledger (in
        # check_all) asserts the conservation invariant under the same
        # faults. Fresh books per soak; restored afterwards.
        ledger_was_enabled = obs_ledger.LEDGER.enabled
        obs_ledger.LEDGER.clear()
        obs_ledger.enable()
        obs_ledger.register_cluster(self.algo)
        try:
            return self._run(n_schedules)
        finally:
            if not was_enabled:
                obs_journal.disable()
            if not ledger_was_enabled:
                obs_ledger.disable()
                obs_ledger.LEDGER.clear()

    def _run(self, n_schedules: int) -> dict:
        ops = (
            [self.op_schedule_gang] * 5
            + [self.op_delete_gang] * 2
            + [self.op_flip_node] * 2
            + [self.op_kill_pod_mid_gang] * 1
        )
        if self.ops_profile == "defrag-v1":
            ops += [self.op_migrate] * 3
        last_restart_at = 0
        while self.schedules_done < n_schedules:
            self.rng.choice(ops)()
            if self.schedules_done - last_restart_at >= self.restart_every:
                last_restart_at = self.schedules_done
                self.crash_restart()
        self._check("final quiesce", quiesce=True)
        from hivedscheduler_tpu.obs import journal as obs_journal
        from hivedscheduler_tpu.obs import ledger as obs_ledger

        return {
            "seed": self.seed,
            "schedules": self.schedules_done,
            "gangs_completed": self.gangs_completed,
            "gangs_live": len(self.groups),
            "restarts": self.restarts,
            "injector": dict(self.chaos.stats),
            "migrations_planned": self.migrations_planned,
            "migrations_killed": self.migrations_killed,
            "migrations_rebound": self.migrations_rebound,
            # non-vacuity: the soak must actually have journaled, and the
            # ledger must actually be accounting chips
            "journal_events": len(obs_journal.JOURNAL),
            "ledger_chips": obs_ledger.LEDGER.chips(),
            "violations": list(self.violations),
        }
