"""Deterministic, seeded fault injection around any ``KubeClient``.

``ChaosKubeClient`` wraps an inner client (the in-memory fake, the REST
client, anything implementing the interface) and perturbs the two surfaces a
real ApiServer perturbs:

- **The informer stream**: watch events may be *delayed* (held back and
  delivered later), *reordered across objects*, or *dropped*. The
  perturbations respect the informer contract consumers are entitled to:
  events for ONE object are never delivered out of order (k8s reflectors
  order per object; only cross-object interleaving is unspecified), a
  DELETED is never dropped outright — a missed delete is synthesized by the
  next relist in a real informer, so "eventually delivered" is the honest
  model — and the ``sync()`` list path is always faithful (the list is
  reliable; only the watch stream is lossy). Dropping an ADDED/MODIFIED is
  legal anywhere: informers legitimately skip intermediate states. Callers
  quiesce with :meth:`flush_held`.
- **Request/response**: reads (``get_node``/``list_nodes``/``get_pod``/
  ``list_pods``) and the bind write raise :class:`InjectedApiError`
  (transient 429/500/timeout class) with a seeded probability, bounded by
  ``max_consecutive_errors`` so no operation is starved forever. Binds
  additionally inject the *ambiguous* failure: the inner bind commits and
  the error surfaces afterwards — exactly the case the runtime's idempotent
  bind retry must absorb.

Everything is driven by one ``random.Random(seed)``: the same seed over the
same call sequence injects the same faults, which is what makes
``tools/check_chaos_seeds.py`` a replayable regression suite.

Scheduler crash-restart (tearing down a ``HivedScheduler`` and replaying
recovery from pod annotations) is orchestrated by ``chaos.harness`` — the
client supports it via :meth:`detach_handlers`, which disconnects the dead
scheduler's informer callbacks so a fresh instance can register cleanly over
the same cluster state.
"""

from __future__ import annotations

import random
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

from hivedscheduler_tpu.k8s.client import KubeClient
from hivedscheduler_tpu.k8s.types import Binding, Node, Pod


class InjectedApiError(Exception):
    """A chaos-injected transient ApiServer failure (429/500/timeout)."""

    def __init__(self, code, op: str):
        super().__init__(f"injected {code} on {op}")
        self.code = code
        self.op = op


@dataclass
class FaultPlan:
    """Knobs for one chaos run (all probabilities in [0, 1])."""

    # informer-stream faults
    drop_event_p: float = 0.05      # ADDED/MODIFIED only; DELETED is delayed
    delay_event_p: float = 0.10     # hold the event for later delivery
    reorder_p: float = 0.25         # chance held events interleave early/late
    # request/response faults
    error_p: float = 0.10
    max_consecutive_errors: int = 2
    error_codes: Tuple = (429, 500, "timeout")
    # bind-specific: of the injected bind errors, fraction that fail AFTER
    # the inner bind committed (the ambiguous case)
    bind_fail_after_p: float = 0.5


class ChaosKubeClient(KubeClient):
    """Seeded fault-injecting wrapper; see module docstring."""

    def __init__(self, inner: KubeClient, seed: int = 0,
                 plan: Optional[FaultPlan] = None):
        self.inner = inner
        self.plan = plan or FaultPlan()
        self.rng = random.Random(seed)
        self._node_handlers: List[tuple] = []
        self._pod_handlers: List[tuple] = []
        # held-back events, per object key (insertion-ordered so a full
        # flush replays oldest-held objects first): key -> deque of
        # (kind, slot, objs)
        self._held: "OrderedDict[tuple, Deque[tuple]]" = OrderedDict()
        self._in_sync = False
        self._consecutive_errors: Dict[str, int] = {}
        self.stats = {
            "dropped": 0, "delayed": 0, "reordered": 0,
            "errors_injected": 0, "binds_failed_after": 0,
        }
        inner.on_node_event(
            lambda n: self._event("node", 0, (n,)),
            lambda o, n: self._event("node", 1, (o, n)),
            lambda n: self._event("node", 2, (n,)),
        )
        inner.on_pod_event(
            lambda p: self._event("pod", 0, (p,)),
            lambda o, p: self._event("pod", 1, (o, p)),
            lambda p: self._event("pod", 2, (p,)),
        )

    # --- informer stream --------------------------------------------------
    def on_node_event(self, add, update, delete) -> None:
        self._node_handlers.append((add, update, delete))

    def on_pod_event(self, add, update, delete) -> None:
        self._pod_handlers.append((add, update, delete))

    def detach_handlers(self) -> None:
        """Disconnect every registered outer handler (a crashed scheduler's
        informer callbacks must stop receiving events before the restarted
        instance registers its own)."""
        self._node_handlers.clear()
        self._pod_handlers.clear()

    @staticmethod
    def _key(kind: str, objs: tuple) -> tuple:
        obj = objs[-1]  # update events carry (old, new): key by the object
        return (kind, obj.name if kind == "node" else obj.key)

    def _deliver(self, kind: str, slot: int, objs: tuple) -> None:
        handlers = self._node_handlers if kind == "node" else self._pod_handlers
        for triple in list(handlers):
            triple[slot](*objs)

    def _flush_key(self, key: tuple) -> None:
        q = self._held.pop(key, None)
        while q:
            kind, slot, objs = q.popleft()
            self._deliver(kind, slot, objs)

    def _event(self, kind: str, slot: int, objs: tuple) -> None:
        if self._in_sync:
            # the list path is reliable (real list+watch): recovery-barrier
            # replays are delivered faithfully
            self._deliver(kind, slot, objs)
            return
        p = self.plan
        key = self._key(kind, objs)
        r = self.rng.random()
        if key in self._held:
            # per-object ordering: this event cannot jump ahead of the
            # object's held events — either release them all now (the
            # stream catches up) or queue behind them
            if r < p.reorder_p:
                self.stats["reordered"] += 1
                self._flush_key(key)
                self._deliver(kind, slot, objs)
            else:
                self._held[key].append((kind, slot, objs))
            return
        if r < p.drop_event_p and slot != 2:
            # a dropped ADDED/MODIFIED is an informer skipping an
            # intermediate state (healed at the latest by the next resync);
            # a DELETED would only be synthesized by a relist, so it is
            # delayed below instead of lost
            self.stats["dropped"] += 1
            return
        if r < p.drop_event_p + p.delay_event_p:
            self.stats["delayed"] += 1
            self._held[key] = deque([(kind, slot, objs)])
            return
        self._deliver(kind, slot, objs)
        # cross-object reordering: another object's held (older) events
        # replay AFTER this (newer) one
        if self._held and self.rng.random() < p.reorder_p:
            self.stats["reordered"] += 1
            self._flush_key(next(iter(self._held)))

    def flush_held(self) -> None:
        """Deliver every held event (per-object order preserved) — the
        quiesce point before invariant checks that compare against an
        external view of the cluster."""
        while self._held:
            self._flush_key(next(iter(self._held)))

    # --- request/response faults ------------------------------------------
    def _maybe_fail(self, op: str) -> None:
        p = self.plan
        if p.error_p <= 0.0:
            return
        streak = self._consecutive_errors.get(op, 0)
        if streak < p.max_consecutive_errors and self.rng.random() < p.error_p:
            self._consecutive_errors[op] = streak + 1
            self.stats["errors_injected"] += 1
            raise InjectedApiError(self.rng.choice(p.error_codes), op)
        self._consecutive_errors[op] = 0

    # --- interface passthrough with faults ---------------------------------
    def sync(self) -> None:
        # held (older) events must not be delivered after the (newer) list
        # replay: release them first, then list faithfully
        self.flush_held()
        self._in_sync = True
        try:
            self.inner.sync()
        finally:
            self._in_sync = False

    def watches_alive(self) -> bool:
        return self.inner.watches_alive()

    def get_node(self, name: str) -> Optional[Node]:
        self._maybe_fail("get_node")
        return self.inner.get_node(name)

    def list_nodes(self) -> List[Node]:
        self._maybe_fail("list_nodes")
        return self.inner.list_nodes()

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        self._maybe_fail("get_pod")
        return self.inner.get_pod(namespace, name)

    def list_pods(self) -> List[Pod]:
        self._maybe_fail("list_pods")
        return self.inner.list_pods()

    def create_pod(self, pod: Pod) -> None:
        """Pod creation (the defrag executor's replacement-pod path):
        transient injected failure before the write, like the reads — the
        executor rolls the half-placed move back on failure."""
        self._maybe_fail("create_pod")
        self.inner.create_pod(pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        """Pod deletion (the defrag executor's SIGTERM-analogue eviction):
        injected failure leaves the pod in place — evictions are
        idempotent and re-issued by resume_migrations."""
        self._maybe_fail("delete_pod")
        self.inner.delete_pod(namespace, name)

    def bind_pod(self, binding: Binding) -> None:
        p = self.plan
        streak = self._consecutive_errors.get("bind_pod", 0)
        if (p.error_p > 0.0 and streak < p.max_consecutive_errors
                and self.rng.random() < p.error_p):
            self._consecutive_errors["bind_pod"] = streak + 1
            self.stats["errors_injected"] += 1
            if self.rng.random() < p.bind_fail_after_p:
                # the ambiguous failure: the bind COMMITTED, the response
                # was lost — a blind retry must be idempotent
                self.inner.bind_pod(binding)
                self.stats["binds_failed_after"] += 1
            raise InjectedApiError(self.rng.choice(p.error_codes), "bind_pod")
        self._consecutive_errors["bind_pod"] = 0
        self.inner.bind_pod(binding)
