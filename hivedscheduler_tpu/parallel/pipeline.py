"""Pipeline parallelism: GPipe-style microbatch pipelining over the ``pp``
mesh axis.

Each pipeline stage holds a contiguous block of transformer layers (the
stacked layer params are sharded on their leading layer axis with
``PartitionSpec('pp', ...)``); microbatches flow stage-to-stage with
``lax.ppermute`` (one ICI hop), with ``n_micro + pp - 1`` pipeline steps and
the classic GPipe bubble. The whole schedule is a differentiable ``lax.scan``,
so one jitted train step backpropagates through the pipeline naturally.

Composition (validated in ``models.transformer.forward_with_aux``):
- tensor parallelism composes — stage weights keep their tp sharding and
  ``_apply_layer`` inserts Megatron-style row-parallel psums;
- sequence parallelism composes with ``attn_impl`` "ring", "ring_flash",
  "ring_zigzag", "ring_zigzag_flash" or "ulysses" —
  ``seq_axis`` shards T into the stage and the manual attention body runs
  directly in the stage (sp > 1 with local attention is rejected);
- MoE composes — expert weights stay ep-sharded, each device computes its
  experts' slots and the combine psums over ep (and tp);
- dp/fsdp compose for activations AND params: layer weights stay
  fsdp-sharded inside stages and are all-gathered ZeRO-style at use time
  (autodiff reduce-scatters the grads back).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


from hivedscheduler_tpu.parallel.shard_utils import varying as _varying


def _pipeline_local(
    params_local: Any,
    hidden_local: jax.Array,
    *,
    layer_block_fn: Callable[[Any, jax.Array], tuple],
    n_micro: int,
    axis: str,
    batch_axes,
    seq_axis=None,
):
    """Per-device body under shard_map. ``params_local`` leaves carry this
    stage's layers on axis 0; ``hidden_local`` is this device's [B_loc, T, D]
    batch shard (replicated over pp)."""
    pp = lax.psum(1, axis)
    stage = lax.axis_index(axis)
    b_loc, t, d = hidden_local.shape
    assert b_loc % n_micro == 0, f"local batch {b_loc} not divisible by {n_micro} microbatches"
    mb = b_loc // n_micro
    micro = hidden_local.reshape(n_micro, mb, t, d)
    steps = n_micro + pp - 1

    # derive from `micro` so the buffers inherit its batch-axes vma, then add
    # only the pp axis (pcast rejects re-casting already-varying axes)
    out_buf = _varying(jnp.zeros_like(micro), (axis,))
    recv0 = _varying(jnp.zeros_like(micro[0]), (axis,))
    aux0 = _varying(jnp.zeros((), jnp.float32), (axis,)) + 0.0 * jnp.sum(
        micro[..., 0, 0, 0]
    )  # inherit batch vma
    # forward perm: stage s -> s+1 (no wraparound; stage 0 receives zeros)
    perm = [(i, i + 1) for i in range(pp - 1)]

    def step_fn(carry, step):
        out_buf, recv, aux_acc = carry
        inject_idx = jnp.clip(step, 0, n_micro - 1)
        injected = lax.dynamic_index_in_dim(micro, inject_idx, 0, keepdims=False)
        my_in = jnp.where(stage == 0, injected, recv)
        h, aux_step = layer_block_fn(params_local, my_in)
        # a stage computes real work for microbatch (step - stage) only; aux
        # from bubble steps (garbage activations) must not count
        real = (step - stage >= 0) & (step - stage < n_micro)
        aux_acc = aux_acc + jnp.where(real, aux_step, 0.0)
        # the last stage banks microbatch `step - (pp-1)` when it's real
        slot = step - (pp - 1)
        valid = (stage == pp - 1) & (slot >= 0) & (slot < n_micro)
        banked = lax.dynamic_update_index_in_dim(
            out_buf, h.astype(out_buf.dtype), jnp.clip(slot, 0, n_micro - 1), 0
        )
        out_buf = jnp.where(valid, banked, out_buf)
        send = lax.ppermute(h, axis, perm) if pp > 1 else h
        return (out_buf, send, aux_acc), None

    (out_buf, _, aux_acc), _ = lax.scan(
        step_fn, (out_buf, recv0, aux0), jnp.arange(steps)
    )
    # only the last stage ever wrote; psum over pp broadcasts it everywhere so
    # the output can be pp-replicated. Aux: sum over stages (each stage's
    # layers), averaged over microbatches (standard per-microbatch aux).
    out = lax.psum(out_buf, axis)
    # aux: sum over stages, average over microbatches, mean over the data
    # shards so the scalar is fully replicated
    aux = lax.psum(aux_acc, axis) / n_micro
    mean_axes = tuple(batch_axes) + ((seq_axis,) if seq_axis else ())
    aux = lax.pmean(aux, mean_axes)
    return out.reshape(b_loc, t, d), aux


def pipeline_apply(
    layer_block_fn: Callable[[Any, jax.Array], tuple],
    stacked_params: Any,
    param_specs: Any,
    hidden: jax.Array,
    mesh,
    *,
    n_micro: int,
    axis: str = "pp",
    batch_axes=("dp", "fsdp"),
    seq_axis=None,
) -> tuple:
    """Run ``hidden`` [B, T, D] through all layers, pipelined over ``axis``.

    Returns (hidden, aux): ``layer_block_fn(stage_params, h) -> (h, aux)``
    applies one stage's worth of layers and reports their (MoE) aux-loss sum
    for that microbatch; bubble steps are excluded and the total is averaged
    over microbatches. ``stacked_params``: pytree whose leaves have the layer
    count on axis 0 (divisible by the pp size); ``param_specs``: matching
    pytree of PartitionSpecs whose first entry is ``axis``; ``seq_axis``
    shards the T dimension into the stage (ring/Ulysses attention runs
    inside the stage body).
    """
    from hivedscheduler_tpu.parallel.ring_attention import _get_shard_map

    shard_map = _get_shard_map()

    hidden_spec = P(tuple(batch_axes), seq_axis, None)
    fn = shard_map(
        functools.partial(
            _pipeline_local,
            layer_block_fn=layer_block_fn,
            n_micro=n_micro,
            axis=axis,
            batch_axes=tuple(batch_axes),
            seq_axis=seq_axis,
        ),
        mesh=mesh,
        in_specs=(param_specs, hidden_spec),
        out_specs=(hidden_spec, P()),
    )
    return fn(stacked_params, hidden)
