"""Sharded train-state checkpointing (orbax) with crash-atomic commits.

The scheduler side persists placements in pod annotations (crash recovery);
this is the *workload* side: periodic save/restore of the sharded training
state so a gang that is preempted (or hits bad hardware and is rescheduled
onto a different sub-mesh) resumes from its last step. Restore distributes
each array directly to its target shards — no host-memory gather of the full
state.

Crash atomicity: a save is only *committed* once a ``hived_complete.json``
marker lands inside the step directory — written via the classic atomic
sequence (temp file in the same directory, flush, fsync, ``os.rename``,
directory fsync) strictly AFTER orbax reports the step fully written. A
process killed mid-save leaves a step directory without a marker; restore
and ``latest_step`` skip such partial steps and fall back to the newest
committed one, and restore additionally survives a marker-bearing step whose
payload is unreadable (torn storage) by walking down the committed-step
ladder. Checkpoints written before this scheme (no markers anywhere) keep
their legacy behavior.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Any, List, Optional, Tuple

import jax

log = logging.getLogger(__name__)

_COMPLETE_MARKER = "hived_complete.json"

# TransformerConfig fields that determine the parameter-tree SHAPES. A
# checkpoint restores onto any (dp, fsdp, pp, ep, tp, sp) mesh — global
# array shapes are mesh-independent, so orbax redistributes shards to the
# target templates — but these fields must match exactly or the restore
# would be loading a different model (doc/design/elastic.md).
GEOMETRY_FIELDS = (
    "vocab_size", "d_model", "n_heads", "n_kv_heads", "n_layers", "d_ff",
    "n_experts", "lora_rank", "lora_mlp",
)


def train_metadata(axes, cfg, *, global_batch: int, seq_len: int,
                   elastic: Optional[dict] = None) -> dict:
    """The elastic-resume sidecar persisted inside the commit marker: the
    SOURCE mesh axes the arrays were sharded over, the model geometry they
    encode, and the data-stream identity (global batch x seq len — the two
    numbers that define the loader's sample plan). ``elastic`` carries the
    job's declared shape ladder (``train --elastic``) so a restarted
    incarnation — and operators reading the marker — can see which slices
    are acceptable."""
    out = {
        "mesh": {name: size for name, size in zip(axes.names, axes.shape)},
        "model": {f: getattr(cfg, f) for f in GEOMETRY_FIELDS},
        "data": {"global_batch": global_batch, "seq_len": seq_len},
    }
    if elastic:
        out["elastic"] = elastic
    return out


def validate_resume_metadata(meta: dict, axes, cfg, *, global_batch: int,
                             seq_len: int) -> Optional[dict]:
    """Gate a resume against the checkpoint's recorded identity.

    Returns the SOURCE mesh dict when the checkpoint was written on a
    different (dp, fsdp, pp, ep, tp, sp) layout (the cross-topology resume
    path: reshard-on-load, loss-trajectory allclose), ``None`` when the
    topology matches (the bit-exact path) or the checkpoint predates the
    metadata (legacy: nothing to validate). Raises ``ValueError`` when the
    checkpoint encodes a different model geometry, or a different data
    stream — silently resuming either would double-train or skip samples,
    or load a differently-shaped model."""
    model = meta.get("model")
    if model:
        mismatched = {
            f: (model[f], getattr(cfg, f))
            for f in GEOMETRY_FIELDS
            if f in model and model[f] != getattr(cfg, f)
        }
        if mismatched:
            raise ValueError(
                "checkpoint model geometry mismatch: "
                + ", ".join(f"{k}: saved {s} != current {c}"
                            for k, (s, c) in sorted(mismatched.items()))
            )
    data = meta.get("data")
    if data:
        saved = (data.get("global_batch"), data.get("seq_len"))
        if saved != (global_batch, seq_len):
            raise ValueError(
                f"checkpoint data stream mismatch: the loader's sample plan "
                f"is defined by (global batch, seq len) = {saved}; resuming "
                f"with {(global_batch, seq_len)} would silently change the "
                f"training stream"
            )
    saved_mesh = meta.get("mesh")
    if saved_mesh:
        current = {name: size for name, size in zip(axes.names, axes.shape)}
        if saved_mesh != current:
            return saved_mesh
    return None


def _manager(directory: str, max_to_keep: int = 3, create: bool = False):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=create),
    )


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Crash-atomic file write: temp file in the SAME directory (rename
    must not cross filesystems), flush + fsync, ``os.rename`` over the
    destination, then best-effort fsync of the directory so the rename
    itself is durable. Readers see either the old content or the new,
    never a torn write."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=".tmp-", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dirfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename still atomic


def _marker_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), str(step), _COMPLETE_MARKER)


def _committed_steps(directory: str) -> Optional[List[int]]:
    """Descending committed steps, or None when NO step carries a marker —
    a legacy (pre-marker) checkpoint directory, handled by orbax's own
    bookkeeping for backward compatibility."""
    directory = os.path.abspath(directory)
    steps: List[int] = []
    any_step_dir = False
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    for name in entries:
        if not name.isdigit():
            continue
        any_step_dir = True
        if os.path.exists(os.path.join(directory, name, _COMPLETE_MARKER)):
            steps.append(int(name))
    if not steps:
        return None if any_step_dir else []
    return sorted(steps, reverse=True)


def save(directory: str, step: int, params: Any, opt_state: Any,
         extra: Optional[dict] = None) -> None:
    """Save one checkpoint (blocking). Arrays keep their shardings. The
    step is committed — visible to ``latest_step``/``restore`` — only once
    its completion marker is atomically in place.

    ``extra``: JSON-serializable sidecar state of record (data-loader RNG
    position, supervisor bookkeeping) stored INSIDE the commit marker, so
    it commits atomically with the step — a resume can never see arrays
    from one save paired with loader state from another. Read it back with
    :func:`read_metadata`."""
    import orbax.checkpoint as ocp

    from hivedscheduler_tpu.obs import goodput as _goodput

    with _goodput.span("checkpoint_save"):
        mgr = _manager(directory, create=True)
        mgr.save(step, args=ocp.args.Composite(
            params=ocp.args.StandardSave(params),
            opt_state=ocp.args.StandardSave(opt_state),
        ))
        mgr.wait_until_finished()
        mgr.close()
        marker = {"step": step, "format": "orbax-composite-v1"}
        if extra:
            marker["extra"] = extra
        atomic_write_bytes(
            _marker_path(directory, step), json.dumps(marker).encode(),
        )


def read_metadata(directory: str, step: Optional[int] = None) -> dict:
    """The commit marker's sidecar dict for ``step`` (default: the newest
    committed step). ``{}`` for legacy markers without ``extra``, steps
    without a marker, or unreadable markers — metadata is best-effort by
    contract; the arrays are the source of truth."""
    if step is None:
        committed = _committed_steps(directory)
        if not committed:
            return {}
        step = committed[0]
    try:
        with open(_marker_path(directory, step)) as f:
            return json.load(f).get("extra", {}) or {}
    except (OSError, ValueError):
        return {}


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None  # a read must not create the directory
    committed = _committed_steps(directory)
    if committed is not None:
        return committed[0] if committed else None
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def _restore_ladder(directory: str, step: Optional[int], do_restore):
    """Shared restore core: resolve the step ladder and walk it.

    An explicit ``step`` is restored exactly (failure raises — the caller
    asked for that step). With ``step=None`` the newest *committed* step is
    tried first; if its payload is unreadable (torn/truncated storage past
    the commit marker), the ladder falls back to the next committed step —
    a resume always lands on the newest complete checkpoint. Legacy
    directories (no markers) use orbax's own latest-step bookkeeping, also
    walking down on unreadable payloads. Returns ``(step, restored)``."""
    if not os.path.isdir(directory):
        raise FileNotFoundError(f"no checkpoint found under {directory}")
    mgr = _manager(directory)
    try:
        if step is not None:
            return step, do_restore(mgr, step)
        committed = _committed_steps(directory)
        if committed is not None:
            candidates = committed
        else:
            candidates = sorted(mgr.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError(f"no checkpoint found under {directory}")
        last_exc: Optional[Exception] = None
        for s in candidates:
            try:
                return s, do_restore(mgr, s)
            except Exception as e:  # torn payload despite the marker
                last_exc = e
                log.warning(
                    "checkpoint step %d under %s is unreadable (%s); "
                    "falling back to the previous complete checkpoint",
                    s, directory, e,
                )
        raise RuntimeError(
            f"every checkpoint under {directory} is unreadable "
            f"(tried steps {candidates})"
        ) from last_exc
    finally:
        mgr.close()


def restore_params(
    directory: str,
    params_template: Any,
    step: Optional[int] = None,
) -> Tuple[int, Any]:
    """Restore (step, params) only — the optimizer state is left untouched.

    For inference (serving never needs moments) and for warm starts
    (``train --init-from``: fine-tune from a pretrained base with a fresh
    optimizer, including LoRA runs whose adapter-only optimizer tree never
    matches the pretraining checkpoint's)."""
    import orbax.checkpoint as ocp

    from hivedscheduler_tpu.obs import goodput as _goodput

    def as_abstract(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            tree,
        )

    with _goodput.span("checkpoint_restore"):
        step, restored = _restore_ladder(directory, step, lambda mgr, s: mgr.restore(
            s, args=ocp.args.Composite(
                params=ocp.args.StandardRestore(as_abstract(params_template)),
            )))
        params = jax.tree.map(
            lambda x, t: (
                jax.device_put(x, t.sharding) if getattr(t, "sharding", None) is not None else x
            ),
            restored["params"],
            params_template,
        )
    return step, params


def restore_serving_params(
    cfg: Any,
    checkpoint_dir: str,
    key: jax.Array,
    *,
    lora_rank: int = 0,
    lora_alpha: float = 16.0,
    lora_mlp: bool = False,
) -> Tuple[Any, Optional[int]]:
    """The generate/serve CLIs' one shared loading path: init a param tree
    (LoRA-shaped when ``lora_rank > 0`` so a fine-tune checkpoint restores),
    restore from ``checkpoint_dir`` when given, then merge the adapters into
    the base weights for serving. Returns (params, restored_step_or_None);
    raises FileNotFoundError like :func:`restore_params`."""
    import dataclasses

    from hivedscheduler_tpu.models import transformer as tm

    init_cfg = cfg
    if lora_rank > 0:
        init_cfg = dataclasses.replace(
            cfg, lora_rank=lora_rank, lora_alpha=lora_alpha,
            lora_mlp=lora_mlp,
        )
    params = tm.init_params(init_cfg, key)
    step = None
    if checkpoint_dir:
        step, params = restore_params(checkpoint_dir, params)
    if lora_rank > 0:
        params = tm.merge_lora(params, init_cfg)
    return params, step


def restore(
    directory: str,
    params_template: Any,
    opt_state_template: Any,
    step: Optional[int] = None,
) -> Tuple[int, Any, Any]:
    """Restore (step, params, opt_state).

    Templates are matching pytrees of ShapeDtypeStruct/arrays carrying the
    target shardings (e.g. the freshly initialized state of a new job
    incarnation on a different slice) — restored arrays land directly on
    those shards."""
    import orbax.checkpoint as ocp

    from hivedscheduler_tpu.obs import goodput as _goodput

    def as_abstract(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            tree,
        )

    with _goodput.span("checkpoint_restore"):
        step, restored = _restore_ladder(directory, step, lambda mgr, s: mgr.restore(
            s, args=ocp.args.Composite(
                params=ocp.args.StandardRestore(as_abstract(params_template)),
                opt_state=ocp.args.StandardRestore(as_abstract(opt_state_template)),
            )))

    # guarantee every leaf lands exactly on its template's sharding (orbax can
    # fall back to single-device placement for leaves without sharding info)
    def replace(tree, template):
        return jax.tree.map(
            lambda x, t: (
                jax.device_put(x, t.sharding) if getattr(t, "sharding", None) is not None else x
            ),
            tree,
            template,
        )

    return (
        step,
        replace(restored["params"], params_template),
        replace(restored["opt_state"], opt_state_template),
    )
