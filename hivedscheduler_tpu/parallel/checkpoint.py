"""Sharded train-state checkpointing (orbax).

The scheduler side persists placements in pod annotations (crash recovery);
this is the *workload* side: periodic save/restore of the sharded training
state so a gang that is preempted (or hits bad hardware and is rescheduled
onto a different sub-mesh) resumes from its last step. Restore distributes
each array directly to its target shards — no host-memory gather of the full
state.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax


def _manager(directory: str, max_to_keep: int = 3, create: bool = False):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=create),
    )


def save(directory: str, step: int, params: Any, opt_state: Any) -> None:
    """Save one checkpoint (blocking). Arrays keep their shardings."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory, create=True)
    mgr.save(step, args=ocp.args.Composite(
        params=ocp.args.StandardSave(params),
        opt_state=ocp.args.StandardSave(opt_state),
    ))
    mgr.wait_until_finished()
    mgr.close()


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None  # a read must not create the directory
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_params(
    directory: str,
    params_template: Any,
    step: Optional[int] = None,
) -> Tuple[int, Any]:
    """Restore (step, params) only — the optimizer state is left untouched.

    For inference (serving never needs moments) and for warm starts
    (``train --init-from``: fine-tune from a pretrained base with a fresh
    optimizer, including LoRA runs whose adapter-only optimizer tree never
    matches the pretraining checkpoint's)."""
    import orbax.checkpoint as ocp

    def as_abstract(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            tree,
        )

    if not os.path.isdir(directory):
        raise FileNotFoundError(f"no checkpoint found under {directory}")
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {directory}")
    restored = mgr.restore(step, args=ocp.args.Composite(
        params=ocp.args.StandardRestore(as_abstract(params_template)),
    ))
    mgr.close()
    params = jax.tree.map(
        lambda x, t: (
            jax.device_put(x, t.sharding) if getattr(t, "sharding", None) is not None else x
        ),
        restored["params"],
        params_template,
    )
    return step, params


def restore_serving_params(
    cfg: Any,
    checkpoint_dir: str,
    key: jax.Array,
    *,
    lora_rank: int = 0,
    lora_alpha: float = 16.0,
    lora_mlp: bool = False,
) -> Tuple[Any, Optional[int]]:
    """The generate/serve CLIs' one shared loading path: init a param tree
    (LoRA-shaped when ``lora_rank > 0`` so a fine-tune checkpoint restores),
    restore from ``checkpoint_dir`` when given, then merge the adapters into
    the base weights for serving. Returns (params, restored_step_or_None);
    raises FileNotFoundError like :func:`restore_params`."""
    import dataclasses

    from hivedscheduler_tpu.models import transformer as tm

    init_cfg = cfg
    if lora_rank > 0:
        init_cfg = dataclasses.replace(
            cfg, lora_rank=lora_rank, lora_alpha=lora_alpha,
            lora_mlp=lora_mlp,
        )
    params = tm.init_params(init_cfg, key)
    step = None
    if checkpoint_dir:
        step, params = restore_params(checkpoint_dir, params)
    if lora_rank > 0:
        params = tm.merge_lora(params, init_cfg)
    return params, step


def restore(
    directory: str,
    params_template: Any,
    opt_state_template: Any,
    step: Optional[int] = None,
) -> Tuple[int, Any, Any]:
    """Restore (step, params, opt_state).

    Templates are matching pytrees of ShapeDtypeStruct/arrays carrying the
    target shardings (e.g. the freshly initialized state of a new job
    incarnation on a different slice) — restored arrays land directly on
    those shards."""
    import orbax.checkpoint as ocp

    def as_abstract(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            tree,
        )

    if not os.path.isdir(directory):
        raise FileNotFoundError(f"no checkpoint found under {directory}")
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {directory}")
    restored = mgr.restore(step, args=ocp.args.Composite(
        params=ocp.args.StandardRestore(as_abstract(params_template)),
        opt_state=ocp.args.StandardRestore(as_abstract(opt_state_template)),
    ))
    mgr.close()

    # guarantee every leaf lands exactly on its template's sharding (orbax can
    # fall back to single-device placement for leaves without sharding info)
    def replace(tree, template):
        return jax.tree.map(
            lambda x, t: (
                jax.device_put(x, t.sharding) if getattr(t, "sharding", None) is not None else x
            ),
            tree,
            template,
        )

    return (
        step,
        replace(restored["params"], params_template),
        replace(restored["opt_state"], opt_state_template),
    )
