"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context support (first-class in this framework): queries stay put while
key/value blocks rotate around the ``sp`` ring one ICI hop per step
(``lax.ppermute``), with online-softmax accumulation so the result is exactly
standard attention. Communication overlaps compute under XLA's async
collectives, and per-chip memory is O(T/sp).

Also provides Ulysses-style all-to-all sequence parallelism
(:func:`ulysses_attention`): all_to_all swaps the sharded axis from sequence
to heads, runs local attention, and swaps back — cheaper for moderate
contexts when heads >= sp.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attention(q, k, v, q_offset, k_offset, causal, scale):
    """Online-softmax attention of a local q block against one k/v block.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]. Returns (o, m, l) partials with
    o: [B, H, Tq, D], m/l: [B, H, Tq] in f32.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + lax.iota(jnp.int32, q.shape[1])
        k_pos = k_offset + lax.iota(jnp.int32, k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1, so clamp
    m_safe = jnp.maximum(m, -0.5 * abs(NEG_INF))
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return o, m_safe, l


from hivedscheduler_tpu.parallel.shard_utils import varying as _varying


def _ring_forward(q, k, v, axis_name: str, causal: bool, mesh_axes):
    """Forward ring: rotate k/v, accumulate online softmax. Returns
    (out [B,Tq,H,D] in q.dtype, m [B,H,Tq] f32 row maxes, l [B,H,Tq] f32
    denominators)."""
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = 1.0 / (d**0.5)
    qf = q.astype(jnp.float32)

    o_acc = _varying(jnp.zeros((b, h, t_q, d), jnp.float32), mesh_axes)
    m_acc = _varying(jnp.full((b, h, t_q), NEG_INF, jnp.float32), mesh_axes)
    l_acc = _varying(jnp.zeros((b, h, t_q), jnp.float32), mesh_axes)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge_block(step, o_acc, m_acc, l_acc, k_cur, v_cur):
        """Attend the local q against the k/v block currently held (which
        originated on shard my_index - step), skipping blocks that a causal
        mask would zero out entirely."""
        src = (my_index - step) % axis_size

        def attend(args):
            o_acc, m_acc, l_acc, k_cur, v_cur = args
            o_blk, m_blk, l_blk = _block_attention(
                qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
                q_offset=my_index * t_q, k_offset=src * t_k,
                causal=causal, scale=scale,
            )
            m_new = jnp.maximum(m_acc, m_blk)
            corr_acc = jnp.exp(m_acc - m_new)
            corr_blk = jnp.exp(m_blk - m_new)
            o_acc = o_acc * corr_acc[..., None] + o_blk * corr_blk[..., None]
            l_acc = l_acc * corr_acc + l_blk * corr_blk
            return o_acc, m_new, l_acc

        if causal:
            # blocks entirely in my future are fully masked: skip the compute
            return lax.cond(
                src <= my_index,
                attend,
                lambda args: (args[0], args[1], args[2]),
                (o_acc, m_acc, l_acc, k_cur, v_cur),
            )
        return attend((o_acc, m_acc, l_acc, k_cur, v_cur))

    def body(step, carry):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        o_acc, m_acc, l_acc = merge_block(step, o_acc, m_acc, l_acc, k_cur, v_cur)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o_acc, m_acc, l_acc, k_nxt, v_nxt

    # rotate only axis_size-1 times; the final block attends outside the loop
    # so no wasted ICI transfer trails the ring
    o_acc, m_acc, l_acc, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, body, (o_acc, m_acc, l_acc, k, v)
    )
    o_acc, m_acc, l_acc = merge_block(
        axis_size - 1, o_acc, m_acc, l_acc, k_last, v_last
    )
    l_safe = jnp.where(l_acc == 0.0, 1.0, l_acc)
    out = (o_acc / l_safe[..., None]).astype(q.dtype)
    return jnp.einsum("bhqd->bqhd", out), m_acc, l_acc


def _ring_backward(q, k, v, out, m, l, g, axis_name: str, causal: bool, mesh_axes):
    """Flash-style backward ring: q/do/delta stay put while k/v travel with
    their gradient accumulators; after a full rotation dk/dv arrive home.
    Per-device memory is O(local block), not O(steps x block) — the reason
    for the custom VJP instead of autodiff through the forward loop."""
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = 1.0 / (d**0.5)

    qf = jnp.einsum("bqhd->bhqd", q.astype(jnp.float32))
    do = jnp.einsum("bqhd->bhqd", g.astype(jnp.float32))
    of = jnp.einsum("bqhd->bhqd", out.astype(jnp.float32))
    delta = jnp.sum(do * of, axis=-1)  # [B,H,Tq]
    m_safe = jnp.maximum(m, -0.5 * abs(NEG_INF))
    l_safe = jnp.where(l == 0.0, 1.0, l)

    dq = _varying(jnp.zeros((b, h, t_q, d), jnp.float32), mesh_axes)
    dk0 = _varying(jnp.zeros((b, h, t_k, d), jnp.float32), mesh_axes)
    dv0 = _varying(jnp.zeros((b, h, t_k, d), jnp.float32), mesh_axes)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur):
        """Gradient contributions of the block currently held (originating on
        shard my_index - step); fully-masked causal blocks are skipped."""
        src = (my_index - step) % axis_size

        def attend(args):
            dq, dk_cur, dv_cur, k_cur, v_cur = args
            kf = jnp.einsum("bkhd->bhkd", k_cur.astype(jnp.float32))
            vf = jnp.einsum("bkhd->bhkd", v_cur.astype(jnp.float32))
            s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
            if causal:
                q_pos = my_index * t_q + lax.iota(jnp.int32, t_q)
                k_pos = src * t_k + lax.iota(jnp.int32, t_k)
                mask = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(mask[None, None], s, NEG_INF)
            # exact probabilities from the saved global max and denominator
            p = jnp.exp(s - m_safe[..., None]) / l_safe[..., None]
            dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, do)
            dp = jnp.einsum("bhqd,bhkd->bhqk", do, vf)
            ds = p * (dp - delta[..., None])
            dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
            dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
            return dq, dk_cur + dk_blk, dv_cur + dv_blk

        if causal:
            return lax.cond(
                src <= my_index,
                attend,
                lambda args: (args[0], args[1], args[2]),
                (dq, dk_cur, dv_cur, k_cur, v_cur),
            )
        return attend((dq, dk_cur, dv_cur, k_cur, v_cur))

    def body(step, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        dq, dk_cur, dv_cur = merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur)
        # rotate the block AND its gradient accumulators together
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = lax.ppermute(dv_cur, axis_name, perm)
        return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

    # n-1 full rotations, then the final block attends without rotating k/v
    # (they are no longer needed); only dk/dv take the last hop home
    dq, k_last, v_last, dk_last, dv_last = lax.fori_loop(
        0, axis_size - 1, body, (dq, k, v, dk0, dv0)
    )
    dq, dk_last, dv_last = merge_grad(
        axis_size - 1, dq, dk_last, dv_last, k_last, v_last
    )
    dk = lax.ppermute(dk_last, axis_name, perm)
    dv = lax.ppermute(dv_last, axis_name, perm)
    return (
        jnp.einsum("bhqd->bqhd", dq).astype(q.dtype),
        jnp.einsum("bhkd->bkhd", dk).astype(k.dtype),
        jnp.einsum("bhkd->bkhd", dv).astype(v.dtype),
    )


_RING_CORES = {}


def _ring_core(axis_name: str, causal: bool, mesh_axes):
    """custom_vjp-wrapped ring attention core, cached per configuration."""
    key = (axis_name, causal, tuple(mesh_axes))
    core = _RING_CORES.get(key)
    if core is not None:
        return core

    @jax.custom_vjp
    def core(q, k, v):
        out, _, _ = _ring_forward(q, k, v, axis_name, causal, mesh_axes)
        return out

    def fwd(q, k, v):
        out, m, l = _ring_forward(q, k, v, axis_name, causal, mesh_axes)
        return out, (q, k, v, out, m, l)

    def bwd(res, g):
        q, k, v, out, m, l = res
        return _ring_backward(q, k, v, out, m, l, g, axis_name, causal, mesh_axes)

    core.defvjp(fwd, bwd)
    _RING_CORES[key] = core
    return core


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, mesh_axes=()):
    """Per-shard body (runs under shard_map): forward ring with a hand-written
    flash-style backward (memory O(local block) instead of autodiff's
    O(ring steps) saved carries)."""
    return _ring_core(axis_name, causal, mesh_axes)(q, k, v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = "sp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    causal: bool = True,
) -> jax.Array:
    """Exact attention with q/k/v sharded on ``seq_axis`` over `mesh`.

    Inputs are [B, T, H, D] logically; physically T is split over ``seq_axis``,
    B over ``batch_axes``, H over ``head_axis``.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(batch_axes, seq_axis, head_axis, None)
    # accumulators inside must be varying exactly over the sharded axes
    vma_axes = tuple(batch_axes) + (seq_axis,) + ((head_axis,) if head_axis else ())
    fn = shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=seq_axis,
            causal=causal,
            mesh_axes=vma_axes,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name: str, causal: bool):
    """All-to-all swap: [B, T/sp, H, D] -> [B, T, H/sp, D], local attention,
    swap back. Requires H % sp == 0."""
    from hivedscheduler_tpu.ops.attention import xla_attention

    # concat_axis=T (1), split_axis=H (2): gather full sequence, split heads
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = xla_attention(q, k, v, causal=causal)
    # swap back: split sequence, gather heads
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = "sp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    causal: bool = True,
) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism via all_to_all."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(batch_axes, seq_axis, head_axis, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
