"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context support (first-class in this framework): queries stay put while
key/value blocks rotate around the ``sp`` ring one ICI hop per step
(``lax.ppermute``), with online-softmax accumulation so the result is exactly
standard attention. Communication overlaps compute under XLA's async
collectives, and per-chip memory is O(T/sp).

Also provides Ulysses-style all-to-all sequence parallelism
(:func:`ulysses_attention`): all_to_all swaps the sharded axis from sequence
to heads, runs local attention, and swaps back — cheaper for moderate
contexts when heads >= sp.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _get_shard_map():
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax import lax as _lax

    if hasattr(_lax, "pcast") or hasattr(_lax, "pvary"):
        return shard_map  # vma-era JAX: keep the checker on (see shard_utils)
    # pre-vma JAX (e.g. 0.4.x): there is no pvary to seed varying state, and
    # the legacy replication checker rejects the hand-written ring
    # collectives it cannot type — run with check_rep off, numerics
    # unchanged (the guard tests compare against the XLA reference either
    # way)

    def compat(f=None, **kw):
        kw.pop("check_vma", None)
        kw.setdefault("check_rep", False)
        if f is None:
            return lambda g: shard_map(g, **kw)
        return shard_map(f, **kw)

    return compat


def _block_attention_pos(q, k, v, q_pos, k_pos, scale, masked: bool):
    """Online-softmax attention of a local q block against one k/v block,
    with explicit per-row positions (zigzag chunks are non-contiguous);
    ``masked=False`` skips the mask for blocks known fully visible.

    q: [B, Tq, H, D]; k/v: [B, Tk, H_kv, D] where H_kv may divide H (GQA —
    q head i shares k/v head i // (H/H_kv); the compact k/v is consumed via
    grouped einsums, never materialized at H heads). Returns (o, m, l)
    partials with o: [B, H, Tq, D], m/l: [B, H, Tq] in f32.
    """
    b, t_q, h, d = q.shape
    h_kv = k.shape[2]
    gsz = h // h_kv  # 1 for MHA; the size-1 group dim is free in XLA
    qg = q.reshape(b, t_q, h_kv, gsz, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if masked:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1, so clamp
    m_safe = jnp.maximum(m, -0.5 * abs(NEG_INF))
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhgqk,bkhd->bhgqd", p, v.astype(jnp.float32))
    return (
        o.reshape(b, h, t_q, d),
        m_safe.reshape(b, h, t_q),
        l.reshape(b, h, t_q),
    )


def _block_attention(q, k, v, q_offset, k_offset, causal, scale):
    """Contiguous-block wrapper over :func:`_block_attention_pos`."""
    q_pos = q_offset + lax.iota(jnp.int32, q.shape[1])
    k_pos = k_offset + lax.iota(jnp.int32, k.shape[1])
    return _block_attention_pos(q, k, v, q_pos, k_pos, scale, masked=causal)


def _block_grad(qh, doh, mh, lh, dh, kf, vf, q_pos, k_pos, scale, masked):
    """Gradients of one attention block (shared by the ring and zigzag
    backwards). qh/doh: [B,H,Tq,D]; mh/lh/dh: [B,H,Tq]; kf/vf:
    [B,H_kv,Tk,D] with H_kv | H (compact GQA k/v, consumed via grouped
    einsums). Returns (dq_blk [B,H,Tq,D], dk_blk/dv_blk [B,H_kv,Tk,D]) —
    dk/dv pre-summed over each kv head's q group."""
    b, h, t_q, d = qh.shape
    h_kv = kf.shape[1]
    gsz = h // h_kv  # 1 for MHA; the size-1 group dim is free in XLA
    qg = qh.reshape(b, h_kv, gsz, t_q, d)
    dog = doh.reshape(b, h_kv, gsz, t_q, d)
    mg = mh.reshape(b, h_kv, gsz, t_q)
    lg = lh.reshape(b, h_kv, gsz, t_q)
    dg = dh.reshape(b, h_kv, gsz, t_q)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kf) * scale
    if masked:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    p = jnp.exp(s - mg[..., None]) / lg[..., None]
    dv_blk = jnp.einsum("bhgqk,bhgqd->bhkd", p, dog)
    dp = jnp.einsum("bhgqd,bhkd->bhgqk", dog, vf)
    ds = p * (dp - dg[..., None])
    dq_blk = jnp.einsum("bhgqk,bhkd->bhgqd", ds, kf).reshape(b, h, t_q, d) * scale
    dk_blk = jnp.einsum("bhgqk,bhgqd->bhkd", ds, qg) * scale
    return dq_blk, dk_blk, dv_blk


def _merge_partial(acc, blk):
    """Merge one (o, m, l) online-softmax partial into an accumulator
    triple — the single home of the numerically delicate merge."""
    o_acc, m_acc, l_acc = acc
    o_blk, m_blk, l_blk = blk
    m_new = jnp.maximum(m_acc, m_blk)
    corr_acc = jnp.exp(m_acc - m_new)
    corr_blk = jnp.exp(m_blk - m_new)
    return (
        o_acc * corr_acc[..., None] + o_blk * corr_blk[..., None],
        m_new,
        l_acc * corr_acc + l_blk * corr_blk,
    )


from hivedscheduler_tpu.parallel.shard_utils import varying as _varying


def _ring_forward(q, k, v, axis_name: str, causal: bool, mesh_axes):
    """Forward ring: rotate k/v, accumulate online softmax. Returns
    (out [B,Tq,H,D] in q.dtype, m [B,H,Tq] f32 row maxes, l [B,H,Tq] f32
    denominators)."""
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = 1.0 / (d**0.5)
    qf = q.astype(jnp.float32)

    o_acc = _varying(jnp.zeros((b, h, t_q, d), jnp.float32), mesh_axes)
    m_acc = _varying(jnp.full((b, h, t_q), NEG_INF, jnp.float32), mesh_axes)
    l_acc = _varying(jnp.zeros((b, h, t_q), jnp.float32), mesh_axes)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge_block(step, o_acc, m_acc, l_acc, k_cur, v_cur):
        """Attend the local q against the k/v block currently held (which
        originated on shard my_index - step), skipping blocks that a causal
        mask would zero out entirely."""
        src = (my_index - step) % axis_size

        def attend(args):
            o_acc, m_acc, l_acc, k_cur, v_cur = args
            blk = _block_attention(
                qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
                q_offset=my_index * t_q, k_offset=src * t_k,
                causal=causal, scale=scale,
            )
            return _merge_partial((o_acc, m_acc, l_acc), blk)

        if causal:
            # blocks entirely in my future are fully masked: skip the compute
            return lax.cond(
                src <= my_index,
                attend,
                lambda args: (args[0], args[1], args[2]),
                (o_acc, m_acc, l_acc, k_cur, v_cur),
            )
        return attend((o_acc, m_acc, l_acc, k_cur, v_cur))

    def body(step, carry):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        o_acc, m_acc, l_acc = merge_block(step, o_acc, m_acc, l_acc, k_cur, v_cur)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o_acc, m_acc, l_acc, k_nxt, v_nxt

    # rotate only axis_size-1 times; the final block attends outside the loop
    # so no wasted ICI transfer trails the ring
    o_acc, m_acc, l_acc, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, body, (o_acc, m_acc, l_acc, k, v)
    )
    o_acc, m_acc, l_acc = merge_block(
        axis_size - 1, o_acc, m_acc, l_acc, k_last, v_last
    )
    l_safe = jnp.where(l_acc == 0.0, 1.0, l_acc)
    out = (o_acc / l_safe[..., None]).astype(q.dtype)
    return jnp.einsum("bhqd->bqhd", out), m_acc, l_acc


def _ring_backward(q, k, v, out, m, l, g, axis_name: str, causal: bool, mesh_axes):
    """Flash-style backward ring: q/do/delta stay put while k/v travel with
    their gradient accumulators; after a full rotation dk/dv arrive home.
    Per-device memory is O(local block), not O(steps x block) — the reason
    for the custom VJP instead of autodiff through the forward loop."""
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = 1.0 / (d**0.5)

    qf = jnp.einsum("bqhd->bhqd", q.astype(jnp.float32))
    do = jnp.einsum("bqhd->bhqd", g.astype(jnp.float32))
    of = jnp.einsum("bqhd->bhqd", out.astype(jnp.float32))
    delta = jnp.sum(do * of, axis=-1)  # [B,H,Tq]
    m_safe = jnp.maximum(m, -0.5 * abs(NEG_INF))
    l_safe = jnp.where(l == 0.0, 1.0, l)

    h_kv = k.shape[2]
    dq = _varying(jnp.zeros((b, h, t_q, d), jnp.float32), mesh_axes)
    dk0 = _varying(jnp.zeros((b, h_kv, t_k, d), jnp.float32), mesh_axes)
    dv0 = _varying(jnp.zeros((b, h_kv, t_k, d), jnp.float32), mesh_axes)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur):
        """Gradient contributions of the block currently held (originating on
        shard my_index - step); fully-masked causal blocks are skipped."""
        src = (my_index - step) % axis_size

        def attend(args):
            dq, dk_cur, dv_cur, k_cur, v_cur = args
            kf = jnp.einsum("bkhd->bhkd", k_cur.astype(jnp.float32))
            vf = jnp.einsum("bkhd->bhkd", v_cur.astype(jnp.float32))
            q_pos = my_index * t_q + lax.iota(jnp.int32, t_q)
            k_pos = src * t_k + lax.iota(jnp.int32, t_k)
            dq_blk, dk_blk, dv_blk = _block_grad(
                qf, do, m_safe, l_safe, delta, kf, vf, q_pos, k_pos, scale,
                masked=causal,
            )
            return dq + dq_blk, dk_cur + dk_blk, dv_cur + dv_blk

        if causal:
            return lax.cond(
                src <= my_index,
                attend,
                lambda args: (args[0], args[1], args[2]),
                (dq, dk_cur, dv_cur, k_cur, v_cur),
            )
        return attend((dq, dk_cur, dv_cur, k_cur, v_cur))

    def body(step, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        dq, dk_cur, dv_cur = merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur)
        # rotate the block AND its gradient accumulators together
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = lax.ppermute(dv_cur, axis_name, perm)
        return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

    # n-1 full rotations, then the final block attends without rotating k/v
    # (they are no longer needed); only dk/dv take the last hop home
    dq, k_last, v_last, dk_last, dv_last = lax.fori_loop(
        0, axis_size - 1, body, (dq, k, v, dk0, dv0)
    )
    dq, dk_last, dv_last = merge_grad(
        axis_size - 1, dq, dk_last, dv_last, k_last, v_last
    )
    dk = lax.ppermute(dk_last, axis_name, perm)
    dv = lax.ppermute(dv_last, axis_name, perm)
    return (
        jnp.einsum("bhqd->bqhd", dq).astype(q.dtype),
        jnp.einsum("bhkd->bkhd", dk).astype(k.dtype),
        jnp.einsum("bhkd->bkhd", dv).astype(v.dtype),
    )


_RING_CORES = {}


def _make_vjp_core(cache: dict, key, forward_fn, backward_fn):
    """custom_vjp-wrapped flash-style core, cached per configuration.
    ``forward_fn(q, k, v) -> (out, *stats)`` — stats are whatever softmax
    residuals the matching backward needs ((m, l) for the einsum rings,
    (lse,) for the flash-block ring);
    ``backward_fn(q, k, v, out, *stats, g) -> (dq, dk, dv)``."""
    core = cache.get(key)
    if core is not None:
        return core

    @jax.custom_vjp
    def core(q, k, v):
        return forward_fn(q, k, v)[0]

    def fwd(q, k, v):
        out, *stats = forward_fn(q, k, v)
        return out, (q, k, v, out, *stats)

    def bwd(res, g):
        return backward_fn(*res, g)

    core.defvjp(fwd, bwd)
    cache[key] = core
    return core


def _ring_core(axis_name: str, causal: bool, mesh_axes):
    return _make_vjp_core(
        _RING_CORES,
        (axis_name, causal, tuple(mesh_axes)),
        functools.partial(
            _ring_forward, axis_name=axis_name, causal=causal, mesh_axes=mesh_axes
        ),
        functools.partial(
            _ring_backward, axis_name=axis_name, causal=causal, mesh_axes=mesh_axes
        ),
    )


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, mesh_axes=()):
    """Per-shard body (runs under shard_map): forward ring with a hand-written
    flash-style backward (memory O(local block) instead of autodiff's
    O(ring steps) saved carries)."""
    return _ring_core(axis_name, causal, mesh_axes)(q, k, v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = "sp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    causal: bool = True,
) -> jax.Array:
    """Exact attention with q/k/v sharded on ``seq_axis`` over `mesh`.

    Inputs are [B, T, H, D] logically; physically T is split over ``seq_axis``,
    B over ``batch_axes``, H over ``head_axis``.
    """
    shard_map = _get_shard_map()

    spec = P(batch_axes, seq_axis, head_axis, None)
    # accumulators inside must be varying exactly over the sharded axes
    vma_axes = tuple(batch_axes) + (seq_axis,) + ((head_axis,) if head_axis else ())
    fn = shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=seq_axis,
            causal=causal,
            mesh_axes=vma_axes,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ring schedule with Pallas flash-attention blocks
# ---------------------------------------------------------------------------
#
# ``_ring_forward`` materializes a [B, H, T_loc, T_loc] f32 score block per
# ring step in einsum. The flash variant instead runs each (local q) x
# (traveling k/v) pair through the fused Pallas kernels (ops/attention.py):
# scores never leave VMEM, so per-shard attention memory drops from
# O(T_loc^2) to O(T_loc x D). Partials merge with the standard (o, lse)
# combine, and the backward maps 1:1 onto the flash backward kernels because
# they take the GLOBAL log-sum-exp: each ring step yields exact dq/dk/dv
# partials that accumulate (dq stays put; dk/dv travel with their k/v,
# exactly like ``_ring_backward``).
#
# Under a causal mask every ring block is either fully visible
# (src < my_index: unmasked kernel) or the aligned diagonal (src == my_index:
# causal kernel) — arbitrary-offset masks never arise, so the kernels need no
# position plumbing.


def _flash_block(q, k, v, diag: bool, block_q, block_k, interpret, vma):
    """One ring block through the flash forward kernel -> (o [B,H,Tq,D] f32,
    lse [B,H,Tq] f32). ``diag``: aligned causal diagonal vs fully visible."""
    from hivedscheduler_tpu.ops import attention as fa

    o, lse = fa._flash_forward(
        q, k, v, causal=diag, block_q=block_q, block_k=block_k,
        interpret=interpret, vma=vma, out_dtype=jnp.float32,
    )
    b, t_q, h, _ = q.shape
    return jnp.einsum("bqhd->bhqd", o), lse[:, :, 0].reshape(b, h, t_q)


def _merge_flash_partial(acc, blk):
    """Merge (o, lse) online-softmax partials: each o is normalized within
    its own blocks, so the combined output needs no final division."""
    o_acc, lse_acc = acc
    o_blk, lse_blk = blk
    lse_new = jnp.logaddexp(lse_acc, lse_blk)
    return (
        o_acc * jnp.exp(lse_acc - lse_new)[..., None]
        + o_blk * jnp.exp(lse_blk - lse_new)[..., None],
        lse_new,
    )


def _ring_flash_forward(q, k, v, axis_name: str, causal: bool, mesh_axes,
                        block_q: int, block_k: int, interpret: bool):
    """Forward ring over flash blocks. Returns (out [B,Tq,H,D] in q.dtype,
    lse [B,H,Tq] f32 — the only residual the backward kernels need)."""
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    kw = dict(block_q=block_q, block_k=block_k, interpret=interpret,
              vma=mesh_axes)

    o_acc = _varying(jnp.zeros((b, h, t_q, d), jnp.float32), mesh_axes)
    lse_acc = _varying(jnp.full((b, h, t_q), NEG_INF, jnp.float32), mesh_axes)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge_block(step, o_acc, lse_acc, k_cur, v_cur):
        src = (my_index - step) % axis_size

        def attend(diag):
            def f(args):
                o_acc, lse_acc, k_cur, v_cur = args
                return _merge_flash_partial(
                    (o_acc, lse_acc),
                    _flash_block(q, k_cur, v_cur, diag=diag, **kw),
                )
            return f

        if not causal:
            return attend(False)((o_acc, lse_acc, k_cur, v_cur))
        return lax.cond(
            src <= my_index,
            lambda args: lax.cond(src == my_index, attend(True),
                                  attend(False), args),
            lambda args: (args[0], args[1]),
            (o_acc, lse_acc, k_cur, v_cur),
        )

    def body(step, carry):
        o_acc, lse_acc, k_cur, v_cur = carry
        o_acc, lse_acc = merge_block(step, o_acc, lse_acc, k_cur, v_cur)
        return (
            o_acc, lse_acc,
            lax.ppermute(k_cur, axis_name, perm),
            lax.ppermute(v_cur, axis_name, perm),
        )

    # rotate axis_size-1 times; the final block attends outside the loop so
    # no wasted ICI transfer trails the ring (same shape as _ring_forward)
    o_acc, lse_acc, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, body, (o_acc, lse_acc, k, v)
    )
    o_acc, lse_acc = merge_block(axis_size - 1, o_acc, lse_acc, k_last, v_last)
    return jnp.einsum("bhqd->bqhd", o_acc).astype(q.dtype), lse_acc


def _ring_flash_backward(q, k, v, out, lse, g, axis_name: str, causal: bool,
                         mesh_axes, block_q: int, block_k: int,
                         interpret: bool):
    """Backward ring over the flash backward kernels: q/do/out/lse stay put,
    k/v travel with their f32 dk/dv accumulators; after a full rotation the
    gradients take one last hop home (mirrors ``_ring_backward``)."""
    from hivedscheduler_tpu.ops import attention as fa

    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k, h_kv = k.shape[1], k.shape[2]
    # kernels take the global lse lane-broadcast as [B*H, Tq, 128]
    lse_lanes = jnp.broadcast_to(
        lse.reshape(b * h, t_q, 1), (b * h, t_q, fa._LANES)
    )
    kw = dict(block_q=block_q, block_k=block_k, interpret=interpret,
              vma=mesh_axes, grad_dtype=jnp.float32)

    dq = _varying(jnp.zeros((b, t_q, h, d), jnp.float32), mesh_axes)
    dk0 = _varying(jnp.zeros((b, t_k, h_kv, d), jnp.float32), mesh_axes)
    dv0 = _varying(jnp.zeros((b, t_k, h_kv, d), jnp.float32), mesh_axes)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur):
        src = (my_index - step) % axis_size

        def attend(diag):
            def f(args):
                dq, dk_cur, dv_cur, k_cur, v_cur = args
                dq_blk, dk_blk, dv_blk = fa._flash_backward(
                    q, k_cur, v_cur, out, lse_lanes, g, causal=diag, **kw
                )
                return dq + dq_blk, dk_cur + dk_blk, dv_cur + dv_blk
            return f

        if not causal:
            return attend(False)((dq, dk_cur, dv_cur, k_cur, v_cur))
        return lax.cond(
            src <= my_index,
            lambda args: lax.cond(src == my_index, attend(True),
                                  attend(False), args),
            lambda args: (args[0], args[1], args[2]),
            (dq, dk_cur, dv_cur, k_cur, v_cur),
        )

    def body(step, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        dq, dk_cur, dv_cur = merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur)
        return (
            dq,
            lax.ppermute(k_cur, axis_name, perm),
            lax.ppermute(v_cur, axis_name, perm),
            lax.ppermute(dk_cur, axis_name, perm),
            lax.ppermute(dv_cur, axis_name, perm),
        )

    dq, k_last, v_last, dk_last, dv_last = lax.fori_loop(
        0, axis_size - 1, body, (dq, k, v, dk0, dv0)
    )
    dq, dk_last, dv_last = merge_grad(
        axis_size - 1, dq, dk_last, dv_last, k_last, v_last
    )
    dk = lax.ppermute(dk_last, axis_name, perm)
    dv = lax.ppermute(dv_last, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_RING_FLASH_CORES = {}


def _ring_flash_core(axis_name: str, causal: bool, mesh_axes, block_q: int,
                     block_k: int, interpret: bool):
    """custom_vjp core for the flash-block ring, cached per configuration
    (the residual is (q, k, v, out, lse) — no O(T^2) score state)."""
    kw = dict(axis_name=axis_name, causal=causal, mesh_axes=mesh_axes,
              block_q=block_q, block_k=block_k, interpret=interpret)
    return _make_vjp_core(
        _RING_FLASH_CORES,
        (axis_name, causal, tuple(mesh_axes), block_q, block_k, interpret),
        functools.partial(_ring_flash_forward, **kw),
        functools.partial(_ring_flash_backward, **kw),
    )


def _ring_flash_attention_local(q, k, v, axis_name: str, causal: bool = True,
                                mesh_axes=(), block_q: int = 128,
                                block_k: int = 128):
    """Per-shard body (runs under shard_map): the ring schedule with every
    block computed by the Pallas flash kernels. Falls back to the einsum
    ring when the kernels can't run — no pallas, shapes that don't tile, or
    interpret mode inside a vma-checked manual context (same rule as
    ``ops.attention.flash_attention``: the HLO interpreter cannot type the
    kernel's fresh accumulators under vma checking; on real TPU the compiled
    kernel is opaque and the vma-stamped out_shapes type it)."""
    from hivedscheduler_tpu.ops import attention as fa

    b, t_loc, h, d = q.shape
    h_kv = k.shape[2]
    block_q = min(block_q, t_loc)
    block_k = min(block_k, t_loc)
    interpret = jax.default_backend() != "tpu"
    if (fa.pl is None or t_loc % block_q or t_loc % block_k or d % 8
            or (h_kv and h % h_kv) or (interpret and mesh_axes)):
        return _ring_attention_local(q, k, v, axis_name, causal, mesh_axes)
    return _ring_flash_core(
        axis_name, causal, tuple(mesh_axes), block_q, block_k, interpret
    )(q, k, v)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = "sp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Exact ring attention whose per-step blocks run through the Pallas
    flash kernels — same contract as :func:`ring_attention`, with per-shard
    attention memory O(T_loc x D) instead of O(T_loc^2)."""
    shard_map = _get_shard_map()

    spec = P(batch_axes, seq_axis, head_axis, None)
    vma_axes = tuple(batch_axes) + (seq_axis,) + ((head_axis,) if head_axis else ())
    fn = shard_map(
        functools.partial(
            _ring_flash_attention_local,
            axis_name=seq_axis,
            causal=causal,
            mesh_axes=vma_axes,
            block_q=block_q,
            block_k=block_k,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Zigzag ring schedule (balanced causal load)
# ---------------------------------------------------------------------------
#
# With contiguous blocks, the causal skip makes shard 0 compute 1 block and
# shard n-1 compute n blocks — the ring stalls on the last shard. In the
# zigzag layout shard i owns sequence chunks (i, 2n-1-i) (half-blocks), and
# each ring step costs every shard the same ~2 quarter-blocks:
#   (hi_q, lo_k): always fully visible  -> unmasked dense
#   (hi_q, hi_k): visible iff src >= i  -> cond-skipped otherwise
#   (lo_q, lo_k): visible iff i >= src  -> cond-skipped otherwise
#   (lo_q, hi_k): never visible         -> never computed
# Total per shard = 2n+1 quarter-blocks, constant across the ring.


def _zigzag_chunk_pos(chunk, half):
    return chunk * half + lax.iota(jnp.int32, half)


def _zigzag_forward(q, k, v, axis_name: str, mesh_axes):
    """Forward zigzag ring (causal). Local rows are [chunk i, chunk 2n-1-i],
    each of ``half`` rows. Returns (out, m, l) like _ring_forward."""
    axis_size = lax.psum(1, axis_name)
    i = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    half = t // 2
    scale = 1.0 / (d**0.5)
    qf = q.astype(jnp.float32)
    q_lo, q_hi = qf[:, :half], qf[:, half:]
    pos_lo = _zigzag_chunk_pos(i, half)
    pos_hi = _zigzag_chunk_pos(2 * axis_size - 1 - i, half)

    def zeros():
        return (
            _varying(jnp.zeros((b, h, half, d), jnp.float32), mesh_axes),
            _varying(jnp.full((b, h, half), NEG_INF, jnp.float32), mesh_axes),
            _varying(jnp.zeros((b, h, half), jnp.float32), mesh_axes),
        )

    acc_lo, acc_hi = zeros(), zeros()
    perm = [(s, (s + 1) % axis_size) for s in range(axis_size)]

    def merge_block(step, acc_lo, acc_hi, k_cur, v_cur):
        src = (i - step) % axis_size
        k_lo, k_hi = k_cur[:, :half], k_cur[:, half:]
        v_lo, v_hi = v_cur[:, :half], v_cur[:, half:]
        kpos_lo = _zigzag_chunk_pos(src, half)
        kpos_hi = _zigzag_chunk_pos(2 * axis_size - 1 - src, half)

        # (hi_q, lo_k): chunk 2n-1-i vs chunk src — always fully visible
        acc_hi = _merge_partial(acc_hi, _block_attention_pos(
            q_hi, k_lo.astype(jnp.float32), v_lo, pos_hi, kpos_lo, scale,
            masked=False,
        ))

        # (hi_q, hi_k): visible iff src >= i (diagonal at src == i)
        def attend_hi(acc):
            return _merge_partial(acc, _block_attention_pos(
                q_hi, k_hi.astype(jnp.float32), v_hi, pos_hi, kpos_hi, scale,
                masked=True,
            ))

        acc_hi = lax.cond(src >= i, attend_hi, lambda a: a, acc_hi)

        # (lo_q, lo_k): visible iff i >= src (diagonal at src == i)
        def attend_lo(acc):
            return _merge_partial(acc, _block_attention_pos(
                q_lo, k_lo.astype(jnp.float32), v_lo, pos_lo, kpos_lo, scale,
                masked=True,
            ))

        acc_lo = lax.cond(i >= src, attend_lo, lambda a: a, acc_lo)
        return acc_lo, acc_hi

    def body(step, carry):
        acc_lo, acc_hi, k_cur, v_cur = carry
        acc_lo, acc_hi = merge_block(step, acc_lo, acc_hi, k_cur, v_cur)
        return (
            acc_lo, acc_hi,
            lax.ppermute(k_cur, axis_name, perm),
            lax.ppermute(v_cur, axis_name, perm),
        )

    acc_lo, acc_hi, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, body, (acc_lo, acc_hi, k, v)
    )
    acc_lo, acc_hi = merge_block(axis_size - 1, acc_lo, acc_hi, k_last, v_last)

    def finish(acc):
        o_acc, m_acc, l_acc = acc
        l_safe = jnp.where(l_acc == 0.0, 1.0, l_acc)
        return (o_acc / l_safe[..., None]).astype(q.dtype)

    out = jnp.concatenate(
        [jnp.einsum("bhqd->bqhd", finish(acc_lo)),
         jnp.einsum("bhqd->bqhd", finish(acc_hi))], axis=1,
    )
    m = jnp.concatenate([acc_lo[1], acc_hi[1]], axis=2)
    l = jnp.concatenate([acc_lo[2], acc_hi[2]], axis=2)
    return out, m, l


def _zigzag_backward(q, k, v, out, m, l, g, axis_name: str, mesh_axes):
    """Backward zigzag ring: same 3-sub-block schedule; dk/dv accumulators
    travel with their k/v halves and arrive home after a full rotation."""
    axis_size = lax.psum(1, axis_name)
    i = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    half = t // 2
    scale = 1.0 / (d**0.5)

    qf = jnp.einsum("bqhd->bhqd", q.astype(jnp.float32))
    do = jnp.einsum("bqhd->bhqd", g.astype(jnp.float32))
    of = jnp.einsum("bqhd->bhqd", out.astype(jnp.float32))
    delta = jnp.sum(do * of, axis=-1)  # [B,H,T]
    m_safe = jnp.maximum(m, -0.5 * abs(NEG_INF))
    l_safe = jnp.where(l == 0.0, 1.0, l)

    pos_lo = _zigzag_chunk_pos(i, half)
    pos_hi = _zigzag_chunk_pos(2 * axis_size - 1 - i, half)
    halves = {
        0: (qf[:, :, :half], do[:, :, :half], m_safe[:, :, :half],
            l_safe[:, :, :half], delta[:, :, :half], pos_lo),
        1: (qf[:, :, half:], do[:, :, half:], m_safe[:, :, half:],
            l_safe[:, :, half:], delta[:, :, half:], pos_hi),
    }

    h_kv = k.shape[2]
    dq = _varying(jnp.zeros((b, h, t, d), jnp.float32), mesh_axes)
    dkv0 = (
        _varying(jnp.zeros((b, h_kv, t, d), jnp.float32), mesh_axes),
        _varying(jnp.zeros((b, h_kv, t, d), jnp.float32), mesh_axes),
    )
    perm = [(s, (s + 1) % axis_size) for s in range(axis_size)]

    def sub_grad(q_half, k_cur, v_cur, dq, dk_cur, dv_cur, q_slice, k_slice,
                 kpos, masked):
        """Gradients of one quarter-block; q_slice/k_slice are static row
        ranges into the local q / traveling kv tensors."""
        qh, doh, mh, lh, dh, qpos = q_half
        kf = jnp.einsum("bkhd->bhkd", k_cur[:, k_slice].astype(jnp.float32))
        vf = jnp.einsum("bkhd->bhkd", v_cur[:, k_slice].astype(jnp.float32))
        dq_blk, dk_blk, dv_blk = _block_grad(
            qh, doh, mh, lh, dh, kf, vf, qpos, kpos, scale, masked=masked,
        )
        dq = dq.at[:, :, q_slice].add(dq_blk)
        dk_cur = dk_cur.at[:, :, k_slice].add(dk_blk)
        dv_cur = dv_cur.at[:, :, k_slice].add(dv_blk)
        return dq, dk_cur, dv_cur

    lo_s, hi_s = slice(0, half), slice(half, t)

    def merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur):
        src = (i - step) % axis_size
        kpos_lo = _zigzag_chunk_pos(src, half)
        kpos_hi = _zigzag_chunk_pos(2 * axis_size - 1 - src, half)

        # (hi_q, lo_k) unmasked
        dq, dk_cur, dv_cur = sub_grad(
            halves[1], k_cur, v_cur, dq, dk_cur, dv_cur, hi_s, lo_s,
            kpos_lo, masked=False,
        )

        def g_hi(args):
            dq, dk_cur, dv_cur = args
            return sub_grad(halves[1], k_cur, v_cur, dq, dk_cur, dv_cur,
                            hi_s, hi_s, kpos_hi, masked=True)

        dq, dk_cur, dv_cur = lax.cond(
            src >= i, g_hi, lambda a: a, (dq, dk_cur, dv_cur))

        def g_lo(args):
            dq, dk_cur, dv_cur = args
            return sub_grad(halves[0], k_cur, v_cur, dq, dk_cur, dv_cur,
                            lo_s, lo_s, kpos_lo, masked=True)

        dq, dk_cur, dv_cur = lax.cond(
            i >= src, g_lo, lambda a: a, (dq, dk_cur, dv_cur))
        return dq, dk_cur, dv_cur

    def body(step, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        dq, dk_cur, dv_cur = merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur)
        return (
            dq,
            lax.ppermute(k_cur, axis_name, perm),
            lax.ppermute(v_cur, axis_name, perm),
            lax.ppermute(dk_cur, axis_name, perm),
            lax.ppermute(dv_cur, axis_name, perm),
        )

    dq, k_last, v_last, dk_last, dv_last = lax.fori_loop(
        0, axis_size - 1, body, (dq, k, v) + dkv0
    )
    dq, dk_last, dv_last = merge_grad(axis_size - 1, dq, dk_last, dv_last,
                                      k_last, v_last)
    dk = lax.ppermute(dk_last, axis_name, perm)
    dv = lax.ppermute(dv_last, axis_name, perm)
    return (
        jnp.einsum("bhqd->bqhd", dq).astype(q.dtype),
        jnp.einsum("bhkd->bkhd", dk).astype(k.dtype),
        jnp.einsum("bhkd->bkhd", dv).astype(v.dtype),
    )


_ZIGZAG_CORES = {}


def _zigzag_core(axis_name: str, mesh_axes):
    return _make_vjp_core(
        _ZIGZAG_CORES,
        (axis_name, tuple(mesh_axes)),
        functools.partial(_zigzag_forward, axis_name=axis_name, mesh_axes=mesh_axes),
        functools.partial(_zigzag_backward, axis_name=axis_name, mesh_axes=mesh_axes),
    )


def _zigzag_relayout(x, axis_name: str, axis_size, inverse: bool):
    """Permute between the contiguous layout (shard i holds chunks 2i, 2i+1)
    and the zigzag layout (shard i holds chunks i, 2n-1-i). Two paired
    ppermutes — a chunk pair (j, 2n-1-j) always has one even and one odd
    member, so each shard sends/receives exactly one half per call. Built
    from differentiable ppermutes, so it lives OUTSIDE the custom-VJP core
    and autodiff transposes it for free."""
    n = axis_size
    i = lax.axis_index(axis_name)
    half = x.shape[1] // 2
    lo, hi = x[:, :half], x[:, half:]

    def owner(c):  # zigzag owner of global half-chunk c
        return c if c < n else 2 * n - 1 - c

    if not inverse:
        # contiguous -> zigzag: shard s sends chunk 2s and chunk 2s+1
        perm_a = [(s, owner(2 * s)) for s in range(n)]
        perm_b = [(s, owner(2 * s + 1)) for s in range(n)]
        recv_a = lax.ppermute(lo, axis_name, perm_a)  # the even chunk of (i, 2n-1-i)
        recv_b = lax.ppermute(hi, axis_name, perm_b)  # the odd chunk
        # shard i's rows must be ordered [chunk i, chunk 2n-1-i]; chunk i has
        # the parity of i
        even_first = (i % 2) == 0
        first = jnp.where(even_first, recv_a, recv_b)
        second = jnp.where(even_first, recv_b, recv_a)
        return jnp.concatenate([first, second], axis=1)
    # zigzag -> contiguous: invert both permutations
    inv_a = [(owner(2 * s), s) for s in range(n)]
    inv_b = [(owner(2 * s + 1), s) for s in range(n)]
    even_first = (i % 2) == 0
    send_a = jnp.where(even_first, lo, hi)  # this shard's even chunk
    send_b = jnp.where(even_first, hi, lo)  # odd chunk
    back_lo = lax.ppermute(send_a, axis_name, inv_a)
    back_hi = lax.ppermute(send_b, axis_name, inv_b)
    return jnp.concatenate([back_lo, back_hi], axis=1)


def _zigzag_ring_attention_local(q, k, v, axis_name: str, mesh_axes=()):
    """Per-shard body: relayout to zigzag, run the balanced ring core,
    relayout back. Inputs are in the model's contiguous layout."""
    if q.shape[1] % 2:
        raise ValueError(
            f"zigzag ring attention needs an even per-shard block to split "
            f"into two chunks; got {q.shape[1]} rows per shard "
            f"(require T % (2 * sp) == 0)"
        )
    axis_size = lax.psum(1, axis_name)
    qz = _zigzag_relayout(q, axis_name, axis_size, inverse=False)
    kz = _zigzag_relayout(k, axis_name, axis_size, inverse=False)
    vz = _zigzag_relayout(v, axis_name, axis_size, inverse=False)
    out = _zigzag_core(axis_name, mesh_axes)(qz, kz, vz)
    return _zigzag_relayout(out, axis_name, axis_size, inverse=True)


def zigzag_ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = "sp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    causal: bool = True,
) -> jax.Array:
    """Causal ring attention with the zigzag-balanced schedule.

    Same contract as :func:`ring_attention` (contiguous sequence layout in
    and out) but every shard does a constant 2n+1 quarter-blocks of causal
    work instead of 4(i+1) — the ring no longer stalls on the last shard.
    Requires an even per-shard block (T/sp rows split into two chunks).
    Causal only; use :func:`ring_attention` for bidirectional attention.
    """
    if not causal:
        raise ValueError(
            "the zigzag schedule balances the CAUSAL skip; use ring_attention "
            "for non-causal attention"
        )
    shard_map = _get_shard_map()

    spec = P(batch_axes, seq_axis, head_axis, None)
    vma_axes = tuple(batch_axes) + (seq_axis,) + ((head_axis,) if head_axis else ())
    fn = shard_map(
        functools.partial(
            _zigzag_ring_attention_local, axis_name=seq_axis, mesh_axes=vma_axes,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Zigzag schedule with Pallas flash-attention blocks
# ---------------------------------------------------------------------------
#
# Same 3-sub-block schedule as ``_zigzag_forward`` (see the layout comment
# above), but every quarter-block runs through the fused Pallas kernels. The
# schedule needs no position plumbing either: each visible quarter-block is
# the aligned diagonal (src == i — both chunks are the same global chunk) or
# fully visible (the k chunk lies entirely in the q chunk's past), so the
# causal/unmasked kernel pair covers it:
#   (hi_q, lo_k): always fully visible            -> unmasked
#   (hi_q, hi_k): src == i diag | src > i visible -> causal | unmasked
#   (lo_q, lo_k): src == i diag | i > src visible -> causal | unmasked
#   (lo_q, hi_k): never visible                   -> never computed


def _zigzag_flash_forward(q, k, v, axis_name: str, mesh_axes, block_q: int,
                          block_k: int, interpret: bool):
    """Forward zigzag over flash blocks. Returns (out [B,T,H,D] q.dtype,
    lse [B,H,T] f32) with rows in the zigzag-local order [chunk i,
    chunk 2n-1-i]."""
    axis_size = lax.psum(1, axis_name)
    i = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    half = t // 2
    q_lo, q_hi = q[:, :half], q[:, half:]
    kw = dict(block_q=block_q, block_k=block_k, interpret=interpret,
              vma=mesh_axes)

    def zeros():
        return (
            _varying(jnp.zeros((b, h, half, d), jnp.float32), mesh_axes),
            _varying(jnp.full((b, h, half), NEG_INF, jnp.float32), mesh_axes),
        )

    acc_lo, acc_hi = zeros(), zeros()
    perm = [(s, (s + 1) % axis_size) for s in range(axis_size)]

    def merge_block(step, acc_lo, acc_hi, k_cur, v_cur):
        src = (i - step) % axis_size
        k_lo, k_hi = k_cur[:, :half], k_cur[:, half:]
        v_lo, v_hi = v_cur[:, :half], v_cur[:, half:]

        def attend(qh, kh, vh, diag):
            def f(acc):
                return _merge_flash_partial(
                    acc, _flash_block(qh, kh, vh, diag=diag, **kw)
                )
            return f

        # (hi_q, lo_k): always fully visible
        acc_hi = attend(q_hi, k_lo, v_lo, False)(acc_hi)
        # (hi_q, hi_k): diagonal at src == i, fully visible for src > i
        acc_hi = lax.cond(
            src >= i,
            lambda acc: lax.cond(src == i, attend(q_hi, k_hi, v_hi, True),
                                 attend(q_hi, k_hi, v_hi, False), acc),
            lambda acc: acc,
            acc_hi,
        )
        # (lo_q, lo_k): diagonal at src == i, fully visible for i > src
        acc_lo = lax.cond(
            i >= src,
            lambda acc: lax.cond(src == i, attend(q_lo, k_lo, v_lo, True),
                                 attend(q_lo, k_lo, v_lo, False), acc),
            lambda acc: acc,
            acc_lo,
        )
        return acc_lo, acc_hi

    def body(step, carry):
        acc_lo, acc_hi, k_cur, v_cur = carry
        acc_lo, acc_hi = merge_block(step, acc_lo, acc_hi, k_cur, v_cur)
        return (
            acc_lo, acc_hi,
            lax.ppermute(k_cur, axis_name, perm),
            lax.ppermute(v_cur, axis_name, perm),
        )

    acc_lo, acc_hi, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, body, (acc_lo, acc_hi, k, v)
    )
    acc_lo, acc_hi = merge_block(axis_size - 1, acc_lo, acc_hi, k_last, v_last)
    # flash partials are block-normalized: the (o, lse) merge already yields
    # the final rows, no closing division
    out = jnp.concatenate(
        [jnp.einsum("bhqd->bqhd", acc_lo[0]),
         jnp.einsum("bhqd->bqhd", acc_hi[0])], axis=1,
    ).astype(q.dtype)
    lse = jnp.concatenate([acc_lo[1], acc_hi[1]], axis=2)
    return out, lse


def _zigzag_flash_backward(q, k, v, out, lse, g, axis_name: str, mesh_axes,
                           block_q: int, block_k: int, interpret: bool):
    """Backward zigzag over the flash backward kernels: the same quarter-block
    schedule; dk/dv accumulate in f32 on the traveling k/v and take the last
    hop home (mirrors ``_zigzag_backward``)."""
    from hivedscheduler_tpu.ops import attention as fa

    axis_size = lax.psum(1, axis_name)
    i = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    t_k, h_kv = k.shape[1], k.shape[2]
    half = t // 2
    kw = dict(block_q=block_q, block_k=block_k, interpret=interpret,
              vma=mesh_axes, grad_dtype=jnp.float32)

    lo_s, hi_s = slice(0, half), slice(half, t)

    def lanes(x):  # [B,H,half] -> [B*H, half, 128] for the kernels
        return jnp.broadcast_to(
            x.reshape(b * h, half, 1), (b * h, half, fa._LANES)
        )

    halves = {
        0: (q[:, lo_s], out[:, lo_s], lanes(lse[:, :, :half]), g[:, lo_s], lo_s),
        1: (q[:, hi_s], out[:, hi_s], lanes(lse[:, :, half:]), g[:, hi_s], hi_s),
    }

    dq = _varying(jnp.zeros((b, t, h, d), jnp.float32), mesh_axes)
    dk0 = _varying(jnp.zeros((b, t_k, h_kv, d), jnp.float32), mesh_axes)
    dv0 = _varying(jnp.zeros((b, t_k, h_kv, d), jnp.float32), mesh_axes)
    perm = [(s, (s + 1) % axis_size) for s in range(axis_size)]

    def sub_grad(q_half, k_cur, v_cur, k_slice, diag):
        def f(args):
            dq, dk_cur, dv_cur = args
            qh, oh, lseh, gh, q_slice = q_half
            dq_blk, dk_blk, dv_blk = fa._flash_backward(
                qh, k_cur[:, k_slice], v_cur[:, k_slice], oh, lseh, gh,
                causal=diag, **kw
            )
            return (
                dq.at[:, q_slice].add(dq_blk),
                dk_cur.at[:, k_slice].add(dk_blk),
                dv_cur.at[:, k_slice].add(dv_blk),
            )
        return f

    def merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur):
        src = (i - step) % axis_size
        args = (dq, dk_cur, dv_cur)
        # (hi_q, lo_k): always fully visible
        args = sub_grad(halves[1], k_cur, v_cur, lo_s, False)(args)
        # (hi_q, hi_k)
        args = lax.cond(
            src >= i,
            lambda a: lax.cond(src == i,
                               sub_grad(halves[1], k_cur, v_cur, hi_s, True),
                               sub_grad(halves[1], k_cur, v_cur, hi_s, False),
                               a),
            lambda a: a,
            args,
        )
        # (lo_q, lo_k)
        args = lax.cond(
            i >= src,
            lambda a: lax.cond(src == i,
                               sub_grad(halves[0], k_cur, v_cur, lo_s, True),
                               sub_grad(halves[0], k_cur, v_cur, lo_s, False),
                               a),
            lambda a: a,
            args,
        )
        return args

    def body(step, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        dq, dk_cur, dv_cur = merge_grad(step, dq, dk_cur, dv_cur, k_cur, v_cur)
        return (
            dq,
            lax.ppermute(k_cur, axis_name, perm),
            lax.ppermute(v_cur, axis_name, perm),
            lax.ppermute(dk_cur, axis_name, perm),
            lax.ppermute(dv_cur, axis_name, perm),
        )

    dq, k_last, v_last, dk_last, dv_last = lax.fori_loop(
        0, axis_size - 1, body, (dq, k, v, dk0, dv0)
    )
    dq, dk_last, dv_last = merge_grad(
        axis_size - 1, dq, dk_last, dv_last, k_last, v_last
    )
    dk = lax.ppermute(dk_last, axis_name, perm)
    dv = lax.ppermute(dv_last, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ZIGZAG_FLASH_CORES = {}


def _zigzag_flash_core(axis_name: str, mesh_axes, block_q: int, block_k: int,
                       interpret: bool):
    kw = dict(axis_name=axis_name, mesh_axes=mesh_axes, block_q=block_q,
              block_k=block_k, interpret=interpret)
    return _make_vjp_core(
        _ZIGZAG_FLASH_CORES,
        (axis_name, tuple(mesh_axes), block_q, block_k, interpret),
        functools.partial(_zigzag_flash_forward, **kw),
        functools.partial(_zigzag_flash_backward, **kw),
    )


def _zigzag_flash_attention_local(q, k, v, axis_name: str, mesh_axes=(),
                                  block_q: int = 128, block_k: int = 128):
    """Per-shard body: relayout to zigzag, run the flash-block balanced ring,
    relayout back. Falls back to the einsum zigzag under the same conditions
    as ``_ring_flash_attention_local`` (tiles are per half-chunk)."""
    from hivedscheduler_tpu.ops import attention as fa

    if q.shape[1] % 2:
        raise ValueError(
            f"zigzag ring attention needs an even per-shard block to split "
            f"into two chunks; got {q.shape[1]} rows per shard "
            f"(require T % (2 * sp) == 0)"
        )
    b, t_loc, h, d = q.shape
    h_kv = k.shape[2]
    half = t_loc // 2
    block_q = min(block_q, half)
    block_k = min(block_k, half)
    interpret = jax.default_backend() != "tpu"
    if (fa.pl is None or half % block_q or half % block_k or d % 8
            or (h_kv and h % h_kv) or (interpret and mesh_axes)):
        return _zigzag_ring_attention_local(
            q, k, v, axis_name=axis_name, mesh_axes=mesh_axes
        )
    axis_size = lax.psum(1, axis_name)
    qz = _zigzag_relayout(q, axis_name, axis_size, inverse=False)
    kz = _zigzag_relayout(k, axis_name, axis_size, inverse=False)
    vz = _zigzag_relayout(v, axis_name, axis_size, inverse=False)
    out = _zigzag_flash_core(
        axis_name, tuple(mesh_axes), block_q, block_k, interpret
    )(qz, kz, vz)
    return _zigzag_relayout(out, axis_name, axis_size, inverse=True)


def zigzag_ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = "sp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Zigzag-balanced causal ring attention whose quarter-blocks run through
    the Pallas flash kernels — :func:`zigzag_ring_attention`'s schedule with
    :func:`ring_flash_attention`'s O(T_loc x D) per-shard attention memory."""
    if not causal:
        raise ValueError(
            "the zigzag schedule balances the CAUSAL skip; use "
            "ring_flash_attention for non-causal attention"
        )
    shard_map = _get_shard_map()

    spec = P(batch_axes, seq_axis, head_axis, None)
    vma_axes = tuple(batch_axes) + (seq_axis,) + ((head_axis,) if head_axis else ())
    fn = shard_map(
        functools.partial(
            _zigzag_flash_attention_local,
            axis_name=seq_axis,
            mesh_axes=vma_axes,
            block_q=block_q,
            block_k=block_k,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name: str, causal: bool):
    """All-to-all swap: [B, T/sp, H, D] -> [B, T, H/sp, D], local attention,
    swap back. Requires H % sp == 0.

    Compact GQA k/v all_to_all on their own H_kv axis when H_kv % sp == 0,
    shipping H_kv/H of the k/v bytes (the ring schedules' compact-transport
    win, applied to the all_to_all): contiguous head grouping survives the
    split — device s gets q heads [s*H/sp, (s+1)*H/sp) and k/v heads
    [s*H_kv/sp, (s+1)*H_kv/sp), and since H/sp is a multiple of the group
    size H/H_kv, the local mapping is again j -> j // (H/H_kv), which is
    exactly how xla_attention consumes compact k/v. Shared heads are
    expanded first only when H_kv doesn't split evenly."""
    from hivedscheduler_tpu.ops.attention import xla_attention

    sp = lax.psum(1, axis_name)
    h, h_kv = q.shape[2], k.shape[2]
    if h_kv != h and h_kv % sp:
        rep = h // h_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # concat_axis=T (1), split_axis=H (2): gather full sequence, split heads
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = xla_attention(q, k, v, causal=causal)
    # swap back: split sequence, gather heads
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = "sp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    causal: bool = True,
) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism via all_to_all."""
    shard_map = _get_shard_map()

    spec = P(batch_axes, seq_axis, head_axis, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
