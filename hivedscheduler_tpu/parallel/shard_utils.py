"""Small helpers shared by the manual-mode (shard_map) modules."""

from __future__ import annotations

from jax import lax


def varying(x, mesh_axes):
    """Seed device-varying state on fresh arrays so they can sit in loop
    carries with ppermuted data (shard_map vma rules). Handles the
    pcast/pvary API rename across JAX versions; on pre-vma JAX (no pcast
    AND no pvary — e.g. 0.4.x) shard_map does not track varying manual
    axes at all, so there is nothing to seed and the array passes through."""
    if not mesh_axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(mesh_axes), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, tuple(mesh_axes))
    return x
