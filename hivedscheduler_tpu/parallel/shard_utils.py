"""Small helpers shared by the manual-mode (shard_map) modules.

Besides the vma-seeding shim this now hosts the **collective matmul**
primitives of the overlapped tensor-parallel path (HIVED_OVERLAP,
models/transformer.py): the all-gather and reduce-scatter that GSPMD would
insert around a column-/row-parallel projection are decomposed into
``lax.ppermute``-pipelined chunks, so each ICI hop transfers while the
previous chunk multiplies on the MXU — the standard collective-matmul
decomposition (Wang et al., ASPLOS'23; used by t5x/maxtext for the same
projections). Both functions are pure JAX inside a manual shard_map
context and autodiff cleanly (the transpose of a ppermute is the inverse
ppermute, so the backward pass is the mirrored pipeline).
"""

from __future__ import annotations

from typing import List, Sequence, Union

import jax.numpy as jnp
from jax import lax


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis inside a manual context.
    ``lax.psum(1, axis)`` constant-folds to a Python int on every JAX
    version this package supports (``lax.axis_size`` does not exist on
    0.4.x), which the ring pipelines need for ``range(size)``."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def varying(x, mesh_axes):
    """Seed device-varying state on fresh arrays so they can sit in loop
    carries with ppermuted data (shard_map vma rules). Handles the
    pcast/pvary API rename across JAX versions; on pre-vma JAX (no pcast
    AND no pvary — e.g. 0.4.x) shard_map does not track varying manual
    axes at all, so there is nothing to seed and the array passes through."""
    if not mesh_axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(mesh_axes), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, tuple(mesh_axes))
    return x


def allgather_matmul(
    x,
    ws: Union[jnp.ndarray, Sequence],
    axis_name: str,
    einsum_str: str,
    *,
    vma_axes=(),
) -> Union[jnp.ndarray, List]:
    """Column-parallel collective matmul: ``einsum(all_gather(x), w)``
    with the gather decomposed into a ppermute pipeline.

    ``x`` is sharded over ``axis_name`` on dim 1 (the sequence dim); each
    ``w`` is a device-local column shard (its output axis is sharded over
    the same ring). Instead of a blocking all-gather followed by one big
    matmul, every device multiplies the sequence chunk it currently holds
    against its weight shard while ppermuting that chunk one hop around
    the ring — after ``size`` steps every device has computed the full
    gathered sequence against its local columns, and each hop's transfer
    overlapped the previous chunk's matmul.

    Passing several weights computes them all from ONE rotation (the
    QKV and gate/up fusions: one gather pipeline, N matmuls per hop).

    Chunk results land at their gathered positions (axis-major order), so
    each output element is produced by the same local dot the un-overlapped
    path runs — per-element bit-identical to gather-then-matmul.

    Returns one output per weight ([B, T_local*size, ...out]); a bare
    (non-sequence) ``ws`` returns a bare output.
    """
    single = not isinstance(ws, (list, tuple))
    ws_l = [ws] if single else list(ws)
    size = axis_size(axis_name)
    if size == 1:
        outs = [jnp.einsum(einsum_str, x, w) for w in ws_l]
        return outs[0] if single else outs
    idx = lax.axis_index(axis_name)
    t_loc = x.shape[1]
    # send backward (i -> i-1): after s hops device i holds the chunk that
    # originated at (i + s) % size, i.e. gathered position (i + s) * t_loc
    perm = [(i, (i - 1) % size) for i in range(size)]
    chunk = x
    outs = None
    for s in range(size):
        if s + 1 < size:
            # start the next hop BEFORE this chunk's matmuls: the ppermute
            # has no data dependency on them, so XLA's async collectives
            # run the transfer under the MXU work
            nxt = lax.ppermute(chunk, axis_name, perm)
        parts = [jnp.einsum(einsum_str, chunk, w) for w in ws_l]
        if outs is None:
            outs = [
                varying(
                    jnp.zeros(
                        (p.shape[0], t_loc * size) + p.shape[2:], p.dtype
                    ),
                    vma_axes,
                )
                for p in parts
            ]
        src = (idx + s) % size
        outs = [
            lax.dynamic_update_slice_in_dim(o, p, src * t_loc, axis=1)
            for o, p in zip(outs, parts)
        ]
        if s + 1 < size:
            chunk = nxt
    return outs[0] if single else outs


def matmul_reducescatter(x, w, axis_name: str, einsum_str: str):
    """Row-parallel collective matmul: ``reduce_scatter(einsum(x, w))``
    with the reduction decomposed into a ppermute-pipelined accumulator.

    The einsum contracts a dimension that is sharded over ``axis_name``
    (each device holds a partial sum of the true output); the result is
    returned sequence-sharded over the same ring (dim 1 shrinks by
    ``size``), ready for the token-local residual/norm of the
    sequence-parallel layer layout. At step ``s`` device ``i`` computes
    its partial for output chunk ``(i + s + 1) % size`` and adds it to the
    traveling accumulator, which then moves one hop backward — the
    ppermute of the previous accumulator overlaps the next chunk's
    matmul, and after ``size`` steps each device holds its own chunk with
    all ``size`` contributions (ring order ``i+1, i+2, ..., i``).
    """
    size = axis_size(axis_name)
    if size == 1:
        return jnp.einsum(einsum_str, x, w)
    idx = lax.axis_index(axis_name)
    t = x.shape[1]
    assert t % size == 0, (t, size)
    t_loc = t // size
    perm = [(i, (i - 1) % size) for i in range(size)]
    acc = None
    for s in range(size):
        c = (idx + s + 1) % size
        chunk = lax.dynamic_slice_in_dim(x, c * t_loc, t_loc, axis=1)
        part = jnp.einsum(einsum_str, chunk, w)
        if acc is None:
            acc = part
        else:
            # ppermute(acc) is independent of this step's einsum: the hop
            # rides under the matmul
            acc = lax.ppermute(acc, axis_name, perm) + part
    return acc
