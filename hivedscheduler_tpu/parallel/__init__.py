"""SPMD runtime for workloads scheduled by tpu-hive.

This is the compute-side counterpart of the scheduler: a gang's pods receive
contiguous ICI sub-meshes (via ``TPU_VISIBLE_CHIPS``), and this package turns
them into ``jax.sharding.Mesh`` axes (dp/fsdp/tp/sp) with sharded training
steps, ring attention for sequence parallelism, and XLA collectives over ICI.
The reference has no training runtime (SURVEY.md §2.15) — this exceeds parity
and makes the framework end-to-end usable on TPU.
"""

from hivedscheduler_tpu.parallel.topology import (  # noqa: F401
    MeshAxes,
    make_mesh,
    mesh_from_slice,
)
