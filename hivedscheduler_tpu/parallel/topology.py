"""Device-mesh construction from scheduler slice handoffs.

Bridges the control plane to the data plane: the scheduler delivers a
contiguous ICI sub-mesh per gang (chip coordinates in the cell's
``mesh_origin``/``mesh_shape``, per-host indices via ``TPU_VISIBLE_CHIPS``);
this module lays a ``jax.sharding.Mesh`` over those devices so collectives
ride ICI neighbor links instead of DCN.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from hivedscheduler_tpu.api.constants import ENV_TPU_VISIBLE_CHIPS


@dataclass(frozen=True)
class MeshAxes:
    """Logical parallelism axes: data, fully-sharded-data, pipeline, expert,
    tensor, sequence.

    Sizes must multiply to the device count. ``sp`` (sequence/context
    parallelism) is first-class: long-context workloads shard the sequence
    dimension and run ring attention over this axis. ``pp`` shards
    transformer layers into pipeline stages (``parallel/pipeline.py``);
    ``ep`` shards MoE experts (``models/transformer.py``).
    """

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def names(self) -> Tuple[str, ...]:
        return ("dp", "fsdp", "pp", "ep", "tp", "sp")

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.dp, self.fsdp, self.pp, self.ep, self.tp, self.sp)

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.pp * self.ep * self.tp * self.sp


def visible_chip_indices() -> Optional[List[int]]:
    """Chip indices this pod was granted by the scheduler (the
    ``TPU_VISIBLE_CHIPS`` handoff written into the pod-leaf-cell-isolation
    annotation by the bind routine)."""
    raw = os.environ.get(ENV_TPU_VISIBLE_CHIPS, "").strip()
    if not raw:
        return None
    return [int(x) for x in raw.split(",") if x != ""]


def get_devices(n: int) -> List:
    """Return n devices: the default backend if it has enough, else the CPU
    backend (which honors --xla_force_host_platform_device_count, giving a
    virtual multi-chip mesh for sharding tests on a single-chip host)."""
    import jax

    devices = jax.devices()
    if len(devices) < n:
        try:
            devices = jax.devices("cpu")
        except RuntimeError:
            pass
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    return list(devices[:n])


def make_mesh(axes: MeshAxes, devices: Optional[Sequence] = None):
    """Build a Mesh with the given logical axes over the available devices.

    Device order: tries ``mesh_utils.create_device_mesh`` (which optimizes
    assignment for the physical ICI topology on real TPU slices) and falls
    back to a plain reshape (CPU/virtual devices). The innermost logical axis
    (sp, then tp) lands on the innermost physical axis, where ICI
    nearest-neighbor bandwidth is highest — ring attention's ppermute then
    moves data one ICI hop per step.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if axes.size != len(devices):
        raise ValueError(
            f"mesh axes {axes.shape} require {axes.size} devices, have {len(devices)}"
        )
    if getattr(devices[0], "platform", "") == "tpu":
        # ICI-topology-aware assignment; a failure here on real TPU is a
        # config error we must surface, not silently degrade
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(axes.shape, devices=list(devices))
    else:
        # CPU/virtual devices have no physical topology: plain reshape
        dev_array = np.array(list(devices)).reshape(axes.shape)
    return Mesh(dev_array, axes.names)


def mesh_from_slice(
    slice_shape: Sequence[int],
    axes: MeshAxes,
    devices: Optional[Sequence] = None,
):
    """Build a Mesh for a scheduler-allocated slice of the given ICI shape
    (e.g. ``(4, 4, 2)`` for a v5p 4x4x2 cell). Validates that the slice is
    large enough and delegates to :func:`make_mesh`."""
    n = math.prod(slice_shape)
    if axes.size != n:
        raise ValueError(
            f"slice {tuple(slice_shape)} has {n} chips but mesh axes {axes.shape} "
            f"need {axes.size}"
        )
    return make_mesh(axes, devices)


def infer_axes(
    n_devices: int, tp: int = 1, sp: int = 1, fsdp: int = 1, pp: int = 1, ep: int = 1
) -> MeshAxes:
    """Fill the dp axis with whatever is left over."""
    rest = tp * sp * fsdp * pp * ep
    if n_devices % rest != 0:
        raise ValueError(
            f"{n_devices} devices not divisible by tp*sp*fsdp*pp*ep={rest}"
        )
    return MeshAxes(dp=n_devices // rest, fsdp=fsdp, pp=pp, ep=ep, tp=tp, sp=sp)


def _divisors_desc(k: int):
    return sorted((d for d in range(1, k + 1) if k % d == 0), reverse=True)


def elastic_axes(
    n_devices: int,
    *,
    tp: int = 1,
    sp: int = 1,
    fsdp: int = 1,
    pp: int = 1,
    ep: int = 1,
    n_heads: int = 0,
    n_kv_heads: int = 0,
    global_batch: int = 0,
    seq_len: int = 0,
) -> MeshAxes:
    """Derive a valid mesh for whatever slice the scheduler actually
    offered (``train --elastic``): the requested degrees are PREFERENCES,
    shrunk only as far as the offered device count forces.

    Each axis takes the largest divisor of its requested degree that fits;
    dp absorbs the remainder (so a 2x-bigger offer doubles dp — the grow
    path — and a halved offer shrinks the most expendable axis first).
    Sacrifice order when the full product does not fit: fsdp, then sp,
    then tp, then ep, then pp — tensor/expert/pipeline parallelism encode
    per-device memory needs, so they are held longest. Model/data
    constraints are enforced where known: ``n_heads``/``n_kv_heads`` must
    divide tp, ``global_batch`` must divide dp*fsdp, ``seq_len`` must
    divide sp. Deterministic: the same inputs always derive the same mesh
    (a restarted incarnation on an equal slice gets an identical layout).
    """
    if n_devices < 1:
        raise ValueError(f"need at least 1 device, offered {n_devices}")

    def fits(t: int, s: int, f: int, p: int, e: int) -> bool:
        rest = t * s * f * p * e
        if rest > n_devices or n_devices % rest:
            return False
        dp = n_devices // rest
        if n_heads and n_heads % t:
            return False
        if n_kv_heads and n_kv_heads % t:
            return False
        if global_batch and global_batch % (dp * f):
            return False
        if seq_len and seq_len % s:
            return False
        return True

    for p in _divisors_desc(pp):
        for e in _divisors_desc(ep):
            for t in _divisors_desc(tp):
                for s in _divisors_desc(sp):
                    for f in _divisors_desc(fsdp):
                        if fits(t, s, f, p, e):
                            return MeshAxes(
                                dp=n_devices // (t * s * f * p * e),
                                fsdp=f, pp=p, ep=e, tp=t, sp=s,
                            )
    raise ValueError(
        f"no valid mesh for {n_devices} offered device(s) within the "
        f"requested degrees tp={tp} sp={sp} fsdp={fsdp} pp={pp} ep={ep} "
        f"(n_heads={n_heads}, global_batch={global_batch}, seq_len={seq_len})"
    )
