"""Host-parallel data loading for gangs.

Each gang pod (one JAX process per host) reads only its shard of the global
batch and assembles the global array with
``jax.make_array_from_process_local_data`` — no host ever materializes the
full batch. Sources: a memory-mapped token file (binary uint16/uint32 stream,
the standard packed-LM format) or the deterministic synthetic corpus used by
``train.py`` when no data file is given.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


class TokenFileDataset:
    """A flat binary token stream, memory-mapped (zero-copy reads)."""

    def __init__(self, path: str, dtype: str = "uint16"):
        self.tokens = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        if len(self.tokens) == 0:
            raise ValueError(f"token file {path} is empty")

    def __len__(self) -> int:
        return len(self.tokens)

    def sample(self, rng: np.random.Generator, batch: int, seq_len: int,
               row_slice: slice = slice(None)) -> np.ndarray:
        """Random contiguous windows (with wraparound). ``row_slice`` gathers
        only those rows of the batch — the start positions are still drawn
        for the whole batch so every host sees the same global plan while
        reading only its own shard.

        The gather itself (the bandwidth-heavy widening copy) runs through
        the native C++ path when available (native/dataloader.cpp:
        per-row two-span copies, threaded, GIL released — bit-identical to
        the numpy expression below; HIVED_NATIVE=0 forces numpy)."""
        from hivedscheduler_tpu import native

        n = len(self.tokens)
        starts = rng.integers(0, n, size=batch)[row_slice]
        out = native.gather_windows(self.tokens, starts, seq_len)
        if out is not None:
            return out
        idx = (starts[:, None] + np.arange(seq_len)[None, :]) % n
        return np.asarray(self.tokens[idx], dtype=np.int32)


def synthetic_dataset(vocab_size: int, size: int = 1 << 20, seed: int = 0):
    """In-memory stand-in with the TokenFileDataset interface."""
    rng = np.random.default_rng(seed)
    dtype = np.uint16 if vocab_size <= (1 << 16) else np.uint32
    ds = TokenFileDataset.__new__(TokenFileDataset)
    ds.tokens = rng.integers(0, vocab_size, size=size).astype(dtype)
    return ds


def host_batches(
    dataset: TokenFileDataset,
    global_batch: int,
    seq_len: int,
    *,
    process_index: int = 0,
    process_count: int = 1,
    seed: int = 0,
    start_step: int = 0,
) -> Iterator[np.ndarray]:
    """Yield this host's [global_batch / process_count, seq_len] shard.

    All hosts derive per-step RNG from (seed, step) and gather only their own
    rows, so the global batch is consistent without coordination and no host
    materializes it. ``start_step`` resumes the stream mid-corpus (checkpoint
    restarts must not replay seen data)."""
    if global_batch % process_count != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by {process_count} hosts"
        )
    local = global_batch // process_count
    rows = slice(process_index * local, (process_index + 1) * local)
    step = start_step
    while True:
        rng = np.random.default_rng((seed, step))
        yield dataset.sample(rng, global_batch, seq_len, row_slice=rows)
        step += 1


def prefetch(batches: Iterator[np.ndarray], depth: int = 2) -> Iterator[np.ndarray]:
    """Background-thread prefetch: batch N+1 assembles (page faults + the
    native gather, which releases the GIL) while step N computes. ``depth``
    bounds the queue so a fast producer cannot run ahead unbounded;
    ``depth <= 0`` is a no-op passthrough. A producer exception is
    re-raised at the consumer's next pull. Abandoning the iterator early
    (generator close / GC — e.g. the train CLI exiting after --steps)
    signals the worker, which exits within one poll slice instead of
    blocking forever on the bounded queue and leaking the thread plus its
    staged batches for the process lifetime."""
    if depth <= 0:
        yield from batches
        return
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = object()
    closed = threading.Event()

    def put(item) -> bool:
        while not closed.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for b in batches:
                if not put(b):
                    return
            put(stop)
        except BaseException as e:  # surface in the consumer, not the log
            put(e)

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is stop:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        closed.set()


def device_put_global(local_batch: np.ndarray, sharding, global_batch: int):
    """Assemble the global [global_batch, seq_len] array from this process's
    local rows, placed per ``sharding``. Single-process: a plain device_put."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(local_batch, sharding)
    global_shape = (global_batch,) + tuple(local_batch.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, local_batch, global_shape=global_shape
    )
