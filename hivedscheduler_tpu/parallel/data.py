"""Host-parallel data loading for gangs.

Each gang pod (one JAX process per host) reads only its shard of the global
batch and assembles the global array with
``jax.make_array_from_process_local_data`` — no host ever materializes the
full batch. Sources: a memory-mapped token file (binary uint16/uint32 stream,
the standard packed-LM format) or the deterministic synthetic corpus used by
``train.py`` when no data file is given.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np

# the most recently started prefetch worker thread (named "hived-prefetch");
# tests join/poll this object directly rather than diffing threading state
_last_prefetch_worker = None


class TokenFileDataset:
    """A flat binary token stream, memory-mapped (zero-copy reads)."""

    def __init__(self, path: str, dtype: str = "uint16"):
        self.tokens = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        if len(self.tokens) == 0:
            raise ValueError(f"token file {path} is empty")

    def __len__(self) -> int:
        return len(self.tokens)

    def sample(self, rng: np.random.Generator, batch: int, seq_len: int,
               row_slice: slice = slice(None)) -> np.ndarray:
        """Random contiguous windows (with wraparound). ``row_slice`` gathers
        only those rows of the batch — the start positions are still drawn
        for the whole batch so every host sees the same global plan while
        reading only its own shard.

        The gather itself (the bandwidth-heavy widening copy) runs through
        the native C++ path when available (native/dataloader.cpp:
        per-row two-span copies, threaded, GIL released — bit-identical to
        the numpy expression below; HIVED_NATIVE=0 forces numpy)."""
        from hivedscheduler_tpu import native

        n = len(self.tokens)
        starts = rng.integers(0, n, size=batch)[row_slice]
        out = native.gather_windows(self.tokens, starts, seq_len)
        if out is not None:
            return out
        idx = (starts[:, None] + np.arange(seq_len)[None, :]) % n
        return np.asarray(self.tokens[idx], dtype=np.int32)


def synthetic_dataset(vocab_size: int, size: int = 1 << 20, seed: int = 0):
    """In-memory stand-in with the TokenFileDataset interface."""
    rng = np.random.default_rng(seed)
    dtype = np.uint16 if vocab_size <= (1 << 16) else np.uint32
    ds = TokenFileDataset.__new__(TokenFileDataset)
    ds.tokens = rng.integers(0, vocab_size, size=size).astype(dtype)
    return ds


def host_batches(
    dataset: TokenFileDataset,
    global_batch: int,
    seq_len: int,
    *,
    process_index: int = 0,
    process_count: int = 1,
    seed: int = 0,
    start_step: int = 0,
) -> Iterator[np.ndarray]:
    """Yield this host's [global_batch / process_count, seq_len] shard.

    All hosts derive per-step RNG from (seed, step) and gather only their own
    rows, so the global batch is consistent without coordination and no host
    materializes it. ``start_step`` resumes the stream mid-corpus (checkpoint
    restarts must not replay seen data)."""
    if global_batch % process_count != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by {process_count} hosts"
        )
    local = global_batch // process_count
    rows = slice(process_index * local, (process_index + 1) * local)
    step = start_step
    while True:
        rng = np.random.default_rng((seed, step))
        yield dataset.sample(rng, global_batch, seq_len, row_slice=rows)
        step += 1


@dataclasses.dataclass
class LoaderState:
    """Checkpointable data-loader state of record.

    ``seed`` seeds the stream; ``step`` is the number of batches already
    emitted (the stream position); ``epoch`` counts full passes of the
    corpus token count (derived — kept explicit so operators can read it
    out of the checkpoint marker); ``bitgen`` is the numpy bit-generator
    state dict of the persistent stream RNG (JSON-serializable: PCG64 state
    is plain ints/strings). Checkpointing this alongside params/opt_state
    is what makes a preempted run resume the EXACT uninterrupted data
    stream instead of silently replaying or skipping data.

    Serialization is the canonical dataclass mapping
    (``dataclasses.asdict``) — never hand-roll a field list here; the guard
    test pins ``to_dict()`` keys to the dataclass fields (CLAUDE.md
    recurring blind spot)."""

    seed: int = 0
    step: int = 0
    epoch: int = 0
    bitgen: Optional[dict] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "LoaderState":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(f"unknown LoaderState fields: {sorted(unknown)}")
        return cls(**d)


class CheckpointableBatches:
    """Stateful host-batch stream with exact-resume checkpointing.

    Same multi-host contract as :func:`host_batches` (every host draws the
    full batch's start positions from an identical RNG stream and gathers
    only its own rows), but the RNG is ONE persistent generator advanced
    per step instead of being re-derived from (seed, step) — its
    bit-generator state is therefore load-bearing, and :meth:`to_dict` /
    :meth:`from_dict` carry (seed, step, epoch, bitgen) through the
    checkpoint so a killed-and-restarted incarnation reproduces the
    uninterrupted stream bit-exactly (guard:
    tests/test_data.py::test_checkpointable_batches_resume_bit_exact).

    ``skip(n)`` advances the stream WITHOUT materializing batches — the
    divergence-rollback path uses it to jump over a poisoned batch, and
    legacy (pre-loader-state) checkpoints use it to fast-forward to their
    step counter."""

    def __init__(self, dataset: TokenFileDataset, global_batch: int,
                 seq_len: int, *, process_index: int = 0,
                 process_count: int = 1, seed: int = 0,
                 state: Optional[LoaderState] = None):
        if global_batch % process_count != 0:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"{process_count} hosts"
            )
        self.dataset = dataset
        self.global_batch = global_batch
        self.seq_len = seq_len
        local = global_batch // process_count
        self._rows = slice(process_index * local, (process_index + 1) * local)
        if state is None:
            state = LoaderState(seed=seed)
        self._state = state
        self._rng = np.random.default_rng(state.seed)
        if state.bitgen is not None:
            self._rng.bit_generator.state = state.bitgen

    @property
    def step(self) -> int:
        return self._state.step

    @property
    def epoch(self) -> int:
        # full passes of the corpus, by token count consumed
        return (self._state.step * self.global_batch * self.seq_len
                // len(self.dataset))

    def state(self) -> LoaderState:
        """Snapshot the CURRENT state of record (bitgen refreshed)."""
        return LoaderState(seed=self._state.seed, step=self._state.step,
                           epoch=self.epoch,
                           bitgen=self._rng.bit_generator.state)

    def to_dict(self) -> dict:
        return self.state().to_dict()

    @classmethod
    def from_dict(cls, d: dict, dataset: TokenFileDataset, global_batch: int,
                  seq_len: int, *, process_index: int = 0,
                  process_count: int = 1) -> "CheckpointableBatches":
        return cls(dataset, global_batch, seq_len,
                   process_index=process_index, process_count=process_count,
                   state=LoaderState.from_dict(d))

    def __iter__(self) -> "CheckpointableBatches":
        return self

    def __next__(self) -> np.ndarray:
        batch = self.dataset.sample(self._rng, self.global_batch,
                                    self.seq_len, row_slice=self._rows)
        self._state.step += 1
        return batch

    def skip(self, n: int = 1) -> None:
        """Advance the stream ``n`` batches without gathering tokens. MUST
        consume exactly the draws :meth:`__next__` would (one full-batch
        ``integers`` draw per step — mirrors ``TokenFileDataset.sample``;
        guard: test_checkpointable_batches_skip_matches_next)."""
        for _ in range(n):
            self._rng.integers(0, len(self.dataset), size=self.global_batch)
            self._state.step += 1


def prefetch(batches: Iterator[np.ndarray], depth: int = 2,
             stop=None) -> Iterator[np.ndarray]:
    """Background-thread prefetch: batch N+1 assembles (page faults + the
    native gather, which releases the GIL) while step N computes. ``depth``
    bounds the queue so a fast producer cannot run ahead unbounded;
    ``depth <= 0`` is a no-op passthrough. A producer exception is
    re-raised at the consumer's next pull. Abandoning the iterator early
    (generator close / GC — e.g. the train CLI exiting after --steps)
    signals the worker, DRAINS the staged batches so a producer blocked on
    the bounded queue unblocks immediately, and joins the thread briefly —
    no deadlock, no leaked thread holding staged batches for the process
    lifetime. ``stop`` (a ``threading.Event``, e.g. the supervisor's
    preemption event) additionally wakes a consumer that is BLOCKED waiting
    on a hung producer: without it, a SIGTERM arriving while ``next()``
    waits on a wedged data source could never reach the step boundary and
    the grace period would force-exit instead of checkpointing."""
    if depth <= 0:
        yield from batches
        return
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    done = object()
    closed = threading.Event()

    def put(item) -> bool:
        while not closed.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for b in batches:
                if not put(b):
                    return
            put(done)
        except BaseException as e:  # surface in the consumer, not the log
            put(e)

    thread = threading.Thread(target=worker, daemon=True,
                              name="hived-prefetch")
    # exposed for tests: poll/join the worker object directly instead of
    # diffing global thread state (ADVICE.md round 5 — an unrelated library
    # thread starting mid-test must not flake the assertion)
    global _last_prefetch_worker
    _last_prefetch_worker = thread
    thread.start()
    try:
        while True:
            try:
                item = q.get(timeout=0.1)
            except queue.Empty:
                if stop is not None and stop.is_set():
                    return  # supervisor abort: wake from a hung producer
                continue
            if item is done:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
            if stop is not None and stop.is_set():
                return
    finally:
        closed.set()
        # drain staged batches so a worker mid-put() unblocks NOW (not
        # after its 0.1 s poll), then reap the thread — a supervisor abort
        # must leave no worker alive racing the checkpoint-and-exit path
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        thread.join(timeout=2.0)


def device_put_global(local_batch: np.ndarray, sharding, global_batch: int):
    """Assemble the global [global_batch, seq_len] array from this process's
    local rows, placed per ``sharding``. Single-process: a plain device_put."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(local_batch, sharding)
    global_shape = (global_batch,) + tuple(local_batch.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, local_batch, global_shape=global_shape
    )
