"""Multi-host runtime initialization for gangs scheduled by tpu-hive.

Each pod of a gang runs on one TPU host; ``initialize_from_gang`` wires
``jax.distributed`` so the hosts form one JAX process group and
``jax.devices()`` spans the whole slice (collectives then ride ICI within the
slice). The process topology comes from the scheduler's own bind records:
the pod's bind-info annotation carries every member's node, so all hosts
derive the same coordinator and a stable rank without any external
coordination service.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Tuple

from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.common import utils as common

log = logging.getLogger(__name__)

DEFAULT_COORDINATOR_PORT = 8476


def gang_process_info(
    bind_info: api.PodBindInfo,
    my_node: str,
    my_chip_indices: Optional[List[int]] = None,
) -> Tuple[str, int, int]:
    """(coordinator_node, process_id, num_processes) for this pod's gang.

    One rank per gang POD: member identity is (node, sorted chip indices),
    so multiple pods sharing a host get distinct ranks. Ranks follow the
    sorted member order; the coordinator is rank 0's node. Every member
    computes the same answer from its own annotation. ``my_chip_indices``
    (e.g. from TPU_VISIBLE_CHIPS) is required to disambiguate when several
    gang pods run on ``my_node``."""
    members: List[Tuple[str, tuple]] = []
    for member in bind_info.affinity_group_bind_info:
        for placement in member.pod_placements:
            members.append(
                (placement.physical_node, tuple(sorted(placement.physical_leaf_cell_indices)))
            )
    members = sorted(set(members))
    if my_chip_indices is not None:
        key = (my_node, tuple(sorted(my_chip_indices)))
        if key not in members:
            raise ValueError(f"pod {key} not part of the gang placement {members}")
        process_id = members.index(key)
    else:
        candidates = [i for i, (n, _) in enumerate(members) if n == my_node]
        if not candidates:
            raise ValueError(f"node {my_node} not part of the gang placement {members}")
        if len(candidates) > 1:
            raise ValueError(
                f"multiple gang pods on node {my_node}; pass my_chip_indices "
                f"(TPU_VISIBLE_CHIPS) to disambiguate"
            )
        process_id = candidates[0]
    return members[0][0], process_id, len(members)


def initialize_from_gang(
    bind_info_yaml: Optional[str] = None,
    my_node: Optional[str] = None,
    coordinator_port: int = DEFAULT_COORDINATOR_PORT,
    node_to_address=None,
) -> Tuple[int, int]:
    """Initialize jax.distributed from the pod's bind-info annotation.

    Inside a scheduled pod, the annotation is exposed via the downward API as
    ``POD_BIND_INFO`` (and the node name as ``NODE_NAME``); pass them
    explicitly otherwise. ``node_to_address`` maps scheduler node names to
    reachable host addresses (defaults to identity — node names are hostnames
    on GKE). Returns (process_id, num_processes); single-host gangs skip
    distributed init entirely."""
    import jax

    bind_info_yaml = bind_info_yaml or os.environ.get("POD_BIND_INFO", "")
    my_node = my_node or os.environ.get("NODE_NAME", "")
    if not bind_info_yaml or not my_node:
        log.info("no gang bind info/node name: single-process run")
        return 0, 1
    bind_info = api.PodBindInfo.from_dict(common.from_yaml(bind_info_yaml))
    from hivedscheduler_tpu.parallel.topology import visible_chip_indices

    coordinator, process_id, num_processes = gang_process_info(
        bind_info, my_node, my_chip_indices=visible_chip_indices()
    )
    if num_processes == 1:
        return 0, 1
    address = (node_to_address or (lambda n: n.split("/")[-1]))(coordinator)
    jax.distributed.initialize(
        coordinator_address=f"{address}:{coordinator_port}",
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info("jax.distributed initialized: rank %s/%s, coordinator %s",
             process_id, num_processes, address)
    return process_id, num_processes
