"""Sharded training step for the flagship transformer.

One jitted function: forward (bf16 on the MXU), next-token cross-entropy,
backward, optax adamw update — with params laid out by
``models.sharding_specs`` (tp/fsdp) and activations by dp/sp. XLA inserts the
gradient reduce-scatters/all-reduces over the mesh; ``jax.checkpoint`` on the
layer scan trades FLOPs for HBM on long contexts.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from hivedscheduler_tpu.common import compileguard
from hivedscheduler_tpu.models import transformer as tm


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01) -> optax.GradientTransformation:
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay)


def loss_fn(params, tokens, cfg: tm.TransformerConfig, mesh=None,
            ce_chunk: int = 0, include_aux: bool = True) -> jax.Array:
    """Next-token LM loss (+ Switch load-balancing aux for MoE models):
    predict tokens[:, 1:] from tokens[:, :-1] with a full-length forward
    (keeps sequence sharding uniform).

    ``ce_chunk > 0`` computes the lm_head matmul + cross-entropy in
    sequence chunks of that size under a ``lax.scan`` with per-chunk
    rematerialization, so the [B, T, vocab] f32 logits tensor (2.1 GB for
    the flagship bench config) never exists in HBM — mathematically
    identical (per-position CE sums linearly; guard:
    test_chunked_ce_matches_full). Best with sp == 1: chunking slices the
    sequence axis, which costs gathers when it is sharded."""
    targets = jnp.roll(tokens, -1, axis=1)
    if ce_chunk <= 0:
        logits, moe_aux = tm.forward_with_aux(params, tokens, cfg, mesh=mesh)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        # the rolled-in last position is not a real target
        mask = jnp.ones_like(per_tok).at[:, -1].set(0.0)
        loss = jnp.sum(per_tok * mask) / jnp.sum(mask)
    else:
        b, t = tokens.shape
        if t % ce_chunk:
            raise ValueError(
                f"seq len {t} not divisible by ce_chunk {ce_chunk}"
            )
        hidden, moe_aux = tm.forward_with_aux(
            params, tokens, cfg, mesh=mesh, return_hidden=True
        )
        n = t // ce_chunk
        mask = jnp.ones((b, t), jnp.float32).at[:, -1].set(0.0)
        # scan over [n, B, C, ...] chunks; checkpoint the body so backward
        # recomputes each chunk's logits instead of saving them all
        chunks = (
            hidden.reshape(b, n, ce_chunk, -1).swapaxes(0, 1),
            targets.reshape(b, n, ce_chunk).swapaxes(0, 1),
            mask.reshape(b, n, ce_chunk).swapaxes(0, 1),
        )
        head = params["lm_head"]

        def chunk_ce(total, xs):
            h_c, t_c, m_c = xs
            logits_c = jnp.einsum(
                "bcd,dv->bcv", h_c, tm.load_weight(head, cfg.dtype)
            ).astype(jnp.float32)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits_c, t_c)
            return total + jnp.sum(ce * m_c), None

        total, _ = jax.lax.scan(
            jax.checkpoint(chunk_ce), jnp.zeros(()), chunks
        )
        loss = total / jnp.sum(mask)
    if cfg.n_experts > 0 and include_aux:
        # moe_aux arrives pre-weighted per layer (load-balance + router
        # z-loss, each with its own configured weight); held-out evaluation
        # excludes these training regularizers (include_aux=False) so
        # perplexity is exp(pure LM loss)
        loss = loss + moe_aux
    return loss


def _accumulated_value_and_grad(grad_fn, diff_params, tokens, grad_accum: int):
    """(loss, grads) of ``grad_fn(diff_params, micro_tokens)`` averaged over
    ``grad_accum`` equal batch slices via ``lax.scan`` — one slice's
    activations live at a time (the standard trade of step latency for
    activation memory on top of remat). For dense models the average equals
    the full-batch gradient exactly (the LM loss is a mean over equal
    slices; guards: test_grad_accum_matches_full_batch,
    test_lora_grad_accum_matches_full_batch); MoE aux losses are nonlinear
    batch statistics, so they are computed per slice and averaged — the
    standard approximation."""
    if grad_accum <= 1:
        return grad_fn(diff_params, tokens)
    b = tokens.shape[0]
    assert b % grad_accum == 0, (
        f"batch {b} not divisible by grad_accum {grad_accum}"
    )
    slices = tokens.reshape(grad_accum, b // grad_accum, *tokens.shape[1:])

    def accumulate(carry, micro_tokens):
        loss_sum, grad_sum = carry
        loss, grads = grad_fn(diff_params, micro_tokens)
        return (loss_sum + loss, jax.tree.map(jnp.add, grad_sum, grads)), None

    zeros = jax.tree.map(jnp.zeros_like, diff_params)
    (loss_sum, grad_sum), _ = jax.lax.scan(
        accumulate, (jnp.zeros(()), zeros), slices
    )
    return loss_sum / grad_accum, jax.tree.map(
        lambda g: g / grad_accum, grad_sum
    )


def train_step(params, opt_state, tokens, cfg: tm.TransformerConfig, optimizer,
               mesh=None, grad_accum: int = 1, ce_chunk: int = 0):
    """One optimizer update; see ``_accumulated_value_and_grad`` for the
    ``grad_accum > 1`` semantics and ``loss_fn`` for ``ce_chunk``."""
    loss, grads = _accumulated_value_and_grad(
        jax.value_and_grad(lambda p, t: loss_fn(p, t, cfg, mesh,
                                                ce_chunk=ce_chunk)),
        params, tokens, grad_accum,
    )
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


REMAT_POLICIES = ("full", "dots", "none")


def _apply_remat_policy(cfg: tm.TransformerConfig, remat_policy):
    """Resolve the per-factory remat override: ``None`` keeps ``cfg.remat``,
    anything else replaces it. The policy only changes WHAT the backward
    pass recomputes — "full" recomputes whole layers (HBM O(1) layers, a
    full extra forward of FLOPs), "dots" saves matmul outputs and replays
    only elementwise work (near-zero FLOP overhead — the MFU-tuned
    choice), "none" saves everything. Loss/grad math is identical across
    policies (guard: tests/test_overlap.py::TestRematPolicy)."""
    if remat_policy is None:
        return cfg
    if remat_policy not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat_policy {remat_policy!r}; expected one of "
            f"{REMAT_POLICIES}"
        )
    return dataclasses.replace(cfg, remat=remat_policy)


def _shardings(cfg: tm.TransformerConfig, mesh):
    """(param_shardings, token_sharding) for `cfg` over `mesh` — the one
    home of the sharding setup shared by the train/eval step factories."""
    mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    tp = mesh_shape.get("tp", 1)
    if cfg.n_heads % tp or cfg.kv_heads % tp:
        # fail here with a clear message instead of deep inside pjit when
        # the head axis of wq/wk/wv cannot shard evenly
        raise ValueError(
            f"head counts must divide the tp axis: n_heads={cfg.n_heads}, "
            f"kv_heads={cfg.kv_heads}, tp={tp}"
        )
    param_shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), tm.sharding_specs(cfg),
        is_leaf=lambda x: isinstance(x, P),
    )
    return param_shardings, NamedSharding(mesh, tm.activation_spec())


def make_sharded_train_step(
    cfg: tm.TransformerConfig,
    mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    grad_accum: int = 1,
    ce_chunk: int = 0,
    skip_nonfinite: bool = False,
    remat_policy: Optional[str] = None,
):
    """Returns (jitted_step, init_fn, token_sharding).

    ``init_fn(key)`` -> (params, opt_state) placed per the sharding specs;
    ``jitted_step(params, opt_state, tokens)`` -> (params, opt_state, loss)
    with donated carries; ``token_sharding`` is the [dp(+fsdp), sp]
    NamedSharding to device_put batches with. ``grad_accum`` splits each
    batch into that many gradient-accumulation slices (see train_step).

    ``skip_nonfinite``: gate the update inside the jitted step — when the
    loss comes out non-finite, params and opt_state pass through UNCHANGED
    (the update, including the optimizer step count, is dropped), so one
    poisoned batch cannot NaN the whole state. The returned loss still
    reports the non-finite value for the caller's divergence accounting
    (the ``train --on-nan skip`` policy; no extra sync — the gate is a
    ``jnp.where`` on the donated carries).

    ``remat_policy``: override ``cfg.remat`` for this step factory
    ("full" | "dots" | "none"; see ``_apply_remat_policy`` for the
    trade-offs) — blanket remat is a direct MFU tax paid on every FLOP,
    so training entry points select the policy here rather than baking
    it into the model config."""
    cfg = _apply_remat_policy(cfg, remat_policy)
    optimizer = optimizer or make_optimizer()
    param_shardings, token_sharding = _shardings(cfg, mesh)

    def init_fn(key: jax.Array):
        init = jax.jit(
            functools.partial(tm.init_params, cfg), out_shardings=param_shardings
        )
        params = init(key)
        # adam moments (mu/nu) are pytrees with exactly the params' structure:
        # substitute the param shardings for those subtrees, replicate the
        # rest (step counters). Explicit out_shardings because jit's own
        # inference can drop to single-device when all specs are effectively
        # replicated.
        params_treedef = jax.tree.structure(params)
        replicated = NamedSharding(mesh, P())

        def is_param_subtree(node):
            try:
                return jax.tree.structure(node) == params_treedef
            except Exception:
                return False

        opt_shapes = jax.eval_shape(optimizer.init, params)
        opt_shardings = jax.tree.map(
            lambda node: param_shardings if is_param_subtree(node) else replicated,
            opt_shapes,
            is_leaf=is_param_subtree,
        )
        opt_state = jax.jit(optimizer.init, out_shardings=opt_shardings)(params)
        return params, opt_state

    def step(params, opt_state, tokens):
        new_params, new_opt, loss = train_step(
            params, opt_state, tokens, cfg, optimizer, mesh,
            grad_accum=grad_accum, ce_chunk=ce_chunk)
        if skip_nonfinite:
            ok = jnp.isfinite(loss)
            new_params = jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new_params, params)
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new_opt, opt_state)
        return new_params, new_opt, loss

    jitted = compileguard.jit(
        step, guard_label="train.step", donate_argnums=(0, 1))
    return jitted, init_fn, token_sharding


def make_sharded_eval_step(cfg: tm.TransformerConfig, mesh, ce_chunk: int = 0):
    """Forward-only LM loss under the training shardings — held-out
    evaluation (the ``eval`` CLI). Returns (jitted_eval, init_fn,
    token_sharding): ``init_fn(key) -> params`` placed per the sharding
    specs (a checkpoint-restore template), ``jitted_eval(params, tokens) ->
    mean next-token CE`` excluding MoE training regularizers, so
    ``exp(loss)`` is the model's perplexity."""
    param_shardings, token_sharding = _shardings(cfg, mesh)

    def init_fn(key: jax.Array):
        return jax.jit(
            lambda k: tm.init_params(cfg, k), out_shardings=param_shardings
        )(key)

    def eval_step(params, tokens):
        return loss_fn(params, tokens, cfg, mesh, ce_chunk=ce_chunk,
                       include_aux=False)

    return (compileguard.jit(eval_step, guard_label="train.eval_step"),
            init_fn, token_sharding)


def make_sharded_lora_train_step(
    cfg: tm.TransformerConfig,
    mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    grad_accum: int = 1,
    ce_chunk: int = 0,
    remat_policy: Optional[str] = None,
):
    """LoRA fine-tuning: the base weights are genuinely frozen — gradients
    are taken w.r.t. the adapter subtree only (no base grads computed, no
    base optimizer moments allocated) and the optimizer state covers just
    the adapters, which is the whole point of parameter-efficient tuning.

    Returns (jitted_step, init_fn, token_sharding) where ``init_fn(key)`` ->
    (base_params, lora_params, opt_state) and ``jitted_step(base, lora,
    opt_state, tokens)`` -> (lora_params, opt_state, loss) with the small
    carries donated. ``grad_accum`` splits the batch into that many
    microbatch slices scanned with averaged adapter gradients (same trade
    and exactness argument as ``train_step``). ``remat_policy`` overrides
    ``cfg.remat`` exactly as in ``make_sharded_train_step``."""
    assert cfg.lora_rank > 0, "set cfg.lora_rank to use the LoRA step"
    cfg = _apply_remat_policy(cfg, remat_policy)
    optimizer = optimizer or make_optimizer()
    param_shardings, token_sharding = _shardings(cfg, mesh)

    def init_fn(key: jax.Array):
        init = jax.jit(
            functools.partial(tm.init_params, cfg), out_shardings=param_shardings
        )
        base, lora = tm.split_lora_params(init(key))
        opt_state = optimizer.init(lora)
        return base, lora, opt_state

    def lora_loss(lora, base, tokens):
        return loss_fn(tm.combine_lora_params(base, lora), tokens, cfg, mesh,
                       ce_chunk=ce_chunk)

    def step(base, lora, opt_state, tokens):
        loss, grads = _accumulated_value_and_grad(
            jax.value_and_grad(lambda lr, t: lora_loss(lr, base, t)),
            lora, tokens, grad_accum,
        )
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        lora = optax.apply_updates(lora, updates)
        return lora, opt_state, loss

    jitted = compileguard.jit(
        step, guard_label="train.lora_step", donate_argnums=(1, 2))
    return jitted, init_fn, token_sharding
