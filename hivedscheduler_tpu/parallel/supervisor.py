"""Workload supervisor: graceful preemption, hang watchdog, divergence guard.

HiveD's preemption story (guaranteed vs opportunistic jobs, lazy preemption,
work-preserving reconfiguration — reference README.md:31-42, OSDI'20 §3)
assumes the *workloads* tolerate being killed and rescheduled. The scheduler
side is hardened (chaos harness, PR 2); this module is the workload side —
the pieces a training/serving process needs so that preemption is actually
work-preserving end to end:

- :class:`PreemptionListener` — SIGTERM/SIGINT set an event instead of
  killing the process; the train/serve loops checkpoint (or drain) at the
  next step boundary and exit cleanly. A bounded **grace period** backstops
  a wedged shutdown: if the process has not exited ``grace_secs`` after the
  signal, it is force-exited (``EXIT_GRACE_EXCEEDED``) — an uncommitted
  checkpoint step is safe by construction (commit markers,
  ``parallel/checkpoint.py``).
- :class:`Watchdog` — a heartbeat thread enforcing a per-step deadline. A
  hung step (deadlocked collective, wedged host callback, stuck data
  loader) would otherwise wedge the whole gang forever — the scheduler
  cannot tell "slow" from "dead". On expiry the watchdog records
  state-of-record metadata (``hived_stall.json``, crash-atomic) and exits
  nonzero (``EXIT_STALLED``) so the gang framework restarts the job from
  its newest committed checkpoint. The first step's deadline is scaled by
  ``first_step_factor`` (compilation is legitimately slow).
- :class:`DivergenceGuard` — non-finite loss (always) and configurable
  loss-spike detection. Without it a single NaN step poisons every later
  checkpoint and the job ratchets itself into an unrecoverable state; the
  train loop's ``--on-nan`` policy decides halt / rollback / skip.
- :func:`FaultInjection.from_env` — seeded chaos hooks (hang at step k,
  NaN at step k, serve preemption at engine step k) used by
  ``chaos/workload.py`` and the fault-ladder tests; inert unless the
  ``HIVED_FAULT_*`` environment variables are set.

Everything here is dependency-light (no jax import at module load) and
single-consumer: one supervisor per workload process, driven from the main
loop. Metrics: ``tpu_hive_watchdog_stalls_total``,
``tpu_hive_train_rollbacks_total``, ``tpu_hive_train_resumes_total``
(see doc/design/observability.md).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import threading
import time
from typing import Callable, Optional

from hivedscheduler_tpu.common import lockcheck
from hivedscheduler_tpu.runtime.metrics import REGISTRY as metrics

log = logging.getLogger(__name__)

# Exit-code contract (consumed by chaos/workload.py and the gang framework's
# restart policy): 0 = clean (including checkpoint-and-exit on preemption —
# the work is preserved, nothing to retry); nonzero = restart me.
EXIT_STALLED = 43  # watchdog fired: step deadline exceeded
EXIT_DIVERGED = 44  # divergence guard halted (or rollback budget exhausted)
EXIT_GRACE_EXCEEDED = 45  # preemption grace period blown mid-shutdown

STALL_RECORD = "hived_stall.json"

# chaos/fault-injection environment hooks (one-shot per process; see
# FaultInjection). Names are the contract chaos/workload.py drives.
ENV_FAULT_HANG_AT = "HIVED_FAULT_HANG_AT"
ENV_FAULT_NAN_AT = "HIVED_FAULT_NAN_AT"
ENV_FAULT_SERVE_PREEMPT_AT = "HIVED_FAULT_SERVE_PREEMPT_AT"
ENV_FAULT_STEP_DELAY = "HIVED_FAULT_STEP_DELAY"


def _atomic_write_json(path: str, obj: dict) -> None:
    """Crash-atomic JSON write without importing the jax-heavy checkpoint
    module at supervisor import time."""
    from hivedscheduler_tpu.parallel.checkpoint import atomic_write_bytes

    atomic_write_bytes(path, json.dumps(obj, sort_keys=True).encode())


class PreemptionListener:
    """SIGTERM/SIGINT → a thread-safe event, with a bounded grace period.

    ``install()`` swaps the handlers in (main thread only — CPython signal
    rule) and remembers the previous ones; ``uninstall()`` restores them, so
    embedding the listener in a library entry point does not permanently
    steal the process's signal disposition. ``trigger()`` requests
    preemption programmatically (tests, chaos hooks) — identical semantics
    to a delivered signal, minus the grace timer's force-exit default being
    overridable via ``on_grace_exceeded``.
    """

    def __init__(self, grace_secs: float = 0.0,
                 on_grace_exceeded: Optional[Callable[[], None]] = None):
        self._event = threading.Event()
        self._prev: dict = {}
        self._grace_secs = grace_secs
        self._grace_timer: Optional[threading.Timer] = None
        self._on_grace_exceeded = on_grace_exceeded
        self.signum: Optional[int] = None

    def install(self) -> "PreemptionListener":
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev[sig] = signal.signal(sig, self._handle)
        except ValueError:
            # not the main thread (embedded use): preemption still works
            # via trigger(); signals stay with the embedder
            log.warning("not on the main thread: signal-driven preemption "
                        "disabled (trigger() still works)")
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        if self._grace_timer is not None:
            self._grace_timer.cancel()
            self._grace_timer = None

    def _handle(self, signum, _frame) -> None:
        self.signum = signum
        log.info("received signal %s: requesting checkpoint-and-exit at the "
                 "next step boundary (grace %.1fs)", signum, self._grace_secs)
        self.trigger()

    def trigger(self) -> None:
        """Request preemption (signal handler, tests, chaos hooks)."""
        first = not self._event.is_set()
        self._event.set()
        if first and self._grace_secs > 0:
            self._grace_timer = threading.Timer(self._grace_secs,
                                                self._grace_exceeded)
            self._grace_timer.daemon = True
            self._grace_timer.start()

    def _grace_exceeded(self) -> None:
        if self._on_grace_exceeded is not None:
            self._on_grace_exceeded()
            return
        log.error("preemption grace period (%.1fs) exceeded before a clean "
                  "exit; force-exiting (uncommitted checkpoint steps are "
                  "invisible to restore)", self._grace_secs)
        os._exit(EXIT_GRACE_EXCEEDED)

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    @property
    def event(self) -> threading.Event:
        """The underlying event — hand it to blocking consumers (e.g.
        ``data.prefetch(stop=...)``) so a preemption wakes them."""
        return self._event


class Watchdog:
    """Per-step deadline enforcement from a daemon heartbeat thread.

    The supervised loop calls ``heartbeat(step)`` at every step boundary;
    the watchdog thread polls and, when the age of the newest heartbeat
    exceeds the deadline, records state-of-record metadata and exits the
    process nonzero (``EXIT_STALLED``) so the gang restarts instead of
    wedging. The record (``hived_stall.json`` in ``record_dir``) is written
    crash-atomically BEFORE the exit — the post-mortem breadcrumb for "why
    did this incarnation die".

    The deadline before the FIRST heartbeat is ``deadline_s *
    first_step_factor``: step 1 of an incarnation includes compilation,
    which is legitimately one to two orders slower than a steady-state
    step. ``on_stall`` (tests) replaces the process exit with a callback.
    """

    def __init__(self, deadline_s: float, *, first_step_factor: float = 10.0,
                 record_dir: str = "", poll_s: Optional[float] = None,
                 on_stall: Optional[Callable[[dict], None]] = None,
                 clock=time.monotonic):
        if deadline_s <= 0:
            raise ValueError(f"watchdog deadline must be > 0, got {deadline_s}")
        self.deadline_s = deadline_s
        self.first_step_factor = max(1.0, first_step_factor)
        self.record_dir = record_dir
        self._poll_s = poll_s if poll_s is not None else min(deadline_s / 4, 1.0)
        self._on_stall = on_stall
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = lockcheck.make_lock("watchdog_lock")
        self._last_beat: Optional[float] = None
        self._last_step: Optional[int] = None
        self._beats = 0
        self._armed_at: Optional[float] = None
        self.fired = False

    def start(self) -> "Watchdog":
        self._armed_at = self._clock()
        self._thread = threading.Thread(target=self._run, name="hived-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def heartbeat(self, step: int) -> None:
        with self._lock:
            self._last_beat = self._clock()
            self._last_step = step
            self._beats += 1

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            with self._lock:
                beat, step, beats = self._last_beat, self._last_step, self._beats
            if beat is None:
                beat = self._armed_at
            # the scaled deadline holds until the SECOND heartbeat: the loop
            # beats BEFORE running each step, so beat #1 precedes the
            # compile-heavy first step — only from beat #2 on is the gap
            # between beats a steady-state step
            deadline = (self.deadline_s if beats >= 2
                        else self.deadline_s * self.first_step_factor)
            age = self._clock() - beat
            if age <= deadline:
                continue
            self._fire(step, age, deadline)
            return

    def _fire(self, step: Optional[int], age: float, deadline: float) -> None:
        self.fired = True
        metrics.inc("tpu_hive_watchdog_stalls_total")
        record = {
            "kind": "watchdog_stall",
            "pid": os.getpid(),
            "last_step": step,
            "heartbeat_age_s": round(age, 3),
            "deadline_s": deadline,
            "wall_time": time.time(),
        }
        log.error("watchdog: no step heartbeat for %.1fs (deadline %.1fs, "
                  "last step %s) — exiting %d so the gang restarts from the "
                  "newest committed checkpoint", age, deadline, step,
                  EXIT_STALLED)
        if self.record_dir:
            try:
                os.makedirs(self.record_dir, exist_ok=True)
                _atomic_write_json(
                    os.path.join(self.record_dir, STALL_RECORD), record)
            except OSError:
                log.exception("failed to write the stall record")
        if self._on_stall is not None:
            self._on_stall(record)
            return
        os._exit(EXIT_STALLED)


class DivergenceGuard:
    """Loss-divergence detection: non-finite always, spikes optionally.

    A non-finite loss is unconditional divergence. With ``spike_factor >
    0``, a loss exceeding ``spike_factor x`` the exponential moving average
    of recent finite losses also counts — catching the loss blow-ups that
    precede NaN by a few steps. The EMA needs ``warmup_steps`` observations
    before spike detection arms (early-training losses move fast and would
    false-positive)."""

    def __init__(self, spike_factor: float = 0.0, ema_decay: float = 0.9,
                 warmup_steps: int = 5):
        self.spike_factor = spike_factor
        self.ema_decay = ema_decay
        self.warmup_steps = warmup_steps
        self._ema: Optional[float] = None
        self._seen = 0

    def check(self, step: int, loss: float) -> Optional[str]:
        """Returns a divergence reason string, or None when healthy."""
        import math

        if not math.isfinite(loss):
            return f"non-finite loss {loss} at step {step}"
        if (self.spike_factor > 0 and self._seen >= self.warmup_steps
                and self._ema is not None
                and loss > self.spike_factor * self._ema):
            return (f"loss spike at step {step}: {loss:.4f} > "
                    f"{self.spike_factor:.1f} x EMA {self._ema:.4f}")
        self._seen += 1
        self._ema = (loss if self._ema is None
                     else self.ema_decay * self._ema
                     + (1.0 - self.ema_decay) * loss)
        return None

    def reset(self) -> None:
        """Forget history (after a rollback: the restored trajectory's EMA
        must not inherit the diverged run's tail)."""
        self._ema = None
        self._seen = 0


@dataclasses.dataclass
class FaultInjection:
    """One-shot chaos hooks for the workload fault ladder, armed via
    environment variables (``HIVED_FAULT_*``). Each fires at most once per
    process — a rollback replaying the same step must not re-trip the
    injected fault (the real-world analogue: a transient bad batch /
    cosmic-ray flip, not a deterministic poison)."""

    hang_at: Optional[int] = None
    nan_at: Optional[int] = None
    serve_preempt_at: Optional[int] = None
    step_delay_s: float = 0.0

    @classmethod
    def from_env(cls) -> "FaultInjection":
        def geti(name):
            v = os.environ.get(name, "")
            return int(v) if v else None

        return cls(hang_at=geti(ENV_FAULT_HANG_AT),
                   nan_at=geti(ENV_FAULT_NAN_AT),
                   serve_preempt_at=geti(ENV_FAULT_SERVE_PREEMPT_AT),
                   step_delay_s=float(
                       os.environ.get(ENV_FAULT_STEP_DELAY, "") or 0.0))

    def pace(self) -> None:
        """Chaos pacing: pad every step by ``step_delay_s`` so the soak
        harness can land signals at deterministic step windows (tiny test
        models otherwise finish a step in microseconds — nothing could be
        killed 'mid-training' reliably). Inert when unarmed."""
        if self.step_delay_s > 0:
            time.sleep(self.step_delay_s)

    def maybe_hang(self, step: int) -> None:
        """Injected stall: sleep far past any watchdog deadline at the
        armed step (the hang the watchdog exists to catch)."""
        if self.hang_at is not None and step == self.hang_at:
            self.hang_at = None
            log.warning("FAULT INJECTION: hanging at step %d", step)
            time.sleep(3600.0)

    def take_nan(self, step: int) -> bool:
        """True exactly once, at the armed step: the caller poisons its
        params with NaN (which genuinely poisons every later loss and
        checkpoint — the failure mode the divergence guard defends)."""
        if self.nan_at is not None and step == self.nan_at:
            self.nan_at = None
            log.warning("FAULT INJECTION: poisoning params with NaN at "
                        "step %d", step)
            return True
        return False

    def take_serve_preempt(self, engine_step: int) -> bool:
        """True exactly once, at the armed serving engine step."""
        if (self.serve_preempt_at is not None
                and engine_step == self.serve_preempt_at):
            self.serve_preempt_at = None
            log.warning("FAULT INJECTION: requesting serve preemption at "
                        "engine step %d", engine_step)
            return True
        return False


class Supervisor:
    """The training loop's one-stop supervision facade.

    Bundles the preemption listener, the optional watchdog, the divergence
    guard and the rollback budget behind a context manager::

        with Supervisor(grace_secs=30, watchdog_secs=120,
                        record_dir=ckpt_dir) as sup:
            for step in range(start, steps):
                sup.heartbeat(step)
                ... run the step ...
                reason = sup.check_loss(step, loss)
                if reason: ... apply the --on-nan policy ...
                if sup.preempt_requested:
                    ... checkpoint and break ...

    ``on_stall`` / ``on_grace_exceeded`` replace the default process exits
    for in-process tests. The rollback budget (``max_rollbacks``) bounds the
    rollback policy: a persistently-diverging run must eventually halt
    (``EXIT_DIVERGED``) rather than livelock restoring forever.
    """

    def __init__(self, *, grace_secs: float = 30.0, watchdog_secs: float = 0.0,
                 spike_factor: float = 0.0, max_rollbacks: int = 3,
                 record_dir: str = "", first_step_factor: float = 10.0,
                 on_stall: Optional[Callable[[dict], None]] = None,
                 on_grace_exceeded: Optional[Callable[[], None]] = None,
                 install_signals: bool = True, clock=time.monotonic):
        self.preemption = PreemptionListener(
            grace_secs=grace_secs, on_grace_exceeded=on_grace_exceeded)
        self.watchdog: Optional[Watchdog] = None
        if watchdog_secs > 0:
            self.watchdog = Watchdog(
                watchdog_secs, first_step_factor=first_step_factor,
                record_dir=record_dir, on_stall=on_stall, clock=clock)
        self.guard = DivergenceGuard(spike_factor=spike_factor)
        self.faults = FaultInjection.from_env()
        self.max_rollbacks = max_rollbacks
        self.rollbacks = 0
        self._install_signals = install_signals

    def __enter__(self) -> "Supervisor":
        if self._install_signals:
            self.preemption.install()
        if self.watchdog is not None:
            self.watchdog.start()
        return self

    def __exit__(self, *exc) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._install_signals:
            self.preemption.uninstall()

    def heartbeat(self, step: int) -> None:
        if self.watchdog is not None:
            self.watchdog.heartbeat(step)

    @property
    def preempt_requested(self) -> bool:
        return self.preemption.requested

    def check_loss(self, step: int, loss: float) -> Optional[str]:
        return self.guard.check(step, loss)

    def note_rollback(self) -> bool:
        """Record one divergence rollback; False when the budget is
        exhausted (the caller must halt)."""
        self.rollbacks += 1
        metrics.inc("tpu_hive_train_rollbacks_total")
        from hivedscheduler_tpu.obs import journal as obs_journal
        if obs_journal.JOURNAL.enabled:
            obs_journal.emit("train_rollback", "train",
                             rollbacks=self.rollbacks)
        self.guard.reset()
        return self.rollbacks <= self.max_rollbacks
