"""Generation CLI: KV-cache autoregressive decoding on the flagship model.

``python -m hivedscheduler_tpu.generate --new-tokens 32 ...`` — model flags
mirror ``hivedscheduler_tpu.train``; ``--checkpoint-dir`` restores params
saved by a training run (same directory layout), otherwise random-init
weights demo the decode path. Prints one line of token ids per sequence.
"""

from __future__ import annotations

import argparse
import logging
import sys

from hivedscheduler_tpu.common import utils as common

log = logging.getLogger(__name__)


def _serving_mesh(args):
    """Build the dp x tp serving mesh from CLI flags; raises ValueError on
    any bad flag combination (the single validation site for both the
    vanilla and the speculative sharded branches)."""
    from hivedscheduler_tpu.parallel import topology

    if args.dp < 1 or args.tp < 1:
        raise ValueError(f"--dp/--tp must be >= 1, got dp={args.dp} tp={args.tp}")
    if args.batch % args.dp:
        raise ValueError(
            f"--batch {args.batch} must be divisible by --dp {args.dp}"
        )
    axes = topology.MeshAxes(dp=args.dp, tp=args.tp)
    return topology.make_mesh(axes, topology.get_devices(axes.size))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-hive-generate")
    parser.add_argument("--batch", type=int, default=1)
    parser.add_argument("--prompt-len", type=int, default=8,
                        help="random prompt length (demo input)")
    parser.add_argument("--new-tokens", type=int, default=32)
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="0 = greedy")
    parser.add_argument("--top-k", type=int, default=0,
                        help="sample only from the k most likely tokens (0 = off)")
    parser.add_argument("--top-p", type=float, default=1.0,
                        help="nucleus sampling: smallest prefix with cumulative "
                        "probability >= p (1.0 = off)")
    parser.add_argument("--decode-steps", type=int, default=1,
                        help="unroll the decode scan by K iterations "
                        "inside the single jitted generate loop (XLA "
                        "software-pipelines consecutive token steps; "
                        "output identical for any K). Ignored by the "
                        "speculative path (--draft-layers)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--expert-capacity-factor", type=float, default=1.25,
                        help="MoE expert capacity factor (must match the "
                        "checkpoint's training value)")
    parser.add_argument("--rope-theta", type=float, default=10000.0,
                        help="RoPE base frequency (must match the "
                        "checkpoint's training value)")
    parser.add_argument("--vocab-size", type=int, default=32000)
    parser.add_argument("--d-model", type=int, default=512)
    parser.add_argument("--n-layers", type=int, default=8)
    parser.add_argument("--n-heads", type=int, default=8)
    parser.add_argument("--n-kv-heads", type=int, default=0,
                        help="GQA shared k/v heads (compact cache)")
    parser.add_argument("--d-ff", type=int, default=1408)
    parser.add_argument("--n-experts", type=int, default=0)
    parser.add_argument("--moe-top-k", type=int, default=1)
    parser.add_argument("--checkpoint-dir", default="",
                        help="restore params from a training checkpoint")
    parser.add_argument("--lora-rank", type=int, default=0,
                        help="the checkpoint is a LoRA run of this rank: "
                        "adapters are restored and merged into the base "
                        "weights before serving")
    parser.add_argument("--lora-alpha", type=float, default=16.0)
    parser.add_argument("--lora-mlp", action="store_true",
                        help="the checkpoint's adapters also cover the "
                             "dense-MLP projections")
    parser.add_argument("--quantize", choices=["none", "int8"], default="none",
                        help="weight-only int8 post-training quantization "
                        "(halves weight HBM traffic vs bf16 while matmuls "
                        "stay in the model dtype)")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel serving over a tp mesh axis")
    parser.add_argument("--dp", type=int, default=1,
                        help="batch-parallel serving over a dp mesh axis")
    parser.add_argument("--draft-layers", type=int, default=0,
                        help="speculative decoding: layers of the draft model "
                        "(0 = off; demo uses random draft weights)")
    parser.add_argument("--draft-d-model", type=int, default=0,
                        help="draft width (default: half the target)")
    parser.add_argument("--gamma", type=int, default=4,
                        help="draft tokens proposed per verification round")
    parser.add_argument("--goodput-file", default="",
                        help="enable the workload goodput ledger "
                        "(obs/goodput.py) and append this run's step-phase "
                        "records to this JSONL spool")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    common.init_all(logging.DEBUG if args.verbose else logging.INFO)
    from hivedscheduler_tpu.obs import goodput as obs_goodput

    if args.goodput_file:
        obs_goodput.enable(spool_path=args.goodput_file)
    import jax
    import jax.numpy as jnp

    from hivedscheduler_tpu.models import decode, transformer as tm

    cfg = tm.TransformerConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        max_seq_len=args.prompt_len + args.new_tokens,
        n_experts=args.n_experts,
        moe_top_k=args.moe_top_k,
        expert_capacity_factor=args.expert_capacity_factor,
        rope_theta=args.rope_theta,
    )
    from hivedscheduler_tpu.parallel import checkpoint as ckpt

    # params-only restore (restore_serving_params): inference needs no
    # optimizer moments, and a LoRA run's adapter-only optimizer tree
    # wouldn't match anyway; adapters merge into the base at load
    try:
        params, step = ckpt.restore_serving_params(
            cfg, args.checkpoint_dir, jax.random.PRNGKey(args.seed),
            lora_rank=args.lora_rank, lora_alpha=args.lora_alpha,
            lora_mlp=args.lora_mlp,
        )
    except FileNotFoundError as e:
        log.error("%s", e)
        return 1
    if step is not None:
        log.info("restored params from step %s", step)
    if args.lora_rank > 0:
        log.info("merged rank-%s LoRA adapters into the base weights",
                 args.lora_rank)
    quantized = args.quantize == "int8"
    if quantized:
        # with --draft-layers the (big) target quantizes; the draft is small
        # enough that its float weights are not the bandwidth term
        from hivedscheduler_tpu.models import quant

        params = quant.quantize_params(params, cfg)
        log.info("quantized weights to int8 (per-output-channel scales)")
    else:
        # serving holds weights in the compute dtype: decode is
        # HBM-bandwidth-bound, and f32 checkpoint weights would stream twice
        # the bytes per generated token (models/transformer.cast_params)
        params = tm.cast_params(params, cfg.dtype)

    prompt = jax.random.randint(
        jax.random.PRNGKey(args.seed + 1), (args.batch, args.prompt_len),
        0, cfg.vocab_size, jnp.int32,
    )
    if args.top_k > cfg.vocab_size:
        log.error("--top-k %s exceeds --vocab-size %s", args.top_k, cfg.vocab_size)
        return 1
    key = jax.random.PRNGKey(args.seed + 2) if args.temperature > 0 else None
    # single-shot decode: compile + decode run inside one jitted call, so
    # the whole generation is attributed to step_compute (the goodput doc
    # notes the folding; train.py's per-step loop separates compile)
    obs_goodput.phase("step_compute")
    if args.draft_layers > 0:
        if args.gamma < 1:
            log.error("--gamma must be >= 1, got %s", args.gamma)
            return 1
        from hivedscheduler_tpu.models.speculative import (
            derive_draft_config,
            generate_speculative,
            make_sharded_speculative,
        )

        try:
            dft_cfg = derive_draft_config(cfg, args.draft_layers,
                                          args.draft_d_model)
        except ValueError as e:
            log.error("%s", e)
            return 1
        dft_params = tm.cast_params(
            tm.init_params(dft_cfg, jax.random.PRNGKey(args.seed + 3)),
            dft_cfg.dtype,
        )
        if args.tp > 1 or args.dp > 1:
            try:
                mesh = _serving_mesh(args)
                run, tgt_sh, dft_sh, prompt_sh = make_sharded_speculative(
                    cfg, dft_cfg, mesh, args.new_tokens, gamma=args.gamma,
                    temperature=args.temperature, top_k=args.top_k,
                    top_p=args.top_p, quantized_target=quantized,
                )
            except ValueError as e:
                log.error("%s", e)
                return 1
            out, stats = run(
                jax.device_put(params, tgt_sh),
                jax.device_put(dft_params, dft_sh),
                jax.device_put(prompt, prompt_sh), key,
            )
        else:
            out, stats = generate_speculative(
                params, dft_params, prompt, cfg, dft_cfg, args.new_tokens,
                gamma=args.gamma, temperature=args.temperature,
                top_k=args.top_k, top_p=args.top_p, key=key,
            )
        log.info(
            "speculation: %s rounds, %s/%s draft tokens accepted (%.0f%%)",
            int(stats.rounds), int(stats.accepted), int(stats.drafted),
            100.0 * int(stats.accepted) / max(1, int(stats.drafted)),
        )
        rows = jax.device_get(out)  # the host sync: decode ends here
        obs_goodput.phase("idle")
        for row in rows:
            print(" ".join(str(int(t)) for t in row))
        return 0
    if args.tp > 1 or args.dp > 1:
        try:
            mesh = _serving_mesh(args)
            run, param_shardings, prompt_sharding = decode.make_sharded_generate(
                cfg, mesh, args.new_tokens, temperature=args.temperature,
                top_k=args.top_k, top_p=args.top_p, quantized=quantized,
                decode_steps=args.decode_steps,
            )
        except ValueError as e:
            # user errors (bad dp/tp/batch flags, head counts vs --tp,
            # device count) get the same one-line treatment everywhere
            log.error("%s", e)
            return 1
        params = jax.device_put(params, param_shardings)
        prompt = jax.device_put(prompt, prompt_sharding)
        out = run(params, prompt, key)
    else:
        out = decode.generate(
            params, prompt, cfg, args.new_tokens,
            temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
            key=key, decode_steps=args.decode_steps,
        )
    rows = jax.device_get(out)  # the host sync: decode ends here
    obs_goodput.phase("idle")
    for row in rows:
        print(" ".join(str(int(t)) for t in row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
