"""Held-out evaluation: token-level loss and perplexity of a checkpoint.

Completes the workload triad (train / eval / generate-serve). Unlike
training's random windows, evaluation walks the corpus in SEQUENTIAL
non-overlapping windows, so two runs over the same file agree bit-for-bit
(each window scores batch x (seq_len - 1) positions — the row-leading
tokens have no preceding context and are not targets):

    python -m hivedscheduler_tpu.eval --checkpoint-dir /ckpt/run1 \
        --data heldout.bin --tp 2 --sp 2

Model/mesh flags mirror ``hivedscheduler_tpu.train``; the forward runs
under the same shardings via ``parallel.train.make_sharded_eval_step``
(MoE training regularizers excluded — the reported loss is pure next-token
cross-entropy, so perplexity is ``exp(loss)``).
"""

from __future__ import annotations

import argparse
import logging
import math
import sys
import time

from hivedscheduler_tpu.common import utils as common

log = logging.getLogger(__name__)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-hive-eval")
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=512)
    parser.add_argument("--vocab-size", type=int, default=32000)
    parser.add_argument("--d-model", type=int, default=512)
    parser.add_argument("--n-layers", type=int, default=8)
    parser.add_argument("--n-heads", type=int, default=8)
    parser.add_argument("--n-kv-heads", type=int, default=0)
    parser.add_argument("--d-ff", type=int, default=1408)
    parser.add_argument("--n-experts", type=int, default=0)
    parser.add_argument("--moe-top-k", type=int, default=1)
    parser.add_argument("--attn", default=None,
                        help="xla|flash|ring|ring_flash|ring_zigzag|"
                             "ring_zigzag_flash|ulysses "
                             "(default: ring when sp>1)")
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--ep", type=int, default=1)
    parser.add_argument("--ce-chunk", type=int, default=0,
                        help="chunked cross-entropy (as in train)")
    parser.add_argument("--data", default="",
                        help="packed token file; synthetic corpus when "
                             "omitted (smoke only — perplexity of random "
                             "tokens is ~vocab size)")
    parser.add_argument("--data-dtype", default="uint16",
                        choices=["uint16", "uint32"])
    parser.add_argument("--max-steps", type=int, default=0,
                        help="cap evaluated windows (0 = whole corpus)")
    parser.add_argument("--checkpoint-dir", default="",
                        help="checkpoint to evaluate (random init when "
                             "omitted — smoke only)")
    parser.add_argument("--goodput-file", default="",
                        help="enable the workload goodput ledger "
                        "(obs/goodput.py) and append this run's step-phase "
                        "records to this JSONL spool")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    common.init_all(logging.DEBUG if args.verbose else logging.INFO)
    from hivedscheduler_tpu.obs import goodput as obs_goodput

    if args.goodput_file:
        obs_goodput.enable(spool_path=args.goodput_file)

    from hivedscheduler_tpu.parallel.distributed import initialize_from_gang

    rank, world = initialize_from_gang()

    import jax
    import numpy as np

    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.parallel import checkpoint as ckpt
    from hivedscheduler_tpu.parallel import data as data_lib
    from hivedscheduler_tpu.parallel import topology
    from hivedscheduler_tpu.parallel.train import make_sharded_eval_step

    n_devices = len(jax.devices())
    axes = topology.infer_axes(n_devices, tp=args.tp, sp=args.sp,
                               fsdp=args.fsdp, ep=args.ep)
    mesh = topology.make_mesh(axes)
    log.info("rank %s/%s: %s devices, mesh %s", rank, world, n_devices, axes)

    cfg = tm.TransformerConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        max_seq_len=args.seq_len,
        attn_impl=args.attn or ("ring" if axes.sp > 1 else "xla"),
        n_experts=args.n_experts,
        moe_top_k=args.moe_top_k,
    )
    eval_step, init_fn, token_sharding = make_sharded_eval_step(
        cfg, mesh, ce_chunk=args.ce_chunk
    )
    params = init_fn(jax.random.PRNGKey(0))
    if args.checkpoint_dir:
        step, params = ckpt.restore_params(args.checkpoint_dir, params)
        log.info("restored params from step %s", step)
    else:
        log.warning("no --checkpoint-dir: evaluating RANDOM init (smoke)")

    if args.data:
        dataset = data_lib.TokenFileDataset(args.data, dtype=args.data_dtype)
    else:
        dataset = data_lib.synthetic_dataset(cfg.vocab_size)
    corpus = dataset.tokens
    window = args.batch * args.seq_len
    n_steps = len(corpus) // window
    if args.max_steps > 0:
        n_steps = min(n_steps, args.max_steps)
    if n_steps == 0:
        log.error("corpus too small: %s tokens < one %s-token batch window",
                  len(corpus), window)
        return 1
    # multi-host: device_put_global takes each process's LOCAL rows (same
    # contract as the train CLI's host_batches)
    proc, n_proc = jax.process_index(), jax.process_count()
    if args.batch % n_proc:
        log.error("--batch %s must divide the process count %s",
                  args.batch, n_proc)
        return 1
    rows = args.batch // n_proc

    t0 = time.perf_counter()
    obs_goodput.phase("eval")
    # accumulate on device; one host sync at the end (float() per window
    # would serialize batch prep with device compute)
    total_loss = None
    for i in range(n_steps):
        batch_np = np.asarray(
            corpus[i * window: (i + 1) * window], dtype=np.int32
        ).reshape(args.batch, args.seq_len)[proc * rows: (proc + 1) * rows]
        tokens = data_lib.device_put_global(batch_np, token_sharding,
                                            args.batch)
        step_loss = eval_step(params, tokens)
        total_loss = step_loss if total_loss is None else total_loss + step_loss
        if args.verbose and (i + 1) % 10 == 0:
            log.info("window %s/%s running loss %.4f", i + 1, n_steps,
                     float(total_loss) / (i + 1))
    dt = time.perf_counter() - t0
    # every window contributes batch*(seq-1) scored positions, so the mean
    # of per-window means IS the corpus token-level mean over scored targets
    loss = float(total_loss) / n_steps
    obs_goodput.phase("idle")
    ppl = math.exp(min(loss, 30.0))
    log.info(
        "%s windows (%s tokens) in %.2fs (%.0f tok/s)",
        n_steps, n_steps * window, dt, n_steps * window / max(dt, 1e-9),
    )
    # enough digits that exp(printed loss) agrees with printed perplexity
    # to ~1e-5 relative: consumers (and the guard test) check the pair for
    # consistency, and a 2-decimal perplexity's rounding grain (±0.005)
    # is coarser than that check at typical ppl magnitudes
    print(f"loss {loss:.6f}  perplexity {ppl:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
