"""Lock registry + opt-in runtime lock-discipline sanitizer.

This module is the single source of truth for the package's lock hierarchy
(the CLAUDE.md concurrency contract, machine-checked by ``tools/hivedlint``
and documented in ``doc/design/concurrency.md``):

- every ``threading.Lock``/``RLock`` in the package is created through
  :func:`make_lock` / :func:`make_rlock` with a name registered in
  :data:`LOCK_HIERARCHY` (hivedlint flags direct ``threading.Lock()`` calls
  and unregistered names — adding a lock means adding a registry row, which
  IS the documented hierarchy);
- with ``HIVED_LOCKCHECK=1`` the factories return :class:`CheckedLock`
  wrappers that track per-thread held-lock sets and assert lock-order
  consistency: a thread may only acquire a lock whose level is strictly
  greater than every *other* lock it already holds (re-acquiring a held
  RLock is always fine). Firing a fake-ApiServer handler while holding the
  store leaf lock, or any other inversion, raises :class:`LockOrderError`
  instead of deadlocking some soak 20 minutes later;
- :func:`assert_serialized` enforces the algorithm layer's single-threaded
  contract at runtime: the runtime registers its scheduler lock on the
  algorithm instance (:func:`serialize_under`) and every algorithm mutating
  entry point asserts that lock is held by the calling thread. Standalone
  algorithm tests (no runtime attached) are unaffected.

The sanitizer is wired into the chaos soaks (tests/test_hivedlint.py), so
every soak doubles as a race/deadlock detector. Overhead when disabled is
one env read per lock *creation* — acquire/release stay native — except
for the module-level singleton locks (metrics REGISTRY, obs
TRACER/RECORDER, compileguard counters), which are created with
``late=True``: they return a :class:`SwitchableLock` that re-reads the
env var per acquisition, so enabling ``HIVED_LOCKCHECK=1`` *after* first
import still puts them under the sanitizer (the ISSUE 7 gap). The
per-instance locks (scheduler/algorithm/store/watchdog) honor the env var
at construction time, which is what the soaks exercise.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# The declared lock hierarchy. Level = acquisition order: a thread holding a
# lock at level L may only acquire locks at levels > L. Low levels are the
# outermost (coarsest) locks; the highest levels are leaves — nothing may be
# acquired while holding them. Gaps are deliberate (room for new locks).
# ---------------------------------------------------------------------------
LOCK_HIERARCHY: Dict[str, int] = {
    # runtime/scheduler.py — ONE coarse lock serializes scheduling; every
    # mutating call into the algorithm layer happens under it.
    "scheduler_lock": 10,
    # algorithm/hived.py — the algorithm's own serialization (defense in
    # depth below the scheduler lock; also covers embedders that drive the
    # algorithm directly).
    "algorithm_lock": 20,
    # parallel/supervisor.py — watchdog beat state.
    "watchdog_lock": 40,
    # k8s/fake.py — the fake-ApiServer object store. LEAF towards handlers:
    # informer handlers (which take the scheduler lock) must never run under
    # it; the only things legal under it are pure store mutations.
    "store_lock": 50,
    # fleet/router.py — the serving-fleet router's bookkeeping. A leaf
    # above only the observability leaves: routing/harvest emit journal
    # events and metrics under it, and NOTHING below it (in particular the
    # scheduler lock — scale backends run outside the router lock).
    "fleet_router_lock": 70,
    # runtime/eventbatch.py — the batched watch-event queue. LEAF: enqueue
    # runs on informer threads that may already hold the scheduler lock
    # (synchronous fake-ApiServer delivery), and nothing is ever acquired
    # under it.
    "event_queue_lock": 75,
    # observability leaves: nothing is ever acquired under these.
    # (ledger_lock, journal_lock and slo_lock sit just below metrics_lock:
    # closing a chip/wait interval / observing an SLO datapoint observes
    # histograms and gauges while holding them — the one legal under-leaf
    # acquisition.)
    # obs/ledger.py — capacity-ledger chip-state books. Acquired by the
    # algorithm chokepoints (under scheduler+algorithm locks) and by
    # webserver reads.
    "ledger_lock": 77,
    # obs/goodput.py — workload step-phase books. A pure leaf like the
    # ledger: phase transitions observe the goodput counter under it.
    "goodput_lock": 76,
    "journal_lock": 78,
    # obs/slo.py — SLO tracker observations/quantiles. Acquired under the
    # fleet router lock (harvest observes TTFTs) and by webserver reads.
    "slo_lock": 79,
    "metrics_lock": 80,
    "trace_lock": 82,
    "decisions_lock": 84,
    # common/compileguard.py — jit cache-miss counters. LEAF.
    "compileguard_lock": 86,
}

# Which file may create each lock (repo-relative); consumed by hivedlint's
# lock-registry rule. Creating a registered lock elsewhere — or any lock
# outside this table — is a lint violation.
LOCK_SITES: Dict[str, str] = {
    "scheduler_lock": "hivedscheduler_tpu/runtime/scheduler.py",
    "algorithm_lock": "hivedscheduler_tpu/algorithm/hived.py",
    "watchdog_lock": "hivedscheduler_tpu/parallel/supervisor.py",
    "store_lock": "hivedscheduler_tpu/k8s/fake.py",
    "fleet_router_lock": "hivedscheduler_tpu/fleet/router.py",
    "event_queue_lock": "hivedscheduler_tpu/runtime/eventbatch.py",
    "ledger_lock": "hivedscheduler_tpu/obs/ledger.py",
    "goodput_lock": "hivedscheduler_tpu/obs/goodput.py",
    "journal_lock": "hivedscheduler_tpu/obs/journal.py",
    "slo_lock": "hivedscheduler_tpu/obs/slo.py",
    "metrics_lock": "hivedscheduler_tpu/runtime/metrics.py",
    "trace_lock": "hivedscheduler_tpu/obs/trace.py",
    "decisions_lock": "hivedscheduler_tpu/obs/decisions.py",
    "compileguard_lock": "hivedscheduler_tpu/common/compileguard.py",
}

# Files allowed to spawn threads (hivedlint's thread-spawn rule). Every
# thread here either only touches leaf state or enters the runtime through
# the scheduler lock.
THREAD_SITES = frozenset({
    "hivedscheduler_tpu/runtime/scheduler.py",   # force-bind executor
    "hivedscheduler_tpu/k8s/rest.py",            # watch threads
    "hivedscheduler_tpu/api/config.py",          # config-watch poller
    "hivedscheduler_tpu/parallel/supervisor.py", # watchdog heartbeat
    "hivedscheduler_tpu/parallel/data.py",       # prefetch worker
    "hivedscheduler_tpu/webserver/server.py",    # HTTP serve thread
})


class LockOrderError(RuntimeError):
    """A lock-discipline violation: out-of-hierarchy acquisition, release of
    an unheld checked lock, or an algorithm mutator entered without the
    serializing lock."""


def enabled() -> bool:
    return os.environ.get("HIVED_LOCKCHECK", "") == "1"


_tls = threading.local()


def _stack() -> List["_Held"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _Held:
    __slots__ = ("lock", "count")

    def __init__(self, lock: "CheckedLock"):
        self.lock = lock
        self.count = 1


class CheckedLock:
    """Order-asserting wrapper around a ``threading.Lock``/``RLock``.

    Exposes the subset of the lock API the package uses (``acquire`` with
    ``blocking``/``timeout``, ``release``, context manager, ``locked``,
    ``_is_owned``) and keeps a per-thread stack of held checked locks to
    assert the :data:`LOCK_HIERARCHY` order on every acquisition."""

    def __init__(self, name: str, level: int, inner):
        self.name = name
        self.level = level
        self._inner = inner

    # -- core ------------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = _stack()
        held = next((h for h in st if h.lock is self), None)
        if held is None:
            worst = max((h for h in st if h.lock.level >= self.level),
                        key=lambda h: h.lock.level, default=None)
            if worst is not None:
                raise LockOrderError(
                    f"lock-order violation: acquiring {self.name!r} (level "
                    f"{self.level}) while holding {worst.lock.name!r} (level "
                    f"{worst.lock.level}); held: "
                    f"{[h.lock.name for h in st]} — see LOCK_HIERARCHY in "
                    f"common/lockcheck.py and doc/design/concurrency.md"
                )
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if held is not None:
                held.count += 1
            else:
                st.append(_Held(self))
        return ok

    def release(self) -> None:
        st = _stack()
        held = next((h for h in st if h.lock is self), None)
        if held is None:
            raise LockOrderError(
                f"release of {self.name!r} which this thread does not hold"
            )
        self._inner.release()
        held.count -= 1
        if held.count == 0:
            st.remove(held)

    # -- sugar the package relies on -------------------------------------
    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        """RLock ownership probe (the fake ApiServer's leaf-lock assertion
        chokepoint uses it); falls back to the held stack for plain locks."""
        inner_probe = getattr(self._inner, "_is_owned", None)
        if inner_probe is not None:
            return inner_probe()
        return any(h.lock is self for h in _stack())

    def __repr__(self) -> str:
        return f"<CheckedLock {self.name!r} level={self.level} {self._inner!r}>"


class SwitchableLock:
    """Late-enabling wrapper for module-level singleton locks.

    A singleton created at first import froze the sanitizer decision
    before any test could set the env var (the ISSUE 7 "NOT done" gap).
    This proxy re-reads ``HIVED_LOCKCHECK`` on every acquisition: when
    enabled it routes through a lazily-built :class:`CheckedLock` over the
    SAME underlying lock (so waiters on either path contend correctly);
    when disabled it acquires the raw lock. Each successful acquisition
    records which path it took so a release always pairs with its acquire
    even if the env var flips mid-hold. Singleton locks are leaves in
    :data:`LOCK_HIERARCHY`, so the extra env read per acquire is off every
    scheduling hot path."""

    __slots__ = ("name", "_inner", "_checked", "_modes")

    def __init__(self, name: str, inner):
        if name not in LOCK_HIERARCHY:
            raise LockOrderError(
                f"lock name {name!r} is not in LOCK_HIERARCHY — register it "
                f"(and its creating file in LOCK_SITES) before use"
            )
        self.name = name
        self._inner = inner
        self._checked: Optional[CheckedLock] = None
        self._modes: List = []  # acquisition path stack (GIL-guarded)

    def _target(self):
        if not enabled():
            return self._inner
        if self._checked is None:
            self._checked = CheckedLock(
                self.name, LOCK_HIERARCHY[self.name], self._inner)
        return self._checked

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        tgt = self._target()
        ok = tgt.acquire(blocking, timeout)
        if ok:
            self._modes.append(tgt)
        return ok

    def release(self) -> None:
        tgt = self._modes.pop() if self._modes else self._target()
        tgt.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        inner_probe = getattr(self._inner, "_is_owned", None)
        if inner_probe is not None:
            return inner_probe()
        return any(
            isinstance(m, CheckedLock) and m._is_owned() for m in self._modes
        ) or bool(self._modes)

    def __repr__(self) -> str:
        return (f"<SwitchableLock {self.name!r} "
                f"checked={self._checked is not None} {self._inner!r}>")


def _make(name: str, factory, late: bool):
    if late:
        return SwitchableLock(name, factory())
    if not enabled():
        return factory()
    if name not in LOCK_HIERARCHY:
        raise LockOrderError(
            f"lock name {name!r} is not in LOCK_HIERARCHY — register it (and "
            f"its creating file in LOCK_SITES) before use"
        )
    return CheckedLock(name, LOCK_HIERARCHY[name], factory())


def make_lock(name: str, late: bool = False):
    """A ``threading.Lock`` registered as ``name`` (checked under
    ``HIVED_LOCKCHECK=1``, plain otherwise). ``late=True`` — for
    module-level singletons — returns a :class:`SwitchableLock` honoring
    the env var per acquisition instead of at creation."""
    return _make(name, threading.Lock, late)


def make_rlock(name: str, late: bool = False):
    """A ``threading.RLock`` registered as ``name`` (checked under
    ``HIVED_LOCKCHECK=1``, plain otherwise). ``late=True`` as in
    :func:`make_lock`."""
    return _make(name, threading.RLock, late)


def held(name: str) -> bool:
    """True when the calling thread holds a checked lock named ``name``."""
    st = getattr(_tls, "stack", None)
    return bool(st) and any(h.lock.name == name for h in st)


def serialize_under(obj, name: str) -> None:
    """Declare that ``obj``'s mutating entry points are serialized by the
    checked lock ``name`` (the runtime calls this on its algorithm)."""
    try:
        obj._lockcheck_serialized_by = name
    except AttributeError:  # slots/frozen implementations: contract unchecked
        pass


def assert_serialized(obj) -> None:
    """Assert the serializing lock declared on ``obj`` is held. No-op unless
    ``HIVED_LOCKCHECK=1`` AND a runtime registered one via
    :func:`serialize_under` (standalone algorithm tests pass through)."""
    if not enabled():
        return
    name: Optional[str] = getattr(obj, "_lockcheck_serialized_by", None)
    if name is None or held(name):
        return
    raise LockOrderError(
        f"{type(obj).__name__} mutating entry point called without the "
        f"serializing lock {name!r} — the algorithm layer is single-threaded "
        f"by contract (doc/design/concurrency.md)"
    )
