"""The ``HIVED_*`` environment-flag registry — single source of truth.

Every environment flag the package (or its test/bench harnesses) reads is
declared here as a :class:`Flag` row: name, default, one-line doc, and the
module that owns the read. Two hivedlint rules key off this table
(``tools/hivedlint/shardlint.py``):

- **ENV001** — any ``HIVED_*`` token appearing in package code or
  docstrings must be a registered flag (or a registered-family prefix such
  as ``HIVED_FAULT_``). An unregistered read — or a docstring advertising a
  flag that does not exist — fails lint instead of rotting silently.
- **ENV002** — every registered flag must actually be read somewhere in
  the tree (package, tests, tools, or the repo-root bench/driver scripts).
  A flag whose last reader was deleted fails lint until the row is dropped.

The registry also renders ``doc/design/flags.md``
(:func:`render_markdown`); a guard test pins the file to the render, so
the human catalogue cannot drift from the machine-checked table::

    python -m hivedscheduler_tpu.common.envflags --write   # regenerate

Flags follow the package's conventions: tri-state gates read ``""`` as
auto, ``"0"`` as force-off, ``"1"`` as force-on; boolean opt-ins treat
exactly ``"1"`` as enabled.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str
    default: str       # effective value when unset (rendered verbatim)
    doc: str           # one line; shows in doc/design/flags.md
    module: str        # repo-relative owning module (the canonical reader)


def _f(name: str, default: str, doc: str, module: str) -> Flag:
    return Flag(name, default, doc, module)


REGISTRY: Dict[str, Flag] = {f.name: f for f in [
    # -- model / parallel layer -------------------------------------------
    _f("HIVED_OVERLAP", "auto",
       "Collective-matmul tensor parallelism gate: `0` forces the GSPMD "
       "reference path (the differential-parity contract); unset/`1` = on "
       "whenever `overlap_applicable` holds.",
       "hivedscheduler_tpu/models/transformer.py"),
    _f("HIVED_PAGED_KV", "1",
       "`0` forces the dense ragged KV cache — the differential reference "
       "for the paged block-pool path.",
       "hivedscheduler_tpu/models/serving.py"),
    # -- scheduler core ---------------------------------------------------
    _f("HIVED_NATIVE", "auto",
       "C++ placement fast path: `0` forces pure Python, `1` requires the "
       "native library (build failure raises instead of degrading).",
       "hivedscheduler_tpu/native/__init__.py"),
    _f("HIVED_NATIVE_SANITIZE", "0",
       "`1` builds the native library with ASan/UBSan into separate "
       "`*.asan.so` caches (see doc/design/concurrency.md).",
       "hivedscheduler_tpu/native/__init__.py"),
    _f("HIVED_INCR", "1",
       "`0` forces the rebuild-per-call cluster-view reference path "
       "instead of the incremental dirty-tracked views.",
       "hivedscheduler_tpu/algorithm/topology_aware.py"),
    _f("HIVED_DIRECT", "1",
       "`0` disables the direct single-chain packing shortcut (escape "
       "hatch; the differential tests pin the two paths equal).",
       "hivedscheduler_tpu/algorithm/topology_aware.py"),
    _f("HIVED_RELAX_CACHE", "1",
       "`0` disables the multi-chain-relax infeasibility cache (waiting "
       "gangs then re-probe every cycle).",
       "hivedscheduler_tpu/algorithm/hived.py"),
    # -- defragmentation / backfill (doc/design/defrag.md) ----------------
    _f("HIVED_DEFRAG", "1",
       "`0` is the kill switch for work-preserving defragmentation: no "
       "migration planning, no reservations, no waiter recording — "
       "decision-identical to the pre-defrag scheduler (differential "
       "guard).",
       "hivedscheduler_tpu/defrag/__init__.py"),
    _f("HIVED_BACKFILL", "1",
       "`0` disables opportunistic backfill into reserved holes "
       "(reservations only form when defrag is on, so backfill is inert "
       "under `HIVED_DEFRAG=0`).",
       "hivedscheduler_tpu/defrag/__init__.py"),
    _f("HIVED_DEFRAG_MAX_MOVES", "2",
       "Largest move-set the migration planner probes per waiter (1 = "
       "singles only).",
       "hivedscheduler_tpu/defrag/planner.py"),
    _f("HIVED_DEFRAG_MAX_PROBES", "24",
       "What-if probe budget per planning attempt — bounds planning cost "
       "regardless of cluster size.",
       "hivedscheduler_tpu/defrag/planner.py"),
    _f("HIVED_DEFRAG_RESERVE_TTL_S", "300",
       "Reservation time-to-live: a migration/waiter hold a crashed "
       "partner never releases is swept after this many seconds.",
       "hivedscheduler_tpu/runtime/scheduler.py"),
    _f("HIVED_ELASTIC", "1",
       "`0` disables elastic offers (shrink a blocked elastic waiter to "
       "its largest feasible ladder shape, grow-promote degraded gangs "
       "when capacity frees); inert for gangs without `elasticMinChips`.",
       "hivedscheduler_tpu/defrag/__init__.py"),
    _f("HIVED_EVENT_BATCH", "0",
       "`1` batches informer watch events into per-cycle coalesced deltas "
       "applied under one scheduler-lock acquisition (runtime/eventbatch"
       ".py); unset/`0` is the per-event reference path, pinned "
       "decision-identical (the kill switch for the batched fast path).",
       "hivedscheduler_tpu/runtime/eventbatch.py"),
    _f("HIVED_GC_FREEZE", "1",
       "`0` opts out of gc.freeze() after scheduler warmup (the scheduler "
       "then pays the gen-2 collection cost).",
       "hivedscheduler_tpu/runtime/utils.py"),
    # -- serving fleet tier (doc/design/fleet.md) -------------------------
    _f("HIVED_FLEET_KV_SHIP", "1",
       "Disaggregated prefill->decode KV handoff mode: unset/`1` ships "
       "the prefix-cache payload host-side (block table + block "
       "contents); `0` re-prefills on the decode replica through its own "
       "prefix cache (re-prefill-on-miss). Both modes are token-exact vs "
       "single-replica serving.",
       "hivedscheduler_tpu/fleet/router.py"),
    _f("HIVED_FLEET_AUTOSCALE_COOLDOWN_S", "30",
       "Fleet autoscaler cooldown: at most one scale action per role per "
       "this many seconds (AutoscalePolicy.cooldown_s < 0 reads it).",
       "hivedscheduler_tpu/fleet/autoscaler.py"),
    # -- sanitizers (opt-in, each wired into tier-1 by its own tests) -----
    _f("HIVED_LOCKCHECK", "0",
       "`1` swaps registry locks to CheckedLock: per-thread lock-order "
       "assertions + the algorithm single-threaded contract "
       "(doc/design/concurrency.md).",
       "hivedscheduler_tpu/common/lockcheck.py"),
    _f("HIVED_COMPILE_GUARD", "0",
       "`1` counts jit cache misses per labelled entry point "
       "(common/compileguard.py): steady-state serving/decode tests "
       "assert zero recompiles and the fused-window log2(K)+1 bound.",
       "hivedscheduler_tpu/common/compileguard.py"),
    # -- observability ----------------------------------------------------
    _f("HIVED_TRACE", "0",
       "`1` enables the span tracer at import time (ad-hoc runs; "
       "programmatic `trace.enable()` otherwise).",
       "hivedscheduler_tpu/obs/trace.py"),
    _f("HIVED_SLO_WINDOW_S", "60",
       "Default sliding window (seconds) for the SLO tracker's windowed "
       "quantiles and error-budget burn rates (obs/slo.py); `0` disables "
       "time-windowing (pure last-N ring semantics). Overridden by "
       "`serve --slo-window-s` / the fleet config `slo_window_s` key.",
       "hivedscheduler_tpu/obs/slo.py"),
    _f("HIVED_JOURNAL", "0",
       "`1` enables the gang-lifecycle flight recorder at import time "
       "(programmatic `journal.enable()` / the CLIs' `--journal-file` "
       "otherwise); backs `/v1/inspect/gangs` and the "
       "`tpu_hive_gang_wait_seconds` attribution histograms.",
       "hivedscheduler_tpu/obs/journal.py"),
    _f("HIVED_LEDGER", "auto",
       "Capacity ledger (obs/ledger.py) gate: `0` is the kill switch — "
       "the scheduler CLI skips the live ledger and `bench.py`'s trace "
       "replay falls back to the legacy hand-rolled busy/wait/overhead "
       "counters (the differential reference path, mirroring "
       "`HIVED_INCR=0`); `1` enables the live ledger at import time "
       "anywhere; unset = on in the CLI and the bench, off for library "
       "users (programmatic `ledger.enable()`).",
       "hivedscheduler_tpu/obs/ledger.py"),
    _f("HIVED_GOODPUT", "0",
       "`1` enables the workload goodput ledger (obs/goodput.py) at "
       "import time (programmatic `goodput.enable()` / the workload "
       "CLIs' `--goodput-file` otherwise); exports "
       "`tpu_hive_goodput_seconds_total{phase=}` and the `workload "
       "goodput` Perfetto phase lane.",
       "hivedscheduler_tpu/obs/goodput.py"),
    _f("HIVED_ETA_DEFAULT_RUN_S", "300",
       "Wait-ETA estimator (obs/eta.py): expected gang run time used "
       "before any completed-gang duration has been observed (the "
       "release-projection and horizon-fallback bases).",
       "hivedscheduler_tpu/obs/eta.py"),
    # -- chaos fault hooks (one-shot per process; unset = unarmed) --------
    _f("HIVED_FAULT_HANG_AT", "unarmed",
       "Wedge the workload at this step index (watchdog-ladder chaos "
       "hook; fires at most once per process).",
       "hivedscheduler_tpu/parallel/supervisor.py"),
    _f("HIVED_FAULT_NAN_AT", "unarmed",
       "Poison the loss with NaN at this step index (on-nan ladder hook).",
       "hivedscheduler_tpu/parallel/supervisor.py"),
    _f("HIVED_FAULT_SERVE_PREEMPT_AT", "unarmed",
       "Trigger the serving drain path deterministically at this engine "
       "step.",
       "hivedscheduler_tpu/parallel/supervisor.py"),
    _f("HIVED_FAULT_STEP_DELAY", "0.0",
       "Pad every workload step by this many seconds so the chaos harness "
       "can land signals at deterministic step windows.",
       "hivedscheduler_tpu/parallel/supervisor.py"),
    # -- test / bench harness (outside the package) -----------------------
    _f("HIVED_TEST_TPU", "0",
       "`1` lets the test session touch the real (single-grant) TPU "
       "backend; default pins tests to the 8-device CPU mesh.",
       "tests/conftest.py"),
    _f("HIVED_ULYSSES_TRAIN_TEST", "0",
       "`1` opts in the standalone ulysses full-train-step test (XLA:CPU "
       "collective rendezvous can trip on the 1-core dev box).",
       "tests/test_parallel.py"),
    _f("HIVED_TPU_ACQUIRE_TIMEOUT_S", "240",
       "Bounded-acquisition budget for the safe TPU backend dial "
       "(`bench_model.acquire_backend`; sweep_mfu raises it to 600).",
       "bench_model.py"),
    _f("HIVED_DRYRUN_CHILD", "0",
       "Internal recursion guard for the driver entry dry-run "
       "(`__graft_entry__.py` re-execs itself once with this set).",
       "__graft_entry__.py"),
]}


def get(name: str, default: Optional[str] = None) -> Optional[str]:
    """Registered-flag environment read. Raises ``KeyError`` for a name
    not in :data:`REGISTRY` — new flags must add a row first (that row is
    what ENV001/ENV002 and doc/design/flags.md key off)."""
    if name not in REGISTRY:
        raise KeyError(
            f"{name!r} is not a registered HIVED flag — add it to "
            f"common/envflags.py REGISTRY (ENV001)")
    return os.environ.get(name, default)


# ---------------------------------------------------------------------------
# doc/design/flags.md renderer
# ---------------------------------------------------------------------------

_HEADER = """\
# HIVED_* environment flags

<!-- GENERATED from hivedscheduler_tpu/common/envflags.py — do not edit.
     Regenerate: python -m hivedscheduler_tpu.common.envflags --write -->

Machine-checked catalogue of every environment flag the tree reads. The
registry in `common/envflags.py` is the source of truth: hivedlint's
ENV001 fails on any unregistered `HIVED_*` token in the package, ENV002
fails on a registered flag nothing reads, and a guard test pins this file
to the registry render — so this page cannot rot. See
[shard-contract.md](shard-contract.md) for the lint rule family and
[concurrency.md](concurrency.md) for the sanitizer flags' semantics.

| Flag | Default | Owner | Meaning |
|---|---|---|---|
"""


def render_markdown() -> str:
    rows = []
    for flag in sorted(REGISTRY.values(), key=lambda f: f.name):
        rows.append(
            f"| `{flag.name}` | `{flag.default}` | `{flag.module}` "
            f"| {flag.doc} |"
        )
    return _HEADER + "\n".join(rows) + "\n"


def flags_md_path(root: str) -> str:
    return os.path.join(root, "doc", "design", "flags.md")


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--write", action="store_true",
                   help="rewrite doc/design/flags.md from the registry")
    args = p.parse_args(argv)
    here = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(os.path.dirname(here))
    path = flags_md_path(root)
    text = render_markdown()
    if args.write:
        with open(path, "w") as f:
            f.write(text)
        print(f"wrote {path}")
    else:
        print(text, end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
