from hivedscheduler_tpu.common.utils import (  # noqa: F401
    from_json,
    from_yaml,
    init_logger,
    to_json,
    to_yaml,
)
