"""Common utilities: codecs, logging, signal handling.

TPU-native analogue of the reference's ``pkg/common`` (``common/utils.go:119-169``,
``common/types.go:33-95``): YAML/JSON marshal-or-raise codecs, logger init
(stderr only, mirroring the klog rationale at ``common/utils.go:124-149``), and
a stop-event wired to SIGINT/SIGTERM. The reference's ``Set`` type is the
builtin ``set``/``frozenset`` here.
"""

from __future__ import annotations

import logging
import signal
import sys
import threading
from typing import Any

import yaml

log = logging.getLogger("tpu-hive")


def init_logger(level: int = logging.INFO) -> None:
    """Log to stderr only: the container runtime collects stderr, and mixing
    stdout/stderr reorders lines (reference rationale: common/utils.go:124-149)."""
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            fmt="%(levelname).1s%(asctime)s.%(msecs)03d %(name)s %(filename)s:%(lineno)d] %(message)s",
            datefmt="%m%d %H:%M:%S",
        )
    )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)


def init_all(level: int = logging.INFO) -> None:
    """Process-wide init (reference: common.InitAll, common/utils.go:119)."""
    init_logger(level)


_DUMPER = getattr(yaml, "CSafeDumper", yaml.SafeDumper)
_LOADER = getattr(yaml, "CSafeLoader", yaml.SafeLoader)


def to_yaml(obj: Any) -> str:
    return yaml.dump(obj, Dumper=_DUMPER, default_flow_style=False, sort_keys=False)


def from_yaml(text: str) -> Any:
    """Parse YAML. JSON being a YAML subset, a JSON fast path handles the
    machine-written annotations (bind-info) ~100x faster than full YAML."""
    stripped = text.lstrip()
    if stripped[:1] in ("{", "["):
        import json

        try:
            return json.loads(text)
        except json.JSONDecodeError:
            pass
    return yaml.load(text, Loader=_LOADER)


def to_json(obj: Any) -> str:
    import json

    return json.dumps(obj, separators=(",", ":"), sort_keys=False)


def from_json(text: str) -> Any:
    import json

    return json.loads(text)


def new_stop_event() -> threading.Event:
    """Event set on SIGINT/SIGTERM (reference: NewStopChannel,
    common/utils.go:155-169). Only callable from the main thread; callers on
    other threads should construct their own Event."""
    stop = threading.Event()

    def _handler(signum: int, _frame: Any) -> None:
        log.info("Received signal %s, stopping", signum)
        stop.set()

    signal.signal(signal.SIGINT, _handler)
    signal.signal(signal.SIGTERM, _handler)
    return stop
