"""Opt-in runtime recompile sanitizer (``HIVED_COMPILE_GUARD=1``).

Silent jit recompiles are the model layer's deadlock-equivalent: nothing
is wrong with the numbers, but a shape or static-arg leak makes every
serving tick pay a compile, and nobody notices until a soak is 100x slow.
This module wraps the package's jit entry points the way
``common.lockcheck`` wraps locks:

- :func:`jit` is a drop-in for ``jax.jit`` taking an extra
  ``guard_label``. Disabled (the default), it returns the raw jitted
  function — zero overhead, identical object semantics. With
  ``HIVED_COMPILE_GUARD=1`` at wrap time it returns a counting proxy that
  attributes every jit-cache miss to its label.
- :func:`counts`/:func:`total` read the per-label miss counters;
  :func:`reset` zeroes them (e.g. after warmup).
- :func:`budget` is the assertion chokepoint: a ``with`` block that
  raises :class:`RecompileError` when more than ``max_new`` compiles land
  inside it. Steady-state serving/decode tests run their warmed loop
  under ``budget(0)`` — every soak doubles as a recompile detector — and
  the fused-window tests pin the ``log2(K)+1`` variant bound that
  ``ServingEngine._fused_window``'s pow2 bucketing promises
  (doc/design/shard-contract.md).

Cache misses are read from the jitted function's ``_cache_size()`` probe
when the JAX version exposes it; otherwise the proxy falls back to
counting distinct abstract call signatures (shape/dtype of array leaves +
values of hashable scalars), which is exactly the jit cache key modulo
sharding. Flag registry row: ``common/envflags.py``; catalogued in
``doc/design/flags.md``.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Dict, Optional

from hivedscheduler_tpu.common import lockcheck

# leaf lock: counter updates only — nothing is ever acquired under it
_lock = lockcheck.make_lock("compileguard_lock", late=True)
_counts: Dict[str, int] = {}


class RecompileError(RuntimeError):
    """A compile-budget violation: more jit cache misses inside a
    :func:`budget` block than the caller declared legal."""


def enabled() -> bool:
    return os.environ.get("HIVED_COMPILE_GUARD", "") == "1"


def jit(fun, *, guard_label: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` with compile accounting. ``guard_label`` names the
    entry point in :func:`counts` (defaults to the function's __name__);
    all other kwargs pass through to ``jax.jit``. Like the lockcheck
    factories, the env var is honored at WRAP time: construct engines
    after setting ``HIVED_COMPILE_GUARD=1`` (the tests' monkeypatch
    pattern) — flipping it later does not retrofit existing wrappers."""
    import jax

    jitted = jax.jit(fun, **jit_kwargs)
    if not enabled():
        return jitted
    label = guard_label or getattr(fun, "__name__", "<jit>")
    return _CountingJit(jitted, label)


class _CountingJit:
    """Counting proxy over a jitted callable: attributes every cache miss
    to its label, delegates everything else to the wrapped function."""

    def __init__(self, inner, label: str):
        self._inner = inner
        self._label = label
        self._sigs: set = set()  # fallback signature cache

    def _misses_around(self, args, kwargs):
        probe = getattr(self._inner, "_cache_size", None)
        if probe is not None:
            before = probe()
            return lambda: probe() - before
        # pre-vma JAX without the probe: distinct abstract signatures.
        # Computed BEFORE the call — donated buffers are dead after it.
        sig = _signature(args, kwargs)
        fresh = sig not in self._sigs

        def delta():
            if fresh:
                self._sigs.add(sig)
                return 1
            return 0

        return delta

    def __call__(self, *args, **kwargs):
        delta = self._misses_around(args, kwargs)
        out = self._inner(*args, **kwargs)
        new = delta()
        if new:
            with _lock:
                _counts[self._label] = _counts.get(self._label, 0) + new
        return out

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<compileguard {self._label!r} wrapping {self._inner!r}>"


def _signature(args, kwargs):
    import jax

    def leaf_key(leaf):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            return ("arr", tuple(shape), str(getattr(leaf, "dtype", "?")))
        try:
            hash(leaf)
        except TypeError:
            return ("obj", type(leaf).__name__)
        return ("val", leaf)

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef),) + tuple(leaf_key(x) for x in leaves)


def counts() -> Dict[str, int]:
    """Per-label jit cache-miss counters since the last :func:`reset`."""
    with _lock:
        return dict(_counts)


def total() -> int:
    with _lock:
        return sum(_counts.values())


def reset() -> None:
    """Zero the counters (the warmup/steady-state boundary)."""
    with _lock:
        _counts.clear()


@contextlib.contextmanager
def budget(max_new: int = 0, label: Optional[str] = None):
    """Assert at most ``max_new`` compiles (for ``label``, or in total)
    happen inside the block. No-op unless the guard is enabled — safe to
    leave in production test paths."""
    if not enabled():
        yield
        return
    before = counts()
    yield
    after = counts()
    if label is not None:
        new = after.get(label, 0) - before.get(label, 0)
        what = f"entry point {label!r}"
    else:
        new = sum(after.values()) - sum(before.values())
        what = "all guarded entry points"
    if new > max_new:
        grew = {
            k: after.get(k, 0) - before.get(k, 0)
            for k in after
            if after.get(k, 0) > before.get(k, 0)
        }
        raise RecompileError(
            f"compile budget exceeded: {new} jit cache miss(es) for {what} "
            f"inside a budget({max_new}) block — per-label growth {grew}; "
            f"a steady-state loop must not recompile "
            f"(doc/design/shard-contract.md)"
        )
