"""Opportunistic backfill: who may ride in a hole the packer is holding?

When a big gang waits, the scheduler holds (reserves) the slice it is
consolidating toward.  Holding chips idle is exactly the utilization gap
this subsystem exists to close — so short or preemptible work is admitted
*into* the hold, bounded so backfill never delays the reservation it rides
in:

- an **opportunistic** job (priority < 0) is always admissible: when the
  waiter's slice becomes placeable, HiveD's existing preemption evicts
  opportunistic work — the reservation holder reclaims its hole by
  contract, so the ride is free;
- a **guaranteed** job is admissible only when its estimated duration is
  known and it finishes before the waiter's estimated start
  (``now + duration * slack <= eta``).  No duration, no ride: an
  unbounded guaranteed job parked in the hole would push the waiter's
  start indefinitely (it cannot be preempted by an equal-priority waiter).

The policy is a pure decision function — deterministic, no clock reads, no
state — so the trace sim and the runtime share it verbatim.  Pods declare
their expected run time via the ``durationSeconds`` scheduling-spec key
(api/types.py); the runtime's honest ETA for a hold is its reservation TTL
deadline — the hold cannot outlive it, so a gang that finishes first
provably never delays the waiter (``HivedScheduler._duration_fits_all_holds``).
Gangs without a declared duration keep the conservative behavior: only
preemptible work rides.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from hivedscheduler_tpu.api.constants import OPPORTUNISTIC_PRIORITY


@dataclasses.dataclass(frozen=True)
class BackfillDecision:
    admit: bool
    reason: str  # preemptible | fits-window | would-delay-waiter |
    #              unknown-duration | no-reservation


class BackfillPolicy:
    """``slack`` > 1 pads the duration estimate (finish-time optimism is the
    classic backfill failure mode)."""

    def __init__(self, slack: float = 1.25):
        if slack < 1.0:
            raise ValueError("backfill slack must be >= 1.0")
        self.slack = slack

    def admits(
        self,
        priority: int,
        now: float,
        duration: Optional[float] = None,
        reservation_eta: Optional[float] = None,
    ) -> BackfillDecision:
        """May a candidate gang use chips held for a waiting reservation?

        ``reservation_eta`` is the waiter's estimated start time on the
        caller's clock (None = unknown — only preemptible work rides then).
        """
        if priority <= OPPORTUNISTIC_PRIORITY:
            return BackfillDecision(True, "preemptible")
        if duration is None:
            return BackfillDecision(False, "unknown-duration")
        if reservation_eta is None:
            return BackfillDecision(False, "would-delay-waiter")
        if now + duration * self.slack <= reservation_eta:
            return BackfillDecision(True, "fits-window")
        return BackfillDecision(False, "would-delay-waiter")
