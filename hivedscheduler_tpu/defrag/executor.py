"""Reservation + migration state — the types behind the runtime executor.

The executor itself lives in ``runtime/scheduler.py`` (the one file allowed
to call algorithm mutators, hivedlint CON003); this module holds the
passive records it drives, so the state machine is importable by the chaos
invariant checker and the inspect path without touching the runtime.

Reservation lifecycle (all transitions under the scheduler lock)::

    plan accepted ──> waiter Reservation(kind="waiter") on the slice the
                      probe found, + one Reservation(kind="migration") per
                      move's re-placement target
    mover rebound ──> its migration reservation released
    waiter bound  ──> waiter reservation released
    TTL expiry    ──> reservation swept (a crashed/partner-less migration
                      must never fence cells forever); in-memory only, so a
                      scheduler crash drops every reservation — recovery
                      rebuilds allocations from bound pods and nothing else
                      (the no-orphaned-reservation invariant).

Migration lifecycle::

    Evicting  — movers' pods deleted (SIGTERM -> the supervisor's
                checkpoint-and-exit-0 contract, parallel/supervisor.py);
                waiting for the informer to release their cells
    Rebinding — all movers released; replacement pods are being created,
                scheduled at the reserved target, and bound (gang-atomic
                per move: any member failure rolls the whole move back)
    Done      — every move rebound; the waiter's next filter cycle lands in
                the freed slice
    Failed    — a move could not re-place (state drifted since the probe);
                the move's replacements were rolled back, reservations
                released.  The job's work survives in its checkpoint; the
                job framework resubmits it like any preempted gang.
    Aborted   — the job died mid-migration (e.g. kill -9 after checkpoint,
                before re-bind) or an operator cancelled; reservations
                released, nothing half-bound remains.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

from hivedscheduler_tpu.defrag.probe import GangSpec
from hivedscheduler_tpu.k8s.types import Pod

MIGRATION_EVICTING = "Evicting"
MIGRATION_REBINDING = "Rebinding"
MIGRATION_DONE = "Done"
MIGRATION_FAILED = "Failed"
MIGRATION_ABORTED = "Aborted"

# states with live reservations / pending work
ACTIVE_MIGRATION_STATES = (MIGRATION_EVICTING, MIGRATION_REBINDING)


@dataclasses.dataclass
class Reservation:
    """A node-granular hold: while live, no gang other than ``holder`` may
    be offered these nodes (unless backfill admits it)."""

    holder: str            # affinity-group name the hold serves
    nodes: Set[str]
    kind: str              # "waiter" | "migration"
    created_at: float      # time.monotonic() domain
    deadline: float        # created_at + TTL; swept when passed
    migration_id: Optional[str] = None

    def expired(self, now: float) -> bool:
        return now >= self.deadline

    def to_dict(self) -> dict:
        return {
            "holder": self.holder,
            "kind": self.kind,
            "nodes": sorted(self.nodes),
            "migrationId": self.migration_id,
        }


@dataclasses.dataclass
class Move:
    """One gang's relocation inside a migration."""

    group: str
    spec: GangSpec
    evicted_pods: List[Pod]          # the old bound incarnation
    target_nodes: List[str]
    rebound_pods: List[Pod] = dataclasses.field(default_factory=list)
    state: str = MIGRATION_EVICTING

    def to_dict(self) -> dict:
        return {
            "group": self.group,
            "chips": self.spec.chips,
            "state": self.state,
            "targetNodes": list(self.target_nodes),
            "evicted": [p.name for p in self.evicted_pods],
            "rebound": [p.name for p in self.rebound_pods],
        }


@dataclasses.dataclass
class Migration:
    id: str
    waiter: str
    waiter_chips: int
    moves: List[Move]
    state: str = MIGRATION_EVICTING
    generation: int = 1   # replacement-pod uid epoch (uids never recycle)
    # observability riders (runtime executor writes them; in-memory like
    # everything else here — a crash drops them with the migration):
    created_at: float = 0.0   # time.monotonic() at plan time
    phase_t: float = 0.0      # start of the current phase (evict/rebind)
    journal_event: int = 0    # the plan's journal event id (causal anchor)

    @property
    def active(self) -> bool:
        return self.state in ACTIVE_MIGRATION_STATES

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "waiter": self.waiter,
            "waiterChips": self.waiter_chips,
            "state": self.state,
            "moves": [m.to_dict() for m in self.moves],
        }
