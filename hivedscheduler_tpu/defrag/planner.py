"""The migration planner: which running gangs should move, and is it worth
it?

Given a waiting gang blocked by *packing* (free chips exist but no
contiguous slice fits — the wait-attribution signal the trace replay
computes), the planner searches for a minimal set of running gangs whose
relocation frees the slice:

- **candidates**: fully-allocated gangs at priority <= the waiter's (a
  migration is work-preserving, but disturbing higher-priority work for a
  lower waiter inverts the priority contract) and no bigger than
  ``max_move_ratio`` x the waiter (moving a whale to seat a minnow never
  scores);
- **search**: singles in ascending chip order first, then pairs, each
  validated by one transactional what-if probe (remove movers -> place
  waiter -> re-place movers; see :mod:`~hivedscheduler_tpu.defrag.probe`),
  bounded by a probe budget — planning cost is bounded regardless of
  cluster size;
- **scoring**: benefit = waiter chips x the chip-time it would otherwise
  burn waiting (``waiter_wait_estimate``); cost = chips moved x the
  checkpoint/restore downtime (``move_downtime``).  When both estimates are
  known a plan must clear ``score = benefit / cost >= 1`` or it is rejected
  as not-worth-it; with unknown estimates the chip-ratio bound alone
  governs (the runtime rarely knows durations; the trace sim always does).

The planner itself never mutates state: every mutation happens inside the
probe's transaction and is rolled back.  Executing a plan is the runtime
executor's job (``runtime/scheduler.py``) or the trace sim's.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from hivedscheduler_tpu.common import envflags
from hivedscheduler_tpu.defrag.probe import GangSpec, WhatIfProbe
from hivedscheduler_tpu.k8s.types import Pod


def _int_or(raw, default: int) -> int:
    try:
        return int(raw or default)
    except ValueError:
        return default


def vc_quota_chips(algo, vc: str) -> int:
    """A VC's guaranteed quota in leaf chips, counted from its static
    virtual cell trees (read-only; ``vc_free_cell_num`` is the *dynamic*
    free count, decremented as preassigned cells bind). This is the
    binding constraint for a guaranteed waiter: migration conserves it, so
    a waiter needing more than the quota's free remainder can never be
    helped by moving gangs."""
    vcs = algo.vc_schedulers.get(vc)
    if vcs is None:
        return 0
    total = 0
    for ccl in vcs.non_pinned_full_cell_list.values():
        total += len(ccl[1])
    for ccl in vcs.pinned_cells.values():
        total += len(ccl[1])
    return total


@dataclasses.dataclass
class RunningGroup:
    """A fully-allocated gang as the planner sees it."""

    name: str
    spec: GangSpec
    bound_pods: List[Pod]

    @property
    def chips(self) -> int:
        return self.spec.chips

    @property
    def priority(self) -> int:
        return self.spec.priority


@dataclasses.dataclass
class PlannedMove:
    group: RunningGroup
    # {node -> leaf indices} the probe found for the re-placement; advisory
    # (the executor re-derives deterministically under the same state, and
    # re-validates under drifted state)
    target_placement: Dict[str, List[int]]

    @property
    def target_nodes(self) -> List[str]:
        return sorted(self.target_placement)


@dataclasses.dataclass
class MigrationPlan:
    waiter: GangSpec
    moves: List[PlannedMove]
    waiter_placement: Dict[str, List[int]]
    score: Optional[float]  # None when wait/downtime estimates are unknown
    probes_spent: int

    @property
    def waiter_nodes(self) -> List[str]:
        return sorted(self.waiter_placement)

    @property
    def moved_chips(self) -> int:
        return sum(m.group.chips for m in self.moves)

    def to_dict(self) -> dict:
        return {
            "waiter": self.waiter.name,
            "waiterChips": self.waiter.chips,
            "waiterNodes": self.waiter_nodes,
            "moves": [
                {
                    "group": m.group.name,
                    "chips": m.group.chips,
                    "targetNodes": m.target_nodes,
                }
                for m in self.moves
            ],
            "movedChips": self.moved_chips,
            "score": self.score,
            "probesSpent": self.probes_spent,
        }


@dataclasses.dataclass
class PlanRejected:
    """Why no plan was produced — feeds the planner-rejection metrics and
    decision traces."""

    reason: str  # capacity | no-candidates | infeasible | not-worth-it
    detail: str = ""
    probes_spent: int = 0


class MigrationPlanner:
    """Bounded greedy search over single- and pair-moves.

    ``max_moves``/``max_probes`` default from the ``HIVED_DEFRAG_MAX_MOVES``
    / ``HIVED_DEFRAG_MAX_PROBES`` env flags (registered in
    common/envflags.py) so operators can tune planning effort without code.
    """

    def __init__(
        self,
        max_moves: Optional[int] = None,
        max_probes: Optional[int] = None,
        max_move_ratio: float = 4.0,
        move_downtime: Optional[float] = None,
    ):
        self.max_moves = (
            max_moves if max_moves is not None
            else _int_or(envflags.get("HIVED_DEFRAG_MAX_MOVES", "2"), 2)
        )
        self.max_probes = (
            max_probes if max_probes is not None
            else _int_or(envflags.get("HIVED_DEFRAG_MAX_PROBES", "24"), 24)
        )
        self.max_move_ratio = max_move_ratio
        self.move_downtime = move_downtime

    # -- scoring -----------------------------------------------------------

    def _score(
        self,
        waiter: GangSpec,
        moved_chips: int,
        waiter_wait_estimate: Optional[float],
    ) -> Optional[float]:
        if waiter_wait_estimate is None or not self.move_downtime:
            return None
        cost = moved_chips * self.move_downtime
        if cost <= 0:
            return float("inf")
        return (waiter.chips * waiter_wait_estimate) / cost

    def _movable_for(self, waiter: GangSpec, g: RunningGroup) -> bool:
        """Which running gangs can possibly unblock this waiter?

        - never a higher-priority gang (work-preserving or not, disturbing
          higher-priority work for a lower waiter inverts the contract);
        - never a whale (``max_move_ratio``);
        - a *guaranteed* waiter is blocked inside its own VC quota: VC
          safety guarantees a physical home for every free virtual cell,
          and opportunistic blockers are lazily preempted — so only
          same-VC *guaranteed* gangs fragment what it needs;
        - an *opportunistic* waiter contends on raw physical cells, so any
          (necessarily opportunistic, by the priority rule) gang may move.
        """
        if g.priority > waiter.priority:
            return False
        if g.chips > self.max_move_ratio * max(1, waiter.chips):
            return False
        if waiter.priority >= 0:
            return g.priority >= 0 and g.spec.vc == waiter.vc
        return True

    # -- the search --------------------------------------------------------

    def plan_promotion(self, probe: WhatIfProbe, group: RunningGroup,
                       to_priority: int):
        """Can ``group`` (typically running opportunistically beyond quota)
        be re-placed at ``to_priority`` right now?  One swap probe: remove
        the running incarnation, place the same gang at the new priority,
        roll back.  Returns a single-move :class:`MigrationPlan` (the move
        relocates the group itself) or :class:`PlanRejected`.

        This is how beyond-quota backfill is made work-preserving: the
        gang rides other VCs' idle guarantees preemptibly, and when its
        own quota frees the executor promotes it — checkpoint, re-place
        under the guarantee, resume — instead of leaving it exposed to
        preemption forever.
        """
        promoted = dataclasses.replace(group.spec, priority=to_priority)
        result = probe.run_swap_probe(group.bound_pods, promoted)
        if not result.feasible:
            return PlanRejected("infeasible", result.reason, probes_spent=1)
        return MigrationPlan(
            waiter=promoted,
            moves=[PlannedMove(
                group=group,
                target_placement=result.placements[promoted.name],
            )],
            waiter_placement=result.placements[promoted.name],
            score=None,
            probes_spent=1,
        )

    def plan_migration(
        self,
        probe: WhatIfProbe,
        waiter: GangSpec,
        running: Sequence[RunningGroup],
        free_chips: Optional[int] = None,
        waiter_wait_estimate: Optional[float] = None,
    ):
        """Returns a :class:`MigrationPlan` or a :class:`PlanRejected`.

        ``free_chips`` (when the caller knows it) short-circuits the
        capacity case: migration conserves free chips, so a waiter needing
        more than exist can never be helped by moving anything.
        """
        if free_chips is not None and free_chips < waiter.chips:
            return PlanRejected("capacity",
                                f"{free_chips} free < {waiter.chips} needed")
        candidates = sorted(
            (g for g in running if self._movable_for(waiter, g)),
            key=lambda g: (g.chips, g.name),
        )
        if not candidates:
            return PlanRejected("no-candidates",
                                "no running gang is movable for this waiter")

        probes = 0
        combos: List[Tuple[RunningGroup, ...]] = [
            (g,) for g in candidates
        ]
        if self.max_moves >= 2:
            combos += list(itertools.combinations(candidates, 2))
        for combo in combos:
            if probes >= self.max_probes:
                return PlanRejected(
                    "infeasible",
                    f"probe budget exhausted ({self.max_probes})",
                    probes_spent=probes,
                )
            probes += 1
            result = probe.run_probe(
                waiter,
                [(g.name, g.spec, g.bound_pods) for g in combo],
            )
            if not result.feasible:
                continue
            moved_chips = sum(g.chips for g in combo)
            score = self._score(waiter, moved_chips, waiter_wait_estimate)
            if score is not None and score < 1.0:
                return PlanRejected(
                    "not-worth-it",
                    f"score {score:.3f} < 1 (moved {moved_chips} chips)",
                    probes_spent=probes,
                )
            return MigrationPlan(
                waiter=waiter,
                moves=[
                    PlannedMove(
                        group=g,
                        target_placement=result.placements[g.name],
                    )
                    for g in combo
                ],
                waiter_placement=result.placements[waiter.name],
                score=score,
                probes_spent=probes,
            )
        return PlanRejected(
            "infeasible",
            f"no move set within bounds frees a slice "
            f"(tried {probes} probe(s))",
            probes_spent=probes,
        )
