"""Work-preserving defragmentation + opportunistic backfill.

HiveD's headline fault-tolerance capability is *work-preserving
reconfiguration* (PAPER.md §0.5); until this subsystem it was exercised only
reactively (node-failure recovery, crash-restart replay).  The trace data
says scheduling *quality* is the cost center now: ~28% of chip-time waits,
~89% of that wait attributed to packing (``trace_wait_packing_share``,
BENCH_r05) — free chips exist, but not in the contiguous shape a waiting
gang needs.  This package turns reconfiguration into a scheduling policy:

- :mod:`~hivedscheduler_tpu.defrag.probe` — transactional what-if placement
  probes against the live cluster view (mutate under the scheduler lock,
  roll back bit-exact via the recovery path's ``add_allocated_pod``);
- :mod:`~hivedscheduler_tpu.defrag.planner` — the migration planner: find a
  minimal set of running gangs whose relocation frees a contiguous slice
  for a packing-blocked waiter, scored by chips-moved x checkpoint cost vs
  the chip-time the waiter burns;
- :mod:`~hivedscheduler_tpu.defrag.backfill` — the opportunistic backfill
  policy: admit short/preemptible jobs into holes held for a big waiting
  gang, bounded so backfill never delays the reservation it rides in;
- :mod:`~hivedscheduler_tpu.defrag.executor` — the reservation + migration
  state machine *types*; the executor itself lives in
  ``runtime/scheduler.py`` (the algorithm-mutation chokepoint, CON003) and
  drives each move through the existing preemption contract: evict
  (SIGTERM -> checkpoint-and-exit-0) -> re-place at the tighter target ->
  resume.

Kill switches: ``HIVED_DEFRAG=0`` / ``HIVED_BACKFILL=0`` reproduce the
pre-defrag scheduler exactly (differential guards pin this, same pattern
as ``HIVED_PAGED_KV=0`` / ``HIVED_INCR=0``).  Contract + state machine:
doc/design/defrag.md.
"""

from __future__ import annotations

from hivedscheduler_tpu.common import envflags

# Probe/planner entry points that mutate algorithm state (through the
# transactional probe). hivedlint's CON002 call-graph fixpoint treats a call
# to any of these attributes inside HivedScheduler as an algorithm-mutating
# site that must hold the scheduler lock; DFG001 confines the raw mutator
# calls themselves to defrag/probe.py. Keep in sync with probe.WhatIfProbe
# and planner.MigrationPlanner method names.
LOCKED_ENTRY_ATTRS = frozenset({
    "run_probe", "plan_migration", "run_fit_probe", "run_swap_probe",
    "plan_promotion",
})


def defrag_enabled() -> bool:
    """``HIVED_DEFRAG=0`` is the kill switch: no planning, no reservations,
    no waiter recording — today's scheduler, bit for bit."""
    return envflags.get("HIVED_DEFRAG", "1") != "0"


def backfill_enabled() -> bool:
    """``HIVED_BACKFILL=0`` disables backfill admission into reserved holes
    (reservations still form when defrag is on)."""
    return envflags.get("HIVED_BACKFILL", "1") != "0"


def elastic_enabled() -> bool:
    """``HIVED_ELASTIC=0`` disables elastic offers: no shrink offers for
    blocked elastic waiters, no grow-promotion of degraded gangs. Inert
    for gangs that declare no ``elasticMinChips`` either way — a cluster
    with no elastic jobs behaves identically under both settings."""
    return envflags.get("HIVED_ELASTIC", "1") != "0"


from hivedscheduler_tpu.defrag.backfill import BackfillDecision, BackfillPolicy  # noqa: E402
from hivedscheduler_tpu.defrag.executor import (  # noqa: E402
    MIGRATION_ABORTED,
    MIGRATION_DONE,
    MIGRATION_EVICTING,
    MIGRATION_FAILED,
    MIGRATION_REBINDING,
    Migration,
    Move,
    Reservation,
)
from hivedscheduler_tpu.defrag.planner import (  # noqa: E402
    MigrationPlan,
    MigrationPlanner,
    PlannedMove,
    PlanRejected,
    RunningGroup,
)
from hivedscheduler_tpu.defrag.probe import (  # noqa: E402
    GangSpec,
    ProbeResult,
    WhatIfProbe,
    shrink_ladder,
)

__all__ = [
    "BackfillDecision",
    "BackfillPolicy",
    "GangSpec",
    "LOCKED_ENTRY_ATTRS",
    "Migration",
    "MigrationPlan",
    "MigrationPlanner",
    "Move",
    "PlannedMove",
    "PlanRejected",
    "ProbeResult",
    "Reservation",
    "RunningGroup",
    "WhatIfProbe",
    "backfill_enabled",
    "defrag_enabled",
    "elastic_enabled",
    "shrink_ladder",
    "MIGRATION_ABORTED",
    "MIGRATION_DONE",
    "MIGRATION_EVICTING",
    "MIGRATION_FAILED",
    "MIGRATION_REBINDING",
]
