"""Transactional what-if placement probes on the live cluster view.

A migration plan is only as good as its feasibility proof: "if gangs A and B
moved, would the waiter fit — and would A and B still fit somewhere else?"
Rather than cloning the (100k-object) cell trees per question, the probe
runs the question against the *live* ``HivedAlgorithm`` and rolls every
mutation back before returning:

- removing a running gang = ``delete_allocated_pod`` per member;
- restoring it = ``add_allocated_pod`` with the member's original bind
  annotations — the crash-recovery path, which rebuilds the exact
  chip-granular placement (the ``check_placement_preserved`` contract);
- placing a hypothetical gang = ``schedule`` + ``add_allocated_pod`` per
  member, removed again on exit.

The rollback is therefore bit-exact by the same mechanism recovery is, and
every chaos soak double-checks it: the from-scratch invariant suite
(``chaos.invariants.check_all``) runs after schedules that interleave with
probes, so a probe that failed to restore state cannot hide.

Concurrency: the probe mutates algorithm state, so the caller must hold the
scheduler lock (in-runtime) or otherwise serialize (the single-threaded
bench/sim). ``runtime/scheduler.py`` is the only runtime caller; hivedlint
DFG001 pins this module as the sole home of raw mutator calls inside the
defrag package, and CON002 requires the runtime entry points that reach
``run_probe`` to hold the scheduler lock.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from hivedscheduler_tpu.api import constants as api_constants
from hivedscheduler_tpu.common.utils import to_json
from hivedscheduler_tpu.k8s.types import Container, Pod
from hivedscheduler_tpu.obs import journal as obs_journal
from hivedscheduler_tpu.runtime import utils as internal_utils
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE

# probe pods live in their own namespace so decision traces and logs
# attribute them unambiguously; they never reach any ApiServer
PROBE_NAMESPACE = "defrag-probe"


@dataclasses.dataclass(frozen=True)
class GangSpec:
    """The scheduling identity of a gang, sufficient to synthesize member
    pods for a what-if placement (mirrors the pod scheduling-spec
    annotation)."""

    name: str
    vc: str
    priority: int
    leaf_cell_type: str
    # (pod_number, leaf_cell_number) per member entry
    members: Tuple[Tuple[int, int], ...]
    multi_chain_relax_policy: str = "fewest"
    # elastic ladder floor in total gang chips (0 = not elastic)
    elastic_min_chips: int = 0
    # set on a DEGRADED incarnation: the original full-shape member list
    # (written into the pods' annotations so it survives crashes)
    elastic_full_members: Optional[Tuple[Tuple[int, int], ...]] = None
    # expected run time (0 = unknown; duration-aware backfill admission)
    duration_seconds: float = 0.0

    @property
    def chips(self) -> int:
        return sum(n * c for n, c in self.members)

    @property
    def pod_count(self) -> int:
        return sum(n for n, _ in self.members)

    @property
    def elastic(self) -> bool:
        return self.elastic_min_chips > 0

    @property
    def degraded(self) -> bool:
        """Is this a shrunk incarnation of a bigger declared shape?"""
        return (self.elastic_full_members is not None
                and self.elastic_full_members != self.members)

    def full_spec(self) -> "GangSpec":
        """The declared full shape (self when not degraded)."""
        if not self.degraded:
            return self
        return dataclasses.replace(
            self, members=self.elastic_full_members, elastic_full_members=None
        )

    @classmethod
    def from_pod(cls, pod: Pod) -> "GangSpec":
        """Derive the gang's spec from any member pod's annotation."""
        s = internal_utils.extract_pod_scheduling_spec(pod)
        return cls(
            name=s.affinity_group.name,
            vc=s.virtual_cluster,
            priority=s.priority,
            leaf_cell_type=s.leaf_cell_type,
            members=tuple(
                (m.pod_number, m.leaf_cell_number)
                for m in s.affinity_group.members
            ),
            multi_chain_relax_policy=s.multi_chain_relax_policy,
            elastic_min_chips=s.elastic_min_chips,
            elastic_full_members=(
                tuple((m.pod_number, m.leaf_cell_number)
                      for m in s.elastic_full_members)
                if s.elastic_full_members is not None else None
            ),
            duration_seconds=s.duration_seconds,
        )

    def to_annotation(self, leaf_cell_number: int) -> str:
        """The scheduling-spec annotation for a member pod holding
        ``leaf_cell_number`` chips (gangs may mix member shapes, so the
        top-level cell count is per-pod)."""
        d = {
            "virtualCluster": self.vc,
            "priority": self.priority,
            "leafCellType": self.leaf_cell_type,
            "leafCellNumber": leaf_cell_number,
            "multiChainRelaxPolicy": self.multi_chain_relax_policy,
            "affinityGroup": {
                "name": self.name,
                "members": [
                    {"podNumber": n, "leafCellNumber": c}
                    for n, c in self.members
                ],
            },
        }
        if self.duration_seconds:
            d[api_constants.SPEC_KEY_DURATION_SECONDS] = self.duration_seconds
        if self.elastic_min_chips:
            d[api_constants.SPEC_KEY_ELASTIC_MIN_CHIPS] = self.elastic_min_chips
        if self.elastic_full_members is not None:
            d[api_constants.SPEC_KEY_ELASTIC_FULL_MEMBERS] = [
                {"podNumber": n, "leafCellNumber": c}
                for n, c in self.elastic_full_members
            ]
        return to_json(d)


def shrink_ladder(spec: GangSpec) -> List[GangSpec]:
    """The declared shape ladder of an elastic gang, largest shrink first.

    Each rung halves every member's per-pod chip count (the natural TPU
    ladder: the workload's per-pod slice halves, ``train --elastic``
    derives a correspondingly smaller mesh); rungs stop when any member's
    count turns odd or the total would fall below ``elastic_min_chips``.
    Every rung records the ORIGINAL full shape in ``elastic_full_members``
    so a degraded incarnation carries its way back up. Empty for
    non-elastic specs."""
    if not spec.elastic:
        return []
    full = spec.elastic_full_members or spec.members
    out: List[GangSpec] = []
    members = spec.members
    while all(c % 2 == 0 for _, c in members):
        members = tuple((n, c // 2) for n, c in members)
        if sum(n * c for n, c in members) < spec.elastic_min_chips:
            break
        out.append(dataclasses.replace(
            spec, members=members, elastic_full_members=full))
    return out


def gang_pods(spec: GangSpec, uid_prefix: str = "") -> List[Pod]:
    """Synthesize one unbound pod per gang member; ``uid_prefix``
    disambiguates replacement incarnations (migration re-binds must carry
    fresh uids — a deleted pod's uid never comes back)."""
    pods: List[Pod] = []
    i = 0
    for pod_number, chips in spec.members:
        annotation = spec.to_annotation(chips)
        for _ in range(pod_number):
            name = f"{uid_prefix}{spec.name.replace('/', '.')}-{i}"
            pods.append(Pod(
                name=name,
                uid=name,
                namespace=PROBE_NAMESPACE if not uid_prefix else "default",
                annotations={
                    api_constants.ANNOTATION_POD_SCHEDULING_SPEC: annotation
                },
                containers=[Container(resource_limits={
                    api_constants.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1
                })],
            ))
            i += 1
    return pods


# a member list per gang, in the order schedule() must see them: every pod
# of one member entry shares leaf_cell_number
@dataclasses.dataclass
class ProbeResult:
    feasible: bool
    reason: str = ""
    # group name -> {node -> sorted leaf-cell indices} of the hypothetical
    # placements found (waiter + each mover's re-placement target)
    placements: Dict[str, Dict[str, List[int]]] = dataclasses.field(
        default_factory=dict
    )
    probes_spent: int = 1

    @property
    def waiter_nodes(self) -> List[str]:
        """Nodes of the first (waiter) placement, if any."""
        if not self.placements:
            return []
        first = next(iter(self.placements.values()))
        return sorted(first)

    def nodes_of(self, group: str) -> List[str]:
        return sorted(self.placements.get(group, {}))


class WhatIfProbe:
    """What-if transactions on one algorithm instance.

    All public methods must be called under the caller's serialization (the
    scheduler lock in the runtime). Every transaction restores the
    algorithm's state exactly before returning.
    """

    def __init__(self, algo, nodes: Sequence[str]):
        self.algo = algo
        self.nodes = list(nodes)

    # -- internals ---------------------------------------------------------

    def _place_gang(self, spec: GangSpec) -> Optional[List[Pod]]:
        """Schedule + allocate every member of a hypothetical gang; returns
        the bound pods, or None (with partial members rolled back). Only a
        pure bind counts: a preemption nomination means the slice is not
        actually free."""
        bound: List[Pod] = []
        for pod in gang_pods(spec):
            result = self.algo.schedule(pod, self.nodes, FILTERING_PHASE)
            if result.pod_bind_info is None:
                for bp in reversed(bound):
                    self.algo.delete_allocated_pod(bp)
                return None
            bp = internal_utils.new_binding_pod(pod, result.pod_bind_info)
            self.algo.add_allocated_pod(bp)
            bound.append(bp)
        return bound

    def _remove_gang(self, bound_pods: Sequence[Pod]) -> None:
        for bp in bound_pods:
            self.algo.delete_allocated_pod(bp)

    def _restore_gang(self, bound_pods: Sequence[Pod]) -> None:
        # the recovery path: bind annotations rebuild the exact placement
        for bp in bound_pods:
            self.algo.add_allocated_pod(bp)

    def _placement_of(self, group: str) -> Dict[str, List[int]]:
        g = self.algo.get_affinity_group(group)
        return {
            n: sorted(ix) for n, ix in g.status.physical_placement.items()
        }

    # -- the transaction ---------------------------------------------------

    def run_probe(
        self,
        waiter: GangSpec,
        movers: Sequence[Tuple[str, GangSpec, Sequence[Pod]]],
    ) -> ProbeResult:
        """One full what-if: remove every mover, place the waiter, re-place
        every mover elsewhere (the waiter claims its slice first, exactly
        the order the executor replays), then roll everything back.

        ``movers`` is a sequence of (group name, spec, bound member pods).
        Feasible only if the waiter AND every mover's re-placement all bind.
        """
        removed: List[Sequence[Pod]] = []
        placed: List[Sequence[Pod]] = []
        placements: Dict[str, Dict[str, List[int]]] = {}
        # the probe's schedule/delete churn is rolled back bit-exactly —
        # it never really happened, so the gang-lifecycle journal must
        # not see it (thread-local: serving threads keep journaling)
        with obs_journal.suppress():
            try:
                for _name, _spec, bound_pods in movers:
                    self._remove_gang(bound_pods)
                    removed.append(bound_pods)
                waiter_pods = self._place_gang(waiter)
                if waiter_pods is None:
                    return ProbeResult(False, reason="waiter-unplaceable")
                placed.append(waiter_pods)
                placements[waiter.name] = self._placement_of(waiter.name)
                for name, spec, _bound in movers:
                    mover_pods = self._place_gang(spec)
                    if mover_pods is None:
                        placements.clear()
                        return ProbeResult(
                            False, reason=f"mover-unplaceable:{name}"
                        )
                    placed.append(mover_pods)
                    placements[name] = self._placement_of(name)
                return ProbeResult(True, placements=placements)
            finally:
                # rollback is unconditional: the probe never leaks state
                for pods in reversed(placed):
                    self._remove_gang(pods)
                for pods in reversed(removed):
                    self._restore_gang(pods)

    def run_fit_probe(self, spec: GangSpec) -> ProbeResult:
        """Would this gang bind RIGHT NOW, as-is?  Place it, record the
        placement, roll back.  The elastic shrink offer walks the shape
        ladder with one fit probe per rung (doc/design/elastic.md)."""
        with obs_journal.suppress():
            placed = self._place_gang(spec)
            if placed is None:
                return ProbeResult(False, reason="fit-unplaceable")
            try:
                return ProbeResult(True, placements={
                    spec.name: self._placement_of(spec.name)
                })
            finally:
                self._remove_gang(placed)

    def run_swap_probe(
        self, bound_pods: Sequence[Pod], new_spec: GangSpec
    ) -> ProbeResult:
        """Can this running gang be re-placed as ``new_spec`` (same group
        name, typically a different priority — the promotion question)?
        Remove the running incarnation, try the new one, roll back."""
        with obs_journal.suppress():
            placed: Optional[List[Pod]] = None
            self._remove_gang(bound_pods)
            try:
                placed = self._place_gang(new_spec)
                if placed is None:
                    return ProbeResult(False, reason="swap-unplaceable")
                return ProbeResult(True, placements={
                    new_spec.name: self._placement_of(new_spec.name)
                })
            finally:
                if placed is not None:
                    self._remove_gang(placed)
                self._restore_gang(bound_pods)
