"""Workload benchmark: single-chip training MFU + decode tokens/sec.

The scheduler's job is to hand out contiguous TPU slices; this benchmark
proves the *workload* runtime those slices feed (models/ + parallel/ + ops/)
is actually fast on the hardware. It runs the real production paths — the
``parallel.train.make_sharded_train_step`` factory on a 1-device mesh with
the Pallas flash-attention kernel, and ``models.decode.generate`` for the
KV-cached serving loop — on a chip-filling flagship configuration, and
reports:

- ``train_mfu_pct``: model FLOPs utilization of the train step vs the chip's
  peak bf16 FLOP/s (analytic 6*N*tokens matmul FLOPs + 3x causal attention
  FLOPs — the standard MFU accounting, no remat/recompute credit);
- ``train_tokens_per_sec``;
- ``decode_tokens_per_sec`` plus its HBM-bandwidth roofline fraction
  (autoregressive decode is bandwidth-bound: every generated token streams
  the full parameter bytes from HBM).

Prints ONE JSON line, same contract as bench.py. On non-TPU backends it runs
a tiny smoke configuration so CI keeps the code path alive; MFU is only
meaningful on the TPU.

The reference scheduler (microsoft/hivedscheduler) ships no workload
runtime, so there is no reference number to beat; ``vs_baseline`` reports
MFU against the 40% bar commonly quoted for well-tuned dense-transformer
training (scaling-book north star), honestly labelled in the note.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import statistics
import sys
import threading
import time


def acquire_backend(timeout_s: float, grace_s: float = 120.0):
    """First TPU touch with a bounded wait.

    Under the axon environment the first backend access enters an
    indefinite sleep-retry loop when the single-grant TPU tunnel is held by
    another process (observed: >16 min asleep in ``make_c_api_client``). A
    watchdog thread turns that into a loud, fast failure: if device
    enumeration hasn't completed within ``timeout_s`` we print a
    self-explanatory JSON line and ``os._exit(3)``. Exiting during the
    *claim* retry loop is safe — the process holds no grant yet.

    The dangerous case is the grant arriving right at the deadline:
    exiting between grant acquisition and clean client shutdown wedges the
    tunnel until the relay's grant timeout (~25 min, observed live). The
    grant is held from *inside* client construction — before any
    Python-visible signal exists — so no check can close the window
    completely. The watchdog therefore (a) follows the deadline with a
    generous ``grace_s`` second-chance window polled in short slices, (b)
    never exits once a backend object exists (construction finished,
    enumeration imminent), and (c) accepts the residual risk that a grant
    arriving silently in the last grace slice is killed mid-construction —
    the alternative (no bound at all) starves the driver forever, which is
    the round-3 failure this exists to fix."""
    done = threading.Event()

    def backend_exists() -> bool:
        xb = sys.modules.get("jax._src.xla_bridge")
        return bool(getattr(xb, "_backends", None))

    def watchdog():
        if done.wait(timeout_s):
            return
        # Deadline passed while still waiting. Poll the grace window in
        # slices: if the grant just arrived, client construction (a few
        # seconds) completes well within it and either `done` fires or a
        # backend object appears.
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if done.wait(min(5.0, max(0.1, deadline - time.monotonic()))):
                return
            if backend_exists():
                return  # grant held, enumeration imminent: never exit now
        if done.is_set() or backend_exists():
            return
        print(json.dumps({
            "metric": "train_step_mfu_1chip",
            "value": None,
            "unit": "%",
            "vs_baseline": None,
            "error": (
                f"tpu_acquire_timeout: backend not granted within "
                f"{timeout_s:.0f}s (+{grace_s:.0f}s grace) — single-grant "
                "TPU tunnel busy (another process holds it); no TPU op "
                "was started"
            ),
        }))
        sys.stdout.flush()
        os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        import jax

        devices = jax.devices()
    except RuntimeError as e:
        # the axon client retries internally for ~25 min and then fails
        # terminally (observed: "UNAVAILABLE: TPU backend setup/compile
        # error" when the pool itself is down). Surface that as a
        # self-explanatory artifact line instead of a bare traceback.
        done.set()
        print(json.dumps({
            "metric": "train_step_mfu_1chip",
            "value": None,
            "unit": "%",
            "vs_baseline": None,
            "error": f"tpu_backend_unavailable: {str(e)[:300]}",
        }))
        sys.stdout.flush()
        raise SystemExit(4)
    finally:
        # disarm even on a fast failure: a still-armed watchdog would
        # os._exit the whole host process minutes later with a bogus
        # 'tunnel busy' note
        done.set()
    return jax, devices

# peak per-chip specs by device_kind substring: (bf16 FLOP/s, HBM bytes/s)
_CHIP_PEAKS = [
    ("v5 lite", (197e12, 819e9)),   # v5e
    ("v5e", (197e12, 819e9)),
    ("v5p", (459e12, 2765e9)),
    ("v6 lite", (918e12, 1640e9)),  # Trillium
    ("v6e", (918e12, 1640e9)),
    ("v4", (275e12, 1228e9)),
]


def chip_peaks(device) -> tuple[float, float] | tuple[None, None]:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peaks in _CHIP_PEAKS:
        if sub in kind:
            return peaks
    return None, None


def train_flops_per_step(cfg, batch: int, seq: int) -> float:
    """Analytic model FLOPs for one train step (fwd+bwd = 3x fwd).

    Matmul fwd FLOPs = 2 * matmul_params * tokens; attention fwd adds
    4 * T^2 * H * Dh per sequence per layer (QK^T and PV), halved for the
    causal mask. Embedding lookup is a gather (0 FLOPs); the tied/untied
    lm_head matmul is counted via its parameters.
    """
    d, dh = cfg.d_model, cfg.head_dim
    h, h_kv = cfg.n_heads, cfg.kv_heads
    attn_params = d * h * dh * 2 + d * h_kv * dh * 2  # wq,wo + wk,wv
    mlp_params = 3 * d * cfg.d_ff
    layer_params = attn_params + mlp_params
    lm_head = d * cfg.vocab_size
    matmul_params = cfg.n_layers * layer_params + lm_head
    tokens = batch * seq
    fwd = 2.0 * matmul_params * tokens
    fwd += cfg.n_layers * batch * (4.0 * seq * seq * h * dh) * 0.5  # causal
    return 3.0 * fwd


def bench_train(cfg, batch: int, seq: int, iters: int, mesh,
                grad_accum: int = 1, ce_chunk: int = 0):
    import jax
    import jax.numpy as jnp

    from hivedscheduler_tpu.parallel.train import make_sharded_train_step

    step, init_fn, token_sharding = make_sharded_train_step(
        cfg, mesh, grad_accum=grad_accum, ce_chunk=ce_chunk
    )
    params, opt_state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size, jnp.int32
        ),
        token_sharding,
    )
    params, opt_state, loss = step(params, opt_state, tokens)  # compile
    float(loss)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, tokens)
        # sync with a host transfer of the step's last-produced value:
        # block_until_ready is a no-op under the axon TPU plugin, and the
        # loss buffer alone can complete before the donated param update
        float(loss)
        times.append(time.perf_counter() - t0)
    return statistics.median(times), float(loss)


def serving_params(cfg):
    """The one shared weight tree for the decode and serving benches: bf16
    up front (both are HBM-bandwidth-bound; f32 master weights would stream
    twice the bytes per step)."""
    import jax

    from hivedscheduler_tpu.models import transformer as tm

    return tm.cast_params(tm.init_params(cfg, jax.random.PRNGKey(0)), cfg.dtype)


def bench_decode(cfg, params, batch: int, prompt_len: int, new_tokens: int,
                 iters: int, decode_steps: int = 1):
    import jax
    import jax.numpy as jnp

    from hivedscheduler_tpu.models import decode as dec
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size, jnp.int32
    )
    import numpy as np

    run = jax.jit(
        lambda p, t: dec.generate(p, t, cfg, new_tokens,
                                  max_len=prompt_len + new_tokens,
                                  decode_steps=decode_steps)
    )
    np.asarray(run(params, prompt))  # compile + host sync
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = run(params, prompt)
        np.asarray(out)  # block_until_ready is a no-op under axon
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def bench_serving(cfg, params, n_requests: int, max_batch: int, budget: int,
                  decode_steps: int = 1):
    """Continuous-batching engine under a staggered synthetic load:
    returns (tokens/sec, occupancy over the measured load only). Shares
    ``params`` with bench_decode so the static-batch number and the churn
    number describe the same weights. ``decode_steps`` > 1 runs the
    engine's fused multi-step decode windows."""
    import jax

    from hivedscheduler_tpu.models import serving

    eng = serving.ServingEngine(params, cfg, max_batch=max_batch,
                                max_len=128 + budget,
                                decode_steps=decode_steps)
    rng = jax.random.PRNGKey(2)
    prompts = []
    for i in range(n_requests):
        rng, k1, k2 = jax.random.split(rng, 3)
        plen = int(jax.random.randint(k1, (), 4, 65))
        prompts.append([int(t) for t in jax.random.randint(
            k2, (plen,), 0, cfg.vocab_size)])
    # warm every prefill bucket (4..64) and the decode step off the clock
    warms = [eng.submit([1] * n, 2) for n in (4, 5, 9, 17, 33)]
    eng.run_until_drained()
    assert all(w.done for w in warms)
    warm_steps, warm_slot_steps = eng.steps, eng.slot_steps
    t0 = time.perf_counter()
    reqs = []
    step = 0
    pending = list(prompts)
    while pending or any(not r.done for r in reqs):
        if pending and step % 2 == 0:  # staggered arrivals
            reqs.append(eng.submit(pending.pop(0), budget))
        eng.step()
        step += 1
    dt = time.perf_counter() - t0
    total = sum(len(r.tokens_out) for r in reqs)
    # occupancy over the measured load only (the warm-up traffic would
    # otherwise blend into the paired metric)
    steps = eng.steps - warm_steps
    occ = (eng.slot_steps - warm_slot_steps) / (steps * max_batch) if steps else 0.0
    return total / dt, occ


def bench_serving_kv_int8(cfg, params, batch: int, ctx: int, new_tokens: int):
    """Long-context decode with the float vs int8 KV cache: all rows hold
    ``ctx`` tokens of context, then decode ``new_tokens`` each — exactly
    the regime where decode streams the whole KV arena per step and int8
    halves those bytes. Returns (float tok/s, int8 tok/s). The warm-up
    decode + prompt prefills run off the clock for both engines."""
    import jax

    from hivedscheduler_tpu.models import serving

    rng = jax.random.PRNGKey(9)
    prompts = []
    for _ in range(batch):
        rng, k = jax.random.split(rng)
        prompts.append([int(t) for t in jax.random.randint(
            k, (ctx,), 0, cfg.vocab_size)])

    def run(kv_dtype):
        eng = serving.ServingEngine(params, cfg, max_batch=batch,
                                    max_len=ctx + new_tokens + 1,
                                    kv_dtype=kv_dtype)
        reqs = [eng.submit(list(p), new_tokens) for p in prompts]
        eng.step()  # admit + prefill every row + first decode (compiles)
        eng.step()  # steady-state decode warm
        done_before = sum(len(r.tokens_out) for r in reqs)
        t0 = time.perf_counter()
        eng.run_until_drained()
        dt = time.perf_counter() - t0
        emitted = sum(len(r.tokens_out) for r in reqs) - done_before
        return max(1, emitted) / dt

    return run(None), run("int8")


def bench_serving_prefix(cfg, params, n_requests: int, system_len: int,
                         tail_max: int, budget: int, max_len: int):
    """Prefix-cache speedup under a shared-system-prompt load: every request
    is system + short tail, served with the cache off then on (ample LRU).
    Returns (throughput speedup, TTFT speedup) = cached/plain tokens-per-sec
    and plain/cached median time-to-first-token — prefix caching's primary
    win is TTFT (the system prompt's prefill vanishes from the user-visible
    latency)."""
    import jax

    from hivedscheduler_tpu.models import serving

    rng = jax.random.PRNGKey(5)
    rng, ks = jax.random.split(rng)
    system = [int(t) for t in jax.random.randint(
        ks, (system_len,), 0, cfg.vocab_size)]
    prompts = []
    for _ in range(n_requests):
        rng, k1, k2 = jax.random.split(rng, 3)
        tlen = int(jax.random.randint(k1, (), 1, tail_max + 1))
        prompts.append(system + [int(t) for t in jax.random.randint(
            k2, (tlen,), 0, cfg.vocab_size)])

    # warm set: same distribution, tail lengths chosen to cover every tail
    # prefill bucket, submitted twice so the cached engine compiles its
    # extract/restore/tail-prefill programs off the clock (hits occur on
    # the second pass); the measured set then runs steady-state
    warm_tails = [t for t in (1, 2, 3, 5, 9, 16) if t <= tail_max]
    warm_prompts = []
    for i, tlen in enumerate(warm_tails):
        rng2 = jax.random.fold_in(jax.random.PRNGKey(6), i)
        warm_prompts.append(system + [int(t) for t in jax.random.randint(
            rng2, (tlen,), 0, cfg.vocab_size)])

    def run_once(cache_size: int) -> float:
        eng = serving.ServingEngine(params, cfg, max_batch=4,
                                    max_len=max_len,
                                    prefix_cache_size=cache_size)
        for _pass in range(2):
            ws = [eng.submit(list(p), 2) for p in warm_prompts]
            eng.run_until_drained()
            assert all(w.done for w in ws)
        t0 = time.perf_counter()
        reqs = [eng.submit(list(p), budget) for p in prompts]
        eng.run_until_drained()
        dt = time.perf_counter() - t0
        ttfts = sorted(r.ttft_s for r in reqs)
        return (sum(len(r.tokens_out) for r in reqs) / dt,
                ttfts[len(ttfts) // 2])

    plain_tps, plain_ttft = run_once(0)
    cached_tps, cached_ttft = run_once(64)
    return cached_tps / plain_tps, plain_ttft / max(cached_ttft, 1e-9)


def bench_serving_paged_ab(cfg, params, n_requests: int, max_len: int,
                           page_size: int, dense_slots: int,
                           paged_slots: int, budget: int,
                           ttft_ceiling_mult: float = 4.0):
    """Serving-throughput headline stage (ROADMAP item 1's success metric):
    one mixed-length request trace replayed against the dense slab engine
    and the paged engine at EQUAL KV HBM — the paged pool holds exactly the
    dense engine's token capacity (``dense_slots * ceil(max_len/page) ``
    blocks), but spreads it over ``paged_slots`` admission slots, so
    concurrency tracks the traffic's actual token footprint instead of the
    worst-case length. Reports, per engine: requests/sec, p99 TTFT,
    goodput (requests finishing within the TTFT ceiling, per second) and
    the max number of simultaneously-resident streams. The ceiling is
    calibrated as ``ttft_ceiling_mult`` x an unloaded single-request TTFT
    on the dense engine — the "users notice" line the A/B is judged at.

    The trace mixes 60% short / 25% medium (~max_len/4) / 15% long
    (~max_len/2) prompts with staggered arrivals — the long-tail regime
    where dense slabs strand HBM on worst-case reservations.
    """
    import jax

    from hivedscheduler_tpu.models import serving

    rng = jax.random.PRNGKey(11)
    prompts = []
    for i in range(n_requests):
        rng, k1, k2 = jax.random.split(rng, 3)
        u = i % 20
        if u < 12:
            plen = int(jax.random.randint(k1, (), 4, 13))
        elif u < 17:
            plen = max(4, max_len // 4 + int(jax.random.randint(k1, (), -4, 5)))
        else:
            plen = max(8, max_len // 2 + int(jax.random.randint(k1, (), -4, 5)))
        plen = min(plen, max_len - budget - 1)
        prompts.append([int(t) for t in jax.random.randint(
            k2, (plen,), 0, cfg.vocab_size)])

    def build(paged: bool):
        if paged:
            nbs = -(-max_len // page_size)
            return serving.ServingEngine(
                params, cfg, max_batch=paged_slots, max_len=max_len,
                page_size=page_size, num_blocks=dense_slots * nbs + 1,
            )
        return serving.ServingEngine(params, cfg, max_batch=dense_slots,
                                     max_len=max_len)

    def run(paged: bool):
        # warm every prefill bucket + the decode step off the clock ON THE
        # MEASURED ENGINE (each engine owns its jitted closures, so a fresh
        # engine would recompile inside the measured window); the warm
        # requests are drained, so the measured load starts from idle slots
        eng = build(paged)
        warm_lens = sorted({len(p) for p in prompts})
        warms = [eng.submit([1] * n, 2) for n in warm_lens]
        eng.run_until_drained()
        assert all(w.done for w in warms)
        # unloaded single-request TTFT on the warmed engine — the dense
        # engine's value calibrates the goodput ceiling
        cal = eng.submit(list(prompts[0]), 2)
        eng.run_until_drained()
        reqs = []
        pending = list(prompts)
        max_streams = 0
        t0 = time.perf_counter()
        while pending or any(not r.done for r in reqs):
            # burst arrivals (3 per engine step): offered load outruns
            # service so concurrency is decided by the ENGINE's admission
            # capacity — slots for dense, block footprint for paged —
            # rather than by the arrival rate
            for _ in range(min(3, len(pending))):
                reqs.append(eng.submit(list(pending.pop(0)), budget))
            eng.step()
            max_streams = max(max_streams,
                              sum(s is not None for s in eng.slots))
        dt = time.perf_counter() - t0
        ttfts = sorted(r.ttft_s for r in reqs if r.ttft_s is not None)
        p99 = ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))]
        return dt, reqs, p99, max_streams, cal.ttft_s

    out = {"page_size": page_size, "dense_slots": dense_slots,
           "paged_slots": paged_slots,
           "num_blocks": dense_slots * (-(-max_len // page_size)) + 1,
           "n_requests": n_requests}
    ceiling = None
    for label, paged in (("dense", False), ("paged", True)):
        dt, reqs, p99, max_streams, cal_ttft = run(paged)
        if ceiling is None:  # dense runs first and calibrates the ceiling
            ceiling = ttft_ceiling_mult * max(cal_ttft, 1e-6)
            out["ttft_ceiling_s"] = round(ceiling, 4)
        good = sum(1 for r in reqs
                   if r.ttft_s is not None and r.ttft_s <= ceiling)
        out[f"{label}_rps"] = round(len(reqs) / dt, 3)
        out[f"{label}_goodput_rps"] = round(good / dt, 3)
        out[f"{label}_p99_ttft_s"] = round(p99, 4)
        out[f"{label}_max_streams"] = max_streams
    out["streams_ratio"] = round(
        out["paged_max_streams"] / max(1, out["dense_max_streams"]), 3)
    out["goodput_ratio"] = round(
        out["paged_goodput_rps"] / max(1e-9, out["dense_goodput_rps"]), 3)
    return out


def bench_serving_fleet(cfg, params, peak_replicas: int, duration_s: float,
                        budget: int, max_len: int, page_size: int,
                        max_batch: int = 2, ttft_ceiling_mult: float = 10.0,
                        peak_util: float = 0.5, curve_power: int = 6,
                        rate_scale: float = 1.0):
    """Fleet stage (ROADMAP item 2's headline): goodput at a p99 TTFT
    ceiling under a DIURNAL open-loop load curve, autoscaled vs a static
    fleet at EQUAL PEAK chip budget.

    Arrivals are scheduled in WALL TIME over one diurnal cycle —
    ``rate(t) = peak_rate * sin(pi*t/T)**curve_power`` (trough -> peak ->
    trough; the power sharpens the peak so the trough really dominates
    the cycle, as diurnal traffic does) — and land open-loop: they do
    not wait for service. ``peak_rate`` is CALIBRATED as ``peak_util`` x
    the measured single-replica service rate x ``peak_replicas``, so the
    static fleet is provisioned for the peak by construction. The static
    arm keeps all ``peak_replicas`` live the whole run; the autoscaled
    arm starts at 1 replica and lets ``FleetAutoscaler`` track the curve
    (scale-down drain-based, as always).

    HONEST REPORTING (the PR 6 precedent): at equal peak budget the
    static arm's ABSOLUTE goodput is an upper bound by construction —
    fewer replicas never serve faster. The autoscaler's win is the chips
    it hands back in the trough, so the headline bar is goodput per
    REPLICA-SECOND (replica-seconds accrue only while a replica is LIVE
    in the router); absolute goodput, p99 TTFT and replica-seconds are
    all reported for both arms. Both arms reuse the SAME pre-warmed
    engines (warm-standby model: the A/B isolates routing/scaling
    policy, not JIT compiles) with the prefix cache OFF, and the
    autoscaled arm runs a fresh prompt set of the same length
    distribution, so neither arm inherits the other's cache state.
    """
    import math as _math

    import jax

    from hivedscheduler_tpu.fleet import (
        AutoscalePolicy,
        FleetAutoscaler,
        FleetRouter,
    )
    from hivedscheduler_tpu.models import serving
    from hivedscheduler_tpu.obs import journal as obs_journal
    from hivedscheduler_tpu.obs import slo as obs_slo

    def build_engine():
        # a small prefix cache rides along so the exactness check below
        # can REUSE these engines for the disaggregated KV handoff (the
        # A/B itself is cache-neutral: each arm runs a fresh random
        # prompt set, so accidental prefix hits are equally rare in both)
        return serving.ServingEngine(
            params, cfg, max_batch=max_batch, max_len=max_len,
            page_size=page_size, prefix_cache_size=8)

    rng = jax.random.PRNGKey(21)

    def make_prompts(n, key):
        out = []
        for i in range(n):
            k1, k2 = jax.random.split(jax.random.fold_in(key, i))
            plen = int(jax.random.randint(k1, (), 4, 9))
            out.append([int(t) for t in jax.random.randint(
                k2, (plen,), 0, cfg.vocab_size)])
        return out

    warm_lens = (4, 8)

    def warm(eng):
        ws = [eng.submit([1] * n, 2) for n in warm_lens]
        eng.run_until_drained()
        assert all(w.done for w in ws)
        return eng

    engines = [warm(build_engine()) for _ in range(peak_replicas)]

    # calibration on one warmed replica: unloaded TTFT (-> the goodput
    # ceiling) and the saturated service rate (-> the peak arrival rate)
    rng, kc = jax.random.split(rng)
    cal_prompts = make_prompts(2 * max_batch, kc)
    cal = engines[0].submit(list(cal_prompts[0]), 2)
    engines[0].run_until_drained()
    ceiling = ttft_ceiling_mult * max(cal.ttft_s, 1e-6)
    t0 = time.perf_counter()
    cal_reqs = [engines[0].submit(list(p), budget) for p in cal_prompts]
    engines[0].run_until_drained()
    rps1 = len(cal_reqs) / (time.perf_counter() - t0)
    # ``rate_scale``: how much the peak arrival rate scales past ONE
    # replica's measured service rate. On real parallel hardware pass
    # ``peak_replicas`` (capacity scales with replicas); on the CPU A/B
    # every replica shares one core, so capacity does NOT scale — keep it
    # near 1 or the calibration saturates BOTH arms and the goodput
    # comparison degenerates (honest-calibration note in the artifact)
    peak_rate = peak_util * rps1 * rate_scale

    # arrival schedule: one diurnal cycle, integrated on a fine grid
    times = []
    acc, t, grid = 0.0, 0.0, 1e-3
    while t < duration_s:
        acc += peak_rate * _math.sin(_math.pi * t / duration_s) \
            ** curve_power * grid
        while acc >= 1.0:
            times.append(t)
            acc -= 1.0
        t += grid

    class _StandbyBackend:
        """Warm-standby pool: grow pops a pre-warmed engine, shrink
        re-arms the drained engine (ServingEngine.end_drain) and returns
        it — a scale-down/regrow cycle never pays a JIT rebuild, which
        is how a real fleet keeps standbys. Only past the pool does a
        grow build fresh, inside the measured wall."""

        def __init__(self, pool):
            self.pool = pool
            self.seq = 0

        def grow(self, role):
            self.seq += 1
            eng = self.pool.pop(0) if self.pool else warm(build_engine())
            return f"auto{self.seq}", eng, ""

        def shrink(self, role, replica):
            replica.engine.end_drain()
            self.pool.append(replica.engine)

    def run(autoscale: bool, prompts):
        # flight recording + the declared SLO: the p99 TTFT objective IS
        # the calibrated goodput ceiling, window 0 = the whole arm, so
        # the burn/attribution tables diagnose the same number the
        # goodput headline counts. The journal ring is cleared per arm
        # (each router restarts fleet fids at 0).
        obs_journal.JOURNAL.clear()
        router = FleetRouter(slo=obs_slo.SLOTracker(
            objectives=(obs_slo.SLObjective("ttft", 0.99, ceiling),),
            window_s=0.0, cap=4096))
        auto = None
        pool = list(engines)
        if autoscale:
            router.add_replica("r0", pool.pop(0))
            auto = FleetAutoscaler(
                router, _StandbyBackend(pool),
                AutoscalePolicy(
                    min_replicas=1, max_replicas=peak_replicas,
                    occ_high=0.6, occ_low=0.1, queue_high=0.75,
                    ttft_ceiling_s=0.5 * ceiling,
                    up_stable_ticks=1, down_stable_ticks=5,
                    cooldown_s=duration_s / 30.0))
        else:
            for i, eng in enumerate(pool):
                router.add_replica(f"r{i}", eng)
        reqs = []
        nxt = 0
        last_tick = -1.0
        tick_dt = duration_s / 100.0
        start = time.perf_counter()
        if auto is not None:
            auto.tick()  # anchor the replica-seconds integral
        while True:
            now = time.perf_counter() - start
            while nxt < len(times) and times[nxt] <= now:
                reqs.append(router.submit(list(prompts[nxt]), budget))
                nxt += 1
            if auto is not None and now - last_tick >= tick_dt:
                auto.tick()
                last_tick = now
            work = router.step()
            if nxt >= len(times) and not work:
                break
            if not work:
                time.sleep(min(0.005, max(0.0, times[nxt] - now)
                               if nxt < len(times) else 0.005))
        if auto is not None:
            auto.tick()
        dt = time.perf_counter() - start
        replica_secs = (auto.replica_seconds if auto is not None
                        else peak_replicas * dt)
        ups = downs = 0
        if auto is not None:
            ups = sum(1 for a in auto.actions if a["phase"] == "added")
            downs = sum(1 for a in auto.actions
                        if a["phase"] == "removed")
        # per-leg TTFT attribution, asserted to sum to the measured TTFT
        # for EVERY completed request (the acceptance criterion) — a new
        # uninstrumented segment on the request path fails the bench, it
        # does not ship as a plausible-looking table
        flights = obs_journal.JOURNAL.flights()
        leg_totals = {}
        checked = 0
        for freq in reqs:
            if freq.ttft_s is None:
                continue
            rec = flights[f"fleet/{freq.fid}"]
            gap = rec["ttft_gap"]
            assert gap is not None and abs(gap) <= 1e-6, (
                f"fleet/{freq.fid}: TTFT legs sum differs from measured "
                f"ttft_s by {gap}s")
            checked += 1
            ft = rec["first_token_t"]
            for leg, s, e in rec["legs"]:
                if e <= ft + 1e-9:
                    leg_totals[leg] = leg_totals.get(leg, 0.0) + (e - s)
        snap = router.slo.snapshot()
        obj = snap["objectives"][0]
        slo_block = {
            "attribution_checked_requests": checked,
            "ttft_leg_seconds": {k: round(v, 4)
                                 for k, v in sorted(leg_totals.items())},
            "p99_ttft_s": obj["value"],
            "compliance": obj["compliance"],
            "burn_rate": obj["burnRate"],
            "violations": obj["windowViolations"],
            "violation_attribution": obj["attribution"],
        }
        return reqs, dt, replica_secs, ups, downs, slo_block

    out = {"peak_replicas": peak_replicas,
           "duration_s": round(duration_s, 2),
           "n_requests": len(times), "budget": budget,
           "calibrated_peak_rps": round(peak_rate, 3),
           "single_replica_rps": round(rps1, 3),
           "ttft_ceiling_s": round(ceiling, 4)}
    # disabled-path overhead gate (the journal's PR 1 contract applied to
    # the flight recorder): with the journal off, a leg emission is ONE
    # attribute check — pinned here in the artifact, asserted generous
    # enough for the 1-core box
    if not obs_journal.JOURNAL.enabled:
        n_probe = 100_000
        t0 = time.perf_counter()
        for _ in range(n_probe):
            obs_journal.note_leg("bench/probe", "route")
        disabled_ns = (time.perf_counter() - t0) / n_probe * 1e9
        assert disabled_ns < 20_000, (
            f"disabled note_leg costs {disabled_ns:.0f} ns — the one-"
            f"attribute-check contract broke")
        out["slo_disabled_leg_overhead_ns"] = round(disabled_ns, 1)
    rng, ka, kb = jax.random.split(rng, 3)
    prev_journal = obs_journal.JOURNAL.enabled
    obs_journal.enable()
    try:
        for label, autoscale, key in (("static", False, ka),
                                      ("autoscaled", True, kb)):
            reqs, dt, rs, ups, downs, slo_block = run(
                autoscale, make_prompts(len(times), key))
            ttfts = sorted(r.ttft_s for r in reqs if r.ttft_s is not None)
            p99 = ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))] \
                if ttfts else None
            good = sum(1 for r in reqs
                       if r.ttft_s is not None and r.ttft_s <= ceiling)
            out[f"{label}_goodput_rps"] = round(good / dt, 3)
            out[f"{label}_good_requests"] = good
            out[f"{label}_p99_ttft_s"] = round(p99, 4) if p99 else None
            out[f"{label}_replica_secs"] = round(rs, 3)
            out[f"{label}_goodput_per_replica_sec"] = round(
                good / max(rs, 1e-9), 4)
            out[f"{label}_slo"] = slo_block
            if autoscale:
                out["autoscaled_scale_ups"] = ups
                out["autoscaled_scale_downs"] = downs
    finally:
        if not prev_journal:
            obs_journal.disable()
        obs_journal.JOURNAL.clear()
    out["legs_sum_to_ttft"] = True  # asserted per arm inside run()
    out["goodput_ratio"] = round(
        out["autoscaled_goodput_rps"]
        / max(1e-9, out["static_goodput_rps"]), 3)
    out["replica_secs_ratio"] = round(
        out["autoscaled_replica_secs"]
        / max(1e-9, out["static_replica_secs"]), 3)
    out["efficiency_ratio"] = round(
        out["autoscaled_goodput_per_replica_sec"]
        / max(1e-9, out["static_goodput_per_replica_sec"]), 3)
    return out, engines


def bench_fleet_disagg_exact(cfg, params, max_len: int, page_size: int,
                             engines=None):
    """Disaggregated serving must be token-exact vs single-replica for
    BOTH KV-handoff modes — asserted in the bench artifact itself, not
    just the tests (the acceptance criterion names it). ``engines``:
    reuse the fleet stage's warmed engines (greedy exactness is a pure
    function of (params, prompt) — carried cache state cannot change the
    streams, and skipping the rebuilds keeps the smoke bench inside the
    tier-1 wall-time budget; the fresh-pool import path is pinned by
    tests/test_fleet_router.py)."""
    from hivedscheduler_tpu.fleet import FleetRouter
    from hivedscheduler_tpu.models import serving
    from hivedscheduler_tpu.obs import journal as obs_journal

    if engines is None or len(engines) < 2:
        engines = [
            serving.ServingEngine(params, cfg, max_batch=2,
                                  max_len=max_len, page_size=page_size,
                                  prefix_cache_size=8)
            for _ in range(2)
        ]
    p0, d0 = engines[0], engines[1]
    for eng in (p0, d0):
        if eng.draining:  # a replica mid-teardown at the A/B's end
            eng.end_drain()
    # one prompt past a block boundary (its leading block ships) and one
    # inside the first block (the miss/re-prefill path)
    prompts = [list(range(1, page_size + 5)),
               list(range(5, page_size + 2))]
    refs = []
    for p in prompts:
        req = d0.submit(list(p), 4)
        d0.run_until_drained()
        refs.append(list(req.tokens_out))
    out = {}
    prev_journal = obs_journal.JOURNAL.enabled
    obs_journal.enable()
    try:
        for mode, ship in (("ship", True), ("reprefill", False)):
            obs_journal.JOURNAL.clear()  # each router restarts fid at 0
            router = FleetRouter(disaggregate=True, kv_ship=ship)
            router.add_replica("p0", p0, role="prefill")
            router.add_replica("d0", d0, role="decode")
            reqs = [router.submit(list(p), 4) for p in prompts]
            router.run_until_drained()
            out[f"{mode}_token_exact"] = all(
                f.tokens_out == ref for f, ref in zip(reqs, refs))
            # the acceptance criterion names BOTH HIVED_FLEET_KV_SHIP
            # modes: every completed request's TTFT legs must sum to its
            # measured ttft_s through this mode's handoff path
            flights = obs_journal.JOURNAL.flights()
            for f in reqs:
                gap = flights[f"fleet/{f.fid}"]["ttft_gap"]
                assert gap is not None and abs(gap) <= 1e-6, (
                    f"{mode} fleet/{f.fid}: TTFT leg sum gap {gap}s")
            out[f"{mode}_legs_sum_ok"] = True
    finally:
        if not prev_journal:
            obs_journal.disable()
        obs_journal.JOURNAL.clear()
    return out


BREAKDOWN_KEYS = ("embed_ms", "attn_ms", "mlp_ms", "collective_ms",
                  "sampling_ms")


def bench_breakdown(cfg, params, batch: int, seq: int, dec_batch: int,
                    mesh, iters: int):
    """Per-phase timings (--breakdown): jitted microbenches of the model's
    phases on the bench shapes, each iteration recorded as an obs span
    (``bench_model/<phase>``, exportable with --trace-file) so a
    train_step_ms delta is attributable to a phase instead of a guess.

    Keys are pinned by tests/test_bench_model.py (hand-rolled-serializer
    rule): embed/attn/mlp are FORWARD timings (attn/mlp scaled by
    n_layers; a train step pays roughly 3x forward plus remat),
    collective is the tp all-reduce of one [B, T, D] projection (exactly
    0-work on a single-chip mesh — reported honestly), sampling is the
    filtered categorical pick on one [B, vocab] logits row."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from hivedscheduler_tpu.models import decode as dec
    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.obs import trace as obs_trace

    dtype = cfg.dtype
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (batch, seq), 0, cfg.vocab_size, jnp.int32
    )
    x = jax.random.normal(
        jax.random.PRNGKey(4), (batch, seq, cfg.d_model), dtype
    )
    logits = jax.random.normal(
        jax.random.PRNGKey(5), (dec_batch, cfg.vocab_size), jnp.float32
    )
    key = jax.random.PRNGKey(6)
    lp0 = jax.tree.map(lambda a: a[0], params["layers"])
    positions = jnp.arange(seq, dtype=jnp.int32)[None, :]
    attn_fn = tm._resolve_attn_fn(cfg)

    def embed_phase(tok):
        return dec.embed_tokens(params, tok, dtype)

    def attn_phase(xx):
        h = tm._rms_norm(xx, lp0["attn_norm"])
        q, k, v = dec.qkv_proj(lp0, h, positions, cfg.rope_theta, dtype)
        attn = tm._dispatch_attention(q, k, v, cfg, attn_fn, mesh)
        return xx + jnp.einsum(
            "bthk,hkd->btd", attn, tm.load_weight(lp0["wo"], dtype)
        )

    def mlp_phase(xx):
        return xx + dec.dense_mlp(lp0, tm._rms_norm(xx, lp0["mlp_norm"]),
                                  dtype)

    tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("tp", 1)
    if tp > 1:
        from jax.sharding import PartitionSpec as P

        from hivedscheduler_tpu.parallel.ring_attention import _get_shard_map

        spec = P(None, None, None)
        kw = dict(mesh=mesh, in_specs=(spec,), out_specs=spec)
        try:
            collective_phase = _get_shard_map()(
                lambda y: lax.psum(y, "tp"), check_vma=False, **kw
            )
        except TypeError:
            collective_phase = _get_shard_map()(
                lambda y: lax.psum(y, "tp"), check_rep=False, **kw
            )
    else:
        # a 1-chip mesh has no cross-chip collective: time the no-op so
        # the key is present and honestly ~0
        def collective_phase(y):
            return y

    def sampling_phase(lg, k):
        return jax.vmap(jax.random.categorical)(
            jax.random.split(k, lg.shape[0]),
            dec.filter_logits(lg / 0.8, top_k=40, top_p=0.9),
        )

    def timed(name, fn, *args, scale: float = 1.0):
        jitted = jax.jit(fn)
        np.asarray(jax.tree.leaves(jitted(*args))[0])  # compile + sync
        times = []
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            out = jitted(*args)
            np.asarray(jax.tree.leaves(out)[0])  # axon: block is a no-op
            t1 = time.perf_counter()
            obs_trace.complete(f"bench_model/{name}", t0, t1, cat="bench")
            times.append(t1 - t0)
        return statistics.median(times) * 1e3 * scale

    return {
        "embed_ms": round(timed("embed", embed_phase, tokens), 3),
        "attn_ms": round(
            timed("attn", attn_phase, x, scale=cfg.n_layers), 3
        ),
        "mlp_ms": round(timed("mlp", mlp_phase, x, scale=cfg.n_layers), 3),
        "collective_ms": round(timed("collective", collective_phase, x), 3),
        "sampling_ms": round(timed("sampling", sampling_phase, logits, key), 3),
    }


def param_count(cfg) -> int:
    d, dh = cfg.d_model, cfg.head_dim
    attn = d * cfg.n_heads * dh * 2 + d * cfg.kv_heads * dh * 2
    mlp = 3 * d * cfg.d_ff
    norms = 2 * d * cfg.n_layers + d
    return cfg.n_layers * (attn + mlp) + norms + 2 * d * cfg.vocab_size


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-hive-bench-model")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--smoke", action="store_true",
                        help="tiny shapes regardless of backend (CI)")
    parser.add_argument("--skip-decode", action="store_true")
    parser.add_argument("--skip-serve", action="store_true",
                        help="skip the continuous-batching throughput bench")
    parser.add_argument("--fleet-duration", type=float, default=0.0,
                        help="diurnal-cycle wall seconds for the fleet "
                             "autoscale A/B (0 = the default: 30 on TPU, "
                             "6 on CPU; the tier-1 smoke test passes a "
                             "smaller value to stay inside the wall-time "
                             "budget — the driver's run keeps the default)")
    parser.add_argument(
        "--acquire-timeout", type=float,
        default=float(os.environ.get("HIVED_TPU_ACQUIRE_TIMEOUT_S", "240")),
        help="max seconds to wait for the TPU grant before exiting rc=3",
    )
    # tuning knobs (defaults = the shipped flagship settings)
    parser.add_argument("--remat", choices=("full", "dots", "none"), default=None)
    parser.add_argument("--ce-chunk", type=int, default=None,
                        help="chunked CE size (default: 512 on the real "
                             "config — the [B,T,vocab] f32 logits never "
                             "materialize; 0 disables)")
    parser.add_argument("--block-q", type=int, default=None)
    parser.add_argument("--block-k", type=int, default=None)
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--grad-accum", type=int, default=1)
    parser.add_argument("--skip-train", action="store_true")
    parser.add_argument("--skip-goodput", action="store_true",
                        help="skip the fault-injected goodput episode "
                        "(kill -9 -> elastic shrink -> grow in CPU-only "
                        "subprocesses; reports the badput breakdown, "
                        "effective MFU and the workload<->capacity-ledger "
                        "bridge check — doc/design/observability.md)")
    parser.add_argument("--decode-steps", type=int, default=1,
                        help="decode fusion window: unrolls the static "
                             "generate loop and fuses K iterations per "
                             "serving-engine step (exact streams)")
    parser.add_argument("--breakdown", action="store_true",
                        help="add a per-phase 'breakdown' dict (embed/attn/"
                             "mlp/collective/sampling ms, keys pinned by "
                             "test_bench_model.py) so train_step_ms deltas "
                             "are attributable; phases run as jitted "
                             "microbenches recorded as obs spans")
    parser.add_argument("--trace-file", default="",
                        help="with --breakdown: write the phase spans as a "
                             "Chrome-trace/Perfetto JSON to this path")
    args = parser.parse_args(argv)

    jax, devices = acquire_backend(args.acquire_timeout)

    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.parallel import topology

    dev = devices[0]
    # "real" = the flagship chip-filling config; --smoke on a TPU must not
    # masquerade as the headline metric
    real = jax.default_backend() == "tpu" and not args.smoke
    peak_flops, peak_bw = chip_peaks(dev)

    if real:
        cfg = tm.TransformerConfig(
            vocab_size=32768, d_model=2048, n_heads=16, n_kv_heads=8,
            n_layers=6, d_ff=8192, max_seq_len=2048, attn_impl="flash",
            remat=args.remat or "dots",
        )
        batch, seq = args.batch or 8, 2048
        dec_batch, dec_prompt, dec_new = 16, 128, 64
        iters = args.iters
    else:
        cfg = tm.TransformerConfig(
            vocab_size=512, d_model=128, n_heads=8, n_kv_heads=4,
            n_layers=2, d_ff=256, max_seq_len=256, attn_impl="flash",
            remat=args.remat or "full",
        )
        batch, seq = args.batch or 2, 256
        dec_batch, dec_prompt, dec_new = 2, 16, 8
        iters = min(args.iters, 2)
    if args.block_q or args.block_k:
        cfg = dataclasses.replace(
            cfg,
            attn_block_q=args.block_q or cfg.attn_block_q,
            attn_block_k=args.block_k or cfg.attn_block_k,
        )

    axes = topology.MeshAxes()  # all-1 axes: single chip
    mesh = topology.make_mesh(axes, jax.devices()[:1])

    ce_chunk = args.ce_chunk if args.ce_chunk is not None else (512 if real else 0)
    eff_accum = args.grad_accum  # the accumulation the train number ran with
    if args.skip_train:
        step_s, loss = None, 0.0
        flops, achieved, mfu, train_tps = 0.0, None, None, None
    else:
        try:
            step_s, loss = bench_train(cfg, batch, seq, iters, mesh,
                                       grad_accum=args.grad_accum,
                                       ce_chunk=ce_chunk)
        except Exception as e:
            # the tuned DEFAULT remat policy trades HBM for FLOPs; if it
            # doesn't fit this chip, degrade in MFU order rather than
            # losing the driver's number entirely: (1) keep dots but halve
            # activation residency with one extra grad-accum slice (loss
            # math identical for the dense model), (2) full remat. An
            # explicit --remat is a tuning question — "does it fit" is the
            # answer, so re-raise.
            if (args.remat is not None or cfg.remat == "full"
                    or "RESOURCE_EXHAUSTED" not in str(e)):
                raise
            try:
                step_s, loss = bench_train(cfg, batch, seq, iters, mesh,
                                           grad_accum=2 * args.grad_accum,
                                           ce_chunk=ce_chunk)
                eff_accum = 2 * args.grad_accum
            except Exception as e2:
                if "RESOURCE_EXHAUSTED" not in str(e2):
                    raise
                cfg = dataclasses.replace(cfg, remat="full")
                step_s, loss = bench_train(cfg, batch, seq, iters, mesh,
                                           grad_accum=args.grad_accum,
                                           ce_chunk=ce_chunk)
        flops = train_flops_per_step(cfg, batch, seq)
        achieved = flops / step_s
        mfu = achieved / peak_flops if peak_flops else None
        train_tps = batch * seq / step_s

    decode_tps = None
    decode_bw_frac = None
    serve_tps = None
    serve_occ = None
    serve_kv_int8_speedup = None
    stage_errors = {}
    params = None
    if not (args.skip_decode and args.skip_serve):
        try:
            params = serving_params(cfg)
        except Exception as e:
            # both downstream stages need the weights; losing them must
            # still not lose the already-measured train MFU number
            note = f"params_init: {type(e).__name__}: {str(e)[:200]}"
            if not args.skip_decode:
                stage_errors["decode_error"] = note
            if not args.skip_serve:
                stage_errors["serve_error"] = note
                stage_errors["serve_prefix_error"] = note
                stage_errors["serve_kv_int8_error"] = note
                stage_errors["serve_fleet_error"] = note
    if params is not None and not args.skip_decode:
        try:
            dec_s = bench_decode(cfg, params, dec_batch, dec_prompt, dec_new,
                                 max(1, iters // 2),
                                 decode_steps=args.decode_steps)
            decode_tps = dec_batch * dec_new / dec_s
            if peak_bw:
                # roofline: each decode step streams the full bf16 param bytes
                param_bytes = 2.0 * param_count(cfg)
                decode_bw_frac = (dec_new * param_bytes / dec_s) / peak_bw
        except Exception as e:
            # stages degrade independently: a decode failure must not lose
            # the train MFU number (the line prints only at the end)
            stage_errors["decode_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    serve_prefix_speedup = serve_prefix_ttft_speedup = None
    serve_paged_ab = None
    serve_fleet = None
    if params is not None and not args.skip_serve:
        try:
            # dense-vs-paged A/B at equal KV HBM under a mixed-length trace
            # (the acceptance metric for the paged cache: concurrent
            # streams per chip / requests-per-sec at the TTFT ceiling)
            serve_paged_ab = bench_serving_paged_ab(
                cfg, params,
                n_requests=32 if real else 8,
                max_len=512 if real else 96,
                page_size=16 if real else 8,
                dense_slots=4 if real else 2,
                paged_slots=16 if real else 8,
                budget=16 if real else 4,
            )
        except Exception as e:
            stage_errors["serve_paged_error"] = (
                f"{type(e).__name__}: {str(e)[:200]}"
            )
        try:
            serve_tps, serve_occ = bench_serving(
                cfg, params,
                n_requests=16 if real else 3,
                max_batch=dec_batch,
                budget=32 if real else 4,
                decode_steps=args.decode_steps,
            )
        except Exception as e:
            stage_errors["serve_error"] = f"{type(e).__name__}: {str(e)[:200]}"
        try:
            # long-context decode, float vs int8 KV: the regime where the
            # per-step HBM traffic is the KV arena, which int8 halves
            kv_f, kv_q = bench_serving_kv_int8(
                cfg, params,
                batch=8 if real else 2,
                ctx=1024 if real else 24,
                new_tokens=48 if real else 6,
            )
            serve_kv_int8_speedup = kv_q / kv_f
        except Exception as e:
            serve_kv_int8_speedup = None
            stage_errors["serve_kv_int8_error"] = (
                f"{type(e).__name__}: {str(e)[:200]}"
            )
        try:
            # fleet stage: autoscaled vs static at equal PEAK chip budget
            # under a diurnal open-loop curve (doc/design/fleet.md) + the
            # disaggregated token-exactness assertion, both handoff modes
            serve_fleet, fleet_engines = bench_serving_fleet(
                cfg, params,
                peak_replicas=4 if real else 2,
                duration_s=args.fleet_duration or (30.0 if real else 6.0),
                budget=12 if real else 4,
                max_len=256 if real else 64,
                page_size=16 if real else 8,
                max_batch=4 if real else 2,
                # real TPUs serve in parallel (capacity scales with
                # replicas); the CPU A/B shares one core across replicas
                rate_scale=4.0 if real else 1.6,
            )
            serve_fleet.update(bench_fleet_disagg_exact(
                cfg, params,
                max_len=256 if real else 64,
                page_size=16 if real else 8,
                engines=fleet_engines,
            ))
        except Exception as e:
            serve_fleet = None
            stage_errors["serve_fleet_error"] = (
                f"{type(e).__name__}: {str(e)[:200]}"
            )
        try:
            serve_prefix_speedup, serve_prefix_ttft_speedup = bench_serving_prefix(
                cfg, params,
                n_requests=12 if real else 3,
                system_len=256 if real else 12,
                tail_max=16 if real else 4,
                budget=16 if real else 3,
                max_len=512 if real else 64,
            )
        except Exception as e:
            stage_errors["serve_prefix_error"] = (
                f"{type(e).__name__}: {str(e)[:200]}"
            )

    breakdown = None
    if args.breakdown:
        from hivedscheduler_tpu.obs import trace as obs_trace

        obs_trace.enable()
        try:
            bd_params = params if params is not None else serving_params(cfg)
            breakdown = bench_breakdown(
                cfg, bd_params, batch, seq, dec_batch, mesh,
                max(1, iters // 2),
            )
            if args.trace_file:
                obs_trace.write_chrome_trace(args.trace_file)
        except Exception as e:
            stage_errors["breakdown_error"] = (
                f"{type(e).__name__}: {str(e)[:200]}"
            )

    # goodput stage (ISSUE 16): a fault-injected elastic episode — kill -9
    # mid-step on the full slice, shrink resume, SIGTERM grow offer, grow
    # to completion — in CPU-only subprocesses (cpu_only_env: never a TPU
    # grant at risk), with the step-phase conservation invariant asserted
    # per incarnation and the workload-observed seconds reconciled against
    # the scheduler-side busy_guaranteed interval for the gang
    goodput_stage = None
    if not args.skip_goodput:
        import tempfile

        from hivedscheduler_tpu.chaos import workload as workload_chaos

        try:
            with tempfile.TemporaryDirectory(prefix="hived-goodput-") as gd:
                # seed 3 = the pinned elastic baseline
                # (tools/check_workload_seeds.py): kill@3 lands between
                # commits, so rework attribution is guaranteed non-vacuous
                gh = workload_chaos.ElasticWorkloadHarness(
                    seed=3, workdir=gd, bridge_ledger=True, reference=False)
                greport = gh.run()
            goodput_stage = dict(greport["goodput"])
            goodput_stage["conservation_ok"] = not greport["violations"]
            goodput_stage["violations"] = greport["violations"][:8]
        except Exception as e:
            stage_errors["goodput_error"] = (
                f"{type(e).__name__}: {str(e)[:200]}"
            )

    # bar inputs, computed once (dec_batch cancels: per-occupied-slot serve
    # throughput over per-row static decode throughput). The BARS apply to
    # the real flagship config only: a smoke/CPU run reports the measured
    # ratio but a None verdict (its tiny shapes are not what the bar was
    # set for — mirroring the _smoke metric-name suffix).
    ROOFLINE_BAR, SLOT_EFF_BAR = 0.5, 0.7
    roofline_frac = (round(decode_bw_frac, 3)
                     if decode_bw_frac is not None else None)
    slot_eff = (round(serve_tps / (serve_occ * decode_tps), 3)
                if None not in (serve_tps, serve_occ, decode_tps)
                and serve_occ and decode_tps else None)

    result = {
        "metric": "train_step_mfu_1chip" if real else "train_step_mfu_1chip_smoke",
        "value": round(mfu * 100.0, 2) if mfu is not None else None,
        "unit": "%",
        "vs_baseline": round(mfu / 0.40, 3) if mfu is not None else None,
        "device": getattr(dev, "device_kind", str(dev)),
        "train_step_ms": round(step_s * 1e3, 2) if step_s else None,
        "train_tokens_per_sec": round(train_tps, 1) if train_tps else None,
        "train_model_tflops_per_step": round(flops / 1e12, 3),
        "achieved_tflops_per_sec": round(achieved / 1e12, 2) if achieved else None,
        "peak_bf16_tflops_per_sec": round(peak_flops / 1e12, 1) if peak_flops else None,
        "decode_tokens_per_sec": round(decode_tps, 1) if decode_tps else None,
        "decode_hbm_roofline_frac": roofline_frac,
        "serve_tokens_per_sec": round(serve_tps, 1) if serve_tps else None,
        "serve_occupancy": round(serve_occ, 3) if serve_occ else None,
        # long-context decode throughput, int8 KV over float KV (>1 = the
        # halved KV HBM stream pays off; CPU smoke values are meaningless)
        "serve_kv_int8_speedup": round(serve_kv_int8_speedup, 3)
        if serve_kv_int8_speedup else None,
        # -- serving bars (BASELINE.md): numbers that can FAIL. pass/fail
        # is computed on the ROUNDED reported value so the artifact is
        # mechanically self-consistent (a reported 0.7 never reads fail
        # against a 0.7 bar) -----------------------------------------------
        # decode: >= 50% of the HBM roofline at the flagship config
        "decode_roofline_bar": ROOFLINE_BAR,
        "decode_roofline_pass": (roofline_frac >= ROOFLINE_BAR)
        if roofline_frac is not None and real else None,
        # continuous batching: throughput per OCCUPIED slot >= 70% of the
        # static-batch decode's per-row throughput (same weights, same
        # batch size) — the engine's churn machinery (admission, bucketed
        # prefills, host round-trips) may cost at most 30%
        "serve_slot_efficiency": slot_eff,
        "serve_slot_efficiency_bar": SLOT_EFF_BAR,
        "serve_slot_efficiency_pass": (slot_eff >= SLOT_EFF_BAR)
        if slot_eff is not None and real else None,
        # shared-system-prompt load, prefix cache on vs off (>1 = the KV
        # restore + tail prefill beats re-prefilling the system prompt)
        "serve_prefix_speedup": round(serve_prefix_speedup, 3)
        if serve_prefix_speedup else None,
        "serve_prefix_ttft_speedup": round(serve_prefix_ttft_speedup, 3)
        if serve_prefix_ttft_speedup else None,
        # serving-throughput stage: paged vs dense at equal KV HBM under a
        # mixed-length trace (full A/B dict: per-engine rps, goodput at the
        # p99 TTFT ceiling, max concurrent streams). Bar: the paged engine
        # must fit >= 1.5x the concurrent streams (structural — same HBM,
        # footprint-granular admission) on EVERY backend incl. the CPU mesh
        "serve_paged_ab": serve_paged_ab,
        "serve_paged_streams_ratio": (serve_paged_ab or {}).get("streams_ratio"),
        "serve_paged_streams_bar": 1.5,
        "serve_paged_streams_pass": (
            serve_paged_ab["streams_ratio"] >= 1.5
            if serve_paged_ab is not None else None),
        "serve_paged_goodput_ratio": (serve_paged_ab or {}).get("goodput_ratio"),
        # fleet stage (doc/design/fleet.md): autoscaled vs static at equal
        # PEAK chip budget under a diurnal open-loop curve. The bar is on
        # goodput per REPLICA-SECOND (the autoscaler's win is the chips it
        # hands back in the trough; static-at-peak bounds absolute goodput
        # by construction — both numbers reported, honestly labelled), and
        # disaggregated serving must be token-exact in BOTH KV-handoff
        # modes (structural, so the bar holds on every backend)
        "serve_fleet": serve_fleet,
        "fleet_efficiency_ratio": (serve_fleet or {}).get("efficiency_ratio"),
        "fleet_efficiency_bar": 1.3,
        "fleet_efficiency_pass": (
            serve_fleet["efficiency_ratio"] >= 1.3
            if serve_fleet is not None else None),
        "fleet_goodput_ratio": (serve_fleet or {}).get("goodput_ratio"),
        "fleet_disagg_token_exact": (
            bool(serve_fleet.get("ship_token_exact")
                 and serve_fleet.get("reprefill_token_exact"))
            if serve_fleet is not None else None),
        # request flight recorder + SLO layer (ISSUE 13): per-leg TTFT
        # attribution asserted (in-stage) to sum to the measured TTFT for
        # every completed request — through BOTH KV-handoff modes — and
        # the A/B's error-budget burn + dominant-leg violation
        # attribution, the diagnosis behind the goodput headline
        "fleet_legs_sum_to_ttft": (
            bool(serve_fleet.get("legs_sum_to_ttft")
                 and serve_fleet.get("ship_legs_sum_ok")
                 and serve_fleet.get("reprefill_legs_sum_ok"))
            if serve_fleet is not None else None),
        "fleet_slo_burn_static": (
            (serve_fleet or {}).get("static_slo") or {}).get("burn_rate"),
        "fleet_slo_burn_autoscaled": (
            (serve_fleet or {}).get("autoscaled_slo") or {}).get(
                "burn_rate"),
        # workload goodput ledger (ISSUE 16, doc/design/observability.md):
        # step-phase badput breakdown of the fault-injected elastic episode
        # (Σ phases == wallclock asserted per incarnation), the rework
        # attribution, and the bridge reconciliation against the capacity
        # ledger's busy_guaranteed interval. effective_mfu discounts the
        # train-step MFU by the episode's goodput fraction — the number the
        # paper's preemption story actually delivers to a faulted job.
        "goodput": goodput_stage,
        "goodput_fraction": (
            round(goodput_stage["goodput_fraction"], 4)
            if goodput_stage is not None
            and goodput_stage.get("goodput_fraction") is not None else None),
        "goodput_conservation_ok": (
            goodput_stage["conservation_ok"]
            if goodput_stage is not None else None),
        "effective_mfu_pct": (
            round(mfu * goodput_stage["goodput_fraction"] * 100.0, 2)
            if mfu is not None and goodput_stage is not None
            and goodput_stage.get("goodput_fraction") is not None else None),
        # null (not vacuously true) when no training ran
        "loss_finite": math.isfinite(loss) if not args.skip_train else None,
        "model": {
            "params_m": round(param_count(cfg) / 1e6, 1),
            "d_model": cfg.d_model, "n_layers": cfg.n_layers,
            "n_heads": cfg.n_heads, "n_kv_heads": cfg.kv_heads,
            "d_ff": cfg.d_ff, "batch": batch, "seq": seq,
            "attn_impl": cfg.attn_impl, "dtype": "bfloat16",
            "remat": cfg.remat, "grad_accum": eff_accum,
            "ce_chunk": ce_chunk, "decode_steps": args.decode_steps,
            "attn_block_q": cfg.attn_block_q, "attn_block_k": cfg.attn_block_k,
        },
        # per-phase attribution (--breakdown; keys pinned by
        # tests/test_bench_model.py::test_breakdown_keys_pinned)
        **({"breakdown": breakdown} if breakdown is not None else {}),
        "vs_baseline_note": (
            "the reference scheduler ships no workload runtime, so there is "
            "no reference MFU; vs_baseline is MFU relative to the 40% "
            "well-tuned-dense-transformer bar"
        ),
        **stage_errors,
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
