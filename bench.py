"""Headline benchmark: gang-schedule latency for a 256-chip slice on a
simulated v5p-1024 cluster under multi-VC load, plus ICI-mesh fragmentation.

Matches the driver metric in BASELINE.json ("p50 gang-schedule latency for
256-chip slice; ICI-mesh fragmentation %" on v5p-1024). The reference
publishes no benchmark numbers (BASELINE.md); the only latency figure in its
artifacts is the 50 ms ``waitingPodSchedulingBlockMilliSec`` knob its sample
deployment spends *per waiting pod* to get FIFO (example/run/deploy.yaml:50),
so ``vs_baseline`` reports 50 ms / our p50 — how many times faster one full
256-chip gang decision is than the reference's single FIFO-blocking tick.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Scenario:
- physical: one v5p-1024 pod (8x8x16 ICI mesh, 4-chip hosts), levels
  4/8/16/32/64/128/256/512 chips;
- VCs: vc-a guarantees 2x 256-chip cells, vc-b 1x 256, vc-c 4x 64;
- load: vc-b and vc-c churn guaranteed + opportunistic gangs at random sizes;
- measured: end-to-end Schedule()+AddAllocatedPod for a 64-pod x 4-chip
  (=256-chip) gang in vc-a, repeated with interleaved churn;
- fragmentation: fraction of attempts where the 256-chip slice could NOT be
  placed contiguously although vc-a's guarantee was free (buddy allocation
  over mesh tilings should make this 0%).
"""

from __future__ import annotations

import json
import logging
import random
import statistics
import time

logging.disable(logging.CRITICAL)

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api.config import Config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualCellSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.algorithm.hived import HivedAlgorithm
from hivedscheduler_tpu.common.utils import to_json
from hivedscheduler_tpu.k8s.types import Container, Node, NodeCondition, Pod
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE, PREEMPTING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

LEVELS = [
    ("v5p-2x2x2", (2, 2, 2)),
    ("v5p-4x2x2", (4, 2, 2)),
    ("v5p-4x4x2", (4, 4, 2)),
    ("v5p-4x4x4", (4, 4, 4)),
    ("v5p-8x4x4", (8, 4, 4)),
    ("v5p-8x8x4", (8, 8, 4)),  # 256 chips: the measured slice
    ("v5p-8x8x8", (8, 8, 8)),
]


def build_config() -> Config:
    mesh = MeshSpec(
        topology=(8, 8, 16),
        chip_type="v5p-chip",
        host_shape=(2, 2, 1),
        levels=[MeshLevelSpec(name=n, shape=s) for n, s in LEVELS],
    )
    return new_config(
        Config(
            physical_cluster=PhysicalClusterSpec(
                cell_types={"v5p-1024": CellTypeSpec(mesh=mesh)},
                physical_cells=[PhysicalCellSpec(cell_type="v5p-1024", cell_address="pod0")],
            ),
            virtual_clusters={
                "vc-a": VirtualClusterSpec(
                    virtual_cells=[VirtualCellSpec(cell_number=2, cell_type="v5p-1024.v5p-8x8x4")]
                ),
                "vc-b": VirtualClusterSpec(
                    virtual_cells=[VirtualCellSpec(cell_number=1, cell_type="v5p-1024.v5p-8x8x4")]
                ),
                "vc-c": VirtualClusterSpec(
                    virtual_cells=[VirtualCellSpec(cell_number=4, cell_type="v5p-1024.v5p-4x4x4")]
                ),
            },
        )
    )


def make_pod(name: str, vc: str, priority: int, group: str, pods: int, chips: int) -> Pod:
    spec = {
        "virtualCluster": vc,
        "priority": priority,
        "leafCellType": "v5p-chip",
        "leafCellNumber": chips,
        "affinityGroup": {
            "name": group,
            "members": [{"podNumber": pods, "leafCellNumber": chips}],
        },
    }
    return Pod(
        name=name,
        uid=name,
        annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: to_json(spec)},
        containers=[Container(resource_limits={C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
    )


class Cluster:
    def __init__(self):
        self.algo = HivedAlgorithm(build_config())
        self.nodes = sorted(
            {
                n
                for ccl in self.algo.full_cell_list.values()
                for c in ccl[max(ccl)]
                for n in c.nodes
            }
        )
        for n in self.nodes:
            self.algo.add_node(Node(name=n))
        self.groups = {}  # name -> list of bound pods
        # steady state after the runtime's recovery barrier: the cell trees
        # are frozen out of gen-2 GC scans (runtime/utils.py:
        # freeze_long_lived_state), which is what bounds scheduling p99
        from hivedscheduler_tpu.runtime.utils import freeze_long_lived_state

        freeze_long_lived_state()

    def schedule_gang(self, vc, priority, group, pods, chips, allow_preempt=False):
        """Schedule + allocate a whole gang; returns (ok, seconds, preempted).

        With ``allow_preempt``, opportunistic victims advertised by the
        scheduler are deleted instantly (simulated kill) and the pod retried —
        preempting OT pods off a VC's guarantee is by-design, not a
        fragmentation failure."""
        bound = []
        preempted = False
        t0 = time.perf_counter()
        for i in range(pods):
            pod = make_pod(f"{group}-{i}", vc, priority, group, pods, chips)
            # victims are advertised one node per round (K8s preempts a node
            # at a time), so a wide gang may need many preempt rounds
            for _attempt in range(128):
                r = self.algo.schedule(
                    pod, self.nodes,
                    PREEMPTING_PHASE if (allow_preempt and _attempt) else FILTERING_PHASE,
                )
                if r.pod_preempt_info is not None and allow_preempt:
                    preempted = True
                    for victim in r.pod_preempt_info.victim_pods:
                        self._kill_pod(victim)
                    continue
                break
            if r.pod_bind_info is None:
                dt = time.perf_counter() - t0
                for bp in bound:  # roll back partial gang
                    self.algo.delete_allocated_pod(bp)
                return False, dt, preempted
            bp = new_binding_pod(pod, r.pod_bind_info)
            self.algo.add_allocated_pod(bp)
            bound.append(bp)
        dt = time.perf_counter() - t0
        self.groups[group] = bound
        return True, dt, preempted

    def _kill_pod(self, victim):
        for name, pods in list(self.groups.items()):
            if any(bp.uid == victim.uid for bp in pods):
                self.free_gang(name)
                return

    def free_gang(self, group):
        for bp in self.groups.pop(group):
            self.algo.delete_allocated_pod(bp)


def run(measure_iters: int = 60, seed: int = 7):
    rng = random.Random(seed)
    cluster = Cluster()

    # steady background load on vc-b / vc-c (guaranteed + opportunistic)
    churn_sizes = [(1, 4), (2, 4), (4, 4), (8, 4), (16, 4)]  # (pods, chips/pod)
    churn_groups = []
    gid = 0
    for _ in range(24):
        vc = rng.choice(["vc-b", "vc-c"])
        prio = rng.choice([-1, 0, 5, 10])
        pods, chips = rng.choice(churn_sizes)
        name = f"churn-{gid}"
        gid += 1
        ok, _, _ = cluster.schedule_gang(vc, prio, name, pods, chips)
        if ok:
            churn_groups.append(name)

    latencies = []
    frag_failures = 0
    for it in range(measure_iters):
        # drop groups preempted away by the previous measured gang
        churn_groups = [g for g in churn_groups if g in cluster.groups]
        # churn: free a random third of load groups, add new ones
        rng.shuffle(churn_groups)
        for name in churn_groups[: len(churn_groups) // 3]:
            cluster.free_gang(name)
            churn_groups.remove(name)
        for _ in range(4):
            vc = rng.choice(["vc-b", "vc-c"])
            prio = rng.choice([-1, 0, 5, 10])
            pods, chips = rng.choice(churn_sizes)
            name = f"churn-{gid}"
            gid += 1
            ok, _, _ = cluster.schedule_gang(vc, prio, name, pods, chips)
            if ok:
                churn_groups.append(name)

        # the measured 256-chip gang in vc-a (guarantee is free): 64 pods x 4
        ok, dt, _ = cluster.schedule_gang("vc-a", 10, f"big-{it}", 64, 4,
                                          allow_preempt=True)
        latencies.append(dt)
        if not ok:
            frag_failures += 1  # guarantee free but slice not placeable
        else:
            cluster.free_gang(f"big-{it}")

    p50 = statistics.median(latencies) * 1000.0
    p99 = sorted(latencies)[max(0, int(len(latencies) * 0.99) - 1)] * 1000.0
    frag_pct = 100.0 * frag_failures / measure_iters
    return p50, p99, frag_pct


def build_scale_config(n_chips: int) -> Config:
    """The scale-point cluster configs: v5p-4096 (16x16x16, the PARITY.md
    figure — specs unchanged so ``scale4096_p50_ms`` stays comparable) and
    v5p-16384 (16x32x32, 4096 hosts — ROADMAP item 1's production-fleet
    order of magnitude)."""
    if n_chips == 4096:
        levels = [("l1", (2, 2, 2)), ("l2", (4, 2, 2)), ("l3", (4, 4, 2)),
                  ("l4", (4, 4, 4)), ("l5", (8, 4, 4)), ("l6", (8, 8, 4)),
                  ("l7", (8, 8, 8)), ("l8", (16, 8, 8)), ("l9", (16, 16, 8))]
        topology, name = (16, 16, 16), "v5p-4096"
        vcs = {
            "vc-a": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=2, cell_type=f"{name}.l8")]),
            "vc-b": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=16, cell_type=f"{name}.l4")]),
        }
    elif n_chips == 16384:
        levels = [("l1", (2, 2, 2)), ("l2", (4, 2, 2)), ("l3", (4, 4, 2)),
                  ("l4", (4, 4, 4)), ("l5", (8, 4, 4)), ("l6", (8, 8, 4)),
                  ("l7", (8, 8, 8)), ("l8", (16, 8, 8)), ("l9", (16, 16, 8)),
                  ("l10", (16, 16, 16)), ("l11", (16, 32, 16))]
        topology, name = (16, 32, 32), "v5p-16384"
        # guarantees: 2x4096 + 4x1024 + 8x256 = 14336 of 16384 chips; the
        # rest is opportunistic headroom (backfill/preemption reachable)
        vcs = {
            "vc-a": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=2, cell_type=f"{name}.l10")]),
            "vc-b": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=4, cell_type=f"{name}.l8")]),
            "vc-c": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=8, cell_type=f"{name}.l6")]),
        }
    else:
        raise ValueError(f"no scale config for {n_chips} chips")
    mesh = MeshSpec(topology=topology, chip_type="v5p-chip",
                    host_shape=(2, 2, 1),
                    levels=[MeshLevelSpec(name=n, shape=sh) for n, sh in levels])
    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={name: CellTypeSpec(mesh=mesh)},
            physical_cells=[PhysicalCellSpec(cell_type=name,
                                             cell_address="pod0")]),
        virtual_clusters=vcs))


def build_scale_algo(n_chips: int):
    """(algo, nodes) for a scale-point cluster with every node healthy —
    shared by the scale stages here and profile_bench's scenarios."""
    algo = HivedAlgorithm(build_scale_config(n_chips))
    nodes = sorted({n for ccl in algo.full_cell_list.values()
                    for c in ccl[max(ccl)] for n in c.nodes})
    for n in nodes:
        algo.add_node(Node(name=n))
    return algo, nodes


def _run_scale(n_chips: int, gang_pods: int, trials: int):
    """Time ``trials`` schedule+allocate rounds of one big gang (one quarter
    of the cluster, from vc-a's free guarantee) then release it."""
    algo, nodes = build_scale_algo(n_chips)
    lat = []
    for trial in range(trials):
        pods = []
        t0 = time.perf_counter()
        for i in range(gang_pods):
            p = make_pod(f"g{trial}-{i}", "vc-a", 10, f"g{trial}",
                         gang_pods, 4)
            r = algo.schedule(p, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, r.pod_wait_info
            bp = new_binding_pod(p, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            pods.append(bp)
        lat.append(time.perf_counter() - t0)
        for bp in pods:
            algo.delete_allocated_pod(bp)
    return statistics.median(lat) * 1000.0, max(lat) * 1000.0


def run_scale_4096(seed: int = 7):
    """Reproduces the PARITY.md v5p-4096 scale figure: a 1024-chip gang
    (256 pods x 4) on a 16x16x16 cluster. Run: python bench.py --scale-4096"""
    return _run_scale(4096, gang_pods=256, trials=8)


def run_scale_16384(seed: int = 7):
    """The v5p-16384 scale point (ROADMAP item 1): a 4096-chip gang
    (1024 pods x 4) on a 16x32x32 cluster of 4096 hosts — reported as
    ``scale16384_p50_ms``. Fewer trials than the 4096 point: one trial is
    1024 schedule+allocate pairs. Run: python bench.py --scale-16384"""
    return _run_scale(16384, gang_pods=1024, trials=3)


# -- sustained churn at 16k chips (ISSUE 15 headline) ------------------------
#
# The raw-speed instrument for the whole scheduler-core stack: continuous
# submit + preempt + complete driven through a REAL HivedScheduler (full
# runtime: informers over the fake ApiServer, extender routines, defrag and
# elastic ticks) on the v5p-16384 cluster, with the journal AND the capacity
# ledger live — the honest production configuration, so the headline tracks
# what a decision actually costs as the feature set grows. Event batching
# (HIVED_EVENT_BATCH=1) is the measured configuration; the artifact also
# pins the kill-switch differentials: a shorter identical-seed churn with
# HIVED_EVENT_BATCH=0 and with HIVED_NATIVE=0 must reproduce byte-identical
# decisions (placements, failure strings, journal events).

_CHURN_SHAPES = [(4, 4), (8, 4), (16, 4), (32, 4), (64, 4), (128, 4)]


def _runtime_churn(n_chips: int, ops: int, seed: int,
                   event_batch: bool = True, py_native: bool = False):
    """Drive ``ops`` gang schedules (interleaved with completions,
    preemptions and defrag/elastic ticks) through a full runtime stack;
    returns (decision log, per-gang latencies, stats). The log carries
    every decision outcome byte-for-byte (placed nodes, failure strings,
    journal events), so two runs at different kill-switch settings can be
    pinned identical."""
    import os

    from hivedscheduler_tpu.chaos import invariants as chaos_invariants
    from hivedscheduler_tpu.k8s.fake import FakeKubeClient
    from hivedscheduler_tpu.obs import journal as obs_journal
    from hivedscheduler_tpu.obs import ledger as obs_ledger
    from hivedscheduler_tpu.runtime import extender as ei
    from hivedscheduler_tpu.runtime.scheduler import HivedScheduler

    saved = {k: os.environ.get(k)
             for k in ("HIVED_EVENT_BATCH", "HIVED_NATIVE")}
    os.environ["HIVED_EVENT_BATCH"] = "1" if event_batch else "0"
    if py_native:
        os.environ["HIVED_NATIVE"] = "0"
    try:
        random.seed(seed)  # the algorithm's victim selection draws globally
        rng = random.Random(seed)
        obs_journal.enable(capacity=1 << 15)
        obs_ledger.LEDGER.clear()
        obs_ledger.enable()
        fake = FakeKubeClient()
        sched = HivedScheduler(build_scale_config(n_chips), fake)
        algo = sched.scheduler_algorithm
        nodes = sorted({n for ccl in algo.full_cell_list.values()
                        for c in ccl[max(ccl)] for n in c.nodes})
        for n in nodes:
            fake.create_node(Node(name=n))
        sched.start()

        log = []
        groups = {}
        latencies = []
        stats = {"filters": 0, "preempts": 0, "binds": 0, "waits": 0,
                 "defrag_planned": 0, "elastic_offers": 0}
        gid = 0

        _CN = C.COMPONENT_NAME

        def filter_member(pod_name):
            stats["filters"] += 1
            pod = fake.get_pod("default", pod_name)
            if pod is None:
                return None
            r = sched.filter_routine(ei.ExtenderArgs(
                pod=pod, node_names=nodes))
            if r.node_names:
                return r.node_names[0]
            log.append(("wait", pod_name,
                        tuple(sorted((r.failed_nodes or {}).items()))))
            if r.failed_nodes and any(k != _CN for k in r.failed_nodes):
                return "PREEMPT"
            stats["waits"] += 1
            return None

        def preempt_member(pod_name):
            stats["preempts"] += 1
            pod = fake.get_pod("default", pod_name)
            if pod is None:
                return
            r = sched.preempt_routine(ei.ExtenderPreemptionArgs(
                pod=pod, node_name_to_meta_victims={n: [] for n in nodes}))
            victims = sorted(u for us in r.node_name_to_meta_victims.values()
                             for u in us)
            log.append(("preempt", pod_name, tuple(victims)))
            for gname, gpods in list(groups.items()):
                if any(u in victims for u in gpods):
                    for p in groups.pop(gname):
                        fake.delete_pod("default", p)

        flapped = []
        for op in range(ops):
            # completions: keep a crowded steady state (the quotas saturate
            # and guaranteed gangs preempt/wait) while still churning —
            # free a quarter of the gangs only once genuinely crowded
            if len(groups) > 80:
                names = sorted(groups)
                rng.shuffle(names)
                for name in names[:len(names) // 4]:
                    for p in groups.pop(name):
                        fake.delete_pod("default", p)
                    log.append(("free", name))
            if op % 10 == 7:
                # node-health churn that heals inside the same event window
                # (folds to a no-op under HIVED_EVENT_BATCH=1; the
                # reference round-trips the doomed-bad machinery)
                n = rng.choice(nodes)
                fake.update_node(Node(name=n, conditions=[
                    NodeCondition(type="Ready", status="False")]))
                fake.update_node(Node(name=n))
                log.append(("flap-roundtrip", n))
            if op % 40 == 17:
                # a lasting bad-node window (~10 ops), healed so defrag and
                # elastic planning get healthy-cluster windows too
                bad = rng.choice(nodes)
                flapped.append(bad)
                fake.update_node(Node(name=bad, conditions=[
                    NodeCondition(type="Ready", status="False")]))
                log.append(("flap", bad))
            elif op % 40 == 27 and flapped:
                healed = flapped.pop()
                fake.update_node(Node(name=healed))
                log.append(("heal", healed))
            vc = rng.choice(["vc-a", "vc-b", "vc-c"])
            prio = rng.choice([-1, -1, 0, 5, 10])
            pods, chips = rng.choice(_CHURN_SHAPES)
            oversized = op % 24 == 11
            if oversized:
                # an oversized elastic gang: blocked at full shape while the
                # cluster is crowded, so the wait/defrag-waiter path and the
                # elastic shrink-offer arm stay exercised
                vc, prio = rng.choice(["vc-b", "vc-c"]), 5
                pods, chips = rng.choice([(192, 4), (256, 4)])
            name = f"c{gid}"
            gid += 1
            spec = {
                "virtualCluster": vc, "priority": prio,
                "leafCellType": "v5p-chip", "leafCellNumber": chips,
                "affinityGroup": {
                    "name": name,
                    "members": [{"podNumber": pods,
                                 "leafCellNumber": chips}]},
            }
            if prio >= 0 and (oversized or op % 8 == 3):
                # elastic gangs keep the shrink-offer/grow arm exercised
                spec["elasticMinChips"] = max(chips, pods * chips // 4)
            created, bound, ok = [], [], True
            t0 = time.perf_counter()
            for i in range(pods):
                pn = f"{name}-{i}"
                fake.create_pod(make_pod(pn, vc, prio, name, pods, chips)
                                if "elasticMinChips" not in spec else
                                _make_spec_pod(pn, spec))
                created.append(pn)
                node = None
                for _attempt in range(6):
                    node = filter_member(pn)
                    if node != "PREEMPT":
                        break
                    preempt_member(pn)
                if node in (None, "PREEMPT"):
                    ok = False
                    break
                sched.bind_routine(ei.ExtenderBindingArgs(
                    pod_name=pn, pod_namespace="default", pod_uid=pn,
                    node=node))
                stats["binds"] += 1
                log.append(("bound", pn, node))
                bound.append(pn)
            latencies.append(time.perf_counter() - t0)
            if ok:
                groups[name] = bound
            elif oversized:
                # a blocked elastic gang WAITS (its pods stay pending, as
                # the real control loop leaves them) so defrag_tick can
                # record the waiter and offer its shrink ladder
                log.append(("waiting", name))
            else:
                for pn in created:
                    fake.delete_pod("default", pn)
                log.append(("rollback", name))
            if op % 6 == 5:
                tick = sched.defrag_tick()
                if tick.get("planned") is not None:
                    stats["defrag_planned"] += 1
                    log.append(("defrag",
                                sorted(tick["planned"].get("moves", []))))
                if tick.get("elasticOffer"):
                    stats["elastic_offers"] += 1
                    log.append(("elastic", tick["elasticOffer"]["group"]))
        sched.flush_events()
        with sched.scheduler_lock:
            chaos_invariants.check_ledger(ctx="churn16k")
            chaos_invariants.check_defrag(sched, ctx="churn16k")
        log.append(("journal",
                    tuple((e.type, e.gang, e.bucket)
                          for e in obs_journal.JOURNAL.snapshot())))
        pending = sched._pending
        stats["coalesced"] = (0 if pending is None else
                              pending.coalesced_pod_pairs
                              + pending.coalesced_node_folds)
        stats["event_batches"] = (0 if pending is None else
                                  pending.drained_batches)
        stats["events_applied"] = (0 if pending is None else
                                   pending.drained_events)
        obs_journal.disable()
        obs_ledger.disable()
        return log, latencies, stats
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _make_spec_pod(name: str, spec: dict) -> Pod:
    return Pod(
        name=name, uid=name,
        annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: to_json(spec)},
        containers=[Container(
            resource_limits={C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
    )


def run_churn_16k(ops: int = 160, parity_ops: int = 32, seed: int = 19):
    """The sustained-churn headline plus its kill-switch differentials.

    Headline: ``ops`` gang schedules through the full runtime on v5p-16384
    with defrag ticks, elastic offers, journal, ledger AND event batching
    all ON — schedules/sec (pod filter decisions per second of sustained
    driving) and gang-decision p50/p99.

    Differentials: three ``parity_ops`` runs at the same seed — the
    measured configuration vs ``HIVED_EVENT_BATCH=0`` vs ``HIVED_NATIVE=0``
    — must produce byte-identical decision logs (placements, failure
    strings, journal events); reported as booleans so a silent divergence
    fails loudly in the artifact, not in a dashboard."""
    log, lat, stats = _runtime_churn(16384, ops, seed, event_batch=True)
    lat_ms = sorted(x * 1000.0 for x in lat)
    p50 = statistics.median(lat_ms) if lat_ms else 0.0
    p99 = lat_ms[max(0, int(len(lat_ms) * 0.99) - 1)] if lat_ms else 0.0
    wall = sum(lat)
    fields = {
        "churn16k_schedules_per_sec": round(stats["filters"] / wall, 1)
        if wall else None,
        "churn16k_gang_p50_ms": round(p50, 3),
        "churn16k_gang_p99_ms": round(p99, 3),
        "churn16k_ops": len(lat),
        "churn16k_filters": stats["filters"],
        "churn16k_binds": stats["binds"],
        "churn16k_preempt_rounds": stats["preempts"],
        "churn16k_defrag_planned": stats["defrag_planned"],
        "churn16k_elastic_offers": stats["elastic_offers"],
        "churn16k_events_coalesced": stats["coalesced"],
        "churn16k_events_per_batch": round(
            stats["events_applied"] / stats["event_batches"], 2)
        if stats["event_batches"] else None,
    }
    ref_log, _, _ = _runtime_churn(16384, parity_ops, seed,
                                   event_batch=True)
    nobatch_log, _, _ = _runtime_churn(16384, parity_ops, seed,
                                       event_batch=False)
    nonative_log, _, _ = _runtime_churn(16384, parity_ops, seed,
                                        event_batch=True, py_native=True)
    fields["churn16k_batch_parity"] = ref_log == nobatch_log
    fields["churn16k_native_parity"] = ref_log == nonative_log
    return fields


def run_recovery(n_target_pods: int = 500, seed: int = 13):
    """Work-preserving reconfiguration at v5p-1024 scale: load the cluster
    with hundreds of allocated pods across the VCs, then "restart" — a fresh
    scheduler runtime over a fake apiserver pre-loaded with the bound pods —
    and time the recovery barrier (runtime/scheduler.py start(): every bound
    pod replays through add_allocated_pod before any request is served;
    reference behavior: hived_algorithm_test.go:1042-1092). Returns
    (recovery_ms, n_pods, n_groups, preserved_pct). Run:
    ``python bench.py --recovery``."""
    from hivedscheduler_tpu.k8s.fake import FakeKubeClient
    from hivedscheduler_tpu.runtime.scheduler import HivedScheduler

    rng = random.Random(seed)
    cluster = Cluster()
    sizes = [(1, 4), (2, 4), (4, 4), (8, 4), (16, 4), (64, 4)]
    gid = 0
    attempts = 0
    while (
        sum(len(v) for v in cluster.groups.values()) < n_target_pods
        and attempts < 4 * n_target_pods
    ):
        attempts += 1
        vc = rng.choice(["vc-a", "vc-b", "vc-c"])
        prio = rng.choice([-1, 0, 5, 10])
        pods, chips = rng.choice(sizes)
        name = f"g{gid}"
        gid += 1
        cluster.schedule_gang(vc, prio, name, pods, chips)
    def chip_placement(algo, name):
        """node -> sorted leaf-cell indices: chip-granular identity of the
        gang's slice (a same-nodes/different-chips restart breaks ICI
        contiguity and must count as NOT preserved; reference reconfig
        asserts exact cell placements, hived_algorithm_test.go:1042-1092)."""
        g = algo.get_affinity_group(name)
        return {n: sorted(ix) for n, ix in g.status.physical_placement.items()}

    groups_before = {
        name: chip_placement(cluster.algo, name) for name in cluster.groups
    }
    bound_pods = [bp for pods in cluster.groups.values() for bp in pods]

    kube = FakeKubeClient()
    for nname in cluster.nodes:
        kube.create_node(Node(name=nname))
    for bp in bound_pods:
        kube.create_pod(bp)
    sched = HivedScheduler(build_config(), kube)
    t0 = time.perf_counter()
    sched.start()
    recovery_s = time.perf_counter() - t0

    algo = sched.scheduler_algorithm
    preserved = 0
    for name, chips_before in groups_before.items():
        try:
            after = chip_placement(algo, name)
        except Exception:
            continue
        if after == chips_before:
            preserved += 1
    preserved_pct = 100.0 * preserved / max(1, len(groups_before))
    return (
        recovery_s * 1000.0,
        len(bound_pods),
        len(groups_before),
        preserved_pct,
    )


# -- trace replay: HiveD vs a topology-unaware strawman ----------------------
#
# HiveD's OSDI'20 evaluation justifies buddy-allocated contiguous slices by
# comparing against topology-UNAWARE scheduling on the same trace
# (/root/reference/README.md:17-23: sharing "without topology-awareness ...
# can considerably affect training performance"). This section reproduces
# that comparison in miniature: the same synthetic multi-tenant trace runs
# through (a) the real HivedAlgorithm cluster and (b) NaiveCluster, a
# first-fit host scheduler with no buddy hierarchy, no cell model and no VC
# quotas. The headline delta is ICI contiguity: every HiveD gang is a
# compact sub-mesh (bounding-box volume == chip count), while first-fit
# scatters gangs across whatever hosts are free — the allocation a TPU
# training job cannot ride ICI on.

TRACE_TOPOLOGY = (8, 8, 16)
TRACE_HOST_SHAPE = (2, 2, 1)
TRACE_TOTAL_CHIPS = TRACE_TOPOLOGY[0] * TRACE_TOPOLOGY[1] * TRACE_TOPOLOGY[2]


def _parse_node_origin(node_name: str):
    """'pod0/x-y-z' -> the host's origin chip coordinate."""
    x, y, z = node_name.rsplit("/", 1)[1].split("-")
    return int(x), int(y), int(z)


def _host_chip_coords(origin):
    """Chip coordinates covered by the host at ``origin``, leaf-index
    (row-major) order — the TPU_VISIBLE_CHIPS contract."""
    ox, oy, oz = origin
    return [
        (ox + dx, oy + dy, oz + dz)
        for dx in range(TRACE_HOST_SHAPE[0])
        for dy in range(TRACE_HOST_SHAPE[1])
        for dz in range(TRACE_HOST_SHAPE[2])
    ]


def _gang_geometry(chips):
    """(contiguous, bbox_inflation): a gang is ICI-contiguous iff its chips
    exactly fill their bounding box; inflation is bbox volume / chip count
    (1.0 = perfect sub-mesh, higher = the ICI detour factor)."""
    xs, ys, zs = zip(*chips)
    vol = (
        (max(xs) - min(xs) + 1)
        * (max(ys) - min(ys) + 1)
        * (max(zs) - min(zs) + 1)
    )
    return vol == len(chips), vol / len(chips)


def hived_gang_chips(cluster, name):
    """Chip coordinates of a scheduled gang from the algorithm's own
    placement record (node -> leaf indices)."""
    g = cluster.algo.get_affinity_group(name)
    chips = []
    for node, idxs in g.status.physical_placement.items():
        host = _host_chip_coords(_parse_node_origin(node))
        chips.extend(host[i] for i in idxs)
    return chips


def naive_gang_chips(cluster, name):
    """Multiple pods of one gang packed onto the same host take
    SUCCESSIVE chip slices (tracked per host within the gang) — without
    the offset the same leading chips would repeat, corrupting the
    geometry metrics for sub-host gangs."""
    chips = []
    offset = {}
    for host, used in cluster.groups[name]:
        start = offset.get(host, 0)
        chips.extend(_host_chip_coords(host)[start:start + used])
        offset[host] = start + used
    return chips


class NaiveCluster:
    """Topology-unaware strawman: first-fit over hosts in address order.

    No buddy hierarchy, no cell model, no VC quotas — the scheduler HiveD's
    evaluation compares against. Gang atomicity and priority preemption are
    kept (a gang either fully places or fully fails; a guaranteed job may
    kill strictly-lower-priority gangs to make room), so the delta vs
    ``Cluster`` isolates topology-awareness, not gang semantics."""

    def __init__(self):
        self.host_free = {}
        for x in range(0, TRACE_TOPOLOGY[0], TRACE_HOST_SHAPE[0]):
            for y in range(0, TRACE_TOPOLOGY[1], TRACE_HOST_SHAPE[1]):
                for z in range(0, TRACE_TOPOLOGY[2], TRACE_HOST_SHAPE[2]):
                    self.host_free[(x, y, z)] = (
                        TRACE_HOST_SHAPE[0] * TRACE_HOST_SHAPE[1]
                        * TRACE_HOST_SHAPE[2]
                    )
        self.hosts = sorted(self.host_free)
        self.groups = {}  # name -> [(host, chips_used)]
        self.prio = {}

    def _place(self, pods, chips):
        placement = []
        for h in self.hosts:
            free = self.host_free[h]
            while free >= chips and len(placement) < pods:
                placement.append(h)
                free -= chips
            if len(placement) == pods:
                return placement
        return None

    def schedule_gang(self, vc, priority, group, pods, chips,
                      allow_preempt=False):
        t0 = time.perf_counter()
        preempted = False
        placement = self._place(pods, chips)
        while placement is None and allow_preempt and priority >= 0:
            victim = min(
                (g for g, p in self.prio.items() if p < priority),
                key=lambda g: self.prio[g], default=None,
            )
            if victim is None:
                break
            self.free_gang(victim)
            preempted = True
            placement = self._place(pods, chips)
        if placement is None:
            return False, time.perf_counter() - t0, preempted
        for h in placement:
            self.host_free[h] -= chips
        self.groups[group] = [(h, chips) for h in placement]
        self.prio[group] = priority
        return True, time.perf_counter() - t0, preempted

    def free_gang(self, group):
        for h, used in self.groups.pop(group):
            self.host_free[h] += used
        self.prio.pop(group, None)


def make_trace_jobs(n_jobs: int, seed: int):
    rng = random.Random(seed)
    sizes = [(1, 4), (2, 4), (4, 4), (8, 4), (16, 4), (32, 4), (64, 4)]
    size_weights = [30, 22, 18, 12, 9, 6, 3]
    vcs = ["vc-a", "vc-b", "vc-c"]
    jobs = []
    t = 0.0
    for j in range(n_jobs):
        t += rng.expovariate(1 / 6.0)  # mean 6 time-units between arrivals
        # (~65% offered load: enough to queue and preempt, not to saturate)
        pods, chips = rng.choices(sizes, weights=size_weights)[0]
        jobs.append({
            "name": f"job-{j}", "arrival": t, "vc": rng.choice(vcs),
            "priority": rng.choice([-1, -1, 0, 5, 10]),
            "pods": pods, "chips": chips,
            "duration": rng.expovariate(1 / 120.0) + 20.0,
        })
    return jobs


class TraceDefrag:
    """Sim-side adapter of the defrag subsystem for :func:`replay_trace`.

    Holds the real planner/probe/backfill objects
    (:mod:`hivedscheduler_tpu.defrag` — the same code the runtime executor
    drives) plus the sim's economics: ``DOWNTIME`` is the checkpoint ->
    re-place -> resume cost charged to every moved gang, in trace time
    units (job durations average ~140, so 3.0 models a few-percent
    checkpoint/restore round-trip — the supervisor's SIGTERM
    checkpoint-and-exit contract, PR 3).

    Only constructed when ``HIVED_DEFRAG`` is on and the cluster is the
    real HiveD one; ``replay_trace(defrag=None)`` executes exactly the
    pre-defrag statements (the kill-switch differential).
    """

    DOWNTIME = 3.0

    def __init__(self, cluster):
        from hivedscheduler_tpu import defrag as defrag_pkg
        from hivedscheduler_tpu.defrag import (
            BackfillPolicy,
            GangSpec,
            MigrationPlanner,
            RunningGroup,
            WhatIfProbe,
        )
        from hivedscheduler_tpu.defrag.planner import vc_quota_chips

        self.GangSpec = GangSpec
        self.RunningGroup = RunningGroup
        self.cluster = cluster
        self.probe = WhatIfProbe(cluster.algo, cluster.nodes)
        self.planner = MigrationPlanner(move_downtime=self.DOWNTIME)
        self.policy = BackfillPolicy()
        self.backfill_on = defrag_pkg.backfill_enabled()
        self.quota = {
            vc: vc_quota_chips(cluster.algo, vc)
            for vc in cluster.algo.vc_schedulers
        }
        self.downgraded = {}  # group name -> original (guaranteed) priority
        self.migrations = 0
        self.promotions = 0
        self.backfills = 0
        self.migrated_chips = 0
        self.overhead_chip_time = 0.0
        self.rejections = {}  # planner rejection reason -> count

    def spec_of(self, job, priority=None):
        return self.GangSpec(
            name=job["name"], vc=job["vc"],
            priority=job["priority"] if priority is None else priority,
            leaf_cell_type="v5p-chip",
            members=((job["pods"], job["chips"]),),
        )

    def running_groups(self, job_by_name):
        """Current gangs as the planner sees them: a downgraded gang's live
        incarnation is opportunistic, whatever its original priority."""
        out = []
        for name, pods in self.cluster.groups.items():
            job = job_by_name[name]
            prio = (OPPORTUNISTIC if name in self.downgraded
                    else job["priority"])
            out.append(self.RunningGroup(
                name=name, spec=self.spec_of(job, priority=prio),
                bound_pods=list(pods),
            ))
        return out

    def reject(self, reason):
        self.rejections[reason] = self.rejections.get(reason, 0) + 1


OPPORTUNISTIC = -1  # api.constants.OPPORTUNISTIC_PRIORITY, numerically


def replay_trace(cluster, jobs, gang_chips_fn, defrag=None):
    """Event-driven replay of ``jobs`` through ``cluster``; shared between
    the HiveD run and the strawman so the comparison is apples-to-apples.

    Beyond the headline stats, decomposes where utilization goes:

    - waiting chip-time split by blocking reason — ``capacity`` (fewer free
      chips than the gang needs anywhere: pure queueing, no scheduler can
      help) vs ``packing`` (enough free chips exist but the gang could not
      be placed: shape/quota/fragmentation — the part a scheduler owns);
    - ``wasted`` chip-time: work preempted gangs had accrued when killed
      (they produce no completed job, but occupied chips);
    - offered load, for reading utilization against what arrived.

    With a :class:`TraceDefrag` adapter (``HIVED_DEFRAG`` on), the replay
    additionally drives the defrag subsystem the way the runtime executor
    would:

    - a *packing*-blocked waiter first gets a **migration** plan (probe-
      validated relocation of same-VC guaranteed gangs when its quota is
      fragmented, of opportunistic gangs when it is opportunistic); an
      executed move charges every moved gang ``DOWNTIME`` (checkpoint ->
      re-place -> resume) and the overhead is *subtracted* from busy time
      so utilization never counts restore windows as work;
    - a *quota*-blocked guaranteed waiter is **backfilled**: admitted
      opportunistically into idle capacity (HiveD's beyond-quota
      mechanism — preemptible, so it can never delay a guarantee owner),
      bounded by the quota's estimated free-up time so a near-term start
      is awaited rather than paying two checkpoint round-trips;
    - when its quota frees, a backfilled gang is **promoted** back to its
      guaranteed priority through the same work-preserving machinery;
      if a guarantee owner preempts it first, its accrued work is NOT
      wasted — it re-queues with only its remaining duration (+ restore
      downtime), the bit-exact kill-and-resume contract.

    Wait accounting is journal-backed (ISSUE 11): the replay drives a
    virtual-clock :class:`obs.journal.Journal` exactly the way the live
    runtime does — every block opens/re-attributes a wait interval in the
    shared bucket taxonomy (``vc_quota`` / ``fragmentation`` /
    ``capacity``), every admission closes it — and the per-bucket
    chip-time summed over the journal's closed intervals is ASSERTED equal
    to the ``advance()``-integrated total wait chip-time. The same buckets
    the live server serves at ``/v1/inspect/gangs`` become the
    ``wait_attribution`` shares in the driver artifact.

    Capacity accounting is ledger-backed (ISSUE 14): unless
    ``HIVED_LEDGER=0``, the replay also drives a virtual-clock
    :class:`obs.ledger.CapacityLedger` through the SAME chip-state
    taxonomy the live scheduler serves at ``/v1/inspect/capacity`` — every
    admission turns the gang's real chip coordinates busy
    (guaranteed/opportunistic/backfill), every move reattributes the
    checkpoint downtime into ``migration_downtime``, idle chips carry the
    oldest waiter's diagnosis — and the ledger-derived
    ``utilization_pct`` / wasted / overhead numbers are ASSERTED equal to
    the legacy hand-rolled ``busy_of``/``wasted_chip_time``/
    ``overhead_chip_time`` counters, which stay as the differential
    reference (the ``HIVED_LEDGER=0`` path reports them directly, one
    release behind, mirroring ``HIVED_INCR=0``). The conservation
    invariant (per-state chip-seconds sum to chips x elapsed) is asserted
    via ``chaos.invariants.check_ledger``, and every gang's first wait
    gets a finite wait-ETA forecast (``obs/eta.py``) recorded alongside
    its realized wait.
    """
    import heapq
    import math

    from hivedscheduler_tpu.chaos import invariants as chaos_invariants
    from hivedscheduler_tpu.common import envflags
    from hivedscheduler_tpu.obs import eta as obs_eta
    from hivedscheduler_tpu.obs import journal as obs_journal
    from hivedscheduler_tpu.obs import ledger as obs_ledger

    # virtual-clock journal instance: metrics off (sim durations must not
    # pollute the process registry), interval cap lifted (the assertion
    # below must see every closed interval)
    jr = obs_journal.Journal(capacity=1 << 17, metrics=False,
                             intervals_per_gang=1 << 16)
    jr.enabled = True

    # -- virtual-clock capacity ledger (HIVED_LEDGER=0 = legacy-counters
    # reference path). Chips are the cluster's REAL coordinates, grouped
    # by host so the live per-node lane semantics carry over.
    lg = None
    if envflags.get("HIVED_LEDGER") != "0":
        lg = obs_ledger.CapacityLedger(metrics=False)
        lg.enabled = True
        _hosts = [
            (x, y, z)
            for x in range(0, TRACE_TOPOLOGY[0], TRACE_HOST_SHAPE[0])
            for y in range(0, TRACE_TOPOLOGY[1], TRACE_HOST_SHAPE[1])
            for z in range(0, TRACE_TOPOLOGY[2], TRACE_HOST_SHAPE[2])
        ]
        chip_index = {}
        for origin in _hosts:
            key = "%d-%d-%d" % origin
            lg.register_node(key, len(_host_chip_coords(origin)),
                             chain="sim", at=0.0)
            for i, coord in enumerate(_host_chip_coords(origin)):
                chip_index[coord] = (key, i)
    led_chips = {}   # gang -> {node -> [chip idx]}
    led_dirty = set()
    wasted_led = 0.0
    eta_pending = {}  # gang -> (forecast time, eta_s)
    eta_pairs = []    # (forecast eta_s, realized wait)
    _ETA_RUN_T = 140.0  # expected run in TRACE time units (mean ~140)

    total_chips = TRACE_TOTAL_CHIPS
    clock = 0.0
    events = []  # completion heap: (time, seq, job)
    seq = 0
    waiting = []  # jobs awaiting capacity, FIFO retry on completions
    latencies = []
    waits = []
    preempt_events = 0
    busy_chip_time = 0.0
    last_t = 0.0
    chips_of = {}  # live group name -> chips (preempted gangs leave it)
    busy_of = {}  # group name -> chip-time accrued while allocated
    scheduled = 0
    contiguous = 0
    inflations = []
    wait_chip_time = {"capacity": 0.0, "packing": 0.0}
    wasted_chip_time = 0.0
    # snapshot before the replay: defrag-mode rescues rewrite a preempted
    # job's duration to its checkpointed remainder, and offered load means
    # what ARRIVED, not what was re-run
    offered = sum(j["pods"] * j["chips"] * j["duration"] for j in jobs)
    # -- defrag-mode state (untouched when defrag is None) -----------------
    job_by_name = {j["name"]: j for j in jobs}
    entry_gen = {}  # heap seq -> job generation at push (stale-entry filter)
    completes_at = {}  # live group name -> its current completion time

    def led_flavor(name):
        """The ledger busy state a gang's chips carry right now (the sim
        mirror of the runtime's hint_flavor/busy_state)."""
        if defrag is not None and name in defrag.downgraded:
            return "busy_backfill"
        return ("busy_guaranteed" if job_by_name[name]["priority"] >= 0
                else "busy_opportunistic")

    def ledger_sync(at):
        """Reconcile the virtual ledger with the cluster at ``at`` (the
        time the changes actually happened — the previous event's clock):
        release dead gangs' chips, (re)place dirty gangs' chips at their
        current flavor, refresh the idle diagnosis from the oldest
        waiter. Diff-based per node so an unchanged chip's interval just
        continues."""
        if lg is None:
            return
        # two phases: ALL releases first, then all claims — within one
        # event a mover's vacated chips are often the waiter's new slice,
        # and a stale release after the claim would clobber the new owner
        claims = []
        for name in [n for n in led_chips if n not in cluster.groups]:
            for node, idxs in led_chips.pop(name).items():
                lg.release(node, idxs, at=at)
        for name in led_dirty:
            if name not in cluster.groups:
                continue
            new_map = {}
            for coord in gang_chips_fn(cluster, name):
                node, i = chip_index[coord]
                new_map.setdefault(node, []).append(i)
            old_map = led_chips.get(name, {})
            for node, idxs in old_map.items():
                keep = set(new_map.get(node, ()))
                gone = [i for i in idxs if i not in keep]
                if gone:
                    lg.release(node, gone, at=at)
            claims.append((name, new_map))
        for name, new_map in claims:
            flavor = led_flavor(name)
            vc = job_by_name[name]["vc"]
            for node, idxs in new_map.items():
                lg.transition(node, idxs, flavor, vc=vc, gang=name, at=at)
            led_chips[name] = new_map
        led_dirty.clear()
        if waiting:
            from hivedscheduler_tpu.obs.ledger import IDLE_STATE_FOR_BUCKET
            diag = IDLE_STATE_FOR_BUCKET.get(wait_bucket(waiting[0]),
                                             "idle_free")
        else:
            diag = "idle_free"
        lg.set_idle_diagnosis(diag, at=at)

    def advance(to):
        nonlocal busy_chip_time, last_t
        # ledger first: everything that changed since the previous event
        # happened AT that event's clock (== last_t)
        ledger_sync(last_t)
        # busy = currently allocated gangs only (a preempted gang stops
        # counting the moment its cells are freed)
        dt = to - last_t
        for name in cluster.groups:
            c = chips_of.get(name, 0)
            busy_chip_time += c * dt
            busy_of[name] = busy_of.get(name, 0.0) + c * dt
        for w in waiting:
            wait_chip_time[w["block_reason"]] += w["pods"] * w["chips"] * dt
        last_t = to

    def push_completion(job, at):
        nonlocal seq
        seq += 1
        if defrag is not None:
            entry_gen[seq] = job.get("gen", 0)
            completes_at[job["name"]] = at
        heapq.heappush(events, (at, seq, job))

    def wait_bucket(job):
        """The journal attribution bucket for a blocked job — the sim-side
        mirror of obs.journal.classify_wait: global shortfall is pure
        queueing (`capacity`); a guaranteed gang whose VC quota has no room
        is `vc_quota` stranding; everything else that has the chips but no
        placement is `fragmentation`."""
        if job["block_reason"] == "capacity":
            return "capacity"
        if (defrag is not None and job["priority"] >= 0
                and guar_quota_free(job["vc"])
                < job["pods"] * job["chips"]):
            return "vc_quota"
        return "fragmentation"

    def register_success(job, dt):
        nonlocal scheduled, contiguous
        jr.note_phase(job["name"], "running", "bind", at=clock)
        led_dirty.add(job["name"])
        if job["name"] in eta_pending and not job.get("_admitted"):
            # score the wait-ETA forecast against the realized wait
            t_fc, eta_s = eta_pending.pop(job["name"])
            eta_pairs.append((eta_s, clock - t_fc))
        if not job.get("_admitted"):
            # stats count each job once; a work-preserving re-admission
            # (defrag mode) is a resume, not a new schedule
            latencies.append(dt)
            waits.append(clock - job["arrival"])
            scheduled += 1
            job["_admitted"] = True
            is_contig, infl = _gang_geometry(
                gang_chips_fn(cluster, job["name"]))
            contiguous += 1 if is_contig else 0
            inflations.append(infl)
            if defrag is not None:
                job["_geom"] = (is_contig, infl)
        chips_of[job["name"]] = job["pods"] * job["chips"]
        push_completion(job, clock + job["duration"])

    def free_chips():
        return total_chips - sum(
            chips_of.get(name, 0) for name in cluster.groups
        )

    def try_schedule(job):
        nonlocal preempt_events
        ok, dt, preempted = cluster.schedule_gang(
            job["vc"], job["priority"], job["name"], job["pods"], job["chips"],
            allow_preempt=job["priority"] >= 0,
        )
        # victims die even when the preemptor ultimately fails to place
        preempt_events += 1 if preempted else 0
        if defrag is not None and preempted:
            rescue_preempted_downgrades()
        if not ok:
            free = free_chips()
            job["block_reason"] = (
                "capacity" if free < job["pods"] * job["chips"] else "packing"
            )
            if (defrag is not None and job["block_reason"] == "packing"
                    and attempt_defrag(job)):
                return True
            jr.note_wait(job["name"], wait_bucket(job), at=clock)
            if lg is not None and job["name"] not in eta_pending \
                    and not job.get("_admitted"):
                # first wait of this job: forecast capacity-without-a-move
                # from the ledger's running-gang ages (finite by contract)
                f = obs_eta.estimate(
                    job["name"], job["pods"] * job["chips"],
                    idle_chips=free_chips(),
                    running=lg.running_gangs(at=clock),
                    completed_durations=lg.completed_durations(),
                    default_run_s=_ETA_RUN_T)
                assert math.isfinite(f.eta_s), (
                    f"wait-ETA forecast for {job['name']} is not finite")
                obs_eta.record(f, jr=jr, at=clock)
                eta_pending[job["name"]] = (clock, f.eta_s)
            return False
        register_success(job, dt)
        return True

    # -- defrag-mode mechanics (every closure below is only reachable with
    # a TraceDefrag adapter; the legacy path never enters them) ------------

    def guar_quota_free(vc):
        used = sum(
            chips_of.get(name, 0) for name in cluster.groups
            if job_by_name[name]["vc"] == vc
            and job_by_name[name]["priority"] >= 0
            and name not in defrag.downgraded
        )
        return defrag.quota[vc] - used

    def quota_eta(vc, need):
        """When will ``vc``'s guaranteed quota have ``need`` chips free?
        Scan pending completions of its guaranteed (non-downgraded) gangs
        in time order. None = not within the current horizon."""
        acc = guar_quota_free(vc)
        if acc >= need:
            return clock
        for at, s, job in sorted(events):
            if entry_gen.get(s) != job.get("gen", 0):
                continue  # stale entry (migrated/preempted/promoted)
            if (job["vc"] == vc and job["priority"] >= 0
                    and job["name"] in cluster.groups
                    and job["name"] not in defrag.downgraded):
                acc += job["pods"] * job["chips"]
                if acc >= need:
                    return at
        return None

    def charge_move(name):
        """A moved gang pays the checkpoint->restore downtime: completion
        slips by DOWNTIME and the overhead never counts as useful work."""
        job = job_by_name[name]
        job["gen"] = job.get("gen", 0) + 1
        push_completion(job, completes_at[name] + defrag.DOWNTIME)
        defrag.overhead_chip_time += (
            defrag.DOWNTIME * job["pods"] * job["chips"])
        defrag.migrated_chips += job["pods"] * job["chips"]
        led_charge_downtime(name)
        led_dirty.add(name)

    def led_charge_downtime(name):
        """Ledger mirror of the downtime charge: move DOWNTIME x chips
        out of the gang's busy bucket into migration_downtime (total
        conserved; paid by the gang's extended occupancy)."""
        if lg is None:
            return
        job = job_by_name[name]
        lg.reattribute(defrag.DOWNTIME * job["pods"] * job["chips"],
                       (led_flavor(name), job["vc"], "sim"),
                       ("migration_downtime", job["vc"], "sim"))

    def execute_migration(plan, waiter_job, t0):
        """Replay the probe-validated sequence for real: evict movers,
        place the waiter, re-place each mover (deterministic: same state,
        same order as the probe)."""
        moved = [(m.group.name, m.group.spec) for m in plan.moves]
        for name, _spec in moved:
            cluster.free_gang(name)
        ok, _, _ = cluster.schedule_gang(
            waiter_job["vc"], waiter_job["priority"], waiter_job["name"],
            waiter_job["pods"], waiter_job["chips"])
        if not ok:  # pragma: no cover - probe guarantees feasibility
            for name, spec in moved:
                job = job_by_name[name]
                cluster.schedule_gang(job["vc"], spec.priority, name,
                                      job["pods"], job["chips"])
            defrag.reject("execute-drift")
            return False
        for name, spec in moved:
            job = job_by_name[name]
            ok2, _, _ = cluster.schedule_gang(
                job["vc"], spec.priority, name, job["pods"], job["chips"])
            assert ok2, f"mover {name} unplaceable after probe said placeable"
            charge_move(name)
            geom_update(name)
        defrag.migrations += 1
        register_success(waiter_job, time.perf_counter() - t0)
        return True

    def geom_update(name):
        """A moved gang's final geometry replaces its admission-time sample
        (the placement-quality stats describe where gangs actually ran)."""
        nonlocal contiguous
        job = job_by_name[name]
        if not job.get("_admitted"):
            return
        was_contig, was_infl = job.get("_geom", (None, None))
        is_contig, infl = _gang_geometry(gang_chips_fn(cluster, name))
        job["_geom"] = (is_contig, infl)
        if was_contig is not None:
            contiguous += (1 if is_contig else 0) - (1 if was_contig else 0)
            inflations[inflations.index(was_infl)] = infl

    def attempt_defrag(job):
        """The runtime policy ladder for a packing-blocked gang:
        migration if its blocker is fragmentation, opportunistic backfill
        if it is quota stranding."""
        t0 = time.perf_counter()
        need = job["pods"] * job["chips"]
        running = defrag.running_groups(job_by_name)
        if job["priority"] >= 0:
            qfree = guar_quota_free(job["vc"])
            if qfree >= need:
                plan = defrag.planner.plan_migration(
                    defrag.probe, defrag.spec_of(job), running,
                    free_chips=qfree)
                if hasattr(plan, "moves"):
                    return execute_migration(plan, job, t0)
                defrag.reject(plan.reason)
            if defrag.backfill_on and free_chips() >= need:
                # quota-stranded: ride other VCs' idle guarantees
                # opportunistically — unless the quota frees sooner than a
                # promote round-trip would cost
                eta = quota_eta(job["vc"], need)
                if eta is not None and eta - clock <= 2 * defrag.DOWNTIME:
                    defrag.reject("quota-frees-soon")
                    return False
                ok, dt, _ = cluster.schedule_gang(
                    job["vc"], OPPORTUNISTIC, job["name"],
                    job["pods"], job["chips"])
                if ok:
                    is_contig, _ = _gang_geometry(
                        gang_chips_fn(cluster, job["name"]))
                    if not is_contig:
                        # a scattered slice cannot ride ICI: a backfill
                        # that degrades the placement is worse than the
                        # wait it saves
                        cluster.free_gang(job["name"])
                        defrag.reject("backfill-noncontiguous")
                        return False
                    defrag.downgraded[job["name"]] = job["priority"]
                    defrag.backfills += 1
                    register_success(job, time.perf_counter() - t0)
                    return True
                defrag.reject("backfill-unplaceable")
            return False
        plan = defrag.planner.plan_migration(
            defrag.probe, defrag.spec_of(job), running,
            free_chips=free_chips())
        if hasattr(plan, "moves"):
            return execute_migration(plan, job, t0)
        defrag.reject(plan.reason)
        return False

    def rescue_preempted_downgrades():
        """Work-preserving preemption: every preempted gang (backfilled or
        natively opportunistic) checkpointed on SIGTERM — it re-queues with
        its remaining duration plus restore downtime instead of counting
        its accrued work wasted (the PR 3 bit-exact kill-and-resume
        contract, which the defrag subsystem turns into policy)."""
        for name in [n for n in completes_at if n not in cluster.groups]:
            job = job_by_name[name]
            led_charge_downtime(name)  # flavor read before the downgrade pop
            defrag.downgraded.pop(name, None)
            job["gen"] = job.get("gen", 0) + 1
            remaining = max(0.0, completes_at.pop(name, clock) - clock)
            job["duration"] = remaining + defrag.DOWNTIME
            defrag.overhead_chip_time += (
                defrag.DOWNTIME * job["pods"] * job["chips"])
            chips_of.pop(name, None)
            job["block_reason"] = (
                "capacity" if free_chips() < job["pods"] * job["chips"]
                else "packing"
            )
            jr.note_wait(name, wait_bucket(job), at=clock)
            waiting.append(job)

    def try_promotions():
        """Quota freed: promote backfilled gangs (oldest first) back to
        their guaranteed priority through the work-preserving machinery."""
        for name in sorted(defrag.downgraded,
                           key=lambda n: job_by_name[n]["arrival"]):
            job = job_by_name[name]
            if guar_quota_free(job["vc"]) < job["pods"] * job["chips"]:
                continue
            group = next(g for g in defrag.running_groups(job_by_name)
                         if g.name == name)
            plan = defrag.planner.plan_promotion(
                defrag.probe, group, defrag.downgraded[name])
            if not hasattr(plan, "moves"):
                defrag.reject("promotion-" + plan.reason)
                continue
            cluster.free_gang(name)
            ok, _, _ = cluster.schedule_gang(
                job["vc"], defrag.downgraded[name], name,
                job["pods"], job["chips"])
            assert ok, f"promotion of {name} failed after probe said placeable"
            # downtime charged BEFORE the downgrade record drops so the
            # ledger reattributes out of busy_backfill (where the gang's
            # past accrual sits), then the flavor flips to guaranteed
            charge_move(name)
            defrag.downgraded.pop(name)
            geom_update(name)
            defrag.promotions += 1

    arrival_i = 0
    while arrival_i < len(jobs) or events:
        next_arrival = jobs[arrival_i]["arrival"] if arrival_i < len(jobs) else float("inf")
        next_done = events[0][0] if events else float("inf")
        if next_arrival <= next_done:
            advance(next_arrival)
            clock = next_arrival
            job = jobs[arrival_i]
            arrival_i += 1
            if not try_schedule(job):
                waiting.append(job)
        else:
            advance(next_done)
            clock = next_done
            _, entry_seq, job = heapq.heappop(events)
            if defrag is not None and entry_gen.pop(entry_seq, 0) != job.get(
                    "gen", 0):
                continue  # stale completion: the gang migrated or re-queued
            if job["name"] in cluster.groups:
                cluster.free_gang(job["name"])
            else:
                # preempted away mid-run: everything it accrued is wasted
                wasted_chip_time += busy_of.get(job["name"], 0.0)
                if lg is not None:
                    wasted_led += sum(
                        lg.gang_seconds(job["name"]).values())
            jr.note_phase(job["name"], "closed", "released", at=clock)
            chips_of.pop(job["name"], None)
            if defrag is not None:
                completes_at.pop(job["name"], None)
                defrag.downgraded.pop(job["name"], None)
                try_promotions()
            # retry FIFO waiters
            still = []
            for w in waiting:
                if not try_schedule(w):
                    still.append(w)
            waiting = still
    lat_ms = sorted(x * 1000.0 for x in latencies)
    p50 = statistics.median(lat_ms) if lat_ms else 0.0
    p99 = lat_ms[max(0, int(len(lat_ms) * 0.99) - 1)] if lat_ms else 0.0
    span = last_t * total_chips
    total_wait = sum(wait_chip_time.values())
    # -- journal-backed wait attribution (ISSUE 11) ------------------------
    # Sum per-bucket chip-time over the journal's closed wait intervals and
    # ASSERT it equals the advance()-integrated total: the attribution the
    # live server serves is pinned to the accounting the bench reports.
    jr.close_all(last_t)
    journal_wait = {}
    for gang, bucket, start, end in jr.wait_intervals():
        j = job_by_name[gang]
        journal_wait[bucket] = (journal_wait.get(bucket, 0.0)
                                + (end - start) * j["pods"] * j["chips"])
    attributed = sum(journal_wait.values())
    assert abs(attributed - total_wait) <= 1e-6 * max(1.0, total_wait), (
        f"journal wait-attribution buckets sum to {attributed} chip-time "
        f"but the replay integrated {total_wait} — an interval was lost or "
        f"double-opened"
    )
    useful_chip_time = busy_chip_time
    if defrag is not None:
        # restore windows occupy chips but are not work
        useful_chip_time -= defrag.overhead_chip_time
    # -- ledger-backed capacity attribution (ISSUE 14) ---------------------
    # Close the virtual ledger, assert the conservation invariant, and PIN
    # the ledger-derived busy/wasted/overhead numbers to the legacy
    # hand-rolled counters — the differential that lets the ledger's
    # numbers be the reported ones while the old counters stay one
    # release behind as the HIVED_LEDGER=0 reference path.
    capacity_attribution = None
    ledger_gap = None
    eta_fields = None
    if lg is not None:
        ledger_sync(last_t)
        lg.settle(last_t)
        chaos_invariants.check_ledger(ledger=lg, ctx="bench replay",
                                      at=last_t)
        led_totals = lg.totals(last_t)
        by_state = {}
        for (state, _vc, _chain), secs in led_totals.items():
            by_state[state] = by_state.get(state, 0.0) + secs
        led_busy = sum(by_state.get(s, 0.0) for s in (
            "busy_guaranteed", "busy_opportunistic", "busy_backfill"))
        led_overhead = by_state.get("migration_downtime", 0.0)
        tol = 1e-6 * max(1.0, span)
        assert abs(led_busy - useful_chip_time) <= tol, (
            f"ledger busy chip-time {led_busy} != legacy useful "
            f"{useful_chip_time} — the chip-state books drifted from the "
            f"hand-rolled counters")
        assert abs(wasted_led - wasted_chip_time) <= tol, (
            f"ledger wasted chip-time {wasted_led} != legacy "
            f"{wasted_chip_time}")
        legacy_overhead = (defrag.overhead_chip_time
                           if defrag is not None else 0.0)
        assert abs(led_overhead - legacy_overhead) <= tol, (
            f"ledger migration_downtime {led_overhead} != legacy "
            f"overhead {legacy_overhead}")
        # ledger numbers become the reported ones (asserted equal above)
        useful_chip_time = led_busy
        wasted_chip_time = wasted_led
        capacity_attribution = {
            s: round(v / span, 4) for s, v in sorted(by_state.items())
            if span and v > 0
        }
        ledger_gap = round(lg.conservation_gap(last_t), 6)
        abs_errs = [abs(e - r) for e, r in eta_pairs]
        errs = [e - r for e, r in eta_pairs]
        eta_fields = {
            "forecasts": len(eta_pairs) + len(eta_pending),
            "scored": len(eta_pairs),
            # unresolved = forecast issued but the gang never admitted
            # before the trace ended (no realized wait to score against)
            "unresolved": len(eta_pending),
            "mean_abs_err_t": round(
                sum(abs_errs) / len(abs_errs), 2) if abs_errs else None,
            "mean_err_t": round(
                sum(errs) / len(errs), 2) if errs else None,
        }
    out = {
        "jobs": len(jobs),
        "scheduled": scheduled,
        "preemption_events": preempt_events,
        "sched_p50_ms": round(p50, 3),
        "sched_p99_ms": round(p99, 3),
        "wait_p50_t": round(statistics.median(waits), 2) if waits else 0.0,
        "utilization_pct": round(100.0 * useful_chip_time / span, 1)
        if span else 0.0,
        # -- the decomposition + placement-quality fields ------------------
        "offered_pct": round(100.0 * offered / span, 1) if span else 0.0,
        "contiguous_pct": round(100.0 * contiguous / max(1, scheduled), 1),
        "bbox_inflation": round(
            statistics.mean(inflations), 3) if inflations else None,
        "wait_chip_time_pct": round(100.0 * total_wait / span, 1)
        if span else 0.0,
        "wait_capacity_share": round(
            wait_chip_time["capacity"] / total_wait, 3) if total_wait else 0.0,
        "wait_packing_share": round(
            wait_chip_time["packing"] / total_wait, 3) if total_wait else 0.0,
        # the journal's finer buckets (vc_quota vs fragmentation split of
        # the old "packing"), shares of total wait chip-time
        "wait_attribution": {
            b: round(v / total_wait, 3)
            for b, v in sorted(journal_wait.items())
        } if total_wait else {},
        "preempt_wasted_pct": round(100.0 * wasted_chip_time / span, 1)
        if span else 0.0,
    }
    if capacity_attribution is not None:
        # per-state shares of chips x elapsed (obs/ledger.py CHIP_STATES);
        # conservation gap is the bench-artifact half of check_ledger
        out["capacity_attribution"] = capacity_attribution
        out["ledger_conservation_gap"] = ledger_gap
        out["eta"] = eta_fields
    if defrag is not None:
        out.update({
            "migrations": defrag.migrations,
            "promotions": defrag.promotions,
            "backfills": defrag.backfills,
            "migrated_chips": defrag.migrated_chips,
            "migration_overhead_pct": round(
                100.0 * defrag.overhead_chip_time / span, 2) if span else 0.0,
            "planner_rejections": dict(sorted(defrag.rejections.items())),
        })
    return out


def run_trace(n_jobs: int = 300, seed: int = 11, baseline: bool = False):
    """Trace-driven evaluation in the style of HiveD's OSDI'20 methodology
    (the paper evaluates on a production trace; the repo ships none, so this
    replays a deterministic synthetic multi-tenant trace). Run:
    ``python bench.py --trace``.

    Event-driven simulation on the v5p-1024 cluster: jobs arrive over virtual
    time with exponential inter-arrivals, sized from a mixed gang
    distribution, split across three VCs with guaranteed and opportunistic
    priorities; completions free their gangs; guaranteed jobs may preempt
    opportunistic ones. Reports scheduling-latency percentiles (wall-clock of
    the real algorithm), queueing stats, preemption counts, chip utilization,
    ICI-contiguity of every placement, and the utilization-gap decomposition
    (see replay_trace). ``baseline=True`` replays the SAME trace through the
    topology-unaware NaiveCluster strawman instead.
    """
    # the algorithm's internal victim selection draws from the global
    # random module (one-random-node victims); seed it so the driver
    # artifact's trace fields are run-to-run deterministic
    random.seed(seed)
    jobs = make_trace_jobs(n_jobs, seed)
    if baseline:
        return replay_trace(NaiveCluster(), jobs, naive_gang_chips)
    from hivedscheduler_tpu.defrag import defrag_enabled

    cluster = Cluster()
    # HIVED_DEFRAG=0 runs exactly the pre-defrag replay statements — the
    # kill-switch differential (guard: tests/test_defrag.py)
    adapter = TraceDefrag(cluster) if defrag_enabled() else None
    return replay_trace(cluster, jobs, hived_gang_chips, defrag=adapter)


def parse_model_bench_output(returncode: int, stdout: str, stderr: str):
    """Pure parse of a bench_model.py child run -> (artifact_fields,
    stamped_result_or_None). The round-3 driver failure (bare "rc=1", all
    diagnostics discarded) lived exactly here, so this is a plain function
    with its own tests:

    - the last JSON *dict* line of stdout is the result (stray scalar JSON
      lines are skipped);
    - any nonzero rc or an ``error`` field degrades to a
      ``model_bench_error`` note carrying the child's own message plus a
      stderr tail — never the headline;
    - a ``*_smoke`` metric (the child saw no TPU) contributes nothing and
      must never overwrite the durable BENCH_MODEL.json — the second
      return value is non-None only for a real TPU result."""
    last_json = None
    for line in reversed(stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            last_json = parsed
            break
    if returncode != 0 or last_json is None or last_json.get("error"):
        note = {"model_bench_error": f"rc={returncode}"}
        if last_json is not None and last_json.get("error"):
            note["model_bench_error"] = last_json["error"]
        tail = stderr.strip()[-600:]
        if tail:
            note["model_bench_stderr_tail"] = tail
        return note, None
    m = last_json
    if m.get("metric", "").endswith("_smoke"):
        return {}, None
    required = ("value", "train_tokens_per_sec", "decode_tokens_per_sec",
                "decode_hbm_roofline_frac", "device", "metric")
    missing = [k for k in required if k not in m]
    if missing:
        # a well-formed dict that isn't a result line still degrades to a
        # note carrying the child's actual output, never an exception
        return {
            "model_bench_error": (
                f"child result missing keys {missing}: {json.dumps(m)[:400]}"
            ),
        }, None
    fields = {
        "model_train_mfu_pct": m["value"],
        "model_train_tokens_per_sec": m["train_tokens_per_sec"],
        "model_decode_tokens_per_sec": m["decode_tokens_per_sec"],
        "model_decode_hbm_roofline_frac": m["decode_hbm_roofline_frac"],
        "model_serve_tokens_per_sec": m.get("serve_tokens_per_sec"),
        "model_serve_occupancy": m.get("serve_occupancy"),
        # serving bars (BASELINE.md): pass/fail travels with the numbers
        "model_decode_roofline_pass": m.get("decode_roofline_pass"),
        "model_serve_slot_efficiency": m.get("serve_slot_efficiency"),
        "model_serve_slot_efficiency_pass": m.get("serve_slot_efficiency_pass"),
        "model_serve_prefix_speedup": m.get("serve_prefix_speedup"),
        "model_serve_prefix_ttft_speedup": m.get("serve_prefix_ttft_speedup"),
        "model_serve_kv_int8_speedup": m.get("serve_kv_int8_speedup"),
        "model_device": m["device"],
        "model_metric_note": m["metric"],
    }
    # per-stage degradation notes (bench_model isolates decode/serve
    # failures so the train MFU survives): a null decode/serve field must
    # arrive explained, not silently absent
    for k in ("decode_error", "serve_error", "serve_prefix_error",
              "serve_kv_int8_error"):
        if m.get(k):
            fields[f"model_{k}"] = m[k]
    stamped = dict(m)
    stamped["captured_at_utc"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
    )
    stamped["captured_by"] = "bench.py driver path"
    return fields, stamped


def model_bench_fields():
    """Fold the workload benchmark (bench_model.py) into the driver's
    one-line artifact when a real TPU is attached: the scheduler p50 stays
    the headline metric, the train-MFU / decode / serving numbers ride
    along as extra fields; see ``parse_model_bench_output`` for the
    degradation contract.

    Deliberately NO subprocess timeout: killing the child mid-TPU-op wedges
    the single-grant axon tunnel for every later process. The child bounds
    its own TPU acquisition instead (bench_model.acquire_backend,
    HIVED_TPU_ACQUIRE_TIMEOUT_S; rc=3 tunnel-busy, rc=4 backend-down, each
    with a diagnostic JSON line)."""
    import os
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "bench_model.py", "--iters", "5"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        fields, stamped = parse_model_bench_output(
            proc.returncode, proc.stdout, proc.stderr
        )
        if stamped is not None:
            # refresh the durable artifact so a stale builder-local number
            # can never stand in for a driver-captured one
            try:
                path = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_MODEL.json",
                )
                with open(path, "w") as f:
                    f.write(json.dumps(stamped) + "\n")
            except OSError:
                pass  # read-only checkout: the inline fields still land
        return fields
    except Exception as e:  # pragma: no cover - defensive
        return {"model_bench_error": f"{type(e).__name__}: {e}"}


if __name__ == "__main__":
    import os
    import sys

    if "--trace" in sys.argv:
        stats = run_trace()
        naive = run_trace(baseline=True)
        print(json.dumps({
            "metric": "trace_sched_p50_ms_v5p1024",
            "value": stats["sched_p50_ms"], "unit": "ms",
            "vs_baseline": round(50.0 / stats["sched_p50_ms"], 3)
            if stats["sched_p50_ms"] else None,
            **stats,
            **{f"naive_{k}": v for k, v in naive.items()},
        }))
        sys.exit(0)
    if "--recovery" in sys.argv:
        rec_ms, n_pods, n_groups, preserved = run_recovery()
        print(json.dumps({
            "metric": "recovery_barrier_ms_v5p1024",
            "value": round(rec_ms, 3), "unit": "ms",
            "vs_baseline": None,
            "allocated_pods": n_pods, "groups": n_groups,
            "placement_preserved_pct": round(preserved, 2),
        }))
        sys.exit(0)
    if "--scale-4096" in sys.argv:
        p50, mx = run_scale_4096()
        print(json.dumps({
            "metric": "p50_gang_schedule_latency_1024chip_slice_v5p4096",
            "value": round(p50, 3), "unit": "ms",
            "vs_baseline": round(50.0 / p50, 3) if p50 > 0 else None,
            # max over 8 trials — honestly labelled (a p99 needs more samples)
            "max_ms": round(mx, 3),
        }))
        sys.exit(0)
    if "--scale-16384" in sys.argv:
        p50, mx = run_scale_16384()
        print(json.dumps({
            "metric": "p50_gang_schedule_latency_4096chip_slice_v5p16384",
            "value": round(p50, 3), "unit": "ms",
            "vs_baseline": round(50.0 / p50, 3) if p50 > 0 else None,
            "max_ms": round(mx, 3),
        }))
        sys.exit(0)
    if "--churn-16k" in sys.argv:
        print(json.dumps({
            "metric": "sustained_churn_schedules_per_sec_v5p16384",
            "unit": "schedules/s",
            "vs_baseline": None,
            **run_churn_16k(),
        }))
        sys.exit(0)
    # Probe for a TPU via env only: importing jax here would acquire the
    # single-grant TPU in THIS process and starve the bench_model child of
    # it (the axon tunnel grants one client at a time). The driver/axon env
    # sets JAX_PLATFORMS=axon; explicit cpu (CI) skips the child.
    platforms = os.environ.get("JAX_PLATFORMS", "")
    model_fields = {}
    if "--no-model" not in sys.argv and platforms and "cpu" not in platforms:
        model_fields = model_bench_fields()  # {} when the child saw no TPU

    def aux_stage_fields():
        """Driver-captured numbers for the round-3/4 scheduler work (VERDICT
        round 3 item 5): the v5p-4096 mesh-direct search scale figure, the
        chip-granular recovery barrier, and the synthetic-trace replay each
        run in ~3 s, so they ride along in the one-line artifact instead of
        living only as CI ceilings."""
        fields = {}
        try:
            s_p50, s_max = run_scale_4096()
            fields.update(scale4096_p50_ms=round(s_p50, 3),
                          scale4096_max_ms=round(s_max, 3))
        except Exception as e:  # pragma: no cover - defensive
            fields["scale4096_error"] = f"{type(e).__name__}: {e}"
        try:
            s_p50, s_max = run_scale_16384()
            fields.update(scale16384_p50_ms=round(s_p50, 3),
                          scale16384_max_ms=round(s_max, 3))
        except Exception as e:  # pragma: no cover - defensive
            fields["scale16384_error"] = f"{type(e).__name__}: {e}"
        try:
            # the sustained-churn headline: raw scheduler speed at 16k
            # chips with defrag/elastic/journal/ledger ON, plus the
            # HIVED_EVENT_BATCH=0 / HIVED_NATIVE=0 parity pins
            fields.update(run_churn_16k())
        except Exception as e:  # pragma: no cover - defensive
            fields["churn16k_error"] = f"{type(e).__name__}: {e}"
        try:
            rec_ms, n_pods, n_groups, preserved = run_recovery()
            fields.update(recovery_ms=round(rec_ms, 3),
                          recovery_pods=n_pods,
                          placement_preserved_pct=round(preserved, 2))
        except Exception as e:  # pragma: no cover - defensive
            fields["recovery_error"] = f"{type(e).__name__}: {e}"
        try:
            t = run_trace()
            fields.update(trace_sched_p50_ms=t["sched_p50_ms"],
                          trace_sched_p99_ms=t["sched_p99_ms"],
                          trace_utilization_pct=t["utilization_pct"],
                          trace_preemption_events=t["preemption_events"],
                          # placement quality + utilization-gap decomposition
                          trace_offered_pct=t["offered_pct"],
                          trace_contiguous_pct=t["contiguous_pct"],
                          trace_bbox_inflation=t["bbox_inflation"],
                          trace_wait_chip_time_pct=t["wait_chip_time_pct"],
                          trace_wait_capacity_share=t["wait_capacity_share"],
                          trace_wait_packing_share=t["wait_packing_share"],
                          trace_wait_attribution=t["wait_attribution"],
                          trace_preempt_wasted_pct=t["preempt_wasted_pct"])
            # defrag/backfill fields (absent under HIVED_DEFRAG=0), and
            # the capacity ledger's attribution + conservation gap + the
            # wait-ETA forecast scoring (absent under HIVED_LEDGER=0)
            for k in ("migrations", "promotions", "backfills",
                      "migrated_chips", "migration_overhead_pct",
                      "capacity_attribution", "ledger_conservation_gap",
                      "eta"):
                if k in t:
                    fields[f"trace_{k}"] = t[k]
        except Exception as e:  # pragma: no cover - defensive
            fields["trace_error"] = f"{type(e).__name__}: {e}"
        try:
            # the OSDI'20-style strawman comparison: same trace, first-fit
            # host scheduler with no buddy hierarchy (NaiveCluster)
            b = run_trace(baseline=True)
            fields.update(
                trace_baseline_contiguous_pct=b["contiguous_pct"],
                trace_baseline_bbox_inflation=b["bbox_inflation"],
                trace_baseline_utilization_pct=b["utilization_pct"],
                trace_baseline_wait_p50_t=b["wait_p50_t"],
                trace_baseline_preemption_events=b["preemption_events"],
            )
        except Exception as e:  # pragma: no cover - defensive
            fields["trace_baseline_error"] = f"{type(e).__name__}: {e}"
        return fields

    p50, p99, frag_pct = run()
    aux_fields = aux_stage_fields()
    baseline_ms = 50.0  # reference deploy's per-pod FIFO blocking tick
    print(
        json.dumps(
            {
                "metric": "p50_gang_schedule_latency_256chip_slice_v5p1024"
                + ("" if frag_pct == 0 else f"_frag{frag_pct:.0f}pct"),
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_ms / p50, 3) if p50 > 0 else None,
                "p50_ms": round(p50, 3),
                "p99_ms": round(p99, 3),
                "frag_pct": round(frag_pct, 3),
                "vs_baseline_note": (
                    "baseline is the reference deploy's 50 ms per-pod FIFO "
                    "blocking knob (example/run/deploy.yaml:50), not a "
                    "measured latency; the reference publishes no numbers"
                ),
                **aux_fields,
                **model_fields,
            }
        )
    )
