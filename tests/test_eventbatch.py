"""Batched watch-event deltas (HIVED_EVENT_BATCH, runtime/eventbatch.py).

Two layers:

- unit tests of the coalescing queue's rules (global FIFO, unbound pod
  add→delete dedup, node-flap folding, bound adds never deduped);
- the churn DIFFERENTIAL: the same seeded churn script — gang schedules,
  preemptions, completions, node flaps, transient pods, defrag ticks —
  driven through two full runtime stacks, one per-event (`=0`, the
  reference) and one batched (`=1`), must produce byte-identical decisions:
  every filter/preempt outcome (placed nodes AND failure strings), every
  bound placement, and the journal event stream, with
  ``check_cluster_views`` / ``check_ledger`` / ``check_defrag`` asserted at
  every step of both runs. Coalescing non-vacuity is asserted (the batched
  run must actually dedup/fold something), so the differential can never
  silently degenerate into comparing two unbatched runs.
"""

import os
import random

import pytest

from hivedscheduler_tpu.api import constants as api_constants
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.chaos import invariants
from hivedscheduler_tpu.chaos.harness import default_config
from hivedscheduler_tpu.common.utils import to_json
from hivedscheduler_tpu.k8s.fake import FakeKubeClient
from hivedscheduler_tpu.k8s.types import Container, Node, NodeCondition, Pod
from hivedscheduler_tpu.obs import journal as obs_journal
from hivedscheduler_tpu.obs import ledger as obs_ledger
from hivedscheduler_tpu.runtime import eventbatch
from hivedscheduler_tpu.runtime import extender as ei
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler

_NOT_READY = [NodeCondition(type="Ready", status="False")]


def _pod(name: str, uid: str, spec: dict, bound: str = "") -> Pod:
    return Pod(
        name=name, uid=uid, node_name=bound,
        annotations={api_constants.ANNOTATION_POD_SCHEDULING_SPEC:
                     to_json(spec)},
        containers=[Container(resource_limits={
            api_constants.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
    )


# ---------------------------------------------------------------------------
# coalescing queue unit tests
# ---------------------------------------------------------------------------

def test_pod_add_delete_dedup_unbound_only():
    q = eventbatch.PendingDeltas()
    spec = {"virtualCluster": "vc", "leafCellNumber": 1,
            "affinityGroup": {"name": "g",
                              "members": [{"podNumber": 1,
                                           "leafCellNumber": 1}]}}
    q.pod_add(_pod("a", "a", spec))
    q.pod_delete(_pod("a", "a", spec))
    assert len(q) == 0 and q.coalesced_pod_pairs == 1
    # a BOUND add (recovery replay) is never deduped: the
    # add_allocated/delete_allocated pair must really apply
    q.pod_add(_pod("b", "b", spec, bound="node-1"))
    q.pod_delete(_pod("b", "b", spec, bound="node-1"))
    assert [e[0] for e in q.drain()] == [eventbatch.POD_ADD,
                                         eventbatch.POD_DELETE]


def test_pod_dedup_blocked_by_intervening_update():
    q = eventbatch.PendingDeltas()
    spec = {"affinityGroup": {"name": "g", "members": []}}
    q.pod_add(_pod("a", "a", spec))
    q.pod_update(_pod("a", "a", spec), _pod("a", "a", spec))
    q.pod_delete(_pod("a", "a", spec))
    # the update is the last pending entry for the uid: conservative, no dedup
    assert [e[0] for e in q.drain()] == [
        eventbatch.POD_ADD, eventbatch.POD_UPDATE, eventbatch.POD_DELETE]


def test_node_flap_folding_and_delete_never_folded():
    q = eventbatch.PendingDeltas()
    healthy, bad = Node(name="n"), Node(name="n", conditions=list(_NOT_READY))
    q.node_update(healthy, bad)
    q.node_update(bad, healthy)
    q.node_update(healthy, bad)
    entries = q.drain()
    # three updates fold to one (first_old, last_new) edge
    assert len(entries) == 1 and entries[0][0] == eventbatch.NODE_UPDATE
    assert entries[0][1] is healthy and entries[0][2] is bad
    assert q.coalesced_node_folds == 2
    # add + update folds into add(latest); a delete is appended verbatim
    q.node_add(healthy)
    q.node_update(healthy, bad)
    q.node_delete(bad)
    kinds = [e[0] for e in q.drain()]
    assert kinds == [eventbatch.NODE_ADD, eventbatch.NODE_DELETE]


def test_global_fifo_across_objects():
    q = eventbatch.PendingDeltas()
    spec = {"affinityGroup": {"name": "g", "members": []}}
    q.pod_add(_pod("a", "a", spec))
    q.node_add(Node(name="n"))
    q.pod_add(_pod("b", "b", spec))
    assert [(e[0]) for e in q.drain()] == [
        eventbatch.POD_ADD, eventbatch.NODE_ADD, eventbatch.POD_ADD]


# ---------------------------------------------------------------------------
# the churn differential: =0 vs =1 decision-identical
# ---------------------------------------------------------------------------

_SHAPES = [(1, 1), (1, 2), (1, 4), (2, 4), (4, 4), (2, 8)]


class _Churn:
    """One deterministic churn run at a given batch mode; every decision
    outcome is appended to ``self.log`` (the pinned artifact)."""

    def __init__(self, seed: int, batch: bool, steps: int):
        self.rng = random.Random(seed)
        # the algorithm's victim selection draws from the GLOBAL random
        # module (see bench.run_trace): both runs must consume the same
        # stream or the differential diffs on victim choice, not batching
        random.seed(seed)
        self.steps = steps
        self.log = []
        os.environ["HIVED_EVENT_BATCH"] = "1" if batch else "0"
        try:
            obs_journal.enable(capacity=1 << 14)
            obs_ledger.LEDGER.clear()
            obs_ledger.enable()
            self.fake = FakeKubeClient()
            self.sched = HivedScheduler(default_config(), self.fake)
        finally:
            os.environ.pop("HIVED_EVENT_BATCH", None)
        self.algo = self.sched.scheduler_algorithm
        self.nodes = sorted({
            n for ccl in self.algo.full_cell_list.values()
            for c in ccl[max(ccl)] for n in c.nodes
        })
        for n in self.nodes:
            self.fake.create_node(Node(name=n))
        self.sched.start()
        self.bad_nodes = set()
        self.groups = {}
        self.gid = 0

    # -- op vocabulary ---------------------------------------------------

    def op_transient_pod(self):
        """A pod created and deleted inside one batch window: the batched
        path dedups the pair; the reference applies both (both no-ops on
        decisions)."""
        name = f"tr{self.gid}"
        self.gid += 1
        spec = {
            "virtualCluster": "vc-b", "priority": 0,
            "leafCellType": "v5p-chip", "leafCellNumber": 1,
            "affinityGroup": {"name": name,
                              "members": [{"podNumber": 1,
                                           "leafCellNumber": 1}]},
        }
        self.fake.create_pod(_pod(name, name, spec))
        self.fake.delete_pod("default", name)
        self.log.append(("transient", name))

    def op_flap(self, roundtrip: bool):
        n = self.rng.choice(self.nodes)
        if n in self.bad_nodes:
            self.bad_nodes.discard(n)
            self.fake.update_node(Node(name=n))
            self.log.append(("heal", n))
            return
        self.fake.update_node(Node(name=n, conditions=list(_NOT_READY)))
        if roundtrip:
            # NotReady -> Ready inside one window: the batched path folds
            # it into a no-op edge; the reference round-trips bad/healthy
            self.fake.update_node(Node(name=n))
            self.log.append(("flap-roundtrip", n))
        else:
            self.bad_nodes.add(n)
            self.log.append(("flap", n))

    def op_delete_gang(self):
        if not self.groups:
            return
        name = self.rng.choice(sorted(self.groups))
        for p in self.groups.pop(name):
            self.fake.delete_pod("default", p)
        self.log.append(("delete", name))

    # -- cycle driving ---------------------------------------------------

    def _filter(self, pod_name: str):
        pod = self.fake.get_pod("default", pod_name)
        if pod is None:
            return None
        try:
            r = self.sched.filter_routine(ei.ExtenderArgs(
                pod=pod, node_names=list(self.nodes)))
        except api.WebServerError as e:
            self.log.append(("filter-error", pod_name, e.code, str(e)))
            return None
        if r.node_names:
            self.log.append(("filter-bind", pod_name, tuple(r.node_names)))
            return r.node_names[0]
        self.log.append((
            "filter-fail", pod_name,
            tuple(sorted((r.failed_nodes or {}).items()))))
        if r.failed_nodes and any(k != api_constants.COMPONENT_NAME
                                  for k in r.failed_nodes):
            return "PREEMPT"
        return None

    def _preempt(self, pod_name: str) -> bool:
        pod = self.fake.get_pod("default", pod_name)
        if pod is None:
            return False
        r = self.sched.preempt_routine(ei.ExtenderPreemptionArgs(
            pod=pod, node_name_to_meta_victims={n: [] for n in self.nodes}))
        victims = sorted(
            uid for uids in r.node_name_to_meta_victims.values()
            for uid in uids)
        self.log.append(("preempt", pod_name, tuple(victims)))
        if not victims:
            return True
        for gname, gpods in list(self.groups.items()):
            if any(u in victims for u in gpods):
                for p in self.groups.pop(gname):
                    self.fake.delete_pod("default", p)
        return True

    def op_schedule_gang(self):
        rng = self.rng
        vc = rng.choice(["vc-a", "vc-b", "vc-c"])
        prio = rng.choice([-1, -1, 0, 5, 10])
        pods, chips = rng.choice(_SHAPES)
        name = f"g{self.gid}"
        self.gid += 1
        spec = {
            "virtualCluster": vc, "priority": prio,
            "leafCellType": rng.choice(["v5p-chip", "v5p-chip", "v4-chip"]),
            "leafCellNumber": chips,
            "affinityGroup": {"name": name,
                              "members": [{"podNumber": pods,
                                           "leafCellNumber": chips}]},
        }
        created, bound, ok = [], [], True
        for i in range(pods):
            pn = f"{name}-{i}"
            self.fake.create_pod(_pod(pn, pn, spec))
            created.append(pn)
            node = None
            for _attempt in range(8):
                node = self._filter(pn)
                if node != "PREEMPT":
                    break
                if not self._preempt(pn):
                    node = None
                    break
            if node in (None, "PREEMPT"):
                ok = False
                break
            self.sched.bind_routine(ei.ExtenderBindingArgs(
                pod_name=pn, pod_namespace="default", pod_uid=pn, node=node))
            self.log.append(("bound", pn, node))
            bound.append(pn)
        if ok:
            self.groups[name] = bound
        else:
            for pn in created:
                self.fake.delete_pod("default", pn)
            self.log.append(("rollback", name))

    def _check(self, ctx: str):
        with self.sched.scheduler_lock:
            invariants.check_cluster_views(self.algo, ctx)
            invariants.check_ledger(ctx=ctx)
            invariants.check_defrag(self.sched, ctx)

    def run(self):
        for step in range(self.steps):
            # mutation window: events pile up with no cycle in between, so
            # the batched path actually coalesces
            for _ in range(self.rng.randint(0, 2)):
                op = self.rng.choice(
                    ["transient", "flap", "flap-roundtrip", "delete"])
                if op == "transient":
                    self.op_transient_pod()
                elif op == "flap":
                    self.op_flap(roundtrip=False)
                elif op == "flap-roundtrip":
                    self.op_flap(roundtrip=True)
                else:
                    self.op_delete_gang()
            self.op_schedule_gang()
            if step % 3 == 2:
                tick = self.sched.defrag_tick()
                self.log.append((
                    "tick",
                    None if tick.get("planned") is None
                    else sorted(tick["planned"].get("moves", [])),
                    None if not tick.get("elasticOffer")
                    else tick["elasticOffer"]["group"],
                ))
            self._check(f"step {step}")
        self.sched.flush_events()
        self._check("final")
        # final ground truth: every bound pod's node from the ApiServer
        placements = {
            p.key: p.node_name for p in self.fake.list_pods() if p.node_name
        }
        journal = [(e.type, e.gang, e.bucket)
                   for e in obs_journal.JOURNAL.snapshot()]
        pending = self.sched._pending
        stats = (0, 0) if pending is None else (
            pending.coalesced_pod_pairs, pending.coalesced_node_folds)
        obs_journal.disable()
        obs_ledger.disable()
        return {"log": self.log, "placements": placements,
                "journal": journal}, stats


def _diff_one_seed(seed: int, steps: int):
    ref, _ = _Churn(seed, batch=False, steps=steps).run()
    fast, stats = _Churn(seed, batch=True, steps=steps).run()
    assert ref["placements"] == fast["placements"], seed
    assert ref["journal"] == fast["journal"], seed
    assert ref["log"] == fast["log"], seed
    return stats


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_churn_differential_batched_vs_reference(seed):
    """HIVED_EVENT_BATCH=0 vs =1: identical filter/preempt outcomes
    (placed nodes and failure strings), bound placements and journal
    events over a seeded churn, with cluster-view/ledger/defrag invariants
    green at every step of both runs."""
    _diff_one_seed(seed, steps=12)


@pytest.mark.slow
def test_churn_differential_long():
    """Longer soak cousin of the tier-1 differential above (same script,
    more steps + seeds); tier-1 keeps the 3-seed short runs."""
    pairs = folds = 0
    for seed in range(5):
        p, f = _diff_one_seed(100 + seed, steps=30)
        pairs += p
        folds += f
    # coalescing non-vacuity: the batched runs really deduped and folded
    assert pairs > 0 and folds > 0, (pairs, folds)


def test_coalescing_non_vacuous_tier1():
    """The tier-1 differential would be vacuous if the batched runs never
    coalesced; pin that the op mix produces both dedups and folds."""
    pairs = folds = 0
    for seed in [0, 1, 2]:
        p, f = _Churn(seed, batch=True, steps=12).run()[1]
        pairs += p
        folds += f
    assert pairs > 0 and folds > 0, (pairs, folds)
