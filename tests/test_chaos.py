"""Chaos soak: the fault-injection harness (hivedscheduler_tpu/chaos/)
attacking the runtime + algorithm stack.

The quick soak is the tier-1 acceptance bar of the chaos PR: >= 25 schedules
across >= 5 seeds under dropped/delayed/reordered watch events, transient
429/500/timeout request errors (including ambiguous bind failures), node
NotReady flaps, mid-gang pod kills and scheduler crash-restarts — with ZERO
invariant violations (VC safety, books, cell ownership, gang atomicity,
chip-granular placement preservation across restart). The long variant
(``-m slow``) runs an order of magnitude more.

Also here: the focused mid-gang crash-restart test (every bound placement
recovered 100% at chip granularity, and the gang completes after restart),
injector-contract unit tests, and the fake-ApiServer leaf-lock assertion
regression test.
"""

import logging
import threading

import pytest

from hivedscheduler_tpu.chaos import (
    ChaosHarness,
    ChaosKubeClient,
    FaultPlan,
    InjectedApiError,
    invariants,
)
from hivedscheduler_tpu.k8s.fake import FakeKubeClient
from hivedscheduler_tpu.k8s.types import Node, Pod


@pytest.fixture(autouse=True)
def _mute_logs():
    logging.disable(logging.CRITICAL)
    yield
    logging.disable(logging.NOTSET)


@pytest.fixture(autouse=True)
def _lockcheck(monkeypatch):
    """Every chaos soak doubles as a race/deadlock detector: the runtime
    lock-order sanitizer (common/lockcheck.py) is active for all harness
    runs in this module — out-of-hierarchy acquisitions and algorithm
    mutators entered without the scheduler lock raise LockOrderError
    instead of deadlocking or corrupting state silently (ISSUE 7)."""
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")


SOAK_PLAN = FaultPlan(
    drop_event_p=0.08, delay_event_p=0.15, reorder_p=0.35,
    error_p=0.2, max_consecutive_errors=2, bind_fail_after_p=0.5,
)


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_chaos_soak_quick(seed):
    """Tier-1 soak: 6 schedules x 5 seeds (= 30 >= 25 required), restarts
    every 3 schedules, zero invariant violations."""
    h = ChaosHarness(seed=seed, plan=SOAK_PLAN, restart_every=3)
    report = h.run(6)
    assert report["violations"] == [], report
    assert report["schedules"] >= 6
    assert report["restarts"] >= 1


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(20)))
def test_chaos_soak_long(seed):
    h = ChaosHarness(seed=seed, plan=SOAK_PLAN, restart_every=5)
    report = h.run(40)
    assert report["violations"] == [], report


@pytest.mark.parametrize("seed", [0, 4])
def test_chaos_soak_defrag_quick(seed):
    """Tier-1 defrag soak (ISSUE 9): the defrag-v1 ops profile constructs
    fragmentation episodes and drives the full migration protocol (plan ->
    evict -> re-bind -> waiter completes) under injected faults and
    crash-restarts, with the reservation/migration invariants
    (check_defrag) active after every schedule. Non-vacuity is asserted:
    these seeds really plan AND re-bind a migration."""
    h = ChaosHarness(seed=seed, plan=SOAK_PLAN, restart_every=3,
                     ops_profile="defrag-v1")
    report = h.run(10)
    assert report["violations"] == [], report
    assert report["migrations_planned"] >= 1
    assert report["migrations_rebound"] >= 1


@pytest.mark.parametrize("seed", [13, 18])
def test_chaos_soak_defrag_kill_window(seed):
    """Tier-1: the kill -9 window — the job dies after its checkpoint,
    before the re-bind; abort_migration must release every hold with
    nothing half-bound (these seeds deterministically take the kill
    branch)."""
    h = ChaosHarness(seed=seed, plan=SOAK_PLAN, restart_every=3,
                     ops_profile="defrag-v1")
    report = h.run(14)
    assert report["violations"] == [], report
    assert report["migrations_killed"] >= 1


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 4, 6, 14, 20, 25, 26, 27, 28])
def test_chaos_soak_defrag_long(seed):
    """Slow cousin: the wider defrag-v1 seed sweep (every seed here planned
    at least one migration in the 14-schedule soak when pinned)."""
    h = ChaosHarness(seed=seed, plan=SOAK_PLAN, restart_every=3,
                     ops_profile="defrag-v1")
    report = h.run(14)
    assert report["violations"] == [], report
    assert report["migrations_planned"] >= 1


def test_crash_restart_mid_gang_recovers_bound_placements():
    """Crash injected mid-gang: some members bound, the rest still pending.
    The restarted scheduler must (a) rebuild the gang from the bound pods'
    annotations with its FULL placement intact at chip granularity — the
    bind-info annotation carries the whole gang's placement, so 100% of
    bound placements recover — and (b) let the remaining members complete
    into the recovered group's open slots."""
    from hivedscheduler_tpu.chaos import harness as chaos_harness
    from hivedscheduler_tpu.runtime import extender as ei

    h = ChaosHarness(seed=7, plan=FaultPlan(
        drop_event_p=0, delay_event_p=0, reorder_p=0, error_p=0))
    spec = {
        "virtualCluster": "vc-a", "priority": 5,
        "leafCellType": "v5p-chip", "leafCellNumber": 4,
        "affinityGroup": {
            "name": "midgang",
            "members": [{"podNumber": 4, "leafCellNumber": 4}],
        },
    }
    placements = {}
    for i in range(4):
        pod_name = f"midgang-{i}"
        h.fake.create_pod(chaos_harness._make_pod(pod_name, spec))
        node = h._filter_member(pod_name, spec)
        assert node is not None
        placements[pod_name] = node
        if i < 2:  # bind only the first two members, then crash
            assert h._bind(pod_name, node)

    with h.scheduler.scheduler_lock:
        before = invariants.placement_snapshot(h.algo, ["midgang"])
    h.crash_restart(quiesced=False)
    assert h.violations == [], h.violations

    # (a) the recovered group carries the identical full-gang placement
    with h.scheduler.scheduler_lock:
        after = invariants.placement_snapshot(h.algo, ["midgang"])
    assert after == before
    # the two bound pods were replayed through the recovery barrier
    g = h.algo.get_affinity_group("midgang")
    assert sorted(g.status.allocated_pods) == ["midgang-0", "midgang-1"]

    # (b) the unbound members finish into the SAME gang placement after
    # restart (member slots may swap between the two open positions; the
    # group-level chip placement below is the binding contract)
    for i in range(2, 4):
        pod_name = f"midgang-{i}"
        node = h._filter_member(pod_name, spec)
        assert node in set(placements.values())
        assert h._bind(pod_name, node)
    with h.scheduler.scheduler_lock:
        final = invariants.placement_snapshot(h.algo, ["midgang"])
    assert final == before
    g = h.algo.get_affinity_group("midgang")
    assert len(g.status.allocated_pods) == 4
    h.groups["midgang"] = [
        h.fake.get_pod("default", f"midgang-{i}") for i in range(4)
    ]
    h._check("after mid-gang recovery", quiesce=True)
    assert h.violations == [], h.violations


def test_bad_cell_flap_and_heal_keeps_invariants():
    """NotReady -> healthy flaps over live gangs: doomed-bad binding and
    healing must keep the books consistent (driven through the runtime's
    informer path, not the algorithm directly)."""
    h = ChaosHarness(seed=3, plan=FaultPlan(
        drop_event_p=0, delay_event_p=0, reorder_p=0, error_p=0))
    h.run(4)
    for _ in range(10):
        h.op_flip_node()
        h._check("flap", quiesce=True)
    h.heal_all()
    h._check("healed", quiesce=True)
    assert h.violations == [], h.violations


# ---------------------------------------------------------------------------
# injector contract
# ---------------------------------------------------------------------------

def _pod(name):
    return Pod(name=name, uid=name)


def test_injector_preserves_per_object_order():
    """Whatever the fault dice roll, one object's events never arrive out
    of order (ADDED before its own DELETED etc.) — the informer contract."""
    fake = FakeKubeClient()
    chaos = ChaosKubeClient(fake, seed=123, plan=FaultPlan(
        drop_event_p=0.2, delay_event_p=0.3, reorder_p=0.5, error_p=0))
    seen = []
    chaos.on_pod_event(
        lambda p: seen.append(("add", p.name)),
        lambda o, p: seen.append(("upd", p.name)),
        lambda p: seen.append(("del", p.name)),
    )
    for i in range(40):
        name = f"p{i}"  # unique per lifecycle: staleness is then decidable
        fake.create_pod(_pod(name))
        fake.update_pod(_pod(name))
        fake.delete_pod("default", name)
    chaos.flush_held()
    per = {}
    for ev, name in seen:
        per.setdefault(name, []).append(ev)
    order = {"add": 0, "upd": 1, "del": 2}
    assert len(per) == 40  # deletes are never dropped: every object surfaced
    for name, evs in per.items():
        # legal delivery = an order-preserving subsequence of
        # [add, upd, del] (adds/updates may be dropped, nothing may be
        # delivered stale after a newer event of the same object)
        assert evs[-1] == "del", f"{name}: stale event after delete: {evs}"
        assert len(set(evs)) == len(evs), f"{name}: duplicated event: {evs}"
        assert [order[e] for e in evs] == sorted(order[e] for e in evs), (
            f"{name}: per-object order broken: {evs}"
        )


def test_injector_sync_is_faithful_and_flushes():
    fake = FakeKubeClient()
    chaos = ChaosKubeClient(fake, seed=0, plan=FaultPlan(
        drop_event_p=1.0, delay_event_p=0.0, reorder_p=0.0, error_p=0))
    seen = []
    chaos.on_node_event(lambda n: seen.append(n.name),
                        lambda o, n: None, lambda n: None)
    chaos.on_pod_event(lambda p: None, lambda o, p: None, lambda p: None)
    fake.create_node(Node(name="n0"))  # dropped (p=1.0)
    assert seen == []
    chaos.sync()  # the list path is reliable
    assert seen == ["n0"]


def test_injector_error_streak_is_bounded():
    fake = FakeKubeClient()
    chaos = ChaosKubeClient(fake, seed=0, plan=FaultPlan(
        drop_event_p=0, delay_event_p=0, reorder_p=0,
        error_p=1.0, max_consecutive_errors=2))
    fake.create_node(Node(name="n0"))
    failures = 0
    for _ in range(2):
        try:
            chaos.list_nodes()
        except InjectedApiError:
            failures += 1
    assert failures == 2
    assert [n.name for n in chaos.list_nodes()] == ["n0"]  # streak bounded


def test_ambiguous_bind_failure_commits():
    """bind_fail_after_p=1: the error reaches the caller but the bind
    LANDED — the case the runtime's idempotent retry must recognize."""
    from hivedscheduler_tpu.k8s.types import Binding

    fake = FakeKubeClient()
    chaos = ChaosKubeClient(fake, seed=0, plan=FaultPlan(
        drop_event_p=0, delay_event_p=0, reorder_p=0,
        error_p=1.0, max_consecutive_errors=1, bind_fail_after_p=1.0))
    chaos.on_pod_event(lambda p: None, lambda o, p: None, lambda p: None)
    chaos.on_node_event(lambda n: None, lambda o, n: None, lambda n: None)
    fake.create_pod(_pod("p0"))
    with pytest.raises(InjectedApiError):
        chaos.bind_pod(Binding(pod_name="p0", pod_namespace="default",
                               pod_uid="p0", node="n0"))
    assert fake.get_pod("default", "p0").node_name == "n0"


# ---------------------------------------------------------------------------
# fake ApiServer leaf-lock assertion (architecture rule regression test)
# ---------------------------------------------------------------------------

class TestFakeLeafLockAssertion:
    def test_handler_under_store_lock_raises(self):
        """The debug-mode chokepoint pins the CLAUDE.md rule: handlers must
        never run while the calling thread holds the store (leaf) lock."""
        fake = FakeKubeClient()
        with fake._lock:
            with pytest.raises(AssertionError, match="leaf"):
                fake._fire(lambda: None, ())

    def test_normal_delivery_passes_the_chokepoint(self):
        fake = FakeKubeClient()
        seen = []
        fake.on_node_event(lambda n: seen.append(n.name),
                           lambda o, n: None, lambda n: None)
        fake.create_node(Node(name="n0"))
        assert seen == ["n0"]

    def test_other_threads_lock_does_not_trip(self):
        """_is_owned is per-thread: another thread holding the store lock
        must not false-positive the assertion (delivery would just block,
        which is the normal mutual exclusion, not an inversion)."""
        fake = FakeKubeClient()
        acquired = threading.Event()
        release = threading.Event()

        def holder():
            with fake._lock:
                acquired.set()
                release.wait(5)

        t = threading.Thread(target=holder)
        t.start()
        try:
            assert acquired.wait(5)
            fake._fire(lambda: None, ())  # must not raise
        finally:
            release.set()
            t.join()
