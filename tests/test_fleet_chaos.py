"""Fleet chaos (ISSUE 12 satellite): check_fleet invariants under
injected replica loss — including a decode replica killed MID-HANDOFF —
and a seeded random soak over submit/kill/drain/add/remove ops.

The invariants re-derived each check (chaos.invariants.check_fleet):
no request lost between shed and retry, no double-routed stream,
drain-before-teardown on every scale-down, and no orphaned blocks after
any handoff (check_block_pool over every live replica's pool)."""

import os
import random
import sys

import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hivedscheduler_tpu.chaos import invariants  # noqa: E402
from hivedscheduler_tpu.fleet import FleetRouter  # noqa: E402
from hivedscheduler_tpu.models import serving, transformer as tm  # noqa: E402
from hivedscheduler_tpu.obs import journal as obs_journal  # noqa: E402


@pytest.fixture(autouse=True)
def _journal_on():
    """ISSUE 13: run every fleet chaos episode with the request flight
    recorder ON, so check_fleet's check_requests leg (terminals, leg
    contiguity, sum-to-ttft, retry re-attribution) is attacked by the
    same kills/drains — not vacuously skipped. Per-test isolation: the
    singleton never leaks state (each router restarts fleet fids at 0)."""
    obs_journal.JOURNAL.clear()
    obs_journal.enable()
    yield
    obs_journal.disable()
    obs_journal.JOURNAL.clear()


@pytest.fixture(scope="module")
def setup():
    cfg = tm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_kv_heads=2, n_layers=1,
        d_ff=64, max_seq_len=64, dtype=jnp.float32)
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def make_engine(setup):
    cfg, params = setup
    return serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                 page_size=8, prefix_cache_size=8)


_REF = {}


def reference(setup, prompt, budget):
    """Single-replica reference stream. ONE shared engine serves every
    reference serially — greedy streams depend only on (params, prompt),
    so cache state between references cannot change them."""
    key = (tuple(prompt), budget)
    if key not in _REF:
        if "_eng" not in _REF:
            _REF["_eng"] = make_engine(setup)
        eng = _REF["_eng"]
        req = eng.submit(list(prompt), budget)
        eng.run_until_drained()
        _REF[key] = list(req.tokens_out)
    return _REF[key]


def test_kill_decode_replica_mid_handoff(setup):
    """The satellite's named episode: the decode replica dies AFTER the
    prefill leg shipped its KV and the decode leg started — the stream
    must retry on the surviving decode replica, token-exactly, with no
    orphaned blocks anywhere."""
    r = FleetRouter(disaggregate=True, kv_ship=True)
    r.add_replica("p0", make_engine(setup), role="prefill")
    r.add_replica("d0", make_engine(setup), role="decode")
    r.add_replica("d1", make_engine(setup), role="decode")
    prompt = list(range(1, 20))
    f = r.submit(prompt, 8)
    # drive until the handoff completed and the decode leg is in flight
    for _ in range(200):
        r.step()
        if f.handoff is None and f.attempts and not f.done:
            break
    assert f.handoff is None and f.replica in ("d0", "d1")
    victim = f.replica
    r.kill(victim)
    r.step()
    invariants.check_fleet(r, "post-kill")  # retried, nothing lost
    assert f.retries == 1 and f.replica != victim
    r.run_until_drained()
    assert f.finish_reason == "length"
    assert f.tokens_out == reference(setup, prompt, 8)
    invariants.check_fleet(r, "post-drain")
    # the dead replica's blocks are NOT checked (its pool died with it);
    # every surviving pool must balance
    for name, rep in r.replicas.items():
        if rep.state != "dead":
            invariants.check_block_pool(rep.engine, name)


@pytest.mark.slow  # tier-1 wall-time budget: the decode-kill episode above is the tier-1 cousin (same retry machinery, the handoff's other end)
def test_kill_prefill_replica_mid_handoff(setup):
    """Losing the PREFILL replica while its leg is in flight: the
    request restarts its dispatch on the surviving prefill replica."""
    r = FleetRouter(disaggregate=True, kv_ship=True)
    r.add_replica("p0", make_engine(setup), role="prefill")
    r.add_replica("p1", make_engine(setup), role="prefill")
    r.add_replica("d0", make_engine(setup), role="decode")
    prompt = list(range(1, 20))
    f = r.submit(prompt, 6)
    assert f.handoff is not None
    first_pre = f.handoff["replica"]
    r.kill(first_pre)
    r.step()
    invariants.check_fleet(r, "post-kill")
    assert f.retries == 1
    assert f.handoff is None or f.handoff["replica"] != first_pre
    r.run_until_drained()
    assert f.tokens_out == reference(setup, prompt, 6)
    invariants.check_fleet(r, "post-drain")


def _soak(setup, seed: int, ops: int) -> None:
    rng = random.Random(seed)
    r = FleetRouter(policy="prefix_affinity", disaggregate=True,
                    kv_ship=True)
    r.add_replica("p0", make_engine(setup), role="prefill")
    r.add_replica("d0", make_engine(setup), role="decode")
    r.add_replica("d1", make_engine(setup), role="decode")
    system = list(range(1, 9))
    reqs = []
    added = 0
    for i in range(ops):
        op = rng.random()
        if op < 0.45:
            tail = [rng.randrange(1, 60)
                    for _ in range(rng.randrange(2, 8))]
            reqs.append((r.submit(system + tail, rng.randrange(2, 5)),
                         system + tail))
        elif op < 0.55 and added < 3:
            # scale-up: a fresh decode replica joins mid-traffic
            added += 1
            r.add_replica(f"dx{added}", make_engine(setup), role="decode")
        elif op < 0.65:
            # abrupt loss of a random non-last decode replica
            decs = [n for n, rep in r.replicas.items()
                    if rep.role == "decode" and rep.state == "active"]
            if len(decs) > 1:
                r.kill(rng.choice(decs))
        elif op < 0.75:
            # drain-based scale-down of a random decode replica
            decs = [n for n, rep in r.replicas.items()
                    if rep.role == "decode" and rep.state == "active"]
            if len(decs) > 1:
                r.begin_drain(rng.choice(decs))
        else:
            r.step()
        r.step()
        invariants.check_fleet(r, f"soak seed={seed} op={i}")
        # drained replicas are removed as the autoscaler would
        for name, rep in list(r.replicas.items()):
            if rep.state == "drained":
                r.remove_replica(name)
    r.run_until_drained()
    invariants.check_fleet(r, f"soak seed={seed} end")
    for freq, prompt in reqs:
        assert freq.done
        if freq.finish_reason == "length":
            assert freq.tokens_out == reference(setup, prompt,
                                                freq.max_new_tokens)


def test_fleet_soak_fast(setup):
    """Tier-1 cousin of the slow soak: one pinned seed, bounded ops."""
    _soak(setup, seed=7, ops=12)


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; the fast cousin stays tier-1
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fleet_soak(setup, seed):
    _soak(setup, seed=seed, ops=80)
