"""Legacy HiveD annotation compatibility.

The reference rewrites old GPU-era annotation keys before parsing
(``convertOldAnnotation``, ``pkg/internal/utils.go:189-197``):
gpuType→leafCellType, gpuNumber→leafCellNumber, gpuIsolation→leafCellIsolation,
physicalGpuIndices→physicalLeafCellIndices. tpu-hive accepts those plus the
chipType/chipNumber TPU aliases. These tests pin the full path: a
reference-format pod spec and bind info round-trip through
extract → schedule → crash recovery. If any legacy key stops parsing,
these fail.
"""

import logging
import os

import pytest

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.algorithm.constants import GROUP_ALLOCATED
from hivedscheduler_tpu.common.utils import to_yaml
from hivedscheduler_tpu.k8s.types import Container, Pod
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
from hivedscheduler_tpu.runtime.utils import (
    convert_old_annotation,
    extract_pod_bind_info,
    extract_pod_scheduling_spec,
    new_binding_pod,
)

logging.getLogger().setLevel(logging.ERROR)

from helpers import all_node_names, set_healthy_nodes

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


def legacy_pod(name, annotation):
    return Pod(
        name=name,
        uid=name,
        annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: annotation},
        containers=[Container(
            resource_limits={C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
    )


@pytest.fixture
def algo():
    h = HivedAlgorithm(load_config(FIXTURE))
    set_healthy_nodes(h)
    return h


class TestLegacySchedulingSpec:
    def test_gpu_era_spec_keys_parse(self):
        """A HiveD-GPU-format spec annotation (gpuType/gpuNumber) parses into
        leafCellType/leafCellNumber."""
        ann = to_yaml({
            "virtualCluster": "vc2",
            "priority": 5,
            "gpuType": "v5e-chip",
            "gpuNumber": 8,
            "affinityGroup": {
                "name": "legacy/grp",
                "members": [{"podNumber": 1, "gpuNumber": 8}],
            },
        })
        spec = extract_pod_scheduling_spec(legacy_pod("l0", ann))
        assert spec.leaf_cell_type == "v5e-chip"
        assert spec.leaf_cell_number == 8
        assert spec.affinity_group.members[0].leaf_cell_number == 8

    def test_gpu_era_spec_schedules_end_to_end(self, algo):
        ann = to_yaml({
            "virtualCluster": "vc2",
            "priority": 5,
            "gpuType": "v5e-chip",
            "gpuNumber": 8,
        })
        pod = legacy_pod("l1", ann)
        r = algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert r.pod_bind_info is not None
        assert len(r.pod_bind_info.leaf_cell_isolation) == 8

    def test_chip_alias_spec_keys_parse(self):
        """The TPU-era chipType/chipNumber aliases keep working too."""
        ann = to_yaml({
            "virtualCluster": "vc2",
            "priority": 5,
            "chipType": "v5e-chip",
            "chipNumber": 4,
        })
        spec = extract_pod_scheduling_spec(legacy_pod("l2", ann))
        assert spec.leaf_cell_type == "v5e-chip"
        assert spec.leaf_cell_number == 4


class TestLegacyBindInfo:
    def test_gpu_era_bind_info_recovers_through_crash(self, algo):
        """Round-trip: schedule → rewrite the bind-info annotation into the
        old GPU key format → replay into a fresh scheduler (crash recovery).
        The recovered group must hold the same placement."""
        ann = to_yaml({
            "virtualCluster": "vc2",
            "priority": 5,
            "gpuType": "v5e-chip",
            "gpuNumber": 8,
            "affinityGroup": {
                "name": "legacy/recover",
                "members": [{"podNumber": 1, "gpuNumber": 8}],
            },
        })
        pod = legacy_pod("l3", ann)
        r = algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert r.pod_bind_info is not None
        bp = new_binding_pod(pod, r.pod_bind_info)

        # downgrade the machine-written annotations to the old key format,
        # as if written by a pre-rename HiveD
        new_to_old = [
            ("leafCellIsolation", "gpuIsolation"),
            ("physicalLeafCellIndices", "physicalGpuIndices"),
            ("leafCellType", "gpuType"),
            ("leafCellNumber", "gpuNumber"),
        ]
        old_bind = bp.annotations[C.ANNOTATION_POD_BIND_INFO]
        for new, old in new_to_old:
            old_bind = old_bind.replace(new, old)
        assert "gpuIsolation" in old_bind
        legacy_bp = bp.deep_copy()
        legacy_bp.annotations[C.ANNOTATION_POD_BIND_INFO] = old_bind
        legacy_bp.annotations[C.ANNOTATION_POD_SCHEDULING_SPEC] = ann
        legacy_bp.node_name = r.pod_bind_info.node

        # the legacy-format bind info parses identically
        info = extract_pod_bind_info(legacy_bp)
        assert info.node == r.pod_bind_info.node
        assert info.leaf_cell_isolation == r.pod_bind_info.leaf_cell_isolation

        # crash recovery: fresh algorithm replays the legacy-format pod
        fresh = HivedAlgorithm(load_config(FIXTURE))
        set_healthy_nodes(fresh)
        fresh.add_allocated_pod(legacy_bp)
        g = fresh.get_affinity_group("legacy/recover")
        assert g.status.state == GROUP_ALLOCATED
        # placement survived: the recovered group holds the same node + chips
        assert r.pod_bind_info.node in g.status.physical_placement
        assert sorted(g.status.physical_placement[r.pod_bind_info.node]) == sorted(
            r.pod_bind_info.leaf_cell_isolation
        )

    def test_memoized_fragment_with_legacy_head_falls_back(self, algo):
        """extract_pod_bind_info's fast path scans only the annotation head
        for legacy keys once the gang fragment is memoized — which is safe
        ONLY because fragments enter the memo after a full-raw scan passed.
        Pin both halves: a legacy-keyed head spliced onto an
        already-memoized clean fragment must take the rewritten full parse,
        with the fragment still parsed correctly."""
        from hivedscheduler_tpu.runtime import utils as iu

        ann = to_yaml({
            "virtualCluster": "vc2",
            "priority": 5,
            "leafCellType": "v5e-chip",
            "leafCellNumber": 8,
            "affinityGroup": {
                "name": "legacy/memo",
                "members": [{"podNumber": 1, "leafCellNumber": 8}],
            },
        })
        pod = legacy_pod("m1", ann)
        r = algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert r.pod_bind_info is not None
        bp = new_binding_pod(pod, r.pod_bind_info)
        raw = bp.annotations[C.ANNOTATION_POD_BIND_INFO]
        # machine format: memoize the clean fragment via the fast path
        info_fast = extract_pod_bind_info(bp)
        head, marker, frag_tail = raw.partition(iu._GROUP_SPLICE_MARKER)
        assert marker and frag_tail[:-1] in iu._group_frag_memo
        # splice a legacy-keyed head onto the SAME fragment bytes
        legacy_head = head.replace(
            '"leafCellIsolation"', '"gpuIsolation"'
        )
        assert legacy_head != head
        legacy_raw = legacy_head + marker + frag_tail
        legacy_bp = bp.deep_copy()
        legacy_bp.annotations[C.ANNOTATION_POD_BIND_INFO] = legacy_raw
        info = extract_pod_bind_info(legacy_bp)
        # the legacy head was rewritten (gpuIsolation -> leafCellIsolation)
        # and the fragment still parsed — NOT skipped by the fast path
        assert info.leaf_cell_isolation == info_fast.leaf_cell_isolation
        assert info.node == info_fast.node
        assert len(info.affinity_group_bind_info) == len(
            info_fast.affinity_group_bind_info
        )

    def test_rewrite_table_is_exhaustive(self):
        """Guard: every key the reference rewrites must be rewritten here."""
        reference_pairs = {
            "gpuType": "leafCellType",
            "gpuNumber": "leafCellNumber",
            "gpuIsolation": "leafCellIsolation",
            "physicalGpuIndices": "physicalLeafCellIndices",
        }
        for old, new in reference_pairs.items():
            assert convert_old_annotation(old) == new, (
                f"legacy key {old!r} no longer rewrites to {new!r}"
            )
