"""Docker-free dry-run of the CI e2e jobs (.github/workflows/test.yaml).

The ``image`` and ``kind-e2e`` jobs have no executable environment here (no
docker), so their LOGIC is executed locally instead of trusted-as-YAML
(VERDICT r4 weak #2 / next #6):

- the RBAC manifest is validated against the REAL REST client's recorded
  wire requests (not a hand-maintained verb list);
- the kind-e2e job's jq payload constructions and assertions are pinned
  to the workflow text and then executed as an equivalent HTTP round-trip
  through the real webserver (filter -> bind -> nodeName + isolation
  annotation on the pod);
- the image job's probe endpoints are extracted from the workflow and
  probed against a booted --fake-cluster stack.

Reference analogue: every feature in the reference carries observed
reproduce steps (/root/reference/example/feature/README.md); these tests
are the in-repo observation for the two jobs that need a cluster.
"""

import json
import logging
import os
import re
import urllib.request

import pytest
import yaml

from hivedscheduler_tpu.api import constants as C

logging.getLogger().setLevel(logging.ERROR)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKFLOW = os.path.join(REPO, ".github", "workflows", "test.yaml")
KIND_DIR = os.path.join(REPO, "example", "run", "kind-e2e")


def _job_script(job_name: str) -> str:
    wf = yaml.safe_load(open(WORKFLOW))
    job = wf["jobs"][job_name]
    return "\n".join(s.get("run", "") for s in job["steps"])


def _post(base, path, obj):
    req = urllib.request.Request(
        base + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


class TestRbacAgainstRecordedClientRequests:
    def test_clusterrole_covers_every_wire_request(self):
        """Drive the real REST client through its full surface (recovery
        sync, reads, bind) against the recording mini apiserver, map every
        request it actually made to a K8s (resource, verb), and require the
        shipped ClusterRole to grant each one. A missing verb in
        manifests.yaml now fails HERE, not in an unrunnable CI job."""
        from hivedscheduler_tpu.k8s.rest import RestKubeClient
        from hivedscheduler_tpu.k8s.types import Binding
        from test_rest_client import MiniApiServer

        srv = MiniApiServer()
        try:
            srv.add_node("n0")
            srv.add_pod("default", "p0")
            client = RestKubeClient(srv.url)
            client.on_node_event(lambda n: None, lambda o, n: None,
                                 lambda n: None)
            client.on_pod_event(lambda p: None, lambda o, p: None,
                                lambda p: None)
            client.sync()          # list + watch, nodes and pods
            client.get_node("n0")  # get
            client.get_pod("default", "p0")
            client.list_nodes()
            client.list_pods()
            client.bind_pod(Binding(pod_name="p0", pod_namespace="default",
                                    pod_uid="p0", node="n0",
                                    annotations={"a": "b"}))
            client.stop()
            with srv.lock:
                recorded = list(srv.requests)
        finally:
            srv.close()
        assert recorded, "client made no requests?"

        def classify(method, path):
            path, _, query = path.partition("?")
            watching = "watch=true" in query
            m = re.fullmatch(r"/api/v1/namespaces/[^/]+/pods/[^/]+/binding",
                             path)
            if method == "POST" and m:
                return ("pods/binding", "create")
            if method != "GET":
                return (path, method)  # unknown -> fails the subset check
            for res in ("nodes", "pods"):
                if path == f"/api/v1/{res}":
                    return (res, "watch" if watching else "list")
                if path.startswith(f"/api/v1/{res}/") and res == "nodes":
                    return (res, "get")
            if re.fullmatch(r"/api/v1/namespaces/[^/]+/pods/[^/]+", path):
                return ("pods", "get")
            return (path, method)

        needed = {classify(m, p) for m, p in recorded}
        docs = list(yaml.safe_load_all(
            open(os.path.join(KIND_DIR, "manifests.yaml"))))
        role = next(d for d in docs if d and d.get("kind") == "ClusterRole")
        granted = {(res, verb) for rule in role["rules"]
                   for res in rule["resources"] for verb in rule["verbs"]}
        assert needed <= granted, (
            f"client wire requests not granted by ClusterRole: "
            f"{needed - granted}"
        )


class TestKindE2eScriptLogic:
    """Execute the kind-e2e job's round-trip logic over real HTTP."""

    def test_jq_payloads_pinned_and_round_trip_executes(self):
        from hivedscheduler_tpu.api.config import Config, new_config
        from hivedscheduler_tpu.k8s import serde
        from hivedscheduler_tpu.k8s.fake import FakeKubeClient
        from hivedscheduler_tpu.k8s.types import Node
        from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
        from hivedscheduler_tpu.webserver import WebServer

        script = _job_script("kind-e2e")
        # pin the jq constructions this test emulates: if the workflow's
        # payload shapes change, this fails and the emulation below must
        # be updated in lockstep
        assert ("'{Pod: $pod, NodeNames: [\"tpu-host-0-0\", "
                "\"tpu-host-2-0\"]}'") in script
        assert ".NodeNames[0]" in script
        assert ("'{PodName: $pod.metadata.name, "
                "PodNamespace: $pod.metadata.namespace,\n"
                "    PodUID: $pod.metadata.uid, Node: $node}'"
                ) in script
        # the asserted annotation key is the shipped constant
        wf_key = "hivedscheduler\\.microsoft\\.com/pod-leaf-cell-isolation"
        assert wf_key in script
        assert wf_key.replace("\\", "") == C.ANNOTATION_POD_CHIP_ISOLATION
        node_names = ["tpu-host-0-0", "tpu-host-2-0"]
        fake_nodes = [d["metadata"]["name"] for d in yaml.safe_load_all(
            open(os.path.join(KIND_DIR, "fake-nodes.yaml"))) if d]
        assert set(node_names) <= set(fake_nodes)

        # boot the same config the job deploys, over real HTTP
        docs = list(yaml.safe_load_all(
            open(os.path.join(KIND_DIR, "manifests.yaml"))))
        cm = next(d for d in docs if d and d.get("kind") == "ConfigMap")
        config = new_config(Config.from_dict(
            yaml.safe_load(cm["data"]["config.yaml"])))
        config.web_server_address = "127.0.0.1:0"
        kube = FakeKubeClient()
        scheduler = HivedScheduler(config, kube)
        for n in fake_nodes:
            kube.create_node(Node(name=n))
        pod_doc = yaml.safe_load(open(os.path.join(KIND_DIR,
                                                   "test-pod.yaml")))
        # kubectl get -o json would carry a server-assigned uid
        pod_doc.setdefault("metadata", {}).setdefault("uid", "e2e-uid-0")
        pod = serde.pod_from_k8s(pod_doc)
        kube.create_pod(pod)
        scheduler.start()
        server = WebServer(scheduler)
        host, port = server.async_run()
        base = f"http://{host}:{port}"
        try:
            pod_json = serde.pod_to_k8s(kube.get_pod(pod.namespace, pod.name))
            # jq: '{Pod: $pod, NodeNames: [...]}' | curl .../filter
            status, flt = _post(base, "/v1/extender/filter",
                                {"Pod": pod_json, "NodeNames": node_names})
            assert status == 200, flt
            # jq -re '.NodeNames[0]' (the -e exit contract: must exist)
            assert flt.get("NodeNames"), flt
            node = flt["NodeNames"][0]
            assert node in node_names
            # jq: '{PodName, PodNamespace, PodUID, Node}' | curl .../bind
            status, _ = _post(base, "/v1/extender/bind", {
                "PodName": pod_json["metadata"]["name"],
                "PodNamespace": pod_json["metadata"]["namespace"],
                "PodUID": pod_json["metadata"]["uid"],
                "Node": node,
            })
            assert status == 200
            # kubectl wait .spec.nodeName == $NODE; ISO non-empty
            bound = kube.get_pod(pod.namespace, pod.name)
            assert bound.node_name == node
            assert bound.annotations.get(C.ANNOTATION_POD_CHIP_ISOLATION)
        finally:
            server.stop()

    def test_wait_targets_exist_in_fixtures(self):
        """Every object the job kubectl-waits on is shipped by the
        fixtures it applies (a renamed node/deployment otherwise fails
        only in CI)."""
        script = _job_script("kind-e2e")
        fake_nodes = {d["metadata"]["name"] for d in yaml.safe_load_all(
            open(os.path.join(KIND_DIR, "fake-nodes.yaml"))) if d}
        for m in re.finditer(r"node/([\w.-]+)", script):
            assert m.group(1) in fake_nodes, m.group(1)
        docs = list(yaml.safe_load_all(
            open(os.path.join(KIND_DIR, "manifests.yaml"))))
        deployments = {d["metadata"]["name"] for d in docs
                       if d and d.get("kind") == "Deployment"}
        for m in re.finditer(r"deployment/([\w.-]+)", script):
            assert m.group(1) in deployments, m.group(1)
        services = {d["metadata"]["name"] for d in docs
                    if d and d.get("kind") == "Service"}
        for m in re.finditer(r"svc/([\w.-]+)", script):
            assert m.group(1) in services, m.group(1)
        pods = {yaml.safe_load(open(os.path.join(
            KIND_DIR, "test-pod.yaml")))["metadata"]["name"]}
        for m in re.finditer(r"pod/([\w.-]+)", script):
            assert m.group(1) in pods, m.group(1)


class TestImageJobProbes:
    def test_probed_endpoints_respond_on_fake_cluster(self):
        """Boot the --fake-cluster stack on the design config (what the
        image job boots) and hit every endpoint the job curls."""
        from hivedscheduler_tpu.api.config import load_config
        from hivedscheduler_tpu.k8s.fake import FakeKubeClient
        from hivedscheduler_tpu.k8s.types import Node
        from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
        from hivedscheduler_tpu.webserver import WebServer

        script = _job_script("image")
        paths = sorted(set(re.findall(r"localhost:30096(/[\w/.-]+)",
                                      script)))
        assert paths, "image job curls nothing?"
        assert "/healthz" in paths
        config = load_config(os.path.join(
            REPO, "example", "config", "design", "tpu-hive.yaml"))
        config.web_server_address = "127.0.0.1:0"
        kube = FakeKubeClient()
        scheduler = HivedScheduler(config, kube)
        algo = scheduler.scheduler_algorithm
        for n in sorted({n for ccl in algo.full_cell_list.values()
                         for c in ccl[max(ccl)] for n in c.nodes}):
            kube.create_node(Node(name=n))
        scheduler.start()
        server = WebServer(scheduler)
        host, port = server.async_run()
        try:
            for path in paths:
                with urllib.request.urlopen(
                        f"http://{host}:{port}{path}") as r:
                    assert r.status == 200, path
                    assert r.read()
        finally:
            server.stop()
