"""Multi-step fused decode (ServingEngine ``decode_steps`` > 1).

The load-bearing property: the emitted token stream of every request is
IDENTICAL to the step-by-step (decode_steps=1) engine for any window size
— greedy and sampled (counter-based keys make per-position draws
independent of windowing), including EOS retirement at and inside window
boundaries, budgets that don't divide the window, chunked-prefill
composition, and mid-flight admissions into recycled slots."""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import decode, serving, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


PROMPTS = [[5, 9, 2], [17, 3, 88, 41, 7], [1], [100, 22, 63, 4]]
BUDGETS = [6, 4, 9, 5]


def run_engine(params, cfg, decode_steps, *, temperature=0.0, eos=None,
               prefill_chunk=0, max_batch=2, prompts=PROMPTS,
               budgets=BUDGETS):
    eng = serving.ServingEngine(
        params, cfg, max_batch=max_batch, max_len=64,
        decode_steps=decode_steps, temperature=temperature,
        top_k=20 if temperature else 0, top_p=0.9 if temperature else 1.0,
        seed=11, eos_id=eos, prefill_chunk=prefill_chunk,
    )
    reqs = [eng.submit(list(p), n) for p, n in zip(prompts, budgets)]
    eng.run_until_drained()
    assert all(r.done for r in reqs)
    return [(r.tokens_out, r.finish_reason) for r in reqs], eng


class TestFusedDecodeExactness:
    def test_greedy_streams_match_k1(self, setup):
        cfg, params = setup
        ref, _ = run_engine(params, cfg, 1)
        out, eng = run_engine(params, cfg, 4)
        assert out == ref
        assert eng.fused_windows > 0  # the fused path actually ran

    @pytest.mark.slow
    def test_greedy_streams_match_k1_nonpow2(self, setup):
        """A non-power-of-two knob (7): full-knob windows interleave with
        pow2-bucketed budget tails."""
        cfg, params = setup
        ref, _ = run_engine(params, cfg, 1)
        out, eng = run_engine(params, cfg, 7)
        assert out == ref and eng.fused_windows > 0

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_sampled_streams_match_k1(self, setup):
        cfg, params = setup
        ref, _ = run_engine(params, cfg, 1, temperature=0.8)
        out, eng = run_engine(params, cfg, 4, temperature=0.8)
        assert out == ref and eng.fused_windows > 0

    def test_eos_at_and_around_window_boundary(self, setup):
        """Pick reference-stream positions as the EOS token: with
        decode_steps=4 position 2 lands inside the first fused window,
        position 3 exactly AT the window boundary (the last slot of the
        window), and position 4 on the first post-window step. Streams
        must match the step-by-step engine at each."""
        cfg, params = setup
        base, _ = run_engine(params, cfg, 1, prompts=[[5, 9, 2]],
                             budgets=[8], max_batch=1)
        stream = base[0][0]
        tested = 0
        for pos in (2, 3, 4):
            eos = stream[pos]
            if eos in stream[:pos]:
                continue  # would retire earlier; exact either way, but
                # not the position under test
            # the k=1 reference with this eos is DERIVED, not re-run:
            # greedy picks don't depend on eos_id (it only stops the
            # stream), so the reference is base truncated at the eos
            ref = [(stream[:pos + 1], "eos")]
            out, _ = run_engine(params, cfg, 4, eos=eos,
                                prompts=[[5, 9, 2]], budgets=[8],
                                max_batch=1)
            assert out == ref, pos
            tested += 1
        assert tested, "every probe position degenerate — new model seed?"

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): budget-clamp
    # variant of the fused-window differential; tier-1 cousins:
    # test_greedy_streams_match_k1 + test_greedy_streams_match_k1_nonpow2
    # above (same window machinery, the clamp path unit-covered by
    # _fused_window tests)
    def test_budget_not_multiple_of_window(self, setup):
        """Budgets 6/4/9/5 against a window of 8: the window clamps to the
        minimum remaining budget (power-of-two bucketed), so no request
        over-emits and lengths finish exactly."""
        cfg, params = setup
        ref, _ = run_engine(params, cfg, 1)
        out, _ = run_engine(params, cfg, 8)
        assert out == ref
        for (toks, reason), budget in zip(out, BUDGETS):
            assert len(toks) == budget and reason == "length"

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): multistep x
    # chunked composition variant; tier-1 cousins:
    # test_greedy_streams_match_k1 above + the chunked parity
    # (test_serving_chunked.py::test_chunked_matches_monolithic[4]); the
    # collapse-to-1-during-chunking rule is unit-covered in
    # test_serving_paged.py::test_fused_window_collapses_during_chunked_prefill
    def test_composes_with_chunked_prefill(self, setup):
        cfg, params = setup
        long_prompts = [list(range(2, 26)), [17, 3], [7] * 19, [1, 2, 3]]
        ref, _ = run_engine(params, cfg, 1, prefill_chunk=4,
                            prompts=long_prompts)
        out, eng = run_engine(params, cfg, 4, prefill_chunk=4,
                              prompts=long_prompts)
        assert out == ref
        assert eng.prefill_chunks_done > 0

    @pytest.mark.slow
    def test_single_slot_forced_queueing(self, setup):
        """max_batch=1: every later request waits on the running one —
        windows + admission churn must leave all streams exact. (slow:
        tier-1's greedy match already queues 4 requests through 2 slots)"""
        cfg, params = setup
        ref, _ = run_engine(params, cfg, 1)
        out, _ = run_engine(params, cfg, 4, max_batch=1)
        assert out == ref

    def test_decode_steps_validation_and_default(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="decode_steps"):
            serving.ServingEngine(params, cfg, max_batch=1, max_len=32,
                                  decode_steps=0)
        _, eng = run_engine(params, cfg, 1, prompts=[[5, 9, 2]],
                            budgets=[3], max_batch=1)
        assert eng.fused_windows == 0  # K=1 never takes the fused path


class TestFusedWindowPolicy:
    def test_window_collapses_for_eos_with_queue(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                    decode_steps=4, eos_id=1)
        eng.submit([5, 9, 2], 8)
        eng.submit([17, 3], 4)  # waits in queue
        eng.step()  # admit + first decode
        assert eng._fused_window([0]) == 1  # EOS could free the slot
        eng.queue.clear()
        assert eng._fused_window([0]) == 4  # nothing waiting: fuse away

    def test_window_power_of_two_bucketing(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                    decode_steps=8)
        r = eng.submit([5, 9, 2], 6)
        eng._admit()  # prefill emits token 1; 5 remaining
        assert len(r.tokens_out) == 1
        assert eng._fused_window([0]) == 4  # largest pow2 <= 5
        eng.run_until_drained()
        assert len(r.tokens_out) == 6


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_generate_decode_steps_unroll_exact(setup=None):
    """decode.generate(decode_steps=K) is a scan-unroll schedule change:
    tokens identical for any K, greedy and sampled."""
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray([[5, 9, 2], [7, 1, 88]], jnp.int32)
    ref = decode.generate(params, prompt, cfg, 7, max_len=16)
    out = decode.generate(params, prompt, cfg, 7, max_len=16,
                          decode_steps=3)
    assert (np.asarray(ref) == np.asarray(out)).all()
    key = jax.random.PRNGKey(4)
    ref_s = decode.generate(params, prompt, cfg, 7, max_len=16,
                            temperature=0.7, top_k=20, key=key)
    out_s = decode.generate(params, prompt, cfg, 7, max_len=16,
                            temperature=0.7, top_k=20, key=key,
                            decode_steps=4)
    assert (np.asarray(ref_s) == np.asarray(out_s)).all()
