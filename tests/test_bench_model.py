"""bench_model.py must stay runnable (CLAUDE.md blind spot: driver-facing
artifacts rot silently). Smoke mode exercises the identical code path the
TPU run takes — the sharded train-step factory + KV-cached decode — on tiny
shapes."""

import json

import pytest

pytest.importorskip("jax")


@pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): the full CPU
# smoke is the heavy variant (~60 s); the tier-1 cousins are this file's
# acquire/flops/degradation tests plus tests/test_bench_driver.py's
# parse-contract suite (the driver-path failure modes the smoke guards)
def test_bench_model_smoke(capsys):
    import bench_model

    # one invocation covers the stage metrics AND the --breakdown schema
    # (a separate breakdown run would repeat the whole smoke bench); the
    # short --fleet-duration keeps the diurnal fleet A/B inside the
    # tier-1 wall-time budget (the driver's run keeps the default cycle)
    rc = bench_model.main(["--smoke", "--iters", "1", "--breakdown",
                           "--fleet-duration", "1.0"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    m = json.loads(line)
    assert m["metric"].startswith("train_step_mfu_1chip")
    assert set(m) >= {"value", "unit", "vs_baseline", "train_tokens_per_sec",
                      "decode_tokens_per_sec", "train_step_ms",
                      "serve_tokens_per_sec", "serve_occupancy"}
    assert m["train_tokens_per_sec"] > 0
    assert m["decode_tokens_per_sec"] > 0
    assert m["serve_tokens_per_sec"] > 0
    assert 0.0 < m["serve_occupancy"] <= 1.0
    assert m["loss_finite"]
    # --breakdown's dict is driver-parsed: pin the EXACT key set
    # (hand-rolled-serializer rule, CLAUDE.md) so it cannot drift silently
    assert "breakdown_error" not in m, m.get("breakdown_error")
    assert set(m["breakdown"]) == {"embed_ms", "attn_ms", "mlp_ms",
                                   "collective_ms", "sampling_ms"}
    assert set(bench_model.BREAKDOWN_KEYS) == set(m["breakdown"])
    for key, val in m["breakdown"].items():
        assert isinstance(val, (int, float)) and val >= 0.0, (key, val)
    assert m["model"]["decode_steps"] == 1
    # fleet stage (ISSUE 12): the A/B ran and disaggregated serving is
    # token-exact in BOTH KV-handoff modes, even at smoke sizes
    assert "serve_fleet_error" not in m, m.get("serve_fleet_error")
    assert m["fleet_disagg_token_exact"] is True
    sf = m["serve_fleet"]
    assert sf["static_good_requests"] > 0
    assert sf["autoscaled_good_requests"] > 0
    # request flight recorder + SLO layer (ISSUE 13): leg attribution
    # summed to the measured TTFT for every completed request in both
    # KV-handoff modes, the burn/attribution tables rode along, and the
    # disabled path stayed one attribute check
    assert m["fleet_legs_sum_to_ttft"] is True
    from hivedscheduler_tpu.obs.journal import REQUEST_LEGS

    for arm in ("static_slo", "autoscaled_slo"):
        blk = sf[arm]
        assert blk["attribution_checked_requests"] > 0
        assert set(blk["ttft_leg_seconds"]) <= set(REQUEST_LEGS)
        assert blk["burn_rate"] is None or blk["burn_rate"] >= 0.0
    assert sf["slo_disabled_leg_overhead_ns"] < 20_000
    # goodput stage (ISSUE 16): the fault-injected elastic episode ran,
    # conservation held in every summarized incarnation, the pinned
    # kill-between-commits seed attributed rework, and the workload span
    # reconciled against the capacity ledger's busy_guaranteed interval
    assert "goodput_error" not in m, m.get("goodput_error")
    assert m["goodput_conservation_ok"] is True, m["goodput"]["violations"]
    gp = m["goodput"]
    assert gp["rework_steps"] >= 1
    assert gp["torn"] == 1 and gp["incarnations"] == 3
    assert 0.0 < m["goodput_fraction"] < 1.0
    assert gp["bridge"]["busy_guaranteed_s"] >= gp["bridge"]["observed_s"]
    from hivedscheduler_tpu.obs.goodput import STEP_PHASES

    assert set(gp["phases"]) <= set(STEP_PHASES)
    # effective_mfu = mfu × goodput_fraction. CPU smoke has no chip peak
    # (chip_peaks → None → mfu None), so the discount must be None exactly
    # when the MFU is — on a real TPU both are populated and effective is
    # the smaller number (goodput_fraction < 1 was asserted above)
    if m["value"] is None:
        assert m["effective_mfu_pct"] is None
    else:
        assert m["effective_mfu_pct"] <= m["value"]


@pytest.mark.slow  # tier-1 wall-time budget (ISSUE 7): fault-ladder
# variant of the driver; test_bench_model_smoke is the tier-1 cousin
def test_stage_failures_keep_train_number(capsys, monkeypatch):
    """Decode/serve failures degrade into per-stage error notes — the train
    MFU number (the driver's deliverable) must survive them, and the driver
    parse must carry the notes into the artifact."""
    import bench_model
    from bench import parse_model_bench_output

    def boom(*a, **k):
        raise RuntimeError("synthetic decode crash")

    monkeypatch.setattr(bench_model, "bench_decode", boom)
    # --skip-goodput: the elastic episode is ~30 s of subprocesses and
    # orthogonal to the stage-degradation contract under test here
    rc = bench_model.main(["--smoke", "--iters", "1", "--skip-goodput"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    m = json.loads(line)
    assert "breakdown" not in m  # only with --breakdown
    assert m["train_tokens_per_sec"] > 0
    assert m["decode_tokens_per_sec"] is None
    assert "synthetic decode crash" in m["decode_error"]
    assert m["serve_tokens_per_sec"] > 0  # serve stage unaffected
    # a real-TPU-shaped line with a stage error keeps the train fields and
    # surfaces the note in the driver artifact
    m2 = dict(m, metric="train_step_mfu_1chip", value=41.0,
              device="TPU v5 lite")
    fields, _ = parse_model_bench_output(0, json.dumps(m2), "")
    assert fields["model_train_mfu_pct"] == 41.0
    assert "synthetic decode crash" in fields["model_decode_error"]
    assert "model_serve_error" not in fields

    def no_params(*a, **k):
        raise RuntimeError("synthetic init OOM")

    monkeypatch.setattr(bench_model, "serving_params", no_params)
    rc = bench_model.main(["--smoke", "--iters", "1", "--skip-goodput"])
    assert rc == 0
    m3 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert m3["train_tokens_per_sec"] > 0
    assert "synthetic init OOM" in m3["decode_error"]
    assert "synthetic init OOM" in m3["serve_error"]


def test_acquire_timeout_fails_fast_and_loud():
    """A wedged TPU tunnel must produce rc=3 + a self-explanatory JSON line
    within the bounded wait — not an indefinite sleep-retry (the round-3
    driver failure mode: rc=1 with all diagnostics discarded)."""
    import os
    import subprocess
    import sys

    code = (
        "import sys, types, time\n"
        "stub = types.ModuleType('jax')\n"
        "stub.devices = lambda: time.sleep(60)\n"
        "sys.modules['jax'] = stub\n"  # simulate: enumeration never returns
        "import bench_model\n"
        "bench_model.acquire_backend(0.3, grace_s=0.3)\n"
        "print('UNREACHABLE')\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=repo, timeout=60,
    )
    assert p.returncode == 3
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert "tpu_acquire_timeout" in out["error"]
    assert "UNREACHABLE" not in p.stdout


def test_backend_unavailable_fails_loud():
    """A terminal backend-init failure (the axon client gives up after its
    internal ~25-min retry with UNAVAILABLE when the pool is down) must
    produce rc=4 + a self-explanatory JSON line, not a bare traceback."""
    import os
    import subprocess
    import sys

    code = (
        "import sys, types\n"
        "stub = types.ModuleType('jax')\n"
        "def boom():\n"
        "    raise RuntimeError(\"Unable to initialize backend 'axon': "
        "UNAVAILABLE: TPU backend setup/compile error\")\n"
        "stub.devices = boom\n"
        "sys.modules['jax'] = stub\n"
        "import bench_model\n"
        "bench_model.acquire_backend(5, grace_s=1)\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=repo, timeout=60,
    )
    assert p.returncode == 4
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert "tpu_backend_unavailable" in out["error"]
    assert "UNAVAILABLE" in out["error"]


def test_train_flops_accounting():
    # analytic FLOPs must track the config: doubling layers ~doubles FLOPs
    import bench_model
    from hivedscheduler_tpu.models import transformer as tm

    def cfg(n_layers):
        return tm.TransformerConfig(
            vocab_size=0x1000, d_model=256, n_heads=8, n_kv_heads=4,
            n_layers=n_layers, d_ff=1024, max_seq_len=512,
        )

    f1 = bench_model.train_flops_per_step(cfg(2), batch=2, seq=512)
    f2 = bench_model.train_flops_per_step(cfg(4), batch=2, seq=512)
    assert f1 > 0
    lm_head = 3 * 2.0 * 256 * 0x1000 * 2 * 512  # layer-count-independent
    assert abs((f2 - lm_head) / (f1 - lm_head) - 2.0) < 1e-6
