"""Readiness vs liveness split on the scheduler webserver: /readyz flips to
503 + Retry-After at drain start (stop SENDING work) while /healthz stays
200 (don't RESTART me — in-flight work is finishing). The acceptance
ordering of graceful termination: /readyz flips strictly before /healthz
ever would."""

import os
import urllib.error
import urllib.request

import pytest

from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.k8s.fake import FakeKubeClient
from hivedscheduler_tpu.k8s.types import Node
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
from hivedscheduler_tpu.webserver import WebServer

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


@pytest.fixture
def drain_stack():
    config = load_config(FIXTURE)
    config.web_server_address = "127.0.0.1:0"  # ephemeral port
    kube = FakeKubeClient()
    scheduler = HivedScheduler(config, kube)
    algo = scheduler.scheduler_algorithm
    for n in sorted({n for ccl in algo.full_cell_list.values()
                     for c in ccl[max(ccl)] for n in c.nodes}):
        kube.create_node(Node(name=n))
    scheduler.start()
    server = WebServer(scheduler)
    host, port = server.async_run()
    yield server, f"http://{host}:{port}"
    server.stop()


def probe(base, path):
    """(status, body, headers) without raising on 503."""
    try:
        with urllib.request.urlopen(base + path) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def test_readyz_ready_when_healthy(drain_stack):
    server, base = drain_stack
    status, body, _ = probe(base, "/readyz")
    assert (status, body) == (200, b"ready")
    status, body, _ = probe(base, "/healthz")
    assert (status, body) == (200, b"ok")


def test_drain_flips_readyz_before_healthz(drain_stack):
    server, base = drain_stack
    server.begin_drain(retry_after_s=17)
    status, body, headers = probe(base, "/readyz")
    assert status == 503 and body == b"draining"
    assert headers.get("Retry-After") == "17"
    # liveness is drain-blind: restarting a draining process would lose
    # exactly the in-flight work the drain exists to finish
    status, body, _ = probe(base, "/healthz")
    assert (status, body) == (200, b"ok")
    # the server still answers real traffic while draining
    status, _, _ = probe(base, "/v1")
    assert status == 200


def test_readyz_also_fails_on_unhealthy_scheduler(drain_stack):
    """Readiness implies liveness: a wedged scheduler must not be ready
    even without a drain."""
    import threading

    server, base = drain_stack
    acquired = threading.Event()
    release = threading.Event()

    def hold_lock():
        with server.scheduler.scheduler_lock:
            acquired.set()
            release.wait(timeout=30)

    t = threading.Thread(target=hold_lock, daemon=True)
    t.start()
    assert acquired.wait(timeout=5)
    try:
        status, body, _ = probe(base, "/readyz")
        assert status == 503 and b"unhealthy" in body
    finally:
        release.set()
        t.join(timeout=5)
