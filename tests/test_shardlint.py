"""shardlint guards (ISSUE 8): every SHD/ENV rule catches its seeded
violation, the env-flag registry is exact and renders the pinned
doc/design/flags.md, and the hivedlint CLI's rule selection / explain /
json modes work. The clean-on-tree pin for the whole suite (including
these rule families) is tests/test_hivedlint.py::test_hivedlint_clean_on_tree."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import tools.hivedlint as hivedlint  # noqa: E402
from tools.hivedlint import shardlint  # noqa: E402
from hivedscheduler_tpu.common import envflags  # noqa: E402


def _write(tmp_path, rel, source):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return str(path)


# ---------------------------------------------------------------------------
# SHD001: fresh arrays in manual loop carries
# ---------------------------------------------------------------------------

def test_shd001_unvaried_carry_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        import jax.numpy as jnp
        from jax import lax

        def _body_local(x, axis_name):
            acc = jnp.zeros((4,), jnp.float32)
            size = lax.psum(1, axis_name)
            def step(c, _):
                return c, None
            out, _ = lax.scan(step, (acc, x), None)
            return out
        """)
    got = shardlint.check_vma_carries(str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["SHD001"]
    assert "varying" in got[0].message


def test_shd001_varied_and_data_derived_carries_pass(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        import jax.numpy as jnp
        from jax import lax
        from shard_utils import varying

        def _body_local(x, axis_name, mesh_axes):
            acc = varying(jnp.zeros((4,), jnp.float32), mesh_axes)
            aux = varying(jnp.zeros((), jnp.float32), mesh_axes) + 0.0 * jnp.sum(x)
            inherited = jnp.zeros_like(x) + 0.0 * x   # data-derived: clean
            size = lax.psum(1, axis_name)
            out = lax.fori_loop(0, size, lambda i, c: c, (acc, aux, inherited))
            return out
        """)
    assert shardlint.check_vma_carries(str(tmp_path / "pkg")) == []


def test_shd001_nonmanual_function_exempt(tmp_path):
    # fresh scan carries are fine OUTSIDE a manual context (GSPMD jit)
    _write(tmp_path, "pkg/mod.py", """
        import jax.numpy as jnp
        from jax import lax

        def gspmd_stack(x, layers):
            aux = jnp.zeros((), jnp.float32)
            (x, aux), _ = lax.scan(lambda c, lp: (c, None), (x, aux), layers)
            return x, aux
        """)
    assert shardlint.check_vma_carries(str(tmp_path / "pkg")) == []


def test_shd001_installed_body_counts_as_manual(tmp_path):
    # no collectives of its own, but installed as a shard_map body
    _write(tmp_path, "pkg/mod.py", """
        import functools
        import jax.numpy as jnp
        from jax import lax

        def _stacked(xx, stack):
            acc = jnp.zeros((2,), jnp.float32)
            out, _ = lax.scan(lambda c, lp: (c, None), acc, stack)
            return out

        def installer(x, layers, mesh, shard_map):
            fn = shard_map(_stacked, mesh=mesh, in_specs=(None, None),
                           out_specs=None)
            return fn(x, layers)
        """)
    got = shardlint.check_vma_carries(str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["SHD001"]


# ---------------------------------------------------------------------------
# SHD002: shard_map reachable from a manual context
# ---------------------------------------------------------------------------

_SHD002_SRC = """
    import functools
    from jax import lax

    def _body_local(x, axis_name):
        y = lax.psum(x, axis_name)
        return _helper(y)

    def _helper(y):
        return _flash_wrap(y)

    def _flash_wrap(y):
        fn = _get_shard_map()(lambda q: q, check_vma=False)
        return fn(y)

    def installer(x, mesh, shard_map):
        fn = shard_map(functools.partial(_body_local, axis_name="tp"),
                       mesh=mesh)
        return fn(x)
    """


def test_shd002_transitive_open_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", _SHD002_SRC)
    got = shardlint.check_manual_context(str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["SHD002"]
    assert "_flash_wrap" in got[0].message


def test_shd002_manual_guard_prunes(tmp_path):
    # the sanctioned dual-mode dispatch: the opener call is under a
    # manual-axes guard, so the GSPMD branch is exempt
    _write(tmp_path, "pkg/mod.py", """
        import functools
        from jax import lax

        def _body_local(x, axis_name):
            y = lax.psum(x, axis_name)
            return _dispatch(y, manual_tp_axis=axis_name)

        def _dispatch(y, manual_tp_axis=None):
            if manual_tp_axis is None:
                return _flash_wrap(y)
            return y

        def _flash_wrap(y):
            fn = _get_shard_map()(lambda q: q)
            return fn(y)

        def installer(x, mesh, shard_map):
            fn = shard_map(functools.partial(_body_local, axis_name="tp"),
                           mesh=mesh)
            return fn(x)
        """)
    assert shardlint.check_manual_context(str(tmp_path / "pkg")) == []


def test_shd002_pipeline_stage_body_is_a_root(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        def stage_block(params, h):
            return _opens(h)

        def _opens(h):
            return shard_map(lambda x: x, mesh=None)(h)

        def forward(params, h):
            return pipeline_apply(stage_block, params, None, h, None)
        """)
    got = shardlint.check_manual_context(str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["SHD002"]


def test_shd002_cross_module_import_resolves(tmp_path):
    _write(tmp_path, "pkg/bodies.py", """
        from pkg.helpers import helper

        def _body_local(x, axis_name):
            from jax import lax
            return helper(lax.psum(x, axis_name))

        def installer(x, mesh, shard_map):
            fn = shard_map(_body_local, mesh=mesh)
            return fn(x)
        """)
    _write(tmp_path, "pkg/helpers.py", """
        def helper(y):
            return _get_shard_map()(lambda q: q)(y)
        """)
    got = shardlint.check_manual_context(str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["SHD002"]
    assert got[0].file == "pkg/helpers.py"


def test_shd002_real_tree_fixpoint_is_not_vacuous():
    """The real tree's dual-mode dispatcher is traversed (not skipped):
    roots exist and _dispatch_attention is reachable from the pipeline
    stage body while its guarded _flash_gspmd call stays exempt."""
    scans = [os.path.join(REPO, "hivedscheduler_tpu", s)
             for s in shardlint.SHARD_SCOPE]
    assert shardlint.check_manual_context(scans) == []
    # mutation: strip every manual-axis guard on the path to the
    # _flash_gspmd opener (the inner dual-mode guard AND the enclosing
    # manual_sp_axis dispatch chain) and the suite must light up
    path = os.path.join(REPO, "hivedscheduler_tpu", "models",
                        "transformer.py")
    with open(path) as f:
        src = f.read()
    inner = ("if manual_tp_axis is None and manual_ep_axis is None "
             "and not device_local:")
    outer = "if manual_sp_axis is not None:"
    assert inner in src and outer in src  # the guards the rule relies on
    mutated = src.replace(inner, "if True:").replace(outer, "if False:")
    import shutil
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        for sub in shardlint.SHARD_SCOPE:
            shutil.copytree(
                os.path.join(REPO, "hivedscheduler_tpu", sub),
                os.path.join(td, "hivedscheduler_tpu", sub),
            )
        with open(os.path.join(td, "hivedscheduler_tpu", "models",
                               "transformer.py"), "w") as f:
            f.write(mutated)
        got = shardlint.check_manual_context(
            [os.path.join(td, "hivedscheduler_tpu", s)
             for s in shardlint.SHARD_SCOPE])
    assert any(f.rule == "SHD002" for f in got)


# ---------------------------------------------------------------------------
# SHD003: literal collective axes must be declared
# ---------------------------------------------------------------------------

def test_shd003_typoed_axis_flagged_and_declared_passes(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def _body_local(x, axis_name):
            good = lax.psum(x, "tp")
            bad = lax.all_gather(x, "ttp", axis=0, tiled=True)
            threaded = lax.ppermute(x, axis_name, [(0, 1)])
            return good + bad + threaded

        def installer(x, mesh, shard_map):
            spec = P("tp", None)
            fn = shard_map(_body_local, mesh=mesh, in_specs=(spec,),
                           out_specs=spec)
            return fn(x)
        """)
    got = shardlint.check_collective_axes(str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["SHD003"]
    assert "'ttp'" in got[0].message


def test_shd003_nested_body_in_installer_checked(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def installer(x, mesh, shard_map):
            spec = P(("dp", "fsdp"), "tp")

            def stacked(xx):
                return lax.all_gather(xx, "fsdp", axis=0, tiled=True)

            def bad(xx):
                return lax.psum(xx, ("tp", "sq"))

            fn = shard_map(stacked, mesh=mesh, in_specs=(spec,),
                           out_specs=spec)
            return fn(x) + bad(x)
        """)
    got = shardlint.check_collective_axes(str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["SHD003"]
    assert "'sq'" in got[0].message


# ---------------------------------------------------------------------------
# SHD004: donated buffers are dead after the call
# ---------------------------------------------------------------------------

def test_shd004_read_after_donation_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        import jax

        def make(step):
            f = jax.jit(step, donate_argnums=(1,))

            def run(params, cache, tok):
                logits, new_cache = f(params, cache, tok)
                stale = cache.lengths   # read after donation!
                return logits, new_cache, stale
            return run
        """)
    got = shardlint.check_donation(str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["SHD004"]
    assert "cache is read after being donated" in got[0].message


def test_shd004_rebind_patterns_pass(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        import jax

        class Engine:
            def __init__(self, step):
                self._decode = jax.jit(step, donate_argnums=(1,))

            def tick(self, params, tok):
                logits, self.cache = self._decode(params, self.cache, tok)
                return logits, self.cache.lengths  # NEW cache: fine

            def loop(self, params, cache, toks):
                for tok in toks:
                    out, cache = self._decode(params, cache, tok)
                return cache
        """)
    assert shardlint.check_donation(str(tmp_path / "pkg")) == []


def test_shd004_write_stops_tracking(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        import jax

        def make(step):
            f = jax.jit(step, donate_argnums=(0,))

            def run(cache, tok):
                out = f(cache, tok)
                cache = out            # rebound: later reads are fine
                return cache.lengths
            return run
        """)
    assert shardlint.check_donation(str(tmp_path / "pkg")) == []


# ---------------------------------------------------------------------------
# ENV001 / ENV002
# ---------------------------------------------------------------------------

def test_env001_unregistered_token_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        import os
        FLAG = os.environ.get("HIVED_BOGUS", "")
        # docstring rot counts too: HIVED_GHOST is documented nowhere
        DOC = "set ``HIVED_GHOST=1`` to do nothing"
        OK = os.environ.get("HIVED_REAL", "")
        """)
    got = shardlint.check_env_flags(
        str(tmp_path), names={"HIVED_REAL"}, package_rel="pkg",
        read_rels=("pkg",))
    rules = [f.rule for f in got]
    assert rules.count("ENV001") == 2
    assert not [f for f in got if f.rule == "ENV002"]  # HIVED_REAL is read


def test_env001_family_prefix_allowed(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        import os
        for k in os.environ:
            if k.startswith("HIVED_FAULT_"):
                pass
        AT = os.environ.get("HIVED_FAULT_HANG_AT", "")
        """)
    got = shardlint.check_env_flags(
        str(tmp_path), names={"HIVED_FAULT_HANG_AT"}, package_rel="pkg",
        read_rels=("pkg",))
    assert got == []


def test_env002_registered_but_never_read_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", "X = 1\n")
    got = shardlint.check_env_flags(
        str(tmp_path), names={"HIVED_UNUSED"}, package_rel="pkg",
        read_rels=("pkg",))
    assert [f.rule for f in got] == ["ENV002"]
    assert "never read" in got[0].message


def test_env002_module_constant_read_counts(tmp_path):
    # supervisor pattern: read through a module-level constant
    _write(tmp_path, "pkg/mod.py", """
        import os
        ENV_HOOK = "HIVED_FAULT_HANG_AT"

        def geti(name):
            v = os.environ.get(name, "")
            return int(v) if v else None

        def from_env():
            return geti(ENV_HOOK)
        """)
    got = shardlint.check_env_flags(
        str(tmp_path), names={"HIVED_FAULT_HANG_AT"}, package_rel="pkg",
        read_rels=("pkg",))
    assert got == []


def test_every_package_flag_is_registered_and_read():
    """The real-tree ENV rules run clean — asserted directly (not only via
    the aggregate clean-on-tree pin) so a registry edit failure names the
    flag."""
    assert shardlint.check_env_flags(REPO) == []


# ---------------------------------------------------------------------------
# flags.md is pinned to the registry render
# ---------------------------------------------------------------------------

def test_flags_md_pinned_to_registry():
    path = envflags.flags_md_path(REPO)
    with open(path) as f:
        on_disk = f.read()
    assert on_disk == envflags.render_markdown(), (
        "doc/design/flags.md is stale — regenerate with "
        "`python -m hivedscheduler_tpu.common.envflags --write`"
    )


def test_registry_rows_are_complete():
    for flag in envflags.REGISTRY.values():
        assert flag.name.startswith("HIVED_")
        assert flag.default and flag.doc and flag.module


# ---------------------------------------------------------------------------
# CLI: --rule / --explain / --json
# ---------------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.hivedlint", *args], cwd=REPO,
        capture_output=True, text=True,
    )


def test_cli_rule_explain():
    proc = _run_cli("--rule", "SHD001", "--explain")
    assert proc.returncode == 0
    assert "SHD001" in proc.stdout and "varying" in proc.stdout
    assert "SHD002" not in proc.stdout


def test_cli_explain_json_lists_all_rules():
    proc = _run_cli("--explain", "--json")
    assert proc.returncode == 0
    docs = json.loads(proc.stdout)
    assert set(docs) == set(hivedlint.RULES)
    assert all("doc" in v and "module" in v for v in docs.values())


@pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): a second full
# subprocess lint pass; tier-1 cousins: test_hivedlint_clean_on_tree
# (tree-clean, tests/test_hivedlint.py) + test_cli_explain_json_lists_
# all_rules (the --json surface, no tree scan)
def test_cli_json_findings_clean():
    proc = _run_cli("--rule", "ENV001,ENV002", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["count"] == 0 and payload["findings"] == []
    assert payload["rules"] == ["ENV001", "ENV002"]


def test_cli_unknown_rule_rejected():
    proc = _run_cli("--rule", "NOPE")
    assert proc.returncode != 0
    assert "unknown rule" in proc.stdout + proc.stderr


def test_rule_registry_matches_implementations():
    assert set(hivedlint.RULES) == {
        "LCK001", "LCK002", "CON001", "CON002", "CON003", "CON004",
        "DFG001",
        "SHD001", "SHD002", "SHD003", "SHD004", "ENV001", "ENV002",
        "CLI001", "CLI002", "GRD001", "SER001", "MET001", "OBS001",
        "OBS002", "OBS003",
    }
