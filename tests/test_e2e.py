"""End-to-end tests: fake ApiServer + scheduler runtime + HTTP webserver.

Exercises the full K8s scheduler-extender protocol over real HTTP, simulating
what the default kube-scheduler does: filter -> bind -> (preempt) with pod and
node lifecycle through the fake ApiServer, plus crash recovery (a second
scheduler instance replaying bound pods). The reference has no automated
equivalent (SURVEY.md §4 notes only manual e2e) — this exceeds parity.
"""

import json
import logging
import os
import urllib.request

import pytest

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.common.utils import to_yaml
from hivedscheduler_tpu.k8s import serde
from hivedscheduler_tpu.k8s.fake import FakeKubeClient
from hivedscheduler_tpu.k8s.types import Container, Node, Pod
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
from hivedscheduler_tpu.webserver import WebServer

logging.getLogger().setLevel(logging.ERROR)

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


from helpers import make_pod


@pytest.fixture
def stack():
    config = load_config(FIXTURE)
    config.web_server_address = "127.0.0.1:0"  # ephemeral port
    kube = FakeKubeClient()
    scheduler = HivedScheduler(config, kube)
    # create all nodes healthy
    algo = scheduler.scheduler_algorithm
    for n in sorted({n for ccl in algo.full_cell_list.values()
                     for c in ccl[max(ccl)] for n in c.nodes}):
        kube.create_node(Node(name=n))
    scheduler.start()
    server = WebServer(scheduler)
    host, port = server.async_run()
    base = f"http://{host}:{port}"
    yield kube, scheduler, base
    server.stop()


def post(base, path, obj):
    req = urllib.request.Request(
        base + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def get(base, path):
    try:
        with urllib.request.urlopen(base + path) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def filter_args(kube, pod, suggested):
    return {"Pod": serde.pod_to_k8s(kube.get_pod(pod.namespace, pod.name) or pod),
            "NodeNames": suggested}


def all_nodes(kube):
    return sorted(n.name for n in kube.list_nodes())


class TestExtenderFlow:
    def test_filter_bind_flow(self, stack):
        kube, scheduler, base = stack
        pod = make_pod("p1", {"virtualCluster": "vc2", "priority": 0,
                              "chipType": "v5e-chip", "chipNumber": 8})
        kube.create_pod(pod)
        status, result = post(base, C.FILTER_PATH, filter_args(kube, pod, all_nodes(kube)))
        assert status == 200
        assert result["NodeNames"] == ["v5e-host0/0-0"]
        # kube-scheduler then calls bind
        status, result = post(base, C.BIND_PATH, {
            "PodName": "p1", "PodNamespace": "default", "PodUID": "p1",
            "Node": "v5e-host0/0-0"})
        assert status == 200 and result == {}
        # the pod is bound in the (fake) apiserver with the isolation handoff
        bound = kube.get_pod("default", "p1")
        assert bound.node_name == "v5e-host0/0-0"
        assert bound.annotations[C.ANNOTATION_POD_CHIP_ISOLATION] == "0,1,2,3,4,5,6,7"
        assert C.ANNOTATION_POD_BIND_INFO in bound.annotations

    def test_filter_wait_and_inspect(self, stack):
        kube, scheduler, base = stack
        pod = make_pod("big", {"virtualCluster": "vc2", "priority": 0,
                               "chipType": "v5e-chip", "chipNumber": 8,
                               "affinityGroup": {"name": "big",
                                                 "members": [{"podNumber": 2,
                                                              "chipNumber": 8}]}})
        kube.create_pod(pod)  # needs 2 hosts, only 1 exists -> wait
        status, result = post(base, C.FILTER_PATH, filter_args(kube, pod, all_nodes(kube)))
        assert status == 200
        assert "FailedNodes" in result and C.COMPONENT_NAME in result["FailedNodes"]
        # inspect endpoints
        status, cs = get(base, C.CLUSTER_STATUS_PATH)
        assert status == 200 and "physicalCluster" in cs and "virtualClusters" in cs
        status, pc = get(base, C.PHYSICAL_CLUSTER_PATH)
        assert status == 200 and len(pc) == 3
        status, vc = get(base, C.VIRTUAL_CLUSTERS_PATH + "vc1")
        assert status == 200 and len(vc) > 0
        status, _ = get(base, C.VIRTUAL_CLUSTERS_PATH + "ghost")
        assert status == 404

    def test_bad_requests(self, stack):
        kube, scheduler, base = stack
        # filter for an uninformed pod
        ghost = make_pod("ghost", {"virtualCluster": "vc2", "priority": 0,
                                   "chipType": "v5e-chip", "chipNumber": 1})
        status, result = post(base, C.FILTER_PATH,
                              {"Pod": serde.pod_to_k8s(ghost), "NodeNames": []})
        assert status == 400
        # malformed bodies
        status, _ = post(base, C.FILTER_PATH, {"NodeNames": []})
        assert status == 400
        status, _ = post(base, C.BIND_PATH, {"PodName": "x"})
        assert status == 400
        # unknown route
        status, _ = post(base, "/v1/extender/nope", {})
        assert status == 404

    def test_preempt_flow(self, stack):
        kube, scheduler, base = stack
        # fill vc2's v5e host with a low-priority pod
        low = make_pod("low", {"virtualCluster": "vc2", "priority": 1,
                               "chipType": "v5e-chip", "chipNumber": 8})
        kube.create_pod(low)
        post(base, C.FILTER_PATH, filter_args(kube, low, all_nodes(kube)))
        post(base, C.BIND_PATH, {"PodName": "low", "PodNamespace": "default",
                                 "PodUID": "low", "Node": "v5e-host0/0-0"})
        # high-priority pod preempts
        hi = make_pod("hi", {"virtualCluster": "vc2", "priority": 100,
                             "chipType": "v5e-chip", "chipNumber": 8})
        kube.create_pod(hi)
        status, result = post(base, C.FILTER_PATH, filter_args(kube, hi, all_nodes(kube)))
        assert status == 200 and "FailedNodes" in result  # victims advertised
        status, result = post(base, C.PREEMPT_PATH, {
            "Pod": serde.pod_to_k8s(hi),
            "NodeNameToMetaVictims": {"v5e-host0/0-0": {"Pods": [{"UID": "low"}]}}})
        assert status == 200
        assert result["NodeNameToMetaVictims"]["v5e-host0/0-0"]["Pods"] == [{"UID": "low"}]
        # victims die
        kube.delete_pod("default", "low")
        # preemptor retried: gets the bind now
        status, result = post(base, C.FILTER_PATH, filter_args(kube, hi, all_nodes(kube)))
        assert status == 200 and result.get("NodeNames") == ["v5e-host0/0-0"]

    def test_crash_recovery_through_stack(self, stack):
        kube, scheduler, base = stack
        pod = make_pod("r1", {"virtualCluster": "vc2", "priority": 0,
                              "chipType": "v5e-chip", "chipNumber": 8})
        kube.create_pod(pod)
        post(base, C.FILTER_PATH, filter_args(kube, pod, all_nodes(kube)))
        post(base, C.BIND_PATH, {"PodName": "r1", "PodNamespace": "default",
                                 "PodUID": "r1", "Node": "v5e-host0/0-0"})
        # "crash": brand-new scheduler on the same apiserver state
        config = load_config(FIXTURE)
        s2 = HivedScheduler(config, kube)
        s2.start()  # recovery barrier replays the bound pod
        g = s2.get_affinity_group("default/r1")
        assert g.status.state == "Allocated"
        # the recovered placement blocks new conflicting pods
        p2 = make_pod("r2", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 8})
        kube.create_pod(p2)
        r = s2.filter_routine(
            __import__("hivedscheduler_tpu.runtime.extender", fromlist=["ExtenderArgs"])
            .ExtenderArgs(pod=kube.get_pod("default", "r2"), node_names=all_nodes(kube)))
        assert r.failed_nodes  # waits


class TestConfigWatch:
    def test_watch_triggers_on_change(self, tmp_path):
        import threading
        import shutil
        from hivedscheduler_tpu.api.config import load_config as lc, watch_config
        path = tmp_path / "cfg.yaml"
        shutil.copy(FIXTURE, path)
        cfg = lc(str(path))
        changed = threading.Event()
        watch_config(str(path), cfg, poll_interval_sec=0.1, on_change=changed.set)
        # touch without change: no trigger
        assert not changed.wait(0.4)
        # real change: trigger
        path.write_text(path.read_text().replace("cellNumber: 2", "cellNumber: 1"))
        assert changed.wait(3.0)


class TestMetrics:
    def test_metrics_endpoint(self, stack):
        kube, scheduler, base = stack
        pod = make_pod("m1", {"virtualCluster": "vc2", "priority": 0,
                              "chipType": "v5e-chip", "chipNumber": 8})
        kube.create_pod(pod)
        post(base, C.FILTER_PATH, filter_args(kube, pod, all_nodes(kube)))
        post(base, C.BIND_PATH, {"PodName": "m1", "PodNamespace": "default",
                                 "PodUID": "m1", "Node": "v5e-host0/0-0"})
        import urllib.request
        with urllib.request.urlopen(base + "/metrics") as r:
            assert r.status == 200
            text = r.read().decode()
        assert 'tpu_hive_extender_requests_total{outcome="bind",routine="filter"}' in text
        assert "tpu_hive_binds_total" in text
        assert "tpu_hive_filter_latency_seconds_count" in text
        assert "tpu_hive_bad_nodes 0" in text


class TestGcFreezeLifecycle:
    def test_second_scheduler_start_reclaims_dropped_graph(self):
        """start() freezes the cell trees out of gen-2 GC scans (p99 win);
        the unfreeze-first in freeze_long_lived_state must let a dropped
        earlier instance's cyclic cell graph be reclaimed instead of leaking
        in the permanent generation."""
        import weakref

        cfg = load_config(FIXTURE)
        cfg.web_server_address = "127.0.0.1:0"
        a = HivedScheduler(cfg, FakeKubeClient())
        a.start()
        ccl = next(iter(a.scheduler_algorithm.full_cell_list.values()))
        ref = weakref.ref(ccl[1][0])
        del a, ccl
        b = HivedScheduler(cfg, FakeKubeClient())
        b.start()  # unfreeze + collect + freeze
        assert ref() is None, "first scheduler's cell graph leaked"


class TestSerializationGuards:
    def test_pod_deep_copy_covers_all_fields(self):
        """Pod.deep_copy is hand-rolled for speed; a new Pod field must be
        added there too — this guard fails if the constructor call drifts."""
        import dataclasses
        import inspect

        src = inspect.getsource(Pod.deep_copy)
        for f in dataclasses.fields(Pod):
            assert f.name in src, f"Pod.deep_copy misses field {f.name!r}"
        # Container is copied inside Pod.deep_copy — its fields must appear in
        # the same source, else a new Container field is silently dropped
        from hivedscheduler_tpu.k8s.types import Container

        for f in dataclasses.fields(Container):
            assert f.name in src, f"Pod.deep_copy misses Container field {f.name!r}"
        # and the copy is actually deep for the mutable fields
        p = make_pod("x", {"virtualCluster": "v", "priority": 0, "chipNumber": 1})
        c = p.deep_copy()
        c.annotations["k"] = "v"
        c.containers[0].resource_limits["r"] = 1
        assert "k" not in p.annotations
        assert "r" not in p.containers[0].resource_limits

    def test_status_shallow_copy_covers_all_fields(self):
        """The cell-status shallow copies are hand-rolled (__dict__ copy)
        for the bind hot path: every field must carry over except the
        cross-link and children, which must reset to break serialization
        cycles."""
        import dataclasses

        from hivedscheduler_tpu.algorithm.cell import (
            _shallow_copy_physical_status,
            _shallow_copy_virtual_status,
        )
        from hivedscheduler_tpu.api.types import (
            PhysicalCellStatus,
            VirtualCellStatus,
        )

        ps = PhysicalCellStatus(
            cell_type="t", cell_address="a", cell_state="Used",
            cell_healthiness="Bad", cell_priority=7, leaf_cell_type="chip",
            is_node_level=True, mesh_origin=(1, 2), mesh_shape=(2, 2),
            vc="vc1", cell_children=[PhysicalCellStatus()],
            virtual_cell=VirtualCellStatus(),
        )
        out = _shallow_copy_physical_status(ps)
        for f in dataclasses.fields(PhysicalCellStatus):
            if f.name in ("cell_children", "virtual_cell"):
                continue
            assert getattr(out, f.name) == getattr(ps, f.name), f.name
        assert out.cell_children == [] and out.virtual_cell is None
        out.cell_children.append(PhysicalCellStatus())
        assert len(ps.cell_children) == 1  # children list must not be shared

        vs = VirtualCellStatus(
            cell_type="t", cell_address="a", cell_state="Used",
            cell_healthiness="Bad", cell_priority=7, leaf_cell_type="chip",
            is_node_level=True, cell_children=[VirtualCellStatus()],
            physical_cell=PhysicalCellStatus(),
        )
        vout = _shallow_copy_virtual_status(vs)
        for f in dataclasses.fields(VirtualCellStatus):
            if f.name in ("cell_children", "physical_cell"):
                continue
            assert getattr(vout, f.name) == getattr(vs, f.name), f.name
        assert vout.cell_children == [] and vout.physical_cell is None

    def test_bind_info_encoder_matches_to_dict(self):
        """The spliced-fragment encoder must stay equivalent to a plain
        to_dict()+json dump (same fields, same values)."""
        import json

        from hivedscheduler_tpu.api import types as api
        from hivedscheduler_tpu.common.utils import to_json
        from hivedscheduler_tpu.runtime.utils import _encode_bind_info

        bi = api.PodBindInfo(
            node="n", leaf_cell_isolation=[0, 1], cell_chain="c",
            affinity_group_bind_info=[api.AffinityGroupMemberBindInfo(
                pod_placements=[api.PodPlacementInfo(
                    physical_node="n", physical_leaf_cell_indices=[0, 1],
                    preassigned_cell_types=["t", "t"])])],
        )
        assert json.loads(_encode_bind_info(bi)) == json.loads(to_json(bi.to_dict()))

    def test_bind_info_fast_decoder_matches_from_dict(self):
        """The spliced-fragment fast parser in extract_pod_bind_info must
        stay equivalent to the canonical PodBindInfo.from_dict — a new field
        added to from_dict but not the fast path would be silently dropped
        (and memoized)."""
        import json

        from hivedscheduler_tpu.api import types as api
        from hivedscheduler_tpu.k8s.types import Pod
        from hivedscheduler_tpu.api import constants as C2
        from hivedscheduler_tpu.runtime import utils as ru

        bi = api.PodBindInfo(
            node="n", leaf_cell_isolation=[2, 3], cell_chain="c",
            affinity_group_bind_info=[api.AffinityGroupMemberBindInfo(
                pod_placements=[api.PodPlacementInfo(
                    physical_node="n", physical_leaf_cell_indices=[2, 3],
                    preassigned_cell_types=["t", "t"])])],
        )
        raw = ru._encode_bind_info(bi)
        pod = Pod(name="g", uid="g",
                  annotations={C2.ANNOTATION_POD_BIND_INFO: raw})
        ru._bind_info_memo.clear()
        ru._group_frag_memo.clear()
        fast = ru.extract_pod_bind_info(pod)
        assert getattr(fast, "_frag", None) is not None, (
            "expected the fast path to handle a machine-written annotation"
        )
        canonical = api.PodBindInfo.from_dict(json.loads(raw))
        assert fast.to_dict() == canonical.to_dict()
        # structural pin: every top-level key PodBindInfo.from_dict consumes
        # must be handled by the fast path too ("affinityGroupBindInfo" is
        # referenced there via the _GROUP_SPLICE_MARKER constant)
        import inspect as _inspect
        import re

        fast_src = _inspect.getsource(ru.extract_pod_bind_info)
        from_dict_src = _inspect.getsource(api.PodBindInfo.from_dict)
        for key in re.findall(r'd\.get\("(\w+)"', from_dict_src):
            assert key in fast_src or key == "affinityGroupBindInfo", (
                f"PodBindInfo.from_dict consumes {key!r} but the fast decoder "
                f"in extract_pod_bind_info does not mention it"
            )


class TestHealthz:
    def test_healthz(self, stack):
        kube, scheduler, base = stack
        with urllib.request.urlopen(base + "/healthz") as r:
            assert r.status == 200 and r.read() == b"ok"

    def test_healthz_detects_wedged_scheduler(self, stack):
        """A scheduler wedged on its lock must fail the liveness probe
        (ADVICE r1: /healthz previously returned 200 unconditionally)."""
        import threading

        kube, scheduler, base = stack
        acquired = threading.Event()
        release = threading.Event()

        def hold_lock():
            with scheduler.scheduler_lock:
                acquired.set()
                release.wait(timeout=30)

        t = threading.Thread(target=hold_lock, daemon=True)
        t.start()
        assert acquired.wait(timeout=5)
        try:
            assert scheduler.healthy(timeout=0.1) is False
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "/healthz")  # ~2s probe bound
            assert exc.value.code == 503
        finally:
            release.set()
            t.join(timeout=5)
        assert scheduler.healthy(timeout=2.0) is True
