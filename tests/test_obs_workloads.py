"""Workload-side observability: serving request spans + per-priority
histograms (serve --metrics-dump) and the train step timeline JSONL
(train --timeline). The serve run's trace JSON must be Perfetto-loadable
(acceptance criterion; schema checked by helpers.validate_chrome_trace)."""

import json

import pytest

pytest.importorskip("jax")

from helpers import validate_chrome_trace

MODEL = ["--d-model", "32", "--n-heads", "4", "--n-layers", "2",
         "--d-ff", "64", "--vocab-size", "64"]


@pytest.fixture(autouse=True)
def _obs_isolation():
    from hivedscheduler_tpu.obs import trace as obs_trace

    obs_trace.disable()
    obs_trace.TRACER.clear()
    yield
    obs_trace.disable()
    obs_trace.TRACER.clear()


def test_serve_metrics_dump_writes_exposition_and_trace(tmp_path, capsys):
    from hivedscheduler_tpu import serve

    dump = tmp_path / "metrics.txt"
    rc = serve.main(MODEL + [
        "--requests", "4", "--max-batch", "2", "--max-len", "64",
        "--max-new-tokens", "4", "--high-priority-every", "2",
        "--metrics-dump", str(dump),
    ])
    assert rc == 0
    text = dump.read_text()
    # per-priority-class serving histograms made it into the registry
    assert '# TYPE tpu_hive_serve_ttft_seconds histogram' in text
    assert 'tpu_hive_serve_ttft_seconds_bucket{priority="0",le=' in text
    assert 'tpu_hive_serve_ttft_seconds_bucket{priority="10",le=' in text
    assert 'tpu_hive_serve_queue_wait_seconds_count{priority="0"}' in text
    assert 'tpu_hive_serve_requests_total{priority="0"}' in text
    # the trace JSON is a valid Chrome trace with request lifecycle spans
    obj = json.loads((tmp_path / "metrics.txt.trace.json").read_text())
    events = validate_chrome_trace(obj)
    names = [e["name"] for e in events]
    assert names.count("request/decode") == 4  # one lane per request
    assert "request/queued" in names and "request/prefill" in names
    decode = next(e for e in events if e["name"] == "request/decode")
    assert {"rid", "priority", "prompt_tokens", "new_tokens"} <= set(
        decode["args"])


def test_request_lifecycle_timestamps_populated():
    """Engine-level: a drained request carries the full queued -> admitted
    -> first-token -> done timestamp chain, in order."""
    import jax

    from hivedscheduler_tpu.models import serving, transformer as tm

    cfg = tm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                               n_layers=2, d_ff=64, max_seq_len=64)
    params = tm.cast_params(tm.init_params(cfg, jax.random.PRNGKey(0)),
                            cfg.dtype)
    eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64)
    reqs = [eng.submit([1, 2, 3], 3, priority=p) for p in (0, 5)]
    eng.run_until_drained()
    for r in reqs:
        assert r.done and r.done_at is not None
        assert r.submitted_at <= r.admitted_at <= r.first_token_at <= r.done_at
        assert r.queue_wait_s is not None and r.queue_wait_s >= 0
        assert r.tpot_s is not None and r.tpot_s >= 0


def test_train_timeline_jsonl(tmp_path):
    from hivedscheduler_tpu import train

    timeline = tmp_path / "steps.jsonl"
    rc = train.main(MODEL + [
        "--steps", "3", "--batch", "4", "--seq-len", "32", "--tp", "2",
        "--log-every", "100", "--timeline", str(timeline),
    ])
    assert rc in (0, None)
    lines = [json.loads(l) for l in timeline.read_text().splitlines()]
    assert [l["step"] for l in lines] == [1, 2, 3]
    for l in lines:
        assert l["wall_s"] > 0
        assert l["tokens_per_sec"] > 0
        assert isinstance(l["loss"], float)
    # only the first step of the incarnation compiles
    assert [l["compile"] for l in lines] == [True, False, False]
