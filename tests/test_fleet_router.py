"""Serving-fleet router (ISSUE 12 tentpole): routing policies,
shed-retry and disaggregated prefill/decode token-exactness under both
KV-handoff modes, and the steady-state recompile pin.

Token-exactness argument under test: greedy streams are a pure function
of (params, prompt) — so a retried stream equals an unshed run, and a
decode leg resumed from an imported prefix equals single-replica serving
(the prefix-cache exactness guarantee crossing a replica boundary)."""

import os
import sys

import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hivedscheduler_tpu.chaos import invariants  # noqa: E402
from hivedscheduler_tpu.common import compileguard  # noqa: E402
from hivedscheduler_tpu.fleet import FleetRouter  # noqa: E402
from hivedscheduler_tpu.models import serving, transformer as tm  # noqa: E402


def cfg_of():
    return tm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_kv_heads=2, n_layers=1,
        d_ff=64, max_seq_len=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def make_engine(setup, paged=True, prefix_cache=8, **kw):
    cfg, params = setup
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    if paged:
        kw.setdefault("page_size", 8)
    return serving.ServingEngine(params, cfg, prefix_cache_size=prefix_cache,
                                 **kw)


_REF_CACHE = {}


def reference_stream(setup, prompt, budget, paged=True):
    """Single-replica reference. ONE shared engine per backend serves
    every reference serially — greedy streams depend only on (params,
    prompt), so carried cache state cannot change them (and the shared
    engine keeps the per-test JIT cost down, the tier-1 budget rule)."""
    key = (tuple(prompt), budget, paged)
    if key not in _REF_CACHE:
        ekey = ("eng", paged)
        if ekey not in _REF_CACHE:
            _REF_CACHE[ekey] = make_engine(setup, paged=paged)
        eng = _REF_CACHE[ekey]
        req = eng.submit(list(prompt), budget)
        eng.run_until_drained()
        _REF_CACHE[key] = list(req.tokens_out)
    return _REF_CACHE[key]


PROMPTS = [list(range(1, 12)), [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9]]


# ---------------------------------------------------------------------------
# routing policies
# ---------------------------------------------------------------------------

class TestRoutingPolicies:
    def test_least_blocks_spread_snapshot_publish(self, setup):
        """One router exercise covers the least-blocks spread, the
        /v1/inspect/fleet snapshot shape, and publish/unpublish (merged
        — the tier-1 wall-time budget rule)."""
        from hivedscheduler_tpu import fleet as fleet_pkg

        r = FleetRouter()
        r.add_replica("a", make_engine(setup))
        r.add_replica("b", make_engine(setup))
        f1 = r.submit(PROMPTS[0], 4)
        f2 = r.submit(PROMPTS[1], 4)
        # the first request's queued footprint makes `a` heavier
        assert {f1.replica, f2.replica} == {"a", "b"}
        r.run_until_drained()
        assert all(f.finish_reason == "length" for f in (f1, f2))
        invariants.check_fleet(r, "least-blocks")
        fleet_pkg.publish(r)
        try:
            snap = fleet_pkg.published().snapshot()
        finally:
            fleet_pkg.publish(None)
        assert fleet_pkg.published() is None
        assert snap["requests"]["done"] == 2
        assert {rep["name"] for rep in snap["replicas"]} == {"a", "b"}
        assert snap["policy"] == "least_blocks"

    def test_prefix_affinity_routes_to_caching_replica(self, setup):
        r = FleetRouter(policy="prefix_affinity")
        r.add_replica("a", make_engine(setup))
        r.add_replica("b", make_engine(setup))
        system = list(range(1, 17))  # two full blocks: indexable boundaries
        f1 = r.submit(system + [40, 41], 3)
        r.run_until_drained()
        first = f1.replica
        # keep the OTHER replica idle: least-blocks would pick it, so a
        # route back to `first` can only be the affinity index
        f2 = r.submit(system + [50, 51, 52], 3)
        assert f2.replica == first
        assert r.affinity_hits >= 1
        r.run_until_drained()
        # the hit really lands in the caching replica's prefix cache
        eng = r.replicas[first].engine
        assert eng.prefix_hits >= 1
        invariants.check_fleet(r, "affinity")

# ---------------------------------------------------------------------------
# shed retry
# ---------------------------------------------------------------------------

class TestShedRetry:
    def test_shed_retry_token_exact_then_exhausted(self, setup):
        """Two scenarios through ONE pair of engines (tier-1 budget):
        (1) a shed waiter retries on another replica, token-exact vs an
        un-shed run; (2) with no alternative left, retries exhaust and
        the request FINISHES with the shed reason — never a silent loss.
        Replica `a` sheds queued waiters on a VIRTUAL deadline (the
        engine's injectable clock — deterministic on a loaded box);
        max_batch=1 so a second submit queues behind the first."""
        clk = [0.0]
        r = FleetRouter()
        r.add_replica("a", make_engine(setup, max_batch=1,
                                       queue_timeout_s=0.5,
                                       clock=lambda: clk[0]))
        f1 = r.submit(PROMPTS[0], 8)
        f2 = r.submit(PROMPTS[1], 8)
        assert f2.replica == "a"  # queued behind f1
        r.step()  # f1 admitted, f2 waiting
        r.add_replica("b", make_engine(setup, max_batch=1))
        clk[0] = 1.0  # f2's queue wait blows the deadline
        r.run_until_drained()
        assert f2.retries == 1 and f2.replica == "b"
        assert f2.finish_reason == "length"
        assert f2.tokens_out == reference_stream(setup, PROMPTS[1], 8)
        assert f1.tokens_out == reference_stream(setup, PROMPTS[0], 8)
        invariants.check_fleet(r, "shed-retry")
        # scenario 2: kill `b`; the only survivor sheds and no
        # alternative exists
        r.kill("b")
        f3 = r.submit(PROMPTS[0], 8)
        f4 = r.submit(PROMPTS[1], 8)
        r.step()  # f3 admitted on a, f4 waiting
        clk[0] = 2.0
        r.run_until_drained()
        assert f3.finish_reason == "length"
        assert f4.finish_reason == "shed" and f4.tokens_out == []
        invariants.check_fleet(r, "shed-exhausted")


# ---------------------------------------------------------------------------
# disaggregated prefill/decode: token-exact under both handoff modes
# ---------------------------------------------------------------------------

class TestDisaggregated:
    def _token_exact(self, setup, kv_ship, paged, monkeypatch):
        monkeypatch.setenv("HIVED_FLEET_KV_SHIP", "1" if kv_ship else "0")
        r = FleetRouter(disaggregate=True)
        assert r.kv_ship is kv_ship  # the env flag selects the mode
        r.add_replica("p0", make_engine(setup, paged=paged), role="prefill")
        r.add_replica("d0", make_engine(setup, paged=paged), role="decode")
        reqs = [r.submit(p, 6) for p in PROMPTS]
        r.run_until_drained()
        for freq, prompt in zip(reqs, PROMPTS):
            assert freq.tokens_out == reference_stream(
                setup, prompt, 6, paged=paged), (freq.fid, r.kv_ship)
        if kv_ship:
            assert r.handoffs["ship"] == len(PROMPTS)  # both prompts
            # shipped blocks really SKIP the decode-side prefill: each
            # decode leg restores the imported leading block instead of
            # recomputing it (the point of shipping, not just exactness)
            dec = r.replicas["d0"].engine
            assert dec.prefix_hits == len(PROMPTS)
            # both prompts (11 and 13 tokens) ship an 8-token leading
            # chunk under either boundary rule (block 8 / pow2 8)
            assert dec.prefix_tokens_reused == 8 * len(PROMPTS)
        else:
            assert r.handoffs["reprefill"] == len(PROMPTS)
        invariants.check_fleet(r, f"disagg ship={kv_ship}")

    # tier-1 covers BOTH handoff modes on the paged backend (the
    # production config); the dense variants ride the slow tier —
    # the ROADMAP wall-time budget move
    @pytest.mark.parametrize("kv_ship", [True, False])
    def test_token_exact_vs_single_replica(self, setup, kv_ship,
                                           monkeypatch):
        self._token_exact(setup, kv_ship, True, monkeypatch)

    @pytest.mark.slow  # tier-1 wall-time budget: dense cousins of the paged tier-1 pair
    @pytest.mark.parametrize("kv_ship", [True, False])
    def test_token_exact_dense(self, setup, kv_ship, monkeypatch):
        self._token_exact(setup, kv_ship, False, monkeypatch)

    def test_speculative_engine_rejected_in_ship_mode(self, setup):
        cfg, params = setup
        from hivedscheduler_tpu.models.speculative import (
            SpecDecodeConfig,
            derive_draft_config,
        )

        dft_cfg = derive_draft_config(cfg, 1, 0)
        dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(7))
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, max_len=64, prefix_cache_size=8,
            spec_decode=SpecDecodeConfig(draft_params=dft_params,
                                         draft_cfg=dft_cfg, gamma=2))
        r = FleetRouter(disaggregate=True, kv_ship=True)
        with pytest.raises(ValueError, match="HIVED_FLEET_KV_SHIP=0"):
            r.add_replica("p0", eng, role="prefill")

    def test_ship_mode_requires_prefix_cache(self, setup):
        r = FleetRouter(disaggregate=True, kv_ship=True)
        with pytest.raises(ValueError, match="prefix_cache_size > 0"):
            r.add_replica("p0", make_engine(setup, prefix_cache=0),
                          role="prefill")


# ---------------------------------------------------------------------------
# steady-state recompiles (HIVED_COMPILE_GUARD pin, per replica)
# ---------------------------------------------------------------------------

class TestCompileGuard:
    def test_disagg_fleet_steady_state_zero_recompiles(self, setup,
                                                       monkeypatch):
        monkeypatch.setenv("HIVED_COMPILE_GUARD", "1")
        compileguard.reset()
        r = FleetRouter(disaggregate=True, kv_ship=True)
        r.add_replica("p0", make_engine(setup), role="prefill")
        r.add_replica("d0", make_engine(setup), role="decode")
        # warm: fresh prompts covering the workload's shapes (full-prompt
        # prefill bucket, the import path's block writes, the tail
        # prefill bucket, decode)
        warm = [r.submit(list(range(1, 12)), 4),
                r.submit(list(range(30, 41)), 4)]
        r.run_until_drained()
        assert all(w.done for w in warm)
        # steady state: DIFFERENT prompts of the same shape — every
        # program is already compiled, per replica
        with compileguard.budget(0):
            reqs = [r.submit([int(t) % 60 + 1 for t in range(i, i + 11)], 4)
                    for i in (5, 17)]
            r.run_until_drained()
        assert all(f.finish_reason == "length" for f in reqs)
        compileguard.reset()


# /v1/inspect/fleet: the published-router snapshot over HTTP is covered
# by test_inspect_endpoints' prefix discovery; publish/unpublish rides
# TestRoutingPolicies.test_least_blocks_spread_snapshot_publish above.
