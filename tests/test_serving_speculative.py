"""Speculative continuous batching (serving.SpeculativeServingEngine).

Load-bearing properties: (1) greedy speculation is an acceleration, not an
approximation — every request's output must equal vanilla greedy decode
even with a garbage draft, under slot recycling and interleaving; (2)
per-row acceptance actually decouples rows (a perfect draft accepts
everything while a bad one doesn't drag it down — the uniform-batch
engine's min-barrier is gone)."""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import decode, serving, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    dft_cfg = cfg_of(d_model=32, n_heads=2, n_kv_heads=1, d_ff=64)
    dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(7))
    return cfg, params, dft_cfg, dft_params


def vanilla(params, cfg, prompt, n):
    out = decode.generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, n,
        max_len=len(prompt) + n,
    )
    return [int(t) for t in np.asarray(out)[0]]


class TestSpeculativeServing:
    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_interleaved_exact_vs_vanilla_with_weak_draft(self, setup):
        cfg, params, dft_cfg, dft_params = setup
        eng = serving.SpeculativeServingEngine(
            params, cfg, dft_params, dft_cfg, gamma=3, max_batch=2, max_len=64,
        )
        prompts = [[5, 9, 2], [17, 3, 88, 41, 7], [1], [100, 22, 63, 4]]
        budgets = [7, 4, 9, 5]
        reqs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
        eng.run_until_drained()
        for req, p, n in zip(reqs, prompts, budgets):
            assert req.done
            assert req.tokens_out == vanilla(params, cfg, p, n), req.rid
        assert 0.0 <= eng.acceptance <= 1.0

    def test_perfect_draft_accepts_everything(self, setup):
        cfg, params, _, _ = setup
        eng = serving.SpeculativeServingEngine(
            params, cfg, params, cfg, gamma=3, max_batch=1, max_len=64,
        )
        r = eng.submit([5, 9, 2], 9)
        eng.run_until_drained()
        assert r.tokens_out == vanilla(params, cfg, [5, 9, 2], 9)
        assert eng.acceptance == 1.0  # draft == target: every proposal lands
        # 1 prefill token + ceil(8 / (gamma+1)) = 2 spec rounds
        assert eng.steps == 2

    def test_per_row_acceptance_no_min_barrier(self, setup):
        """A perfect-draft row keeps its full acceptance while sharing the
        engine with nothing to drag it: two rows with different prompt
        streams must each match vanilla AND the total step count must be
        below what a min-barrier would allow if either row rejected."""
        cfg, params, _, _ = setup
        eng = serving.SpeculativeServingEngine(
            params, cfg, params, cfg, gamma=3, max_batch=2, max_len=64,
        )
        a = eng.submit([5, 9, 2], 9)
        b = eng.submit([17, 3, 88], 9)
        eng.run_until_drained()
        assert a.tokens_out == vanilla(params, cfg, [5, 9, 2], 9)
        assert b.tokens_out == vanilla(params, cfg, [17, 3, 88], 9)
        assert eng.acceptance == 1.0
        assert eng.steps == 2  # both rows advance 4 tokens/round, no barrier

    def test_recycled_slot_mid_flight(self, setup):
        cfg, params, dft_cfg, dft_params = setup
        eng = serving.SpeculativeServingEngine(
            params, cfg, dft_params, dft_cfg, gamma=2, max_batch=1, max_len=64,
        )
        a = eng.submit([5, 9, 2], 3)
        b = eng.submit([100, 22, 63, 4], 6)  # waits for a's slot
        eng.run_until_drained()
        assert a.tokens_out == vanilla(params, cfg, [5, 9, 2], 3)
        assert b.tokens_out == vanilla(params, cfg, [100, 22, 63, 4], 6)

    @pytest.mark.parametrize("prefill_chunk", [0, 3])
    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_fuzz_random_interleavings(self, setup, prefill_chunk):
        """Random prompts/budgets at random arrival offsets through the
        speculative engine (weak draft): every request still equals its solo
        vanilla run — the speculative analogue of the plain engine's fuzz.
        Runs monolithic AND chunked (prefill_chunk + gamma both active)."""
        import random

        cfg, params, dft_cfg, dft_params = setup
        rng = random.Random(23)
        eng = serving.SpeculativeServingEngine(
            params, cfg, dft_params, dft_cfg, gamma=2, max_batch=2,
            max_len=64, prefill_chunk=prefill_chunk,
        )
        plan = sorted(
            ((rng.randrange(0, 8),
              [rng.randrange(1, cfg.vocab_size) for _ in
               range(rng.randrange(1, 7))],
              rng.randrange(1, 7)) for _ in range(5)),
            key=lambda t: t[0],
        )
        live = []
        step = 0
        while plan or eng.queue or any(eng.slots) or not live:
            while plan and plan[0][0] <= step:
                _, p, n = plan.pop(0)
                live.append((eng.submit(p, n), p, n))
            if not eng.step() and not plan:
                break
            step += 1
        eng.run_until_drained()
        for req, p, n in live:
            assert req.done
            assert req.tokens_out == vanilla(params, cfg, p, n), req.rid

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_mesh_sharded_engine_exact(self, setup):
        """Speculative serving over a dp x tp mesh: the target shards
        tensor-parallel, the draft shards when its kv heads divide tp
        (tp=1 here) and is replicated otherwise (tp=2: draft kv 1 % 2) —
        greedy streams must equal vanilla decode either way."""
        cfg, params, dft_cfg, dft_params = setup
        from hivedscheduler_tpu.parallel import topology

        # a draft whose kv heads DO divide tp=2, so the genuinely
        # tensor-parallel draft branch (sharded params + tp-sharded draft
        # cache head axis) runs, not just the replicated fallback
        tp_dft_cfg = cfg_of(d_model=32, n_heads=2, n_kv_heads=2, d_ff=64)
        tp_dft_params = tm.init_params(tp_dft_cfg, jax.random.PRNGKey(8))

        prompts = [[5, 9, 2], [17, 3, 88, 41, 7], [1]]
        budgets = [6, 4, 7]
        for tp, dcfg, dparams in (
            (1, dft_cfg, dft_params),        # trivially sharded
            (2, dft_cfg, dft_params),        # kv 1 % 2 -> replicated draft
            (2, tp_dft_cfg, tp_dft_params),  # kv 2 % 2 -> tp-sharded draft
        ):
            axes = topology.MeshAxes(dp=2, tp=tp)
            mesh = topology.make_mesh(axes, jax.devices("cpu")[:axes.size])
            eng = serving.SpeculativeServingEngine(
                params, cfg, dparams, dcfg, gamma=3, max_batch=2,
                max_len=64, mesh=mesh,
            )
            reqs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
            eng.run_until_drained()
            for req, p, n in zip(reqs, prompts, budgets):
                assert req.tokens_out == vanilla(params, cfg, p, n), (tp, req.rid)

    def test_validation(self, setup):
        cfg, params, dft_cfg, dft_params = setup
        # temperature > 0 is supported since round 5 (sampled speculation
        # with per-row residual resampling; test_serving_speculative_sampled)
        eng = serving.SpeculativeServingEngine(
            params, cfg, dft_params, dft_cfg, temperature=0.5)
        assert eng._spec_round_sampled is not None
        with pytest.raises(ValueError, match="gamma"):
            serving.SpeculativeServingEngine(
                params, cfg, dft_params, dft_cfg, gamma=0)
        with pytest.raises(ValueError, match="vocab"):
            serving.SpeculativeServingEngine(
                params, cfg, dft_params, cfg_of(vocab_size=64))
        eng = serving.SpeculativeServingEngine(
            params, cfg, dft_params, dft_cfg, gamma=4, max_len=32)
        with pytest.raises(ValueError, match="headroom"):
            eng.submit([1] * 20, 8)  # 20 + 8 + 5 > 32
