"""Pipeline (pp) and expert (ep) parallelism tests: the pipelined forward must
produce exactly the non-pipelined logits; the MoE layer must run ep-sharded
and train; gradients must flow through the pipeline."""

import numpy as np

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import transformer as tm  # noqa: E402
from hivedscheduler_tpu.parallel import topology  # noqa: E402


def cpu_mesh(axes):
    return topology.make_mesh(axes, topology.get_devices(axes.size))


def tiny_cfg(**kw):
    base = dict(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    base.update(kw)
    return tm.TransformerConfig(**base)


class TestPipeline:
    def test_pipelined_forward_matches_dense(self):
        cfg_ref = tiny_cfg()
        cfg_pp = tiny_cfg(pipeline_microbatches=2)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=4))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
            ref = tm.forward(params, tokens, cfg_ref)
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg_pp, mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_pipeline_gradients_flow(self):
        cfg_pp = tiny_cfg(pipeline_microbatches=2)
        cfg_ref = tiny_cfg()
        mesh = cpu_mesh(topology.MeshAxes(pp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)

        def loss_pp(p):
            return jnp.mean(tm.forward(p, tokens, cfg_pp, mesh=mesh) ** 2)

        def loss_ref(p):
            return jnp.mean(tm.forward(p, tokens, cfg_ref) ** 2)

        g_pp = jax.jit(jax.grad(loss_pp))(params)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            g_ref = jax.jit(jax.grad(loss_ref))(params)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_pipelined_train_step(self):
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tiny_cfg(pipeline_microbatches=2)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=2, ep=1))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), token_sharding
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]


class TestMoE:
    def test_moe_forward_shapes_and_finite(self):
        cfg = tiny_cfg(n_experts=4)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, ep=4))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg, mesh=mesh))(params, tokens)
        assert out.shape == (4, 16, 64)
        assert bool(jnp.isfinite(out).all())

    def test_moe_capacity_drops_overflow(self):
        # n_experts=1 + capacity factor ~0 floors capacity at 1: only the
        # first token per row keeps its expert output; all later (dropped)
        # positions must equal a model whose expert down-projection is zero
        # (residual path only)
        cfg = tiny_cfg(n_experts=1, n_layers=1, expert_capacity_factor=1e-9)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg, jax.random.PRNGKey(0))
            zeroed = jax.tree.map(lambda x: x, params)
            zeroed["layers"] = dict(params["layers"])
            zeroed["layers"]["w_down"] = jnp.zeros_like(params["layers"]["w_down"])
            tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
            out = tm.forward(params, tokens, cfg)
            out_res = tm.forward(zeroed, tokens, cfg)
        # first token per row got expert compute -> differs from residual-only
        assert not np.allclose(np.asarray(out[:, 0]), np.asarray(out_res[:, 0]))
        # every overflowed token was dropped -> identical to residual-only
        np.testing.assert_allclose(
            np.asarray(out[:, 1:]), np.asarray(out_res[:, 1:]), atol=1e-6
        )

    def test_moe_train_step_ep_sharded(self):
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tiny_cfg(n_experts=4)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, ep=4))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        # expert weights actually sharded over ep
        w = params["layers"]["w_gate"]
        assert "ep" in str(w.sharding.spec)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), token_sharding
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]


class TestPipelineTensorParallel:
    def test_pipelined_tp_matches_dense(self):
        """pp=2 x tp=2: weights tensor-sharded inside stages with manual
        row-parallel psums must reproduce the dense logits exactly."""
        cfg_ref = tiny_cfg()
        cfg_pp = tiny_cfg(pipeline_microbatches=2)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=2, tp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
            ref = tm.forward(params, tokens, cfg_ref)
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg_pp, mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_pipelined_tp_train_step(self):
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tiny_cfg(pipeline_microbatches=2)
        mesh = cpu_mesh(topology.MeshAxes(pp=2, tp=2, dp=2))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        # layer weights really are tp-sharded under the pipeline
        spec = str(params["layers"]["wq"].sharding.spec)
        assert "pp" in spec and "tp" in spec
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), token_sharding
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]



class TestPipelineSequenceParallel:
    def test_pipelined_ring_matches_dense(self):
        """pp=2 x sp=2: sequence sharded through the pipeline with ring
        attention inside the stage must reproduce dense logits exactly."""
        cfg_ref = tiny_cfg()
        cfg_pp = tiny_cfg(pipeline_microbatches=2, attn_impl="ring")
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
            ref = tm.forward(params, tokens, cfg_ref)
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg_pp, mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): dp x pp x
    # tp x sp composition variant; tier-1 cousins: TestPipelineFSDP::
    # test_pipelined_fsdp_train_step (pp x dp) + the ring-attention
    # train-step guards (test_parallel.py TestGQA[ring])
    def test_pipelined_ring_tp_train_step(self):
        """The full composition: dp x pp x tp x sp in one jitted train step."""
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tiny_cfg(pipeline_microbatches=2, attn_impl="ring")
        mesh = cpu_mesh(topology.MeshAxes(pp=2, tp=2, sp=2))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), token_sharding
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_pipeline_sp_with_local_attention_rejected(self):
        cfg = tiny_cfg(pipeline_microbatches=2)  # xla attention
        mesh = cpu_mesh(topology.MeshAxes(pp=2, sp=2, dp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        with pytest.raises(ValueError, match="requires one of attn_impl"):
            tm.forward(params, tokens, cfg, mesh=mesh)


class TestTop2MoE:
    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): top-2
    # routing variant of the MoE train step; tier-1 cousins: TestMoE::
    # test_moe_train_step_ep_sharded (top-1 train) + the serving-side
    # top-2 routing guards (test_serving_moe.py, moe_top_k=2)
    def test_top2_forward_and_train(self):
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tiny_cfg(n_experts=4, moe_top_k=2)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, ep=4))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), token_sharding
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_top2_output_equals_dense_mixture(self):
        """With ample capacity (nothing dropped), the top-2 MoE output must
        equal the dense mixture sum_k gate_k * FFN_{expert_k}(h) with gates
        renormalized over the two chosen experts."""
        cfg = tiny_cfg(n_experts=4, n_layers=1, moe_top_k=2,
                       expert_capacity_factor=8.0)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg, jax.random.PRNGKey(0))
            h = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 32), jnp.float32)
            lp = jax.tree.map(lambda x: x[0], params["layers"])
            out, aux = tm._moe_mlp(h, lp, cfg, jnp.float32)

            # dense reference: run every expert on every token, mix by the
            # renormalized top-2 gates
            logits = jnp.einsum("btd,de->bte", h, lp["router"])
            probs = jax.nn.softmax(logits, axis=-1)
            g2, i2 = jax.lax.top_k(probs, 2)
            g2 = g2 / g2.sum(-1, keepdims=True)
            every = jnp.einsum(
                "ebtf,efd->ebtd",
                jax.nn.silu(jnp.einsum("btd,edf->ebtf", h, lp["w_gate"]))
                * jnp.einsum("btd,edf->ebtf", h, lp["w_up"]),
                lp["w_down"],
            )  # [E, B, T, D]
            expected = jnp.zeros_like(h)
            for kk in range(2):
                sel = jnp.take_along_axis(
                    jnp.einsum("ebtd->bted", every), i2[:, :, kk][..., None, None],
                    axis=2,
                )[:, :, 0]
                expected = expected + g2[:, :, kk][..., None] * sel
        assert bool(jnp.isfinite(aux))
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=1e-5, rtol=1e-5)

    def test_top1_behavior_unchanged(self):
        """moe_top_k=1 must keep the raw-gate switch semantics (covered by the
        capacity-drop test); just confirm the config default wiring."""
        cfg = tiny_cfg(n_experts=2)
        assert cfg.moe_top_k == 1


class TestMeshLayoutInvariance:
    @pytest.mark.slow  # KNOWN-RED (pre-existing, ROADMAP item 5: manual-pp layout 2e-3 loss gap);
    # moved out of tier-1 for the wall-time budget — still runs (red) under -m slow
    def test_loss_identical_across_layouts(self):
        """The same model/seed/batch must produce the same loss under any
        mesh layout — dp-only, tp+sp GSPMD, and pp+tp manual mode."""
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        layouts = [
            (tiny_cfg(), topology.MeshAxes(dp=8)),
            (tiny_cfg(attn_impl="ring"), topology.MeshAxes(dp=2, tp=2, sp=2)),
            (tiny_cfg(attn_impl="ring_zigzag"), topology.MeshAxes(dp=2, tp=2, sp=2)),
            (tiny_cfg(attn_impl="ring_zigzag", pipeline_microbatches=2),
             topology.MeshAxes(dp=2, pp=2, sp=2)),
            (tiny_cfg(pipeline_microbatches=2), topology.MeshAxes(dp=2, pp=2, tp=2)),
        ]
        losses = []
        for cfg, axes in layouts:
            mesh = cpu_mesh(axes)
            step, init_fn, tok_sh = make_sharded_train_step(cfg, mesh)
            params, opt_state = init_fn(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64), tok_sh
            )
            _, _, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        for other in losses[1:]:
            assert abs(other - losses[0]) < 1e-4, losses


class TestRouterZLoss:
    def test_zloss_adds_weighted_penalty(self):
        """aux with z-loss enabled = aux without + zloss_weight *
        mean(logsumexp(router logits)^2); gradients stay finite."""
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg0 = tiny_cfg(n_experts=2)
        cfgz = tiny_cfg(n_experts=2, moe_zloss_weight=0.5)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg0, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
            _, aux0 = tm.forward_with_aux(params, tokens, cfg0)
            _, auxz = tm.forward_with_aux(params, tokens, cfgz)
            assert float(auxz) > float(aux0)
        # end-to-end: a train step with z-loss produces a finite loss and the
        # router still receives gradients
        mesh = cpu_mesh(topology.MeshAxes(dp=2, ep=2))
        step, init_fn, tok_sh = make_sharded_train_step(cfgz, mesh)
        params, opt = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), tok_sh)
        _, _, loss = step(params, opt, tokens)
        assert bool(jnp.isfinite(loss))

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_zloss_shrinks_router_logits_when_trained(self):
        """Training with a strong z-loss must drive router logit norms down
        relative to training without it."""
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        mesh = cpu_mesh(topology.MeshAxes(dp=2, ep=2))
        norms = {}
        for w in (0.0, 1.0):
            cfg = tiny_cfg(n_experts=2, moe_zloss_weight=w)
            step, init_fn, tok_sh = make_sharded_train_step(cfg, mesh)
            params, opt = init_fn(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64),
                tok_sh)
            for _ in range(8):
                params, opt, _ = step(params, opt, tokens)
            norms[w] = float(jnp.linalg.norm(params["layers"]["router"]))
        assert norms[1.0] < norms[0.0], norms


class TestMoEInPipeline:
    def test_pipelined_moe_matches_gspmd(self):
        """pp=2 x ep=2 MoE inside stages must equal the GSPMD (non-pipelined)
        MoE model exactly."""
        cfg_ref = tiny_cfg(n_experts=4)
        cfg_pp = tiny_cfg(n_experts=4, pipeline_microbatches=2)
        mesh_ref = cpu_mesh(topology.MeshAxes(dp=2, ep=4))
        mesh_pp = cpu_mesh(topology.MeshAxes(dp=2, pp=2, ep=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        ref = jax.jit(lambda p, t: tm.forward(p, t, cfg_ref, mesh=mesh_ref))(
            params, tokens)  # the GSPMD ep-sharded path
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg_pp, mesh=mesh_pp))(
            params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)

    def test_pipelined_moe_train_step(self):
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tiny_cfg(n_experts=4, pipeline_microbatches=2, moe_top_k=2)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=2, ep=2))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        assert "ep" in str(params["layers"]["w_gate"].sharding.spec)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), token_sharding
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    @pytest.mark.parametrize("cf,top_k", [(1.25, 1), (2.0, 1), (1.25, 2)])
    def test_moe_inside_sp_pipeline_matches_dense(self, cf, top_k):
        """pp=2 x sp=2 with MoE layers: the sequence-sharded stage must
        reproduce GLOBAL routing-capacity semantics exactly (same tokens
        overflow as in the dense computation), so the pipelined logits equal
        the dense ones. cf=1.25 gives an sp-indivisible capacity (the psum
        fallback); cf=2.0 an even one (the reduce-scatter path); top_k=2
        pins the cross-shard choice-ordering (global choice-0 counts before
        any choice-1 slot)."""
        cfg_ref = tiny_cfg(n_experts=4, expert_capacity_factor=cf,
                           moe_top_k=top_k)
        cfg_pp = tiny_cfg(n_experts=4, expert_capacity_factor=cf,
                          moe_top_k=top_k,
                          pipeline_microbatches=2, attn_impl="ring")
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
            ref = tm.forward(params, tokens, cfg_ref)
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg_pp, mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): triple-
    # composition variant; tier-1 cousins: TestMoE::
    # test_moe_train_step_ep_sharded (moe x ep) + TestPipelineFSDP::
    # test_pipelined_fsdp_train_step (pp composition)
    def test_moe_sp_ep_pipeline_train_step(self):
        """Full composition including experts: pp x sp x ep in one jitted
        train step, loss finite and decreasing."""
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tiny_cfg(n_experts=4, pipeline_microbatches=2, attn_impl="ring")
        mesh = cpu_mesh(topology.MeshAxes(pp=2, sp=2, ep=2))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), token_sharding
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_indivisible_experts_rejected(self):
        cfg = tiny_cfg(n_experts=3, pipeline_microbatches=2)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=2, ep=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        with pytest.raises(ValueError, match="not divisible"):
            tm.forward(params, tokens, cfg, mesh=mesh)


class TestUlyssesInPipeline:
    def test_pipelined_ulysses_matches_dense(self):
        """pp=2 x sp=2 with Ulysses all-to-all inside the stage: H=4 heads
        swap across sp=2."""
        cfg_ref = tiny_cfg()
        cfg_pp = tiny_cfg(pipeline_microbatches=2, attn_impl="ulysses")
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
            ref = tm.forward(params, tokens, cfg_ref)
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg_pp, mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


class TestPipelineFSDP:
    def test_pipelined_fsdp_matches_dense(self):
        """pp=2 x fsdp=2: layer weights sharded over fsdp inside stages and
        gathered per use must reproduce dense logits exactly."""
        cfg_ref = tiny_cfg()
        cfg_pp = tiny_cfg(pipeline_microbatches=2)
        mesh = cpu_mesh(topology.MeshAxes(fsdp=2, pp=2, tp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
            ref = tm.forward(params, tokens, cfg_ref)
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg_pp, mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_pipelined_fsdp_train_step(self):
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tiny_cfg(pipeline_microbatches=2)
        mesh = cpu_mesh(topology.MeshAxes(fsdp=2, pp=2, dp=2))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        # weights genuinely fsdp-sharded under the pipeline
        spec = str(params["layers"]["wq"].sharding.spec)
        assert "pp" in spec and "fsdp" in spec
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64), token_sharding
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]
