"""Inspect-endpoint rot guard (ISSUE 11 satellite): every registered
``/v1/inspect/*`` endpoint must return valid JSON from a booted
fake-cluster server — under load AND mid-drain — the same blind-spot
class as ``TestExampleConfigsValid`` (shipped artifacts rot silently
unless a test boots them).

The endpoint inventory is derived from ``api.constants`` by prefix, so a
new inspect path is covered the moment its constant lands; the test also
pins the ``GET /v1`` listing to that inventory so the discovery surface
cannot drift from the registered routes.
"""

import json
import os
import urllib.request

import pytest

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.obs import decisions as obs_decisions
from hivedscheduler_tpu.obs import journal as obs_journal
from hivedscheduler_tpu.obs import ledger as obs_ledger
from hivedscheduler_tpu.obs import trace as obs_trace

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)

# every /v1/inspect/* route constant, discovered — not hand-listed
INSPECT_PATHS = sorted({
    v for k, v in vars(C).items()
    if isinstance(v, str) and v.startswith(C.INSPECT_PATH + "/")
})


@pytest.fixture(scope="module")
def stack():
    from helpers import make_pod

    from hivedscheduler_tpu.k8s.fake import FakeKubeClient
    from hivedscheduler_tpu.k8s.types import Node
    from hivedscheduler_tpu.runtime import extender as ei
    from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
    from hivedscheduler_tpu.webserver import WebServer

    # full observability on, as the demo CLI runs it (the capacity
    # ledger BEFORE the scheduler so the algorithm registers its chips)
    obs_decisions.RECORDER.enable()
    obs_trace.enable()
    obs_journal.enable()
    obs_ledger.enable()
    config = load_config(FIXTURE)
    config.web_server_address = "127.0.0.1:0"
    kube = FakeKubeClient()
    scheduler = HivedScheduler(config, kube)
    algo = scheduler.scheduler_algorithm
    nodes = sorted({n for ccl in algo.full_cell_list.values()
                    for c in ccl[max(ccl)] for n in c.nodes})
    for n in nodes:
        kube.create_node(Node(name=n))
    scheduler.start()
    # load: schedule real gangs through the extender so every inspect
    # surface has live state to render (groups, traces, journal, defrag)
    for i in range(3):
        pod = make_pod(f"load{i}", {"virtualCluster": "vc2", "priority": 0,
                                    "chipType": "v5e-chip",
                                    "chipNumber": 8})
        kube.create_pod(pod)
        r = scheduler.filter_routine(ei.ExtenderArgs(
            pod=kube.get_pod(pod.namespace, pod.name), node_names=nodes))
        if r.node_names:
            scheduler.bind_routine(ei.ExtenderBindingArgs(
                pod_name=pod.name, pod_namespace=pod.namespace,
                pod_uid=pod.uid, node=r.node_names[0]))
    server = WebServer(scheduler)
    host, port = server.async_run()
    yield server, f"http://{host}:{port}"
    server.stop()
    obs_decisions.RECORDER.disable()
    obs_decisions.RECORDER.clear()
    obs_trace.disable()
    obs_trace.TRACER.clear()
    obs_journal.disable()
    obs_journal.JOURNAL.clear()
    obs_ledger.disable()
    obs_ledger.LEDGER.clear()


def get_json(base, path):
    with urllib.request.urlopen(base + path) as r:
        assert r.status == 200, f"{path}: HTTP {r.status}"
        return json.loads(r.read())


def test_v1_listing_covers_every_registered_inspect_path(stack):
    _, base = stack
    listed = set(get_json(base, C.VERSION_PREFIX)["paths"])
    for path in INSPECT_PATHS:
        assert path in listed, (
            f"{path} is a registered inspect constant but missing from the "
            f"GET /v1 listing — new endpoints must be discoverable"
        )


@pytest.mark.parametrize("path", INSPECT_PATHS)
def test_inspect_endpoint_serves_valid_json_under_load(stack, path):
    _, base = stack
    body = get_json(base, path)
    assert isinstance(body, (dict, list))


@pytest.mark.parametrize("path", INSPECT_PATHS)
def test_inspect_endpoint_survives_drain(stack, path):
    """Mid-drain (/readyz 503) the inspect surface must stay readable —
    that is exactly when an operator needs it."""
    server, base = stack
    server.begin_drain(retry_after_s=1)
    try:
        body = get_json(base, path)
        assert isinstance(body, (dict, list))
    finally:
        server.draining = False


def test_gang_timeline_detail_endpoint(stack):
    """The parametrized sweep covers collection endpoints; the per-gang
    timeline needs an id — reconstruct one from the live journal."""
    _, base = stack
    gangs = get_json(base, C.GANGS_PATH)
    assert gangs["enabled"] and gangs["items"]
    gang = gangs["items"][0]["gang"]
    tl = get_json(base, C.GANGS_PATH + f"/{gang}/timeline")
    assert tl["gang"] == gang and tl["events"]
    assert all(e["type"] for e in tl["events"])
