"""Tier-1 bench smoke: perf-path regressions fail tests instead of only
showing up in the end-of-round bench (ISSUE 4 CI satellite).

Runs the REAL headline-bench scenario (bench.py: v5p-1024 multi-VC churn +
measured 256-chip gang) at a few iterations, CPU-only — asserting the two
properties the driver metric cares about:

- ``frag_pct == 0.0``: the 256-chip slice always places contiguously while
  vc-a's guarantee is free (buddy allocation over mesh tilings);
- a full gang decision completes under a GENEROUS wall-clock ceiling, so an
  accidental O(n^2) (or a broken fast path falling back to something
  pathological) trips CI rather than the next bench round. The ceiling is
  ~50x the expected p50 to stay robust on slow shared CI boxes.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


GENEROUS_CEILING_MS = 500.0  # expected p50 ~5-10 ms on the dev box


def test_bench_smoke_frag_zero_and_bounded_latency():
    p50, p99, frag_pct = bench.run(measure_iters=3)
    assert frag_pct == 0.0, (
        f"fragmentation in the measured 256-chip gang: {frag_pct}%"
    )
    assert p50 < GENEROUS_CEILING_MS, (
        f"gang-schedule p50 {p50:.1f} ms blew the generous ceiling "
        f"({GENEROUS_CEILING_MS} ms) — a perf-path regression"
    )
    assert p99 < 4 * GENEROUS_CEILING_MS


def test_bench_views_consistent_after_run():
    """After the bench scenario's churn, every persistent cluster view must
    still compare equal to a from-scratch rebuild (ties the CI smoke to the
    incremental-view differential)."""
    from hivedscheduler_tpu.chaos import invariants

    cluster = bench.Cluster()
    ok, _, _ = cluster.schedule_gang("vc-a", 10, "g", 64, 4,
                                    allow_preempt=True)
    assert ok
    invariants.check_cluster_views(cluster.algo, ctx="bench smoke")
    cluster.free_gang("g")
    invariants.check_cluster_views(cluster.algo, ctx="bench smoke post-free")
    invariants.check_all(cluster.algo, ctx="bench smoke post-free")
