"""Guard: the metric-name catalogue lint (tools/check_metrics.py) passes on
the package, and actually catches the two drift directions it exists for."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_metrics.py")


def test_package_metric_names_all_described():
    """Every REGISTRY.inc/observe/set_gauge literal name has a describe()
    entry and no described name is dead (ISSUE satellite)."""
    proc = subprocess.run([sys.executable, TOOL], cwd=REPO,
                          capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"check_metrics failed:\n{proc.stdout}{proc.stderr}"
    )
    assert "OK" in proc.stdout


def test_collector_catches_drift(tmp_path):
    """The AST collector flags undescribed emits, dead describes, and
    non-literal names."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_metrics
    finally:
        sys.path.pop(0)
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "REGISTRY.describe('tpu_hive_dead_total', 'never emitted')\n"
        "REGISTRY.inc('tpu_hive_orphan_total')\n"
        "metrics.observe('tpu_hive_lat_seconds', 0.1)\n"
        "name = 'tpu_hive_dynamic'\n"
        "REGISTRY.inc(name)\n"
    )
    emitted, described, dynamic = check_metrics.collect(str(pkg))
    assert set(emitted) == {"tpu_hive_orphan_total", "tpu_hive_lat_seconds"}
    assert described == {"tpu_hive_dead_total"}
    assert len(dynamic) == 1 and "non-literal" in dynamic[0]
