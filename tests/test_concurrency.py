"""Concurrency stress: hammer the scheduler runtime from multiple threads
(filter/bind/preempt + pod/node events + inspect reads) and assert no
deadlock, no unhandled exception, and consistent final state.

The reference's only concurrency testing is `go test -race` in CI
(SURVEY.md §5); this drives the actual locking design under real thread
interleavings.
"""

import logging
import random
import threading

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.common.utils import to_yaml
from hivedscheduler_tpu.k8s.fake import FakeKubeClient
from hivedscheduler_tpu.k8s.types import Container, Node, Pod
from hivedscheduler_tpu.runtime import extender as ei
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler

logging.getLogger().setLevel(logging.CRITICAL)

import os

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


from helpers import make_pod as _make_pod


def make_pod(name, vc, chips, chip_type, priority=0):
    return _make_pod(name, {"virtualCluster": vc, "priority": priority,
                            "chipType": chip_type, "chipNumber": chips})


def test_concurrent_schedule_bind_delete_and_node_events():
    config = load_config(FIXTURE)
    kube = FakeKubeClient()
    scheduler = HivedScheduler(config, kube)
    algo = scheduler.scheduler_algorithm
    nodes = sorted({n for ccl in algo.full_cell_list.values()
                    for c in ccl[max(ccl)] for n in c.nodes})
    for n in nodes:
        kube.create_node(Node(name=n))
    scheduler.start()

    errors = []
    barrier = threading.Barrier(5)
    ops_per_thread = 30

    def worker(tid):
        rng = random.Random(tid)
        barrier.wait()
        for i in range(ops_per_thread):
            name = f"t{tid}-p{i}"
            vc, chip_type = rng.choice(
                [("vc1", "v5p-chip"), ("vc2", "v5p-chip"), ("vc2", "v5e-chip")]
            )
            pod = make_pod(name, vc, rng.choice([1, 2, 4]), chip_type,
                           priority=rng.choice([-1, 0, 5]))
            try:
                kube.create_pod(pod)
                result = scheduler.filter_routine(
                    ei.ExtenderArgs(pod=pod, node_names=nodes)
                )
                if result.node_names:
                    scheduler.bind_routine(ei.ExtenderBindingArgs(
                        pod_name=pod.name, pod_namespace=pod.namespace,
                        pod_uid=pod.uid, node=result.node_names[0],
                    ))
                    if rng.random() < 0.5:
                        kube.delete_pod(pod.namespace, pod.name)
                else:
                    kube.delete_pod(pod.namespace, pod.name)
            except api.WebServerError:
                pass  # user-class errors are expected under contention
            except Exception as e:  # pragma: no cover
                errors.append((name, repr(e)))

    def chaos():
        rng = random.Random(99)
        barrier.wait()
        for _ in range(40):
            n = rng.choice(nodes)
            if rng.random() < 0.5:
                kube.delete_node(n)
            else:
                kube.create_node(Node(name=n))
            scheduler.get_cluster_status()
            scheduler.get_all_affinity_groups()

    # daemon threads: if the deadlock this test hunts for ever comes back,
    # pytest must be able to report the failure and exit
    threads = [threading.Thread(target=worker, args=(t,), daemon=True) for t in range(4)]
    threads.append(threading.Thread(target=chaos, daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "deadlock: thread did not finish"
    assert not errors, errors

    # consistency: every cell priority/state pairing is legal, and cell usage
    # accounting is internally consistent per chain level
    for chain, ccl in algo.full_cell_list.items():
        for level, cells in ccl.items():
            for cell in cells:
                if cell.state == "Free":
                    assert cell.priority == -2, (cell.address, cell.priority)
                used = sum(cell.used_leaf_cell_num_at_priorities.values())
                assert 0 <= used <= cell.total_leaf_cell_num
    # the safety invariant survived the storm
    for chain, by_level in algo.all_vc_free_cell_num.items():
        for level, num in by_level.items():
            assert algo.total_left_cell_num[chain][level] >= num, (
                chain, level, algo.total_left_cell_num[chain][level], num)
