"""Reference-scale adversarial placement goldens.

Drives the 5-chain gnarly fixture (``example/config/design/tpu-hive-gnarly.yaml``
— asymmetric 8x4x2 mesh with a pinned half, forged sub-host v5e levels,
a two-multi-node-level generic chain, non-standard addresses/chip indices,
a scrambled hierarchy and a multi-type node) through a 40+ pod table with
exact expected bind infos, expected preemption victims, full-delete
invariants (including free-list restoration), a stateful preemption chain
with preemptor-cancellation goldens, reconfiguration lazy-preempt
expectations, and bad-node behavior.

Mirrors the reference's table-driven suite
(``pkg/algorithm/hived_algorithm_test.go:172-608`` over
``example/config/design/hivedscheduler.yaml:29-290``). Any change to packing
order, buddy tie-breaking, or mesh-tiling order diffs here.
"""

import logging
import os
import random

import pytest
import yaml

from helpers import make_pod, set_healthy_nodes

from hivedscheduler_tpu.api.config import Config, load_config, new_config
from hivedscheduler_tpu.api.types import WebServerError
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.algorithm.constants import (
    GROUP_ALLOCATED,
    GROUP_BEING_PREEMPTED,
    GROUP_PREEMPTING,
)
from hivedscheduler_tpu.k8s.types import Node
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE, PREEMPTING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive-gnarly.yaml",
)


def spec(vc, prio, typ, num, group, members, pinned="", lazy=True):
    s = {"virtualCluster": vc, "priority": prio, "leafCellNumber": num,
         "lazyPreemptionEnable": lazy,
         "affinityGroup": {"name": group, "members": [
             {"podNumber": p, "leafCellNumber": n} for p, n in members]}}
    if typ:
        s["leafCellType"] = typ
    if pinned:
        s["pinnedCellId"] = pinned
    return s


@pytest.fixture
def algo():
    random.seed(0)
    h = HivedAlgorithm(load_config(FIXTURE))
    set_healthy_nodes(h)
    return h


def free_list_snapshot(h):
    """(chain, level) -> sorted cell addresses of the free list."""
    return {
        (chain, lv): sorted(c.address for c in ccl[lv])
        for chain, ccl in h.free_cell_list.items()
        for lv in sorted(ccl)
    }


# ---------------------------------------------------------------------------
# The table. BIND entries carry exact (node, chips) goldens; WAIT entries
# must not bind. Sequence order is load-bearing (placements build on each
# other), exactly like the reference's pss table.
# ---------------------------------------------------------------------------

SUCCEED = [
    # buddy packing on the asymmetric mesh
    ("p01", spec("vcB", 0, "v5p-chip", 1, "g01", [(1, 1)]),
     ("gp0/0-0-0", [0])),
    ("p02", spec("vcB", 1, "v5p-chip", 1, "g02", [(1, 1)]),
     ("gp0/0-0-0", [1])),  # buddy chip of p01
    # 8-chip gang: the gang-contiguity pass places it on ONE contiguous
    # 2x2x2 (hosts 2-0-0 + 2-0-1) instead of the reference-greedy L-shape
    # across buddy cells (0-0-1 + 2-0-0) — the TPU-first improvement over
    # the reference's flat per-pod bin-packing
    ("p03a", spec("vcB", 2, "v5p-chip", 4, "g03", [(2, 4)]),
     ("gp0/2-0-0", [0, 1, 2, 3])),
    ("p03b", spec("vcB", 2, "v5p-chip", 4, "g03", [(2, 4)]),
     ("gp0/2-0-1", [0, 1, 2, 3])),
    # opportunistic stays away from guaranteed pods; backfills the cell
    # already fragmented by p01/p02 instead of breaking a fresh one
    ("p04", spec("vcB", -1, "v5p-chip", 1, "g04", [(1, 1)]),
     ("gp0/0-0-1", [0])),
    # pinned-cell gang fills the pinned 4x4x2 half host by host
    ("p05a", spec("vcA", 1, "v5p-chip", 4, "g05", [(8, 4)], pinned="pin-gp"),
     ("gp0/4-0-0", [0, 1, 2, 3])),
    ("p05b", spec("vcA", 1, "v5p-chip", 4, "g05", [(8, 4)], pinned="pin-gp"),
     ("gp0/4-0-1", [0, 1, 2, 3])),
    ("p05c", spec("vcA", 1, "v5p-chip", 4, "g05", [(8, 4)], pinned="pin-gp"),
     ("gp0/6-0-0", [0, 1, 2, 3])),
    ("p05d", spec("vcA", 1, "v5p-chip", 4, "g05", [(8, 4)], pinned="pin-gp"),
     ("gp0/6-0-1", [0, 1, 2, 3])),
    ("p05e", spec("vcA", 1, "v5p-chip", 4, "g05", [(8, 4)], pinned="pin-gp"),
     ("gp0/4-2-0", [0, 1, 2, 3])),
    ("p05f", spec("vcA", 1, "v5p-chip", 4, "g05", [(8, 4)], pinned="pin-gp"),
     ("gp0/4-2-1", [0, 1, 2, 3])),
    ("p05g", spec("vcA", 1, "v5p-chip", 4, "g05", [(8, 4)], pinned="pin-gp"),
     ("gp0/6-2-0", [0, 1, 2, 3])),
    ("p05h", spec("vcA", 1, "v5p-chip", 4, "g05", [(8, 4)], pinned="pin-gp"),
     ("gp0/6-2-1", [0, 1, 2, 3])),
    # pinned chip with non-standard index 8 on the multi-type node
    ("p06", spec("vcA", 1, "ct-chip", 1, "g06", [(1, 1)], pinned="pin-ct"),
     ("10.0.0.2", [8])),
    # any-leaf-cell-type heterogeneous group -> generic g-chain node (and it
    # CONSUMES vcA's g-node, see p17)
    ("p08", spec("vcA", 1, "", 7, "g08", [(1, 7), (1, 1)]),
     ("12", [1, 2, 3, 4, 5, 6, 7])),
    ("p09", spec("vcA", 1, "", 1, "g08", [(1, 7), (1, 1)]),
     ("12", [0])),
    # standard-address ct node
    ("p10", spec("vcB", 0, "ct-chip", 2, "g10", [(1, 2)]),
     ("10.0.0.3", [0, 1])),
    # forged sub-host tiles on the single-host v5e: 2x2 tiles then the 4x2
    ("p11", spec("vcA", 0, "v5e-chip", 4, "g11", [(1, 4)]),
     ("ve0/0-0", [0, 1, 4, 5])),
    ("p12", spec("vcA", 0, "v5e-chip", 4, "g12", [(1, 4)]),
     ("ve0/0-0", [8, 9, 12, 13])),
    ("p13", spec("vcC", 0, "v5e-chip", 8, "g13", [(1, 8)]),
     ("ve0/0-0", [2, 3, 6, 7, 10, 11, 14, 15])),
    # generic chain nodes (default addresses 12..17)
    ("p14", spec("vcB", 0, "g-chip", 8, "g14", [(1, 8)]),
     ("14", [0, 1, 2, 3, 4, 5, 6, 7])),
    ("p15", spec("vcB", 0, "g-chip", 8, "g15", [(1, 8)]),
     ("13", [0, 1, 2, 3, 4, 5, 6, 7])),
    # multi-node gang across a whole g-rack
    ("p16a", spec("vcC", 0, "g-chip", 8, "g16", [(3, 8)]),
     ("15", [0, 1, 2, 3, 4, 5, 6, 7])),
    ("p16b", spec("vcC", 0, "g-chip", 8, "g16", [(3, 8)]),
     ("16", [0, 1, 2, 3, 4, 5, 6, 7])),
    ("p16c", spec("vcC", 0, "g-chip", 8, "g16", [(3, 8)]),
     ("17", [0, 1, 2, 3, 4, 5, 6, 7])),
    # whole mx node with default chip addresses on the multi-type node
    ("p18", spec("vcC", 0, "mx-chip", 8, "g18", [(1, 8)]),
     ("10.0.0.2", [0, 1, 2, 3, 4, 5, 6, 7])),
    # two sockets on the scrambled-address node: the SCRAMBLED chip indices
    # surface in the isolation handoff
    ("p19a", spec("vcB", 0, "mx-chip", 4, "g19", [(2, 4)]),
     ("10.0.0.0", [1, 3, 4, 7])),
    ("p19b", spec("vcB", 0, "mx-chip", 4, "g19", [(2, 4)]),
     ("10.0.0.0", [0, 2, 5, 6])),
    # vcC's guaranteed 4x2x2 share in the free half
    ("p20a", spec("vcC", 2, "v5p-chip", 4, "g20", [(4, 4)]),
     ("gp0/0-2-0", [0, 1, 2, 3])),
    ("p20b", spec("vcC", 2, "v5p-chip", 4, "g20", [(4, 4)]),
     ("gp0/0-2-1", [0, 1, 2, 3])),
    ("p20c", spec("vcC", 2, "v5p-chip", 4, "g20", [(4, 4)]),
     ("gp0/2-2-0", [0, 1, 2, 3])),
    ("p20d", spec("vcC", 2, "v5p-chip", 4, "g20", [(4, 4)]),
     ("gp0/2-2-1", [0, 1, 2, 3])),
]

WAIT = [
    # vcA's only g-node was consumed by the any-type group g08
    ("p17", spec("vcA", 0, "g-chip", 8, "g17", [(1, 8)])),
    # gang larger than vcC's remaining v5p guarantee
    ("p07", spec("vcC", 1, "v5p-chip", 4, "g07", [(5, 4)])),
]

USER_ERRORS = [
    # leaf cell type not in the VC
    ("f1", spec("vcB", 1, "v5e-chip", 1, "gf1", [(1, 1)])),
    # pod's leafCellNumber not among the group members
    ("f2", spec("vcB", 1, "v5p-chip", 3, "gf2", [(1, 4)])),
    # unknown VC
    ("f3", spec("surprise!", 1, "v5p-chip", 1, "gf3", [(1, 1)])),
    # unknown pinned cell
    ("f4", spec("vcA", 1, "v5p-chip", 1, "gf4", [(1, 1)], pinned="surprise!")),
    # priority above the guaranteed maximum
    ("f5", spec("vcB", 1001, "v5p-chip", 1, "gf5", [(1, 1)])),
    # leaf cell type the whole cluster does not have
    ("f6", spec("vcB", 1, "surprise-chip", 1, "gf6", [(1, 1)])),
]


class TestGnarlyNormalOperations:
    def test_table(self, algo):
        nodes = set_healthy_nodes(algo)
        initial_free = free_list_snapshot(algo)
        allocated = []
        for name, s, expected in SUCCEED:
            pod = make_pod(name, s)
            r = algo.schedule(pod, nodes, PREEMPTING_PHASE)
            assert r.pod_bind_info is not None, (
                name, r.pod_wait_info, r.pod_preempt_info)
            got = (r.pod_bind_info.node,
                   sorted(r.pod_bind_info.leaf_cell_isolation))
            assert got == expected, f"{name}: got {got}, want {expected}"
            bp = new_binding_pod(pod, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            allocated.append(bp)

        for name, s in WAIT:
            r = algo.schedule(make_pod(name, s), nodes, PREEMPTING_PHASE)
            assert r.pod_wait_info is not None, (
                name, r.pod_bind_info, r.pod_preempt_info)

        for name, s in USER_ERRORS:
            with pytest.raises(WebServerError) as exc:
                algo.schedule(make_pod(name, s), nodes, PREEMPTING_PHASE)
            assert 400 <= exc.value.code < 500, (name, exc.value.code)

        # full-delete invariant: reverse deletion returns the cluster to its
        # initial state — no groups left, free list exactly restored
        for bp in reversed(allocated):
            algo.delete_allocated_pod(bp)
        assert not list(algo.get_all_affinity_groups())
        assert free_list_snapshot(algo) == initial_free


class TestGnarlyPreemption:
    def _fill(self, algo, nodes):
        allocated = []
        for name, s, _ in SUCCEED:
            pod = make_pod(name, s)
            r = algo.schedule(pod, nodes, PREEMPTING_PHASE)
            assert r.pod_bind_info is not None, name
            bp = new_binding_pod(pod, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            allocated.append(bp)
        return allocated

    def test_preempt_victim_goldens(self, algo):
        nodes = set_healthy_nodes(algo)
        self._fill(algo, nodes)
        # q1: higher-priority pinned gang preempts g05; victims come one node
        # at a time, all from g05
        q1 = make_pod("q1", spec("vcA", 2, "v5p-chip", 4, "gq1", [(8, 4)],
                                 pinned="pin-gp"))
        r = algo.schedule(q1, nodes, PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        victims = {v.name for v in r.pod_preempt_info.victim_pods}
        assert victims and victims <= {f"p05{c}" for c in "abcdefgh"}
        assert algo.get_affinity_group("gq1").status.state == GROUP_PREEMPTING
        assert algo.get_affinity_group("g05").status.state == GROUP_BEING_PREEMPTED
        # canceling the preemptor returns the cells to g05
        algo.delete_unallocated_pod(q1)
        assert algo.get_affinity_group("g05").status.state in (
            GROUP_ALLOCATED, GROUP_BEING_PREEMPTED)
        assert "gq1" not in {g.name for g in algo.get_all_affinity_groups()}

        # q2: exact single-group victim golden on the ct chain
        q2 = make_pod("q2", spec("vcB", 1, "ct-chip", 2, "gq2", [(1, 2)],
                                 lazy=False))
        r = algo.schedule(q2, nodes, PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        assert {v.name for v in r.pod_preempt_info.victim_pods} == {"p10"}


STATEFUL = lambda prio, g, lazy=True: spec(
    "vcA", prio, "v5p-chip", 4, g, [(8, 4)], pinned="pin-gp", lazy=lazy)


class TestGnarlyStatefulPreemption:
    def test_preemptor_chain(self, algo):
        """Reference pods 28-35: preemptor displacement, waiting behind a
        victim, cancellation of displaced preemptors, allocation after the
        victim dies, and cancellation-by-delete."""
        nodes = set_healthy_nodes(algo)
        s1_pods = []
        for i in range(8):
            p = make_pod(f"s1-{i}", STATEFUL(1, "g-s1"))
            r = algo.schedule(p, nodes, PREEMPTING_PHASE)
            assert r.pod_bind_info is not None
            bp = new_binding_pod(p, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            s1_pods.append(bp)
        s1_names = {f"s1-{i}" for i in range(8)}

        # s2 preempts s1
        r = algo.schedule(make_pod("s2-0", STATEFUL(2, "g-s2")), nodes,
                          PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        assert {v.name for v in r.pod_preempt_info.victim_pods} <= s1_names
        assert algo.get_affinity_group("g-s2").status.state == GROUP_PREEMPTING
        assert algo.get_affinity_group("g-s1").status.state == GROUP_BEING_PREEMPTED

        # s3 (same priority as s1) must wait: s1 still holds the cells
        r = algo.schedule(make_pod("s3-0", STATEFUL(1, "g-s3")), nodes,
                          PREEMPTING_PHASE)
        assert r.pod_wait_info is not None

        # s4 (higher) displaces preemptor g-s2 and keeps preempting g-s1
        r = algo.schedule(make_pod("s4-0", STATEFUL(3, "g-s4")), nodes,
                          PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        assert {v.name for v in r.pod_preempt_info.victim_pods} <= s1_names
        names = {g.name for g in algo.get_all_affinity_groups()}
        assert "g-s2" not in names, "displaced preemptor must be deleted"
        assert algo.get_affinity_group("g-s4").status.state == GROUP_PREEMPTING

        # victims die; s4 allocates
        for bp in s1_pods:
            algo.delete_allocated_pod(bp)
        for i in range(8):
            p = make_pod(f"s4-{i}", STATEFUL(3, "g-s4"), uid=f"s4-{i}")
            r = algo.schedule(p, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, (i, r.pod_wait_info)
            algo.add_allocated_pod(new_binding_pod(p, r.pod_bind_info))
        assert algo.get_affinity_group("g-s4").status.state == GROUP_ALLOCATED

        # s5 preempts s4, then dies before the victims: preemption canceled,
        # s4 keeps its placement (BeingPreempted, as in the reference)
        s5 = make_pod("s5-0", STATEFUL(4, "g-s5", lazy=False))
        r = algo.schedule(s5, nodes, PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        assert {v.name for v in r.pod_preempt_info.victim_pods} <= {
            f"s4-{i}" for i in range(8)}
        algo.delete_unallocated_pod(s5)
        names = {g.name: g.status.state for g in algo.get_all_affinity_groups()}
        assert "g-s5" not in names
        assert names["g-s4"] in (GROUP_ALLOCATED, GROUP_BEING_PREEMPTED)


class TestGnarlyReconfiguration:
    def test_shrunk_vc_lazy_preempts_only_the_loser(self, algo):
        """Work-preserving reconfiguration: vcC loses its v5p share to vcB;
        on replay vcC's group is lazy-preempted, vcB's keeps its placement."""
        nodes = set_healthy_nodes(algo)
        allocated = []
        for i in range(2):
            p = make_pod(f"r1-{i}", spec("vcB", 2, "v5p-chip", 4, "g-r1",
                                         [(2, 4)]))
            r = algo.schedule(p, nodes, PREEMPTING_PHASE)
            assert r.pod_bind_info is not None
            bp = new_binding_pod(p, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            allocated.append(bp)
        for i in range(4):
            p = make_pod(f"r2-{i}", spec("vcC", 2, "v5p-chip", 4, "g-r2",
                                         [(4, 4)]))
            r = algo.schedule(p, nodes, PREEMPTING_PHASE)
            assert r.pod_bind_info is not None
            bp = new_binding_pod(p, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            allocated.append(bp)

        raw = yaml.safe_load(open(FIXTURE))
        vcs = raw["virtualClusters"]
        vcs["vcC"]["virtualCells"] = [
            v for v in vcs["vcC"]["virtualCells"]
            if v["cellType"] != "v5p-8x4x2.g-4x2x2"
        ]
        vcs["vcB"]["virtualCells"].append(
            {"cellType": "v5p-8x4x2.g-4x2x2", "cellNumber": 1})
        h2 = HivedAlgorithm(new_config(Config.from_dict(raw)))
        set_healthy_nodes(h2)
        for bp in allocated:
            h2.add_allocated_pod(bp)
        g1 = h2.get_affinity_group("g-r1")
        g2 = h2.get_affinity_group("g-r2")
        assert g1.status.state == GROUP_ALLOCATED
        assert g1.status.lazy_preemption_status is None
        assert g2.status.state == GROUP_ALLOCATED
        assert g2.status.lazy_preemption_status is not None


class TestGnarlySuggestedNodes:
    """Suggested-nodes interplay on the asymmetric mesh (reference:
    testSuggestedNodes, hived_algorithm_test.go:753-853). The 2x2x2 cells
    span one z=0 and one z=1 host, so restricting suggestions to z=1 makes
    host-sized pods placeable but whole-cell gangs impossible."""

    Z1 = staticmethod(lambda nodes: [
        n for n in nodes if n.startswith("gp0/") and n.endswith("-1")])

    def test_single_host_lands_in_suggested_set(self, algo):
        nodes = set_healthy_nodes(algo)
        s = spec("vcB", 2, "v5p-chip", 4, "sg1", [(1, 4)])
        s["ignoreK8sSuggestedNodes"] = False
        r = algo.schedule(make_pod("sg1", s), self.Z1(nodes), FILTERING_PHASE)
        assert r.pod_bind_info is not None
        assert r.pod_bind_info.node.endswith("-1")

    def test_whole_cell_gang_waits_outside_suggested_set(self, algo):
        """No 2x2x2 fits inside z=1 alone: the mapping failure reason must
        surface to the user (FailedNodes wait reason)."""
        nodes = set_healthy_nodes(algo)
        s = spec("vcB", 2, "v5p-chip", 4, "sg2", [(2, 4)])
        s["ignoreK8sSuggestedNodes"] = False
        r = algo.schedule(make_pod("sg2", s), self.Z1(nodes), FILTERING_PHASE)
        assert r.pod_wait_info is not None
        assert "bad or non-suggested node" in r.pod_wait_info.reason

    def test_buddy_alloc_backtracks_past_bad_cell(self, algo):
        """One bad host in the first candidate 2x2x2: the gang must land on
        the next whole healthy cell (golden), not an L-shape around the bad
        host."""
        nodes = set_healthy_nodes(algo)
        algo.delete_node(Node(name="gp0/0-0-1"))
        s = spec("vcB", 2, "v5p-chip", 4, "sg3", [(2, 4)])
        got = []
        for i in range(2):
            p = make_pod(f"sg3-{i}", s)
            r = algo.schedule(p, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None
            algo.add_allocated_pod(new_binding_pod(p, r.pod_bind_info))
            got.append(r.pod_bind_info.node)
        assert got == ["gp0/2-0-0", "gp0/2-0-1"]


class TestGnarlyPhysicalReconfiguration:
    def test_moved_pin_lazy_preempts_instead_of_crashing(self, algo):
        """Physical reconfiguration analogue of the reference's
        cell-hierarchy-splitting cases (pods 18-23): the pinned 4x4x2 MOVES
        to the other half of the mesh. Replaying the old placements must
        not panic (the reference's allocatePreassignedCell would) — both
        affected groups are lazy-preempted but keep running."""
        nodes = set_healthy_nodes(algo)
        allocated = []

        def alloc(name, s):
            p = make_pod(name, s)
            r = algo.schedule(p, nodes, PREEMPTING_PHASE)
            assert r.pod_bind_info is not None, (name, r.pod_wait_info)
            bp = new_binding_pod(p, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            allocated.append(bp)
            return r.pod_bind_info.node

        # pinned gang in the (current) x>=4 pinned half
        for i in range(4):
            node = alloc(f"pa-{i}", spec("vcA", 1, "v5p-chip", 4, "g-pa",
                                         [(4, 4)], pinned="pin-gp"))
            assert node.startswith(("gp0/4", "gp0/6"))
        # non-pinned vcB gang in the free half (which the pin will move onto)
        for i in range(2):
            node = alloc(f"pb-{i}", spec("vcB", 2, "v5p-chip", 4, "g-pb",
                                         [(2, 4)]))
            assert node.startswith(("gp0/0", "gp0/2"))

        raw = yaml.safe_load(open(FIXTURE))
        for pc in raw["physicalCluster"]["physicalCells"]:
            if pc.get("cellAddress") == "gp0":
                pc["cellChildren"][0]["cellAddress"] = "0-0-0"  # pin moves
        h2 = HivedAlgorithm(new_config(Config.from_dict(raw)))
        nodes2 = set_healthy_nodes(h2)
        for bp in allocated:  # must not raise
            h2.add_allocated_pod(bp)

        g_pa = h2.get_affinity_group("g-pa")
        g_pb = h2.get_affinity_group("g-pb")
        # both groups keep their placements and keep running...
        assert g_pa.status.state == GROUP_ALLOCATED
        assert g_pb.status.state == GROUP_ALLOCATED
        # ...but are demoted (lazy-preempted): pa's cells left the pin, pb's
        # cells are now inside it
        assert g_pa.status.lazy_preemption_status is not None
        assert g_pb.status.lazy_preemption_status is not None
        # and a fresh pinned gang can take the NEW pin location
        p = make_pod("new-pin", spec("vcA", 5, "v5p-chip", 4, "g-new",
                                     [(1, 4)], pinned="pin-gp"))
        r = h2.schedule(p, nodes2, PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None or (
            r.pod_bind_info is not None
            and r.pod_bind_info.node.startswith(("gp0/0", "gp0/2"))
        )


class TestGnarlyBadNodes:
    def test_bad_host_avoided_and_doomed_bad_binding(self, algo):
        nodes = set_healthy_nodes(algo)
        algo.delete_node(Node(name="gp0/0-0-0"))
        got = []
        for i in range(2):
            p = make_pod(f"b1-{i}", spec("vcB", 2, "v5p-chip", 4, "g-b1",
                                         [(2, 4)]))
            r = algo.schedule(p, nodes, PREEMPTING_PHASE)
            assert r.pod_bind_info is not None
            algo.add_allocated_pod(new_binding_pod(p, r.pod_bind_info))
            got.append(r.pod_bind_info.node)
        assert "gp0/0-0-0" not in got
        assert got == ["gp0/2-0-0", "gp0/2-0-1"]  # golden: healthy 2x2x2

        # enough bad hosts doom a VC cell: badness must surface in vcB's view
        for nn in ["gp0/0-0-1", "gp0/2-0-0", "gp0/2-0-1"]:
            algo.delete_node(Node(name=nn))

        def walk(ss):
            for s in ss:
                yield s
                yield from walk(s.cell_children)

        bad = [s for s in walk(algo.get_virtual_cluster_status("vcB"))
               if getattr(s, "cell_healthiness", "") == "Bad"]
        assert bad, "doomed bad cells must be visible in the VC status"
