"""Ring attention with Pallas flash blocks (attn_impl="ring_flash").

The flash-block ring must be bit-for-bit the same *algorithm* as standard
attention: every test here is a differential check against the dense einsum
reference or the einsum ring. The Pallas kernels really execute on CPU via
the interpreter — the tests drive the local body under a
``check_vma=False`` shard_map, which is the one context where the
interpreter can run inside a manual mesh (the production vma-checked path
compiles the kernels on TPU and falls back to the einsum ring elsewhere;
see ``_ring_flash_attention_local``).
"""

import functools

import pytest

pytest.importorskip("jax")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from hivedscheduler_tpu.ops.attention import xla_attention
from hivedscheduler_tpu.parallel.ring_attention import (
    _get_shard_map,
    _ring_attention_local,
    _ring_flash_attention_local,
    _zigzag_flash_attention_local,
    ring_flash_attention,
    zigzag_ring_flash_attention,
)

B, T, H, D = 2, 32, 4, 8
SP = 4


def _mesh():
    return Mesh(np.array(jax.devices("cpu")[:SP]).reshape(SP), ("sp",))


def _qkv(h_kv=H, dtype=jnp.float32, d=D):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return (
        jax.random.normal(ks[0], (B, T, H, d), dtype),
        jax.random.normal(ks[1], (B, T, h_kv, d), dtype),
        jax.random.normal(ks[2], (B, T, h_kv, d), dtype),
    )


def _ring_flash(mesh, causal=True, block=8, interpret_kernels=True):
    """The local body under shard_map; check_vma=False + mesh_axes=() lets
    the Pallas interpreter actually run the kernels on CPU."""
    spec = P(None, "sp", None, None)
    return _get_shard_map()(
        functools.partial(
            _ring_flash_attention_local, axis_name="sp", causal=causal,
            mesh_axes=(), block_q=block, block_k=block,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=not interpret_kernels,
    )


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    out = jax.jit(_ring_flash(_mesh(), causal=causal))(q, k, v)
    ref = xla_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


# h_kv=1 (MQA) is slow-marked: tier-1 wall-time budget (ISSUE 15) — the
# MHA (H) and GQA (2) variants are the tier-1 cousins through the same
# grouped-head read path (mirrors tests/test_decode.py's MQA mark)
@pytest.mark.parametrize(
    "h_kv", [H, 2, pytest.param(1, marks=pytest.mark.slow)])
def test_gradients_match_dense(h_kv):
    """Forward AND backward parity, incl. compact GQA/MQA k/v (the flash
    kernels consume the shared head directly)."""
    q, k, v = _qkv(h_kv=h_kv)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, T, H, D))
    fn = _ring_flash(_mesh())

    def loss(f):
        return lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32) * w)

    o_r, g_r = jax.value_and_grad(loss(jax.jit(fn)), (0, 1, 2))(q, k, v)
    o_d, g_d = jax.value_and_grad(
        loss(lambda q, k, v: xla_attention(q, k, v, causal=True)), (0, 1, 2)
    )(q, k, v)
    assert abs(float(o_r - o_d)) < 1e-3
    for got, want in zip(g_r, g_d):
        assert jnp.max(jnp.abs(got - want)) < 1e-4


def test_bf16_matches_einsum_ring():
    """Same schedule, same f32 accumulation: the flash-block ring tracks the
    einsum ring to bf16 resolution on bf16 inputs."""
    q, k, v = _qkv(dtype=jnp.bfloat16)
    mesh = _mesh()
    out = jax.jit(_ring_flash(mesh))(q, k, v)
    spec = P(None, "sp", None, None)
    ring = _get_shard_map()(
        functools.partial(_ring_attention_local, axis_name="sp", causal=True,
                          mesh_axes=()),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    ref = jax.jit(ring)(q, k, v)
    assert jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))) < 0.02


def test_nontiling_head_dim_falls_back():
    """D not a multiple of 8 can't tile on the kernels: the local body must
    degrade to the einsum ring, not crash (same contract as
    flash_attention's xla fallback)."""
    q, k, v = _qkv(d=6)
    out = jax.jit(_ring_flash(_mesh()))(q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_vma_checked_context_falls_back():
    """Under the production vma-checked shard_map on CPU the interpreter
    cannot run the kernels; the public wrapper must still produce exact
    ring-attention results via the einsum fallback."""
    q, k, v = _qkv()
    out = ring_flash_attention(
        q, k, v, _mesh(), seq_axis="sp", batch_axes=(), head_axis=None,
        block_q=8, block_k=8,
    )
    ref = xla_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def _zigzag_flash(mesh, block=4):
    spec = P(None, "sp", None, None)
    return _get_shard_map()(
        functools.partial(
            _zigzag_flash_attention_local, axis_name="sp", mesh_axes=(),
            block_q=block, block_k=block,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )


@pytest.mark.parametrize("h_kv", [H, 2])
@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_zigzag_flash_matches_dense(h_kv):
    """The zigzag schedule's quarter-blocks are all diagonal-or-fully-
    visible, so the same two flash kernels cover it: forward and gradients
    must match dense causal attention exactly (incl. compact GQA)."""
    q, k, v = _qkv(h_kv=h_kv)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, T, H, D))
    fn = _zigzag_flash(_mesh())

    def loss(f):
        return lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32) * w)

    o_z, g_z = jax.value_and_grad(loss(jax.jit(fn)), (0, 1, 2))(q, k, v)
    o_d, g_d = jax.value_and_grad(
        loss(lambda q, k, v: xla_attention(q, k, v, causal=True)), (0, 1, 2)
    )(q, k, v)
    assert abs(float(o_z - o_d)) < 1e-3
    for got, want in zip(g_z, g_d):
        assert jnp.max(jnp.abs(got - want)) < 1e-4


def test_zigzag_flash_vma_checked_falls_back():
    """Production vma-checked wrapper off-TPU degrades to the einsum zigzag
    and still matches dense attention."""
    q, k, v = _qkv()
    out = zigzag_ring_flash_attention(
        q, k, v, _mesh(), seq_axis="sp", batch_axes=(), head_axis=None,
        block_q=4, block_k=4,
    )
    ref = xla_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_zigzag_flash_odd_block_rejected():
    q, k, v = _qkv()
    mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(8), ("sp",))
    # T=32 over sp=8 -> 4 rows/shard, odd half is fine; force odd rows:
    with pytest.raises(ValueError, match="even per-shard block"):
        spec = P(None, "sp", None, None)
        fn = _get_shard_map()(
            functools.partial(_zigzag_flash_attention_local, axis_name="sp",
                              mesh_axes=()),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        fn(q[:, :24], k[:, :24], v[:, :24])  # 3 rows per shard


@pytest.mark.slow
def test_train_step_wiring():
    """attn_impl="ring_flash" is reachable from the sharded train step and
    optimizes the same loss as attn_impl="ring" (on CPU both resolve to the
    einsum ring inside the vma-checked sp shard_map — this pins the config
    plumbing; the kernel math is pinned by the differential tests above).

    slow: two full train-step compiles on the 1-core box (~19 s); the
    config plumbing it pins is structural, and the op-level differential
    tests above stay in tier-1."""
    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.parallel import topology
    from hivedscheduler_tpu.parallel.train import make_sharded_train_step

    losses = {}
    for impl in ("ring", "ring_flash", "ring_zigzag_flash"):
        cfg = tm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=T, attn_impl=impl, attn_block_q=8, attn_block_k=8,
        )
        axes = topology.MeshAxes(sp=SP)
        mesh = topology.make_mesh(axes, jax.devices("cpu")[:SP])
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (2, T), 0, 64,
                               jnp.int32),
            token_sharding,
        )
        _, _, loss = step(params, opt_state, tokens)
        losses[impl] = float(loss)
    assert losses["ring"] == pytest.approx(losses["ring_flash"], abs=1e-5)
    assert losses["ring"] == pytest.approx(losses["ring_zigzag_flash"],
                                           abs=1e-5)
