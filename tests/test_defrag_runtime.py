"""Runtime migration executor: plan -> reserve -> evict -> re-bind ->
waiter lands, with kill switches, reservation steering/backfill, TTL
sweeps, the kill -9 abort window, and the inspect/admission-hints surface
(ISSUE 9).

Scenario used throughout (see tests/test_defrag.py.fragmented_state): a
two-cell VC where g1+g2 fill cell A, g3 half-fills cell B, g2 dies — a
4-chip waiter has the quota but no contiguous cell until one survivor
moves.
"""

import json
import os
import sys
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tests.test_defrag import make_pod, mini_config  # noqa: E402

from hivedscheduler_tpu.chaos import invariants  # noqa: E402
from hivedscheduler_tpu.k8s.fake import FakeKubeClient  # noqa: E402
from hivedscheduler_tpu.k8s.types import Node  # noqa: E402
from hivedscheduler_tpu.runtime import extender as ei  # noqa: E402
from hivedscheduler_tpu.runtime.metrics import REGISTRY  # noqa: E402
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler  # noqa: E402


def build_scheduler(kube=None):
    kube = kube or FakeKubeClient()
    sched = HivedScheduler(mini_config(), kube)
    nodes = sorted({
        n for ccl in sched.scheduler_algorithm.full_cell_list.values()
        for c in ccl[max(ccl)] for n in c.nodes
    })
    for n in nodes:
        kube.create_node(Node(name=n))
    sched.start()
    return sched, kube, nodes


def drive(sched, kube, nodes, pod):
    """Play the kube-scheduler: create, filter, bind. Returns the node or
    None (waiting)."""
    if kube.get_pod(pod.namespace, pod.name) is None:
        kube.create_pod(pod)
    r = sched.filter_routine(ei.ExtenderArgs(pod=pod, node_names=list(nodes)))
    if not r.node_names:
        return None
    sched.bind_routine(ei.ExtenderBindingArgs(
        pod_name=pod.name, pod_namespace=pod.namespace, pod_uid=pod.uid,
        node=r.node_names[0]))
    return r.node_names[0]


def fragmented_scheduler():
    sched, kube, nodes = build_scheduler()
    assert drive(sched, kube, nodes, make_pod("g1-0", "g1", 2)) is not None
    assert drive(sched, kube, nodes, make_pod("g2-0", "g2", 2)) is not None
    assert drive(sched, kube, nodes, make_pod("g3-0", "g3", 2)) is not None
    kube.delete_pod("default", "g2-0")
    return sched, kube, nodes


def check(sched, ctx):
    with sched.scheduler_lock:
        invariants.check_all(sched.scheduler_algorithm, ctx, scheduler=sched)


class TestMigrationEndToEnd:
    def test_full_pipeline(self):
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None  # waits: fragmentation
        tick = sched.defrag_tick()
        plan = tick["planned"]
        assert plan is not None and plan["waiter"] == "w"
        assert len(plan["moves"]) == 1 and plan["movedChips"] == 2
        check(sched, "post-plan")
        report = sched.resume_migrations()
        assert report[plan["migrationId"]]["state"] == "Done"
        check(sched, "post-rebind")
        # the mover runs again under a NEW pod identity on its target node
        move = report[plan["migrationId"]]["moves"][0]
        rb = kube.get_pod("default", move["rebound"][0])
        assert rb is not None and rb.node_name in move["targetNodes"]
        # the waiter lands in the freed (reserved) slice
        node = drive(sched, kube, nodes, w)
        assert node in plan["waiterNodes"]
        st = sched.get_defrag_status()
        assert st["reservations"] == [] and st["waiters"] == []
        check(sched, "end")

    def test_waiter_reservation_blocks_equal_gang_until_bound(self):
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        sched.resume_migrations()
        # a competitor of the same shape arrives while the slice is held:
        # the reserved node is withheld, so it must wait
        rival = make_pod("rival-0", "rival", 4)
        assert drive(sched, kube, nodes, rival) is None
        blocked = REGISTRY.render()
        assert 'tpu_hive_backfill_admissions_total{outcome="blocked"}' in blocked
        # the holder still lands
        assert drive(sched, kube, nodes, w) in plan["waiterNodes"]
        check(sched, "end")

    def test_opportunistic_backfill_rides_reservation(self):
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        sched.resume_migrations()
        # an opportunistic gang may ride INTO the held slice (the holder
        # reclaims by preemption — the ride cannot delay it)
        opp = make_pod("opp-0", "opp", 4, prio=-1)
        node = drive(sched, kube, nodes, opp)
        assert node in plan["waiterNodes"]
        check(sched, "end")

    def test_backfill_kill_switch_blocks_the_ride(self, monkeypatch):
        monkeypatch.setenv("HIVED_BACKFILL", "0")
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        sched.resume_migrations()
        opp = make_pod("opp-0", "opp", 4, prio=-1)
        assert drive(sched, kube, nodes, opp) is None  # reserved = withheld
        check(sched, "end")


class TestKillSwitchAndFaults:
    def test_defrag_off_is_inert(self, monkeypatch):
        monkeypatch.setenv("HIVED_DEFRAG", "0")
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        assert sched.defrag_tick() == {"enabled": False}
        assert sched.plan_defrag_for(w) is None
        assert sched.resume_migrations() == {}
        st = sched.get_defrag_status()
        assert (st["reservations"] == [] and st["migrations"] == []
                and st["waiters"] == [])
        check(sched, "flags-off")

    def test_abort_in_the_kill_window_releases_everything(self):
        """kill -9 after checkpoint, before re-bind: nothing half-bound,
        no orphaned reservation, invariants clean."""
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        mover = plan["moves"][0]["group"]
        assert sched.abort_migration(plan["migrationId"], why="kill -9")
        st = sched.get_defrag_status()
        assert st["reservations"] == []
        assert [m["state"] for m in st["migrations"]] == ["Aborted"]
        assert mover not in sched.scheduler_algorithm.affinity_groups
        check(sched, "post-abort")
        # second abort is a no-op, not an error
        assert not sched.abort_migration(plan["migrationId"])

    def test_reservation_ttl_expiry_aborts_stuck_migration(self):
        sched, kube, nodes = fragmented_scheduler()
        sched.defrag_reserve_ttl_s = 0.0  # everything expires immediately
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        report = sched.resume_migrations()  # first act: sweep expiries
        assert report.get(plan["migrationId"], {}).get("state") in (
            None, "Aborted")
        st = sched.get_defrag_status()
        assert st["reservations"] == []
        assert all(m["state"] != "Evicting" for m in st["migrations"])
        check(sched, "post-expiry")

    def test_rebind_failure_rolls_the_move_back(self):
        class NoCreate(FakeKubeClient):
            def create_pod(self, pod):
                if pod.name.startswith("mig-"):
                    raise RuntimeError("ApiServer down for replacements")
                super().create_pod(pod)

        sched, kube, nodes = build_scheduler(NoCreate())
        assert drive(sched, kube, nodes, make_pod("g1-0", "g1", 2))
        assert drive(sched, kube, nodes, make_pod("g2-0", "g2", 2))
        assert drive(sched, kube, nodes, make_pod("g3-0", "g3", 2))
        kube.delete_pod("default", "g2-0")
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        report = sched.resume_migrations()
        assert report[plan["migrationId"]]["state"] == "Failed"
        st = sched.get_defrag_status()
        assert st["reservations"] == []  # a failed consolidation holds nothing
        check(sched, "post-failed-rebind")
        # the evicted job's work lives in its checkpoint; the waiter still
        # fits once the failed migration released the freed cells
        assert drive(sched, kube, nodes, w) is not None
        check(sched, "end")

    def test_cancelled_waiter_drops_record_and_reservation(self):
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        sched.resume_migrations()
        kube.delete_pod("default", "w-0")  # the user gave up
        st = sched.get_defrag_status()
        assert st["waiters"] == [] and st["reservations"] == []
        check(sched, "post-cancel")

    def test_planning_refused_while_nodes_bad(self):
        from hivedscheduler_tpu.k8s.types import NodeCondition

        sched, kube, nodes = fragmented_scheduler()
        kube.update_node(Node(name=nodes[1], conditions=[
            NodeCondition(type="Ready", status="False")]))
        w = make_pod("w-0", "w", 4)
        drive(sched, kube, nodes, w)
        assert sched.defrag_tick()["planned"] is None
        assert ('tpu_hive_defrag_planner_rejections_total'
                '{reason="cluster-unhealthy"}') in REGISTRY.render()
        check(sched, "bad-node-reject")


class TestInspectSurface:
    def test_admission_hints_surface_serving_occupancy(self):
        sched, _, _ = build_scheduler()
        REGISTRY.set_gauge("tpu_hive_serve_block_pool_occupancy", 0.75)
        hints = sched.get_admission_hints()
        assert hints["serveBlockPoolOccupancy"] == 0.75
        assert hints["serveBlockPoolHeadroom"] == 0.25
        assert hints["defragReservedNodes"] == []
        assert hints["defragMigrationsInFlight"] == 0

    def test_admission_hints_include_live_holds(self):
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        hints = sched.get_admission_hints()
        assert set(plan["waiterNodes"]) <= set(hints["defragReservedNodes"])
        assert hints["defragMigrationsInFlight"] == 1
        assert "w" in hints["waitingGangs"]

    def test_webserver_serves_hints_and_defrag_status(self):
        from hivedscheduler_tpu.webserver import WebServer

        sched, _, _ = build_scheduler()
        sched.config.web_server_address = "127.0.0.1:0"
        server = WebServer(sched)
        host, port = server.async_run()
        try:
            REGISTRY.set_gauge("tpu_hive_serve_block_pool_occupancy", 0.5)
            with urllib.request.urlopen(
                    f"http://{host}:{port}/v1/inspect/admission-hints") as r:
                hints = json.loads(r.read())
            assert hints["serveBlockPoolHeadroom"] == 0.5
            with urllib.request.urlopen(
                    f"http://{host}:{port}/v1/inspect/defrag") as r:
                st = json.loads(r.read())
            assert "reservations" in st and "migrations" in st
            with urllib.request.urlopen(f"http://{host}:{port}/v1") as r:
                idx = json.loads(r.read())
            assert "/v1/inspect/admission-hints" in idx["paths"]
            assert "/v1/inspect/defrag" in idx["paths"]
        finally:
            server.stop()
