"""Deterministic placement goldens: exact (node, chips) decisions for a fixed
pod sequence on the design fixture, pinning scheduler determinism the way the
reference's table-driven expectedBindInfos do
(hived_algorithm_test.go:566-592). Any change to placement order, packing, or
buddy tie-breaking shows up here as a concrete diff."""

import logging
import os

from helpers import make_pod, set_healthy_nodes

from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)

# (name, spec) -> expected (node, sorted chip indices)
SEQUENCE = [
    ("a1", {"virtualCluster": "vc2", "priority": 0, "chipType": "v5e-chip",
            "chipNumber": 2},
     ("v5e-host0/0-0", [0, 1])),
    ("a2", {"virtualCluster": "vc2", "priority": 0, "chipType": "v5e-chip",
            "chipNumber": 2},
     ("v5e-host0/0-0", [2, 3])),  # packs onto the same host, next buddy pair
    ("b1", {"virtualCluster": "vc2", "priority": 5, "chipType": "v5p-chip",
            "chipNumber": 4},
     ("v5p-pod0/2-2-0", [0, 1, 2, 3])),  # vc2's 2x2x2 lands in the pin's half
    ("b2", {"virtualCluster": "vc2", "priority": 5, "chipType": "v5p-chip",
            "chipNumber": 4},
     ("v5p-pod0/2-2-1", [0, 1, 2, 3])),  # buddy host of the same 2x2x2
    ("c1", {"virtualCluster": "vc1", "priority": 5, "chipType": "v5p-chip",
            "chipNumber": 4},
     ("v5p-pod0/0-0-2", [0, 1, 2, 3])),  # vc1's 4x4x2 claims the z=2,3 half
    ("d1", {"virtualCluster": "vc1", "priority": 0, "chipType": "v4-chip",
            "chipNumber": 8},
     ("0", [0, 1, 2, 3, 4, 5, 6, 7])),  # whole first v4 node
    ("e1", {"virtualCluster": "vc1", "priority": 2, "pinnedCellId": "pin1",
            "chipNumber": 4},
     ("v5p-pod0/0-0-0", [0, 1, 2, 3])),  # pinned 2x2x2's first host
]


def test_placement_goldens():
    h = HivedAlgorithm(load_config(FIXTURE))
    nodes = set_healthy_nodes(h)
    got = []
    for name, spec, expected in SEQUENCE:
        r = h.schedule(make_pod(name, spec), nodes, FILTERING_PHASE)
        assert r.pod_bind_info is not None, (name, r.pod_wait_info)
        h.add_allocated_pod(new_binding_pod(make_pod(name, spec), r.pod_bind_info))
        got.append((name, (r.pod_bind_info.node,
                           sorted(r.pod_bind_info.leaf_cell_isolation))))
    expected_all = [(name, exp) for name, _, exp in SEQUENCE]
    assert got == expected_all, "\n".join(
        f"{n}: got {g}, want {e}" for (n, g), (_, e) in zip(got, expected_all)
    )
