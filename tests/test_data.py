"""Host-parallel data loading tests."""

import numpy as np
import pytest

from hivedscheduler_tpu.parallel import data as data_lib


def test_token_file_dataset(tmp_path):
    tokens = np.arange(1000, dtype=np.uint16) % 50
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)
    ds = data_lib.TokenFileDataset(str(path))
    assert len(ds) == 1000
    rng = np.random.default_rng(0)
    batch = ds.sample(rng, 4, 16)
    assert batch.shape == (4, 16) and batch.dtype == np.int32
    assert batch.max() < 50


def test_empty_file_rejected(tmp_path):
    path = tmp_path / "empty.bin"
    path.write_bytes(b"")
    with pytest.raises(ValueError, match="empty"):
        data_lib.TokenFileDataset(str(path))


def test_host_shards_partition_the_global_batch():
    ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
    shards = [
        next(data_lib.host_batches(ds, 8, 16, process_index=i, process_count=4, seed=7))
        for i in range(4)
    ]
    # same step on every host: shards concatenate to one consistent batch
    full = next(data_lib.host_batches(ds, 8, 16, process_index=0, process_count=1, seed=7))
    np.testing.assert_array_equal(np.concatenate(shards), full)


def test_indivisible_batch_rejected():
    ds = data_lib.synthetic_dataset(10, size=128)
    with pytest.raises(ValueError, match="not divisible"):
        next(data_lib.host_batches(ds, 7, 8, process_count=2))


def test_determinism_across_restarts():
    ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
    a = [next(iter([b])) for b in
         (x for _, x in zip(range(3), data_lib.host_batches(ds, 4, 8, seed=3)))]
    b = [x for _, x in zip(range(3), data_lib.host_batches(ds, 4, 8, seed=3))]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
