"""Host-parallel data loading tests."""

import numpy as np
import pytest

from hivedscheduler_tpu.parallel import data as data_lib


def test_token_file_dataset(tmp_path):
    tokens = np.arange(1000, dtype=np.uint16) % 50
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)
    ds = data_lib.TokenFileDataset(str(path))
    assert len(ds) == 1000
    rng = np.random.default_rng(0)
    batch = ds.sample(rng, 4, 16)
    assert batch.shape == (4, 16) and batch.dtype == np.int32
    assert batch.max() < 50


def test_empty_file_rejected(tmp_path):
    path = tmp_path / "empty.bin"
    path.write_bytes(b"")
    with pytest.raises(ValueError, match="empty"):
        data_lib.TokenFileDataset(str(path))


def test_host_shards_partition_the_global_batch():
    ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
    shards = [
        next(data_lib.host_batches(ds, 8, 16, process_index=i, process_count=4, seed=7))
        for i in range(4)
    ]
    # same step on every host: shards concatenate to one consistent batch
    full = next(data_lib.host_batches(ds, 8, 16, process_index=0, process_count=1, seed=7))
    np.testing.assert_array_equal(np.concatenate(shards), full)


def test_indivisible_batch_rejected():
    ds = data_lib.synthetic_dataset(10, size=128)
    with pytest.raises(ValueError, match="not divisible"):
        next(data_lib.host_batches(ds, 7, 8, process_count=2))


def test_determinism_across_restarts():
    ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
    a = [next(iter([b])) for b in
         (x for _, x in zip(range(3), data_lib.host_batches(ds, 4, 8, seed=3)))]
    b = [x for _, x in zip(range(3), data_lib.host_batches(ds, 4, 8, seed=3))]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_native_gather_matches_numpy():
    """The C++ gather (native/dataloader.cpp) must be bit-identical to the
    numpy expression for uint16 AND uint32, including wraparound starts and
    the degenerate seq_len > corpus case."""
    from hivedscheduler_tpu import native

    if not native.dataloader_available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(0)
    for dtype, vocab in ((np.uint16, 60000), (np.uint32, 200000)):
        tokens = rng.integers(0, vocab, size=997).astype(dtype)  # odd length
        for seq in (1, 16, 250, 1200):  # 1200 > 997: multi-wrap fallback
            starts = np.concatenate([
                rng.integers(0, 997, size=13),
                [0, 996, 995],  # boundary starts
            ])
            got = native.gather_windows(tokens, starts, seq)
            assert got is not None and got.dtype == np.int32
            idx = (starts[:, None] + np.arange(seq)[None, :]) % 997
            np.testing.assert_array_equal(got, tokens[idx].astype(np.int32))
    # unsupported dtype degrades to None (callers fall back to numpy)
    assert native.gather_windows(
        rng.standard_normal(8).astype(np.float32), np.array([0]), 4) is None


def test_sample_uses_native_and_matches_forced_numpy(tmp_path, monkeypatch):
    """TokenFileDataset.sample must produce identical batches through the
    native path and the HIVED_NATIVE=0 numpy path (same RNG plan)."""
    import subprocess
    import sys

    from hivedscheduler_tpu import native

    if not native.dataloader_available():
        pytest.skip("native toolchain unavailable")  # else numpy-vs-numpy

    tokens = (np.arange(5000, dtype=np.uint16) * 7) % 331
    path = tmp_path / "tok.bin"
    tokens.tofile(path)
    ds = data_lib.TokenFileDataset(str(path))
    got = ds.sample(np.random.default_rng(5), 6, 64)
    # force-numpy in a subprocess (the native lib loads once per process)
    code = (
        "import numpy as np, sys\n"
        "from hivedscheduler_tpu.parallel import data as data_lib\n"
        f"ds = data_lib.TokenFileDataset({str(path)!r})\n"
        "b = ds.sample(np.random.default_rng(5), 6, 64)\n"
        "np.save(sys.argv[1], b)\n"
    )
    out_npy = tmp_path / "numpy_batch.npy"
    env = {"HIVED_NATIVE": "0", "PATH": "/usr/bin:/bin",
           "PYTHONPATH": ":".join(sys.path)}
    subprocess.run([sys.executable, "-c", code, str(out_npy)], check=True,
                   env=env)
    np.testing.assert_array_equal(got, np.load(out_npy))


def test_prefetch_preserves_order_and_values():
    ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
    plain = [x for _, x in zip(range(5), data_lib.host_batches(ds, 4, 8, seed=3))]
    pre = [x for _, x in zip(
        range(5), data_lib.prefetch(data_lib.host_batches(ds, 4, 8, seed=3)))]
    for x, y in zip(plain, pre):
        np.testing.assert_array_equal(x, y)
    # depth 0 = passthrough
    off = [x for _, x in zip(
        range(2), data_lib.prefetch(data_lib.host_batches(ds, 4, 8, seed=3),
                                    depth=0))]
    np.testing.assert_array_equal(off[0], plain[0])


def test_prefetch_reraises_producer_errors():
    def boom():
        yield np.zeros((1, 1), np.int32)
        raise RuntimeError("producer exploded")

    it = data_lib.prefetch(boom(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="producer exploded"):
        next(it)


def test_prefetch_abandoned_iterator_stops_worker():
    """Closing the consumer early (the train CLI's normal exit after
    --steps) must signal the producer thread to exit instead of leaving it
    blocked forever on the bounded queue (thread + staged-batch leak)."""
    import threading

    produced = []

    def infinite():
        i = 0
        while True:
            produced.append(i)
            yield np.full((2, 2), i, np.int32)
            i += 1

    # capture the worker thread itself via an enumerate() diff — asserting
    # on the global active_count() flakes when an unrelated library thread
    # starts mid-test (ADVICE.md round 5)
    before = set(threading.enumerate())
    it = data_lib.prefetch(infinite(), depth=2)
    next(it)
    workers = [t for t in threading.enumerate() if t not in before]
    assert workers, "prefetch started no worker thread"
    it.close()  # GeneratorExit -> finally -> closed.set()
    for t in workers:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in workers), (
        "prefetch worker still alive after the consumer was closed"
    )
    # the producer stopped near where it was abandoned, not unbounded
    assert len(produced) <= 6
