"""Host-parallel data loading tests."""

import numpy as np
import pytest

from hivedscheduler_tpu.parallel import data as data_lib


def test_token_file_dataset(tmp_path):
    tokens = np.arange(1000, dtype=np.uint16) % 50
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)
    ds = data_lib.TokenFileDataset(str(path))
    assert len(ds) == 1000
    rng = np.random.default_rng(0)
    batch = ds.sample(rng, 4, 16)
    assert batch.shape == (4, 16) and batch.dtype == np.int32
    assert batch.max() < 50


def test_empty_file_rejected(tmp_path):
    path = tmp_path / "empty.bin"
    path.write_bytes(b"")
    with pytest.raises(ValueError, match="empty"):
        data_lib.TokenFileDataset(str(path))


def test_host_shards_partition_the_global_batch():
    ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
    shards = [
        next(data_lib.host_batches(ds, 8, 16, process_index=i, process_count=4, seed=7))
        for i in range(4)
    ]
    # same step on every host: shards concatenate to one consistent batch
    full = next(data_lib.host_batches(ds, 8, 16, process_index=0, process_count=1, seed=7))
    np.testing.assert_array_equal(np.concatenate(shards), full)


def test_indivisible_batch_rejected():
    ds = data_lib.synthetic_dataset(10, size=128)
    with pytest.raises(ValueError, match="not divisible"):
        next(data_lib.host_batches(ds, 7, 8, process_count=2))


def test_determinism_across_restarts():
    ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
    a = [next(iter([b])) for b in
         (x for _, x in zip(range(3), data_lib.host_batches(ds, 4, 8, seed=3)))]
    b = [x for _, x in zip(range(3), data_lib.host_batches(ds, 4, 8, seed=3))]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_native_gather_matches_numpy():
    """The C++ gather (native/dataloader.cpp) must be bit-identical to the
    numpy expression for uint16 AND uint32, including wraparound starts and
    the degenerate seq_len > corpus case."""
    from hivedscheduler_tpu import native

    if not native.dataloader_available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(0)
    for dtype, vocab in ((np.uint16, 60000), (np.uint32, 200000)):
        tokens = rng.integers(0, vocab, size=997).astype(dtype)  # odd length
        for seq in (1, 16, 250, 1200):  # 1200 > 997: multi-wrap fallback
            starts = np.concatenate([
                rng.integers(0, 997, size=13),
                [0, 996, 995],  # boundary starts
            ])
            got = native.gather_windows(tokens, starts, seq)
            assert got is not None and got.dtype == np.int32
            idx = (starts[:, None] + np.arange(seq)[None, :]) % 997
            np.testing.assert_array_equal(got, tokens[idx].astype(np.int32))
    # unsupported dtype degrades to None (callers fall back to numpy)
    assert native.gather_windows(
        rng.standard_normal(8).astype(np.float32), np.array([0]), 4) is None


def test_sample_uses_native_and_matches_forced_numpy(tmp_path, monkeypatch):
    """TokenFileDataset.sample must produce identical batches through the
    native path and the HIVED_NATIVE=0 numpy path (same RNG plan)."""
    import subprocess
    import sys

    from hivedscheduler_tpu import native

    if not native.dataloader_available():
        pytest.skip("native toolchain unavailable")  # else numpy-vs-numpy

    tokens = (np.arange(5000, dtype=np.uint16) * 7) % 331
    path = tmp_path / "tok.bin"
    tokens.tofile(path)
    ds = data_lib.TokenFileDataset(str(path))
    got = ds.sample(np.random.default_rng(5), 6, 64)
    # force-numpy in a subprocess (the native lib loads once per process)
    code = (
        "import numpy as np, sys\n"
        "from hivedscheduler_tpu.parallel import data as data_lib\n"
        f"ds = data_lib.TokenFileDataset({str(path)!r})\n"
        "b = ds.sample(np.random.default_rng(5), 6, 64)\n"
        "np.save(sys.argv[1], b)\n"
    )
    out_npy = tmp_path / "numpy_batch.npy"
    env = {"HIVED_NATIVE": "0", "PATH": "/usr/bin:/bin",
           "PYTHONPATH": ":".join(sys.path)}
    subprocess.run([sys.executable, "-c", code, str(out_npy)], check=True,
                   env=env)
    np.testing.assert_array_equal(got, np.load(out_npy))


def test_prefetch_preserves_order_and_values():
    ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
    plain = [x for _, x in zip(range(5), data_lib.host_batches(ds, 4, 8, seed=3))]
    pre = [x for _, x in zip(
        range(5), data_lib.prefetch(data_lib.host_batches(ds, 4, 8, seed=3)))]
    for x, y in zip(plain, pre):
        np.testing.assert_array_equal(x, y)
    # depth 0 = passthrough
    off = [x for _, x in zip(
        range(2), data_lib.prefetch(data_lib.host_batches(ds, 4, 8, seed=3),
                                    depth=0))]
    np.testing.assert_array_equal(off[0], plain[0])


def test_prefetch_reraises_producer_errors():
    def boom():
        yield np.zeros((1, 1), np.int32)
        raise RuntimeError("producer exploded")

    it = data_lib.prefetch(boom(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="producer exploded"):
        next(it)


class TestLoaderState:
    def test_serializer_pins_dataclass_fields(self):
        """Guard (CLAUDE.md blind spot): the canonical serializer must
        cover exactly the dataclass fields — a field added to LoaderState
        without surviving to_dict/from_dict would silently break exact
        resume."""
        import dataclasses

        state = data_lib.LoaderState(seed=7, step=3, epoch=1,
                                     bitgen={"bit_generator": "PCG64"})
        d = state.to_dict()
        assert set(d) == {f.name for f in dataclasses.fields(
            data_lib.LoaderState)}
        assert data_lib.LoaderState.from_dict(d) == state
        with pytest.raises(ValueError, match="unknown LoaderState fields"):
            data_lib.LoaderState.from_dict({"seed": 0, "bogus": 1})

    def test_state_is_json_roundtrippable(self):
        """The state rides inside the checkpoint commit marker as JSON —
        numpy bit-generator state must survive the trip bit-exactly."""
        import json

        ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
        loader = data_lib.CheckpointableBatches(ds, 4, 8, seed=5)
        for _ in range(3):
            next(loader)
        d = json.loads(json.dumps(loader.to_dict()))
        restored = data_lib.CheckpointableBatches.from_dict(d, ds, 4, 8)
        np.testing.assert_array_equal(next(loader), next(restored))


class TestCheckpointableBatches:
    def test_resume_mid_stream_is_bit_exact(self):
        ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
        ref = data_lib.CheckpointableBatches(ds, 4, 8, seed=3)
        full = [next(ref) for _ in range(6)]
        a = data_lib.CheckpointableBatches(ds, 4, 8, seed=3)
        for _ in range(3):
            next(a)
        snap = a.to_dict()
        b = data_lib.CheckpointableBatches.from_dict(snap, ds, 4, 8)
        assert b.step == 3
        for want in full[3:]:
            np.testing.assert_array_equal(next(b), want)

    def test_skip_matches_next(self):
        """skip(n) must consume exactly the RNG draws next() would (the
        rollback path jumps a poisoned batch with it)."""
        ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
        a = data_lib.CheckpointableBatches(ds, 4, 8, seed=3)
        b = data_lib.CheckpointableBatches(ds, 4, 8, seed=3)
        for _ in range(2):
            next(a)
        b.skip(2)
        assert a.step == b.step == 2
        np.testing.assert_array_equal(next(a), next(b))

    def test_host_shards_partition_the_global_batch(self):
        ds = data_lib.synthetic_dataset(100, size=4096, seed=1)
        shards = [
            next(data_lib.CheckpointableBatches(
                ds, 8, 16, process_index=i, process_count=4, seed=7))
            for i in range(4)
        ]
        full = next(data_lib.CheckpointableBatches(ds, 8, 16, seed=7))
        np.testing.assert_array_equal(np.concatenate(shards), full)

    def test_epoch_tracks_corpus_passes(self):
        ds = data_lib.synthetic_dataset(50, size=64, seed=1)
        loader = data_lib.CheckpointableBatches(ds, 2, 8, seed=0)
        assert loader.epoch == 0
        for _ in range(4):  # 4 steps x 16 tokens = one 64-token pass
            next(loader)
        assert loader.epoch == 1

    def test_indivisible_batch_rejected(self):
        ds = data_lib.synthetic_dataset(10, size=128)
        with pytest.raises(ValueError, match="not divisible"):
            data_lib.CheckpointableBatches(ds, 7, 8, process_count=2)


def test_prefetch_stop_event_wakes_blocked_consumer():
    """A consumer blocked on a wedged producer must wake when the stop
    event (the supervisor's preemption event) is set — otherwise SIGTERM
    could never reach the step boundary and the grace period would
    force-exit instead of checkpointing."""
    import threading
    import time

    release = threading.Event()

    def wedged():
        yield np.zeros((1,), np.int32)
        release.wait(30.0)  # simulated hung data source
        yield np.ones((1,), np.int32)

    stop = threading.Event()
    it = data_lib.prefetch(wedged(), depth=2, stop=stop)
    try:
        next(it)
        threading.Timer(0.1, stop.set).start()
        t0 = time.monotonic()
        with pytest.raises(StopIteration):
            next(it)
        assert time.monotonic() - t0 < 5.0
    finally:
        release.set()


def test_prefetch_close_with_full_queue_drains_and_reaps_worker():
    """Closing the consumer while the producer is BLOCKED on the full
    bounded queue (the supervisor-abort shape) must drain the staged
    batches and reap the worker promptly — no deadlock, no leak."""
    import time

    def infinite():
        i = 0
        while True:
            yield np.full((2, 2), i, np.int32)
            i += 1

    it = data_lib.prefetch(infinite(), depth=1)
    next(it)
    time.sleep(0.2)  # let the worker fill the queue and block in put()
    # track the worker OBJECT exposed by prefetch — an enumerate() diff
    # flakes when an unrelated library thread starts mid-test (ADVICE.md)
    workers = [data_lib._last_prefetch_worker]
    assert workers[0] is not None and workers[0].is_alive()
    t0 = time.monotonic()
    it.close()
    assert time.monotonic() - t0 < 3.0, "close() blocked on the full queue"
    for t in workers:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in workers)


def test_prefetch_abandoned_iterator_stops_worker():
    """Closing the consumer early (the train CLI's normal exit after
    --steps) must signal the producer thread to exit instead of leaving it
    blocked forever on the bounded queue (thread + staged-batch leak)."""
    import threading

    produced = []

    def infinite():
        i = 0
        while True:
            produced.append(i)
            yield np.full((2, 2), i, np.int32)
            i += 1

    # track the worker thread OBJECT directly (exposed by prefetch as
    # data_lib._last_prefetch_worker, named "hived-prefetch") — an
    # enumerate()/active_count() diff flakes when an unrelated library
    # thread starts mid-test (ADVICE.md round 5)
    it = data_lib.prefetch(infinite(), depth=2)
    next(it)
    worker = data_lib._last_prefetch_worker
    assert worker is not None and worker.name == "hived-prefetch", (
        "prefetch did not expose its worker thread"
    )
    workers = [worker]
    it.close()  # GeneratorExit -> finally -> closed.set()
    for t in workers:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in workers), (
        "prefetch worker still alive after the consumer was closed"
    )
    # the producer stopped near where it was abandoned, not unbounded
    assert len(produced) <= 6
