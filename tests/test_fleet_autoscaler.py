"""Fleet autoscaler (ISSUE 12 tentpole): hysteresis + cooldown state
machine under a virtual clock, drain-based scale-down, and the
scheduler-coupled backend where scale-up competes under VC quotas."""

import os
import sys

import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hivedscheduler_tpu.chaos import invariants  # noqa: E402
from hivedscheduler_tpu.fleet import (  # noqa: E402
    AutoscalePolicy,
    FleetAutoscaler,
    FleetConfig,
    FleetRouter,
    LocalScaleBackend,
    SchedulerScaleBackend,
)
from hivedscheduler_tpu.models import serving, transformer as tm  # noqa: E402


@pytest.fixture(scope="module")
def setup():
    cfg = tm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_kv_heads=2, n_layers=1,
        d_ff=64, max_seq_len=64, dtype=jnp.float32)
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def make_engine(setup, **kw):
    cfg, params = setup
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    return serving.ServingEngine(params, cfg, prefix_cache_size=8, **kw)


PROMPT = list(range(1, 12))


def build(setup, policy, clock):
    seq = [0]

    def factory(role):
        seq[0] += 1
        return f"auto{seq[0]}", make_engine(setup)

    r = FleetRouter(clock=clock)
    r.add_replica("r0", make_engine(setup))
    a = FleetAutoscaler(r, LocalScaleBackend(factory), policy, clock=clock)
    return r, a


class TestHysteresisAndCooldown:
    def test_up_needs_stable_pressure(self, setup):
        clk = [0.0]
        r, a = build(setup, AutoscalePolicy(
            max_replicas=3, queue_high=1.0, up_stable_ticks=3,
            cooldown_s=0.0), lambda: clk[0])
        for _ in range(6):
            r.submit(PROMPT, 4)
        a.tick()
        a.tick()
        assert len(r.replicas) == 1  # 2 ticks of pressure < up_stable_ticks
        a.tick()
        assert len(r.replicas) == 2  # third consecutive tick scales
        r.run_until_drained()
        invariants.check_fleet(r, "up-hysteresis")

    def test_cooldown_bounds_action_rate(self, setup):
        clk = [0.0]
        r, a = build(setup, AutoscalePolicy(
            max_replicas=4, queue_high=0.5, up_stable_ticks=1,
            cooldown_s=10.0), lambda: clk[0])
        for _ in range(8):
            r.submit(PROMPT, 4)
        clk[0] = 100.0
        a.tick()
        assert len(r.replicas) == 2
        clk[0] = 101.0  # inside the cooldown: pressure ignored
        a.tick()
        assert len(r.replicas) == 2
        clk[0] = 111.0  # cooldown expired
        a.tick()
        assert len(r.replicas) == 3
        r.run_until_drained()

    def test_scale_down_is_drain_based_and_floored(self, setup):
        clk = [0.0]
        r, a = build(setup, AutoscalePolicy(
            min_replicas=1, max_replicas=3, down_stable_ticks=2,
            cooldown_s=0.0), lambda: clk[0])
        r.add_replica("r1", make_engine(setup))
        # idle fleet: down-pressure accumulates, the victim drains first
        a.tick()
        acts = a.tick()
        assert any(x["phase"] == "draining" for x in acts)
        victim = next(x["replica"] for x in acts if x["phase"] == "draining")
        assert r.replicas[victim].state in ("draining", "drained")
        r.step()  # router observes the drain
        acts = a.tick()
        assert any(x["phase"] == "removed" for x in acts)
        assert victim not in r.replicas
        assert r.removed[-1].name == victim
        invariants.check_fleet(r, "drain-down")
        # floor: never below min_replicas
        for _ in range(8):
            a.tick()
        assert len(r.replicas) == 1

    def test_replica_seconds_integrates_cost(self, setup):
        clk = [0.0]
        r, a = build(setup, AutoscalePolicy(cooldown_s=0.0),
                     lambda: clk[0])
        a.tick()
        clk[0] = 5.0
        a.tick()
        assert a.replica_seconds == pytest.approx(5.0)  # 1 replica x 5 s

    def test_signals_shape(self, setup):
        clk = [0.0]
        r, a = build(setup, AutoscalePolicy(), lambda: clk[0])
        for _ in range(3):
            r.submit(PROMPT, 2)
        sig = a.signals("serve")
        assert sig["replicas"] == 1 and sig["queueDepth"] >= 1
        assert 0.0 <= sig["occupancy"] <= 1.0
        r.run_until_drained()


class TestSchedulerBackend:
    """Scale-up through a live HivedScheduler: each replica is a gang
    member pod in the fleet VC — a grow beyond quota stays PENDING (the
    autoscaler reports phase=pending) until capacity frees, i.e. the
    fleet competes under VC quotas like any gang."""

    def test_grow_competes_under_vc_quota(self, setup):
        from tests.test_defrag_runtime import build_scheduler

        sched, kube, nodes = build_scheduler()
        try:
            built = []

            def factory(role, pod_name):
                # this test never serves: a stub engine keeps the JIT
                # cost out of tier-1 (the backend is engine-agnostic)
                built.append(pod_name)
                return object()

            backend = SchedulerScaleBackend(
                sched, kube, nodes, factory, vc="vc-x",
                leaf_cell_type="v5p-chip", chips_per_replica=4,
                elastic_min_chips=2)
            # the VC owns two 4-chip cells: two grows bind, the third
            # stays pending
            h1 = backend.grow("serve")
            h2 = backend.grow("serve")
            assert h1 is not None and h2 is not None
            h3 = backend.grow("serve")
            assert h3 is None  # quota-limited: pod submitted, waiting
            # capacity frees (a replica shrinks): the SAME pending pod
            # binds on the next tick
            backend.shrink("serve", type("R", (), {"gang": h1[2]})())
            h3 = backend.grow("serve")
            assert h3 is not None
            assert len(built) == 3
        finally:
            sched.stop() if hasattr(sched, "stop") else None

    def test_autoscaler_reports_pending_when_quota_blocked(self, setup):
        from tests.test_defrag_runtime import build_scheduler

        sched, kube, nodes = build_scheduler()

        def factory(role, pod_name):
            return make_engine(setup)

        backend = SchedulerScaleBackend(
            sched, kube, nodes, factory, vc="vc-x",
            leaf_cell_type="v5p-chip", chips_per_replica=4)
        clk = [0.0]
        r = FleetRouter(clock=lambda: clk[0])
        h = backend.grow("serve")
        r.add_replica(h[0], h[1], gang=h[2])
        h = backend.grow("serve")
        r.add_replica(h[0], h[1], gang=h[2])
        a = FleetAutoscaler(r, backend, AutoscalePolicy(
            max_replicas=4, queue_high=0.5, up_stable_ticks=1,
            cooldown_s=0.0), clock=lambda: clk[0])
        for _ in range(8):
            r.submit(PROMPT, 2)
        acts = a.tick()
        # up-pressure is real but the VC is full: the grow stays pending
        assert any(x["direction"] == "up" and x["phase"] == "pending"
                   for x in acts)
        assert len(r.replicas) == 2
        r.run_until_drained()
        invariants.check_fleet(r, "quota-pending")


class TestFleetConfig:
    def test_yaml_round_trip(self):
        path = os.path.join(REPO, "example", "config", "design",
                            "fleet.yaml")
        fc = FleetConfig.from_yaml(path)
        assert fc is not None and fc.disaggregate and fc.autoscale
        assert fc.policy == "prefix_affinity"
        pol = fc.autoscale_policy()
        assert pol.max_replicas == 3 and pol.cooldown_s == 5.0

    def test_unknown_keys_raise(self):
        with pytest.raises(ValueError, match="unknown fleet config keys"):
            FleetConfig.from_dict({"replicsa": 3})

    def test_missing_section_is_none(self, tmp_path):
        p = tmp_path / "nofleet.yaml"
        p.write_text("physicalCluster: {}\n")
        assert FleetConfig.from_yaml(str(p)) is None
