"""Request flight recorder + SLO layer (ISSUE 13): leg-attribution core
semantics (exclusive, non-overlapping, contiguous legs whose TTFT subset
sums to the measured ttft_s), the SLO tracker's windowed quantiles /
error-budget burn / dominant-leg violation attribution, the autoscaler's
signal swap pinned decision-identical to the old hand-sorted p95, the
chaos invariant (check_requests), the inspect endpoints, the Perfetto
merge, the disabled-path overhead gate, and the serve CLI flag smoke.
"""

import json
import os
import sys
import types
import urllib.request

import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from helpers import validate_chrome_trace  # noqa: E402

from hivedscheduler_tpu.chaos import invariants  # noqa: E402
from hivedscheduler_tpu.fleet import (  # noqa: E402
    AutoscalePolicy,
    FleetAutoscaler,
    FleetConfig,
    FleetRouter,
    LocalScaleBackend,
)
from hivedscheduler_tpu.models import serving, transformer as tm  # noqa: E402
from hivedscheduler_tpu.obs import journal  # noqa: E402
from hivedscheduler_tpu.obs import slo as obs_slo  # noqa: E402
from hivedscheduler_tpu.obs import trace as obs_trace  # noqa: E402


@pytest.fixture(autouse=True)
def _journal_isolation():
    journal.disable()
    journal.JOURNAL.clear()
    obs_trace.disable()
    obs_trace.TRACER.clear()
    yield
    journal.disable()
    journal.JOURNAL.clear()
    obs_trace.disable()
    obs_trace.TRACER.clear()


@pytest.fixture(scope="module")
def setup():
    cfg = tm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_kv_heads=2, n_layers=1,
        d_ff=64, max_seq_len=64, dtype=jnp.float32)
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def make_engine(setup, **kw):
    cfg, params = setup
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefix_cache_size", 8)
    return serving.ServingEngine(params, cfg, **kw)


# ------------------------------------------------------- recorder core


class TestFlightCore:
    def test_disabled_is_noop(self):
        assert journal.note_request_submit("fleet/0") is None
        assert journal.note_leg("fleet/0", "route") is None
        assert journal.note_request_done("fleet/0", "length") is None
        assert journal.JOURNAL.requests() == []
        assert journal.JOURNAL.flights() == {}

    def test_unregistered_leg_rejected(self):
        journal.enable()
        with pytest.raises(ValueError,
                           match="not a registered request leg"):
            journal.note_leg("fleet/0", "made_up_leg")

    def test_legs_tile_and_ttft_gap_is_zero(self):
        journal.enable()
        journal.note_request_submit("fleet/0", at=10.0)
        journal.note_leg("fleet/0", "route", at=10.5)
        journal.note_leg("fleet/0", "admission_wait", at=12.0)
        journal.note_leg("fleet/0", "prefill", at=13.0)
        journal.note_request_done("fleet/0", "length",
                                  first_token_at=13.0, at=15.0)
        fl = journal.JOURNAL.flights()["fleet/0"]
        assert [(l, s, e) for l, s, e in fl["legs"]] == [
            ("route", 10.0, 10.5), ("admission_wait", 10.5, 12.0),
            ("prefill", 12.0, 13.0)]
        assert fl["terminal"] == "length" and fl["terminals"] == 1
        assert fl["ttft_gap"] == pytest.approx(0.0, abs=1e-9)
        summary = journal.JOURNAL.requests()[0]
        assert summary["ttftS"] == pytest.approx(3.0)
        assert summary["dominantLeg"] == "admission_wait"

    def test_gap_surfaces_uninstrumented_segment(self):
        journal.enable()
        journal.note_request_submit("fleet/1", at=0.0)
        journal.note_leg("fleet/1", "admission_wait", at=1.0)
        # nothing attributed [1.0, 3.0] — the measured first token at 3.0
        # leaves a 2 s hole the sum cannot cover
        journal.note_request_done("fleet/1", "length",
                                  first_token_at=3.0, at=4.0)
        fl = journal.JOURNAL.flights()["fleet/1"]
        assert fl["ttft_gap"] == pytest.approx(-2.0)

    def test_resubmit_resets_the_flight(self):
        journal.enable()
        journal.note_request_submit("fleet/2", at=0.0)
        journal.note_leg("fleet/2", "route", at=1.0)
        journal.note_request_done("fleet/2", "length",
                                  first_token_at=1.0, at=1.0)
        # a later router incarnation reuses the fid: fresh record
        journal.note_request_submit("fleet/2", at=100.0)
        fl = journal.JOURNAL.flights()["fleet/2"]
        assert fl["legs"] == [] and fl["terminals"] == 0
        assert fl["t0"] == 100.0

    def test_request_timeline_payload(self):
        journal.enable()
        journal.note_request_submit("fleet/3", at=0.0)
        journal.note_leg("fleet/3", "route", at=0.25)
        journal.note_request_done("fleet/3", "eos",
                                  first_token_at=0.25, at=0.5)
        tl = journal.JOURNAL.request_timeline("fleet/3")
        assert [e["type"] for e in tl["events"]] == [
            "request_submit", "request_leg", "request_done"]
        # cause-chained: each event chains to the previous
        assert tl["events"][1]["cause"] == tl["events"][0]["id"]
        assert tl["events"][2]["cause"] == tl["events"][1]["id"]
        assert tl["legs"] == [{"leg": "route", "start": 0.0, "end": 0.25,
                               "durS": 0.25}]
        assert tl["summary"]["terminal"] == "eos"
        assert tl["summary"]["ttftGapS"] == pytest.approx(0.0)

    def test_every_leg_documented(self):
        assert all(doc for doc in journal.REQUEST_LEGS.values())
        assert set(journal.REQUEST_LEGS) == {
            "route", "router_queue", "retry", "admission_wait", "prefill",
            "handoff_ship", "handoff_import", "first_decode"}


# ------------------------------------------------------------- tracker


class TestSLOTracker:
    def test_quantile_matches_hand_sorted_convention(self):
        t = obs_slo.SLOTracker(window_s=0.0, metrics=False)
        vals = [0.5, 0.1, 0.9, 0.3, 0.7, 0.2, 0.4]
        for i, v in enumerate(vals):
            t.observe("ttft", v, at=float(i))
        for q in (0.5, 0.95, 0.99):
            ref = sorted(vals)[int(q * (len(vals) - 1))]
            assert t.quantile(q, "ttft", now=100.0) == ref

    def test_window_excludes_stale_observations(self):
        t = obs_slo.SLOTracker(window_s=10.0, metrics=False)
        t.observe("ttft", 5.0, at=0.0)     # stale at now=20
        t.observe("ttft", 1.0, at=15.0)
        assert t.quantile(0.99, "ttft", now=20.0) == 1.0
        assert t.quantile(0.99, "ttft", now=100.0) == 0.0  # all aged out

    def test_burn_rate_and_attribution(self):
        o = obs_slo.SLObjective("ttft", 0.99, ceiling_s=1.0)
        t = obs_slo.SLOTracker(objectives=(o,), window_s=0.0,
                               metrics=False)
        for i in range(98):
            t.observe("ttft", 0.5, at=float(i), leg="prefill")
        t.observe("ttft", 2.0, at=98.0, leg="admission_wait")
        t.observe("ttft", 3.0, at=99.0, leg="admission_wait")
        # 2 violations / 100 observations at a 1% budget = burn 2.0
        assert t.burn_rate(o, now=100.0) == pytest.approx(2.0)
        snap = t.snapshot(now=100.0)
        obj = snap["objectives"][0]
        assert obj["windowViolations"] == 2
        assert obj["compliance"] == pytest.approx(0.98)
        assert obj["attribution"] == {"admission_wait": 2}

    def test_per_priority_objective_scopes(self):
        o = obs_slo.SLObjective("ttft", 0.99, ceiling_s=1.0, priority=10)
        t = obs_slo.SLOTracker(objectives=(o,), window_s=0.0,
                               metrics=False)
        t.observe("ttft", 5.0, priority=0, at=0.0)   # out of scope
        t.observe("ttft", 5.0, priority=10, at=1.0)  # violates
        assert t.burn_rate(o, now=2.0) == pytest.approx(100.0)
        assert t.violations[o.name] == {"unattributed": 1}

    def test_objective_validation(self):
        with pytest.raises(ValueError, match="unknown SLO series"):
            obs_slo.SLObjective("latency", 0.99, 1.0)
        with pytest.raises(ValueError, match="quantile must be in"):
            obs_slo.SLObjective("ttft", 1.0, 1.0)
        with pytest.raises(ValueError, match="ceiling must be > 0"):
            obs_slo.SLObjective("ttft", 0.99, 0.0)

    def test_objectives_from_knobs(self):
        objs = obs_slo.objectives_from_knobs(
            ttft_p99_s=0.5, tpot_p95_s=0.05,
            per_priority_ttft_p99={10: 0.2})
        assert [o.name for o in objs] == ["ttft_p99", "tpot_p95",
                                         "ttft_p99/p10"]
        assert objs[2].priority == 10

    def test_fleet_config_slo_knobs(self):
        cfg = FleetConfig.from_dict({
            "slo_ttft_p99_s": 0.5, "slo_window_s": 30.0,
            "slo_ttft_p99_by_priority": {"10": 0.2}})
        tracker = cfg.slo_tracker(metrics=False)
        assert tracker.window_s == 30.0
        assert [o.name for o in tracker.objectives] == [
            "ttft_p99", "ttft_p99/p10"]
        with pytest.raises(ValueError, match="unknown fleet config keys"):
            FleetConfig.from_dict({"slo_ttft_p99": 0.5})


# ------------------------------------------- autoscaler signal swap pin


class _FakeEngine:
    """Just enough engine surface for Replica/FleetAutoscaler signals."""

    paged = False
    prefix_cache_size = 0
    max_batch = 1

    def __init__(self):
        self.queue = []
        self.slots = [None]

    def begin_drain(self):
        pass


def test_autoscaler_decisions_identical_to_hand_rolled_p95():
    """Satellite pin: the SLO tracker's windowed quantile replaces the
    hand-sorted ring p95 (`sorted(...)[int(0.95 * (n - 1))]` over the
    last 256) — on a recorded TTFT signal sequence the autoscaler's
    decisions must be identical to a reference driven by the old math."""
    from collections import deque

    import random

    rng = random.Random(13)
    recorded = [rng.uniform(0.1, 2.5) for _ in range(120)]

    now = [0.0]
    tracker = obs_slo.SLOTracker(window_s=0.0, cap=256,
                                 clock=lambda: now[0], metrics=False)
    router = FleetRouter(slo=tracker, clock=lambda: now[0])
    router.add_replica("r0", _FakeEngine())
    policy = AutoscalePolicy(min_replicas=1, max_replicas=4,
                             ttft_ceiling_s=1.5, up_stable_ticks=2,
                             down_stable_ticks=10 ** 6, cooldown_s=0.0)
    auto = FleetAutoscaler(
        router, LocalScaleBackend(lambda role: (f"x{now[0]}",
                                                _FakeEngine())),
        policy, clock=lambda: now[0])

    # reference: the pre-ISSUE-13 implementation's exact decision logic
    ref_ring = deque(maxlen=256)
    ref_up = 0
    ref_n = 1
    ref_actions = []

    got_actions = []
    for i, v in enumerate(recorded):
        now[0] = float(i + 1)
        tracker.observe("ttft", v, at=now[0])
        ref_ring.append(v)
        # live autoscaler tick
        for a in auto.tick():
            got_actions.append((i, a["direction"], a["phase"]))
        # reference tick (ttft is the only pressure: occupancy 0, queue 0)
        ttfts = sorted(ref_ring)
        p95 = ttfts[int(0.95 * (len(ttfts) - 1))] if ttfts else 0.0
        ref_up = ref_up + 1 if p95 > policy.ttft_ceiling_s else 0
        if ref_up >= policy.up_stable_ticks and ref_n < 4:
            ref_actions.append((i, "up", "added"))
            ref_n += 1
            ref_up = 0
    assert got_actions == ref_actions
    assert len(got_actions) > 0, "the recorded sequence never scaled — " \
                                 "the pin is vacuous"
    sig = auto.signals("serve")
    assert sig["ttftP95"] == tracker.quantile(0.95, "ttft", now=now[0])


# -------------------------------------------------- end-to-end (fleet)


def test_fleet_flight_sums_and_slo_attribution(setup):
    """Ship-mode fleet: every completed request's TTFT legs sum to its
    measured ttft_s, the dominant leg feeds the SLO tracker's violation
    attribution, and check_requests passes on the live router."""
    journal.enable()
    tracker = obs_slo.SLOTracker(
        objectives=(obs_slo.SLObjective("ttft", 0.99, ceiling_s=1e-9),),
        window_s=0.0, metrics=False)
    r = FleetRouter(disaggregate=True, kv_ship=True, slo=tracker)
    r.add_replica("p0", make_engine(setup), role="prefill")
    r.add_replica("d0", make_engine(setup), role="decode")
    reqs = [r.submit(list(range(1, 14)), 4),
            r.submit(list(range(2, 10)), 3)]
    r.run_until_drained()
    invariants.check_fleet(r, "flights")
    flights = journal.JOURNAL.flights()
    for f in reqs:
        fl = flights[f"fleet/{f.fid}"]
        assert fl["terminal"] == f.finish_reason
        assert fl["ttft_gap"] == pytest.approx(0.0, abs=1e-6)
        legs = [leg for leg, _s, _e in fl["legs"]]
        assert legs[0] == "route" and "admission_wait" in legs
    # the 1e-9 ceiling makes every request a violation: attribution is
    # by dominant leg, not "unattributed"
    obj = tracker.snapshot(now=reqs[-1].done_at + 1)["objectives"][0]
    assert obj["attribution"] and \
        set(obj["attribution"]) <= set(journal.REQUEST_LEGS)


def test_single_engine_flights(setup):
    """record_flights: serve/<rid> flights with engine-owned terminals —
    admission_wait + prefill sum to the engine-level TTFT; shed requests
    reach a single `shed` terminal."""
    journal.enable()
    eng = make_engine(setup)
    eng.record_flights = True
    reqs = [eng.submit(list(range(1, 10)), 3),
            eng.submit(list(range(2, 12)), 2)]
    eng.run_until_drained()
    flights = journal.JOURNAL.flights()
    for req in reqs:
        fl = flights[f"serve/{req.rid}"]
        assert fl["terminal"] == req.finish_reason
        assert fl["terminals"] == 1
        assert fl["ttft_gap"] == pytest.approx(0.0, abs=1e-6)
        assert [leg for leg, _s, _e in fl["legs"]] == [
            "admission_wait", "prefill"]

    shed_eng = make_engine(setup, queue_timeout_s=0.0)
    shed_eng.record_flights = True
    shed = [shed_eng.submit([1, 2, 3], 2) for _ in range(2)]
    shed_eng.run_until_drained()
    flights = journal.JOURNAL.flights()
    for req in shed:
        assert req.finish_reason == "shed"
        fl = flights[f"serve/{req.rid}"]
        assert fl["terminal"] == "shed" and fl["terminals"] == 1


# --------------------------------------------------- chaos invariant


def _fake_router(*freqs):
    return types.SimpleNamespace(requests=list(freqs))


def _freq(fid, done=True, reason="length", submitted=0.0, done_at=5.0,
          ttft=None, retries=0):
    return types.SimpleNamespace(
        fid=fid, done=done, finish_reason=reason, submitted_at=submitted,
        done_at=done_at, ttft_s=ttft, retries=retries)


class TestCheckRequests:
    def test_noop_when_disabled(self):
        invariants.check_requests(_fake_router(_freq(0)))

    def test_clean_flight_passes(self):
        journal.enable()
        journal.note_request_submit("fleet/0", at=0.0)
        journal.note_leg("fleet/0", "route", at=0.5)
        journal.note_leg("fleet/0", "admission_wait", at=1.0)
        journal.note_leg("fleet/0", "prefill", at=2.0)
        journal.note_request_done("fleet/0", "length",
                                  first_token_at=2.0, at=5.0)
        invariants.check_requests(_fake_router(_freq(0, ttft=2.0)))

    def test_done_without_terminal_flagged(self):
        journal.enable()
        journal.note_request_submit("fleet/0", at=0.0)
        with pytest.raises(invariants.InvariantViolation,
                           match="never reached a terminal"):
            invariants.check_requests(_fake_router(_freq(0)))

    def test_double_terminal_flagged(self):
        journal.enable()
        journal.note_request_submit("fleet/0", at=0.0)
        journal.note_request_done("fleet/0", "length", at=1.0)
        journal.note_request_done("fleet/0", "length", at=2.0)
        with pytest.raises(invariants.InvariantViolation,
                           match="terminal legs — exactly one"):
            invariants.check_requests(_fake_router(_freq(0)))

    def test_live_request_with_terminal_flagged(self):
        journal.enable()
        journal.note_request_submit("fleet/0", at=0.0)
        journal.note_request_done("fleet/0", "length", at=1.0)
        with pytest.raises(invariants.InvariantViolation,
                           match="live but its flight"):
            invariants.check_requests(_fake_router(_freq(0, done=False)))

    def test_ttft_gap_flagged(self):
        journal.enable()
        journal.note_request_submit("fleet/0", at=0.0)
        journal.note_leg("fleet/0", "route", at=0.5)
        # [0.5, 2.0] unattributed; first token measured at 2.0
        journal.note_request_done("fleet/0", "length",
                                  first_token_at=2.0, at=5.0)
        with pytest.raises(invariants.InvariantViolation,
                           match="uninstrumented"):
            invariants.check_requests(_fake_router(_freq(0, ttft=2.0)))

    def test_lost_retry_leg_flagged(self):
        journal.enable()
        journal.note_request_submit("fleet/0", at=0.0)
        journal.note_leg("fleet/0", "route", at=0.5)
        journal.note_request_done("fleet/0", "length", at=5.0)
        with pytest.raises(invariants.InvariantViolation,
                           match="lost between shed and retry"):
            invariants.check_requests(
                _fake_router(_freq(0, retries=1)))


# --------------------------------------------------------- endpoints


def _serve_dummy():
    from hivedscheduler_tpu.webserver.server import WebServer

    server = WebServer(types.SimpleNamespace(), address="127.0.0.1:0")
    host, port = server.async_run()
    return server, f"http://{host}:{port}"


def _get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return r.status, json.loads(r.read())


def test_requests_and_slo_endpoints_serve_the_live_fleet():
    from hivedscheduler_tpu import fleet as fleet_pkg
    from hivedscheduler_tpu.api import constants as C

    journal.enable()
    journal.note_request_submit("fleet/0", at=0.0)
    journal.note_leg("fleet/0", "route", at=0.5)
    journal.note_request_done("fleet/0", "length",
                              first_token_at=0.5, at=1.0)
    tracker = obs_slo.SLOTracker(
        objectives=(obs_slo.SLObjective("ttft", 0.99, 1.0),),
        window_s=0.0, metrics=False)
    tracker.observe("ttft", 0.5, leg="route", at=1.0)
    router = FleetRouter(slo=tracker)
    fleet_pkg.publish(router)
    server, base = _serve_dummy()
    try:
        status, body = _get(base, C.REQUESTS_PATH)
        assert status == 200 and body["enabled"]
        assert body["items"][0]["request"] == "fleet/0"
        assert body["items"][0]["legs"] == {"route": 0.5}
        status, tl = _get(base, C.REQUESTS_PATH + "/fleet/0/timeline")
        assert status == 200 and tl["request"] == "fleet/0"
        assert tl["summary"]["terminal"] == "length"
        status, slo_body = _get(base, C.SLO_PATH)
        assert status == 200 and slo_body["enabled"]
        assert slo_body["objectives"][0]["name"] == "ttft_p99"
        assert slo_body["series"]["ttft"]["count"] == 1
    finally:
        server.stop()
        fleet_pkg.publish(None)


def test_perfetto_merge_draws_request_lanes():
    obs_trace.enable()
    journal.enable()
    journal.note_request_submit("fleet/0")
    journal.note_leg("fleet/0", "route")
    journal.note_request_done("fleet/0", "no_replica")
    events = validate_chrome_trace(obs_trace.to_chrome_trace())
    names = [e["name"] for e in events]
    assert "leg:route" in names
    lanes = [e for e in events if e["ph"] == "M"
             and e["args"].get("name") == "request fleet/0"]
    assert lanes, "each flight must get a named request lane"


# ------------------------------------------------------ overhead gate


def test_disabled_path_takes_no_lock_and_allocates_nothing():
    """The journal's PR 1 contract applied to the flight recorder:
    disabled note_leg/note_request_* is ONE attribute check — it must
    return before ever touching the lock or the records."""
    j = journal.JOURNAL
    saved = j._lock
    j._lock = None
    try:
        for _ in range(1000):
            assert journal.note_request_submit("fleet/0") is None
            assert journal.note_leg("fleet/0", "route") is None
            assert journal.note_request_done("fleet/0", "length") is None
    finally:
        j._lock = saved
    assert len(j) == 0 and j.flights() == {}


# --------------------------------------------------- CLI parse smoke


def test_serve_cli_parses_slo_flags(capsys):
    from hivedscheduler_tpu import serve

    with pytest.raises(SystemExit) as exc:
        serve.main(["--help"])
    assert exc.value.code == 0
    out = capsys.readouterr().out
    assert "--slo-ttft-p99" in out and "--slo-window-s" in out
