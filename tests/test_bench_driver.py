"""Driver-artifact plumbing in bench.py (pure parts).

The round-3 driver lost its workload number to an undiagnosable bare
"rc=1" in exactly this code path, so the parse is a plain function with
its degradation contract pinned here."""

import json

import bench


def _result_line(**over):
    m = {
        "metric": "train_step_mfu_1chip", "value": 45.2, "unit": "%",
        "vs_baseline": 1.13, "device": "TPU v5 lite",
        "train_tokens_per_sec": 31000.0, "decode_tokens_per_sec": 11000.0,
        "decode_hbm_roofline_frac": 0.81, "serve_tokens_per_sec": 9000.0,
        "serve_occupancy": 0.9, "serve_prefix_speedup": 1.4,
        "serve_prefix_ttft_speedup": 2.1,
        "decode_roofline_pass": True, "serve_slot_efficiency": 0.85,
        "serve_slot_efficiency_pass": True,
    }
    m.update(over)
    return json.dumps(m)


class TestParseModelBenchOutput:
    def test_success_extracts_fields_and_stamps(self):
        fields, stamped = bench.parse_model_bench_output(
            0, _result_line() + "\n", "")
        assert fields["model_train_mfu_pct"] == 45.2
        assert fields["model_decode_hbm_roofline_frac"] == 0.81
        assert fields["model_serve_tokens_per_sec"] == 9000.0
        assert fields["model_serve_prefix_speedup"] == 1.4
        assert fields["model_serve_prefix_ttft_speedup"] == 2.1
        # the serving bars' pass/fail travels with the numbers
        assert fields["model_decode_roofline_pass"] is True
        assert fields["model_serve_slot_efficiency"] == 0.85
        assert fields["model_serve_slot_efficiency_pass"] is True
        assert stamped["captured_by"] == "bench.py driver path"
        assert stamped["captured_at_utc"].endswith("Z")

    def test_stray_scalar_json_lines_are_skipped(self):
        out = _result_line() + "\nNaN\nnull\n3\n"
        fields, stamped = bench.parse_model_bench_output(0, out, "")
        assert fields["model_train_mfu_pct"] == 45.2
        assert stamped is not None

    def test_smoke_result_contributes_nothing_and_never_stamps(self):
        out = _result_line(metric="train_step_mfu_1chip_smoke")
        fields, stamped = bench.parse_model_bench_output(0, out, "")
        assert fields == {}
        assert stamped is None  # must never overwrite BENCH_MODEL.json

    def test_nonzero_rc_carries_child_error_and_stderr_tail(self):
        err = json.dumps({"metric": "train_step_mfu_1chip", "value": None,
                          "error": "tpu_acquire_timeout: tunnel busy"})
        fields, stamped = bench.parse_model_bench_output(
            3, err, "WARNING: Platform 'axon' is experimental\n")
        assert stamped is None
        assert "tpu_acquire_timeout" in fields["model_bench_error"]
        assert "experimental" in fields["model_bench_stderr_tail"]

    def test_bare_crash_still_reports_rc_and_stderr(self):
        fields, stamped = bench.parse_model_bench_output(
            1, "", "Traceback ...\nRuntimeError: boom\n")
        assert stamped is None
        assert fields["model_bench_error"] == "rc=1"
        assert "boom" in fields["model_bench_stderr_tail"]

    def test_non_result_dict_degrades_to_note_with_payload(self):
        out = json.dumps({"metric": "train_step_mfu_1chip", "note": "odd"})
        fields, stamped = bench.parse_model_bench_output(0, out, "")
        assert stamped is None
        assert "missing keys" in fields["model_bench_error"]
        assert "odd" in fields["model_bench_error"]  # child payload kept

    def test_error_field_wins_even_with_rc_zero(self):
        out = _result_line() + "\n" + json.dumps(
            {"error": "tpu_backend_unavailable: UNAVAILABLE"})
        fields, stamped = bench.parse_model_bench_output(0, out, "")
        assert stamped is None
        assert "tpu_backend_unavailable" in fields["model_bench_error"]


class TestTraceStrawman:
    """The OSDI'20-style comparison (bench.run_trace baseline=True): the
    topology-unaware first-fit strawman must replay the same trace with the
    same gang semantics, and the geometry/decomposition fields must expose
    HiveD's placement advantage."""

    def test_gang_geometry(self):
        # a 2x2x1 block is contiguous; punch a hole and it isn't
        block = [(0, 0, 0), (0, 1, 0), (1, 0, 0), (1, 1, 0)]
        contig, infl = bench._gang_geometry(block)
        assert contig and infl == 1.0
        holed = [(0, 0, 0), (0, 1, 0), (1, 0, 0), (3, 1, 0)]
        contig, infl = bench._gang_geometry(holed)
        assert not contig and infl == 2.0  # bbox 4x2x1=8 over 4 chips

    def test_naive_cluster_gang_semantics(self):
        c = bench.NaiveCluster()
        ok, _, pre = c.schedule_gang("vc", 0, "a", 4, 4)
        assert ok and not pre
        assert sum(f for f in c.host_free.values()) == 1024 - 16
        # gang atomicity: an impossible gang changes nothing
        ok, _, _ = c.schedule_gang("vc", -1, "big", 300, 4)
        assert not ok and "big" not in c.groups
        assert sum(f for f in c.host_free.values()) == 1024 - 16
        c.free_gang("a")
        assert sum(f for f in c.host_free.values()) == 1024

    def test_naive_preemption_kills_lower_priority_only(self):
        c = bench.NaiveCluster()
        # fill the cluster with opportunistic gangs
        for i in range(4):
            ok, _, _ = c.schedule_gang("vc", -1, f"ot-{i}", 64, 4)
            assert ok
        # a guaranteed gang preempts; an equal-priority one cannot
        ok, _, pre = c.schedule_gang("vc", 5, "guar", 64, 4,
                                     allow_preempt=True)
        assert ok and pre
        ok, _, pre = c.schedule_gang("vc", 5, "guar2", 300, 4,
                                     allow_preempt=True)
        assert not ok
        # refill: guaranteed gangs occupy everything...
        while c.schedule_gang("vc", 5, f"fill-{len(c.groups)}", 16, 4)[0]:
            pass
        before = dict(c.prio)
        # ...an opportunistic arrival with allow_preempt must NOT kill
        # anyone (prio < 0 never preempts), and an equal-priority
        # guaranteed arrival must not either (strictly-lower only)
        ok, _, pre = c.schedule_gang("vc", -1, "ot-new", 64, 4,
                                     allow_preempt=True)
        assert not ok and not pre and c.prio == before
        ok, _, pre = c.schedule_gang("vc", 5, "guar3", 64, 4,
                                     allow_preempt=True)
        assert not ok and not pre and c.prio == before

    def test_replay_decomposition_fields(self):
        jobs = bench.make_trace_jobs(40, seed=3)
        out = bench.replay_trace(bench.NaiveCluster(), jobs,
                                 bench.naive_gang_chips)
        for k in ("contiguous_pct", "bbox_inflation", "offered_pct",
                  "wait_chip_time_pct", "wait_capacity_share",
                  "wait_packing_share", "preempt_wasted_pct"):
            assert k in out, k
        assert out["scheduled"] <= out["jobs"]
        if out["wait_chip_time_pct"] > 0:
            assert 0.999 <= (out["wait_capacity_share"]
                             + out["wait_packing_share"]) <= 1.001

    def test_hived_beats_strawman_on_placement_quality(self):
        """The reason-to-exist assertion: same trace, HiveD's placements
        are strictly better-shaped than first-fit's (more contiguous gangs,
        lower bounding-box inflation)."""
        hived = bench.run_trace(n_jobs=120, seed=11)
        naive = bench.run_trace(n_jobs=120, seed=11, baseline=True)
        assert hived["contiguous_pct"] > naive["contiguous_pct"]
        assert hived["bbox_inflation"] < naive["bbox_inflation"]

    def test_same_host_multi_pod_gang_chips_distinct(self):
        """Sub-host gangs: two pods packed onto one host must take
        successive chip slices, not the same leading chips twice."""
        c = bench.NaiveCluster()
        ok, _, _ = c.schedule_gang("vc", 0, "g", 2, 2)
        assert ok
        chips = bench.naive_gang_chips(c, "g")
        assert len(set(chips)) == 4
        assert bench._gang_geometry(chips) == (True, 1.0)
