"""Driver-artifact plumbing in bench.py (pure parts).

The round-3 driver lost its workload number to an undiagnosable bare
"rc=1" in exactly this code path, so the parse is a plain function with
its degradation contract pinned here."""

import json

import bench


def _result_line(**over):
    m = {
        "metric": "train_step_mfu_1chip", "value": 45.2, "unit": "%",
        "vs_baseline": 1.13, "device": "TPU v5 lite",
        "train_tokens_per_sec": 31000.0, "decode_tokens_per_sec": 11000.0,
        "decode_hbm_roofline_frac": 0.81, "serve_tokens_per_sec": 9000.0,
        "serve_occupancy": 0.9, "serve_prefix_speedup": 1.4,
        "serve_prefix_ttft_speedup": 2.1,
    }
    m.update(over)
    return json.dumps(m)


class TestParseModelBenchOutput:
    def test_success_extracts_fields_and_stamps(self):
        fields, stamped = bench.parse_model_bench_output(
            0, _result_line() + "\n", "")
        assert fields["model_train_mfu_pct"] == 45.2
        assert fields["model_decode_hbm_roofline_frac"] == 0.81
        assert fields["model_serve_tokens_per_sec"] == 9000.0
        assert fields["model_serve_prefix_speedup"] == 1.4
        assert fields["model_serve_prefix_ttft_speedup"] == 2.1
        assert stamped["captured_by"] == "bench.py driver path"
        assert stamped["captured_at_utc"].endswith("Z")

    def test_stray_scalar_json_lines_are_skipped(self):
        out = _result_line() + "\nNaN\nnull\n3\n"
        fields, stamped = bench.parse_model_bench_output(0, out, "")
        assert fields["model_train_mfu_pct"] == 45.2
        assert stamped is not None

    def test_smoke_result_contributes_nothing_and_never_stamps(self):
        out = _result_line(metric="train_step_mfu_1chip_smoke")
        fields, stamped = bench.parse_model_bench_output(0, out, "")
        assert fields == {}
        assert stamped is None  # must never overwrite BENCH_MODEL.json

    def test_nonzero_rc_carries_child_error_and_stderr_tail(self):
        err = json.dumps({"metric": "train_step_mfu_1chip", "value": None,
                          "error": "tpu_acquire_timeout: tunnel busy"})
        fields, stamped = bench.parse_model_bench_output(
            3, err, "WARNING: Platform 'axon' is experimental\n")
        assert stamped is None
        assert "tpu_acquire_timeout" in fields["model_bench_error"]
        assert "experimental" in fields["model_bench_stderr_tail"]

    def test_bare_crash_still_reports_rc_and_stderr(self):
        fields, stamped = bench.parse_model_bench_output(
            1, "", "Traceback ...\nRuntimeError: boom\n")
        assert stamped is None
        assert fields["model_bench_error"] == "rc=1"
        assert "boom" in fields["model_bench_stderr_tail"]

    def test_non_result_dict_degrades_to_note_with_payload(self):
        out = json.dumps({"metric": "train_step_mfu_1chip", "note": "odd"})
        fields, stamped = bench.parse_model_bench_output(0, out, "")
        assert stamped is None
        assert "missing keys" in fields["model_bench_error"]
        assert "odd" in fields["model_bench_error"]  # child payload kept

    def test_error_field_wins_even_with_rc_zero(self):
        out = _result_line() + "\n" + json.dumps(
            {"error": "tpu_backend_unavailable: UNAVAILABLE"})
        fields, stamped = bench.parse_model_bench_output(0, out, "")
        assert stamped is None
        assert "tpu_backend_unavailable" in fields["model_bench_error"]
